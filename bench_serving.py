"""Serving latency benchmark — ONE JSON line, the BENCH_SERVING series.

The serving counterpart of ``bench.py``'s training suite: drives a real
``ModelServer`` over HTTP with the keep-alive client and reports what a
caller actually feels —

- **cold vs warm first request**: the same model registered with
  ``warmup="off"`` vs ``warmup="sync"`` — the XLA compile spike the AOT
  bucket warmup removes from the request path, and what it cost at
  registration instead (``warmup_seconds``);
- **closed loop**: N worker threads in lockstep request/response —
  p50/p95/p99 latency and saturated throughput;
- **open loop**: fixed arrival rate (latency-independent, the
  coordinated-omission-free number) — achieved rate, SLO hit rate, and
  goodput (completed-within-SLO per second);
- **steady_state_compiles**: XLA compiles observed while the measured
  traffic ran. The fast path's invariant is that this is ZERO; it is also
  the deterministic regression oracle ``--check`` enforces (wall-clock
  latency on shared CI flakes; "did a compile hit the hot path" does not);
- **dispatch_micro**: the host-side coalesce+pad step timed in isolation,
  preallocated pad buffer vs the old concatenate-then-pad path, plus one
  in-process ``ParallelInference`` round-trip time for context;
- **int8**: the quantized-serving config — same measurements through a
  ``dtype_policy="int8"`` version plus calibration error and weight bytes.

Comparator discipline (same as bench.py): latencies through a loopback
HTTP stack on a shared host drift session to session; ``cold - warm``
first-request delta, ``steady_state_compiles``, compile/bucket counts and
byte ratios are the stable comparators. BENCH_SERVING_r01.json is the
committed r01 of this series.

Round 2 (``--chaos``) — availability under injected faults: a fault plan
crashes the live version's forward repeatedly while a retry-budget client
drives traffic. The run proves (and ``--check BENCH_SERVING_r02.json``
re-proves deterministically on every CI run) that the breaker trips, the
dispatcher restarts under its budget, traffic fails over to the designated
fallback with ZERO client-visible 5xx after the trip, the breaker
half-opens and closes once the faults stop — and client-observed
availability stays at/above the recorded floor the whole way. All control
timing runs on a ``ManualTimeSource`` (breaker cooldowns and restart
backoff are *advanced*, not slept), so the choreography is exact.

Round 3 (``--slo``) — the request-cost & SLO plane under open-loop load:
a cost-metered, tail-sampled server carries a latency SLO whose threshold
sits below the lowest histogram bucket, so every request is a
deterministic budget violation. The run proves (and ``--check
BENCH_SERVING_r03.json`` re-proves on every CI run) that the compiled
burn-rate rule fires exactly once and resolves on traffic silence (pure
``ManualTimeSource``, zero control-path sleeps), the cost ledger's
conservation invariant holds with zero steady-state compiles (compile
time is excluded from request bills by construction), the tail sampler
both keeps the injected stall's trace and drops the boring ones, and the
latency histogram's tail-bucket exemplar names a trace that
``capture_bundle`` actually returns.

Usage:
    python bench_serving.py                       # full run, prints JSON
    python bench_serving.py --chaos               # chaos/recovery record
    python bench_serving.py --slo                 # cost/SLO-plane record
    python bench_serving.py --out FILE            # also write FILE
    python bench_serving.py --check BENCH_SERVING_rNN.json
        # regression mode: tiny config, deterministic oracles only —
        # exercised by the smoke tier on every CI run (r01 = fast path,
        # r02 = chaos/recovery, r03 = cost/SLO plane)
"""

import argparse
import json
import sys
import threading
import time

import numpy as np

SCHEMA_CONFIG_KEYS = ("config", "buckets", "warmup_seconds",
                      "cold_first_request_ms", "warm_first_request_ms",
                      "steady_state_compiles", "closed_loop", "open_loop")


# --------------------------------------------------------------------- models
def _mlp(seed=7):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=64, n_out=256, activation="relu"))
            .layer(DenseLayer(n_in=256, n_out=256, activation="relu"))
            .layer(OutputLayer(n_in=256, n_out=16, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    return MultiLayerNetwork(conf).init()


def _lenet(seed=7):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.zoo.models import LeNet
    net = MultiLayerNetwork(LeNet(num_labels=10, seed=seed).conf())
    return net.init()


def _tiny(seed=7):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    return MultiLayerNetwork(conf).init()


CONFIGS = {
    "mlp_ff": dict(
        make=_mlp, row_shape=(64,), buckets=[1, 2, 4, 8, 16, 32],
        desc="3-layer MLP 64-256-256-16, f32", slo_ms=50.0,
        closed_threads=4, closed_reps=60, open_rps=60.0, open_s=3.0),
    "lenet_cnn": dict(
        make=_lenet, row_shape=(28, 28, 1), buckets=[1, 4, 16],
        desc="zoo LeNet 28x28x1, f32", slo_ms=150.0,
        closed_threads=4, closed_reps=30, open_rps=40.0, open_s=3.0),
}


# ---------------------------------------------------------------- measurement
def _percentiles(lat_ms):
    lat = np.asarray(sorted(lat_ms))
    return {"p50_ms": round(float(np.percentile(lat, 50)), 3),
            "p95_ms": round(float(np.percentile(lat, 95)), 3),
            "p99_ms": round(float(np.percentile(lat, 99)), 3)}


def _stack(model, buckets, *, warmup, metrics=None):
    from deeplearning4j_tpu.serving import (MetricsRegistry, ModelRegistry,
                                            ModelServer, ModelServingClient)
    m = metrics if metrics is not None else MetricsRegistry()
    registry = ModelRegistry(metrics=m, buckets=buckets, warmup=warmup,
                             max_batch_size=max(buckets))
    registry.register("bench", model)
    server = ModelServer(registry, metrics=m, max_inflight=256)
    server.start()
    return registry, server, ModelServingClient(server.url)


def _teardown(registry, server, client):
    client.close()
    server.stop(drain=False)
    registry.shutdown()


def _first_request_ms(client, rows, row_shape):
    x = np.random.default_rng(0).normal(size=(rows,) + row_shape)
    x = x.astype(np.float32)
    t0 = time.perf_counter()
    client.predict("bench", x, binary=True)
    return (time.perf_counter() - t0) * 1e3


def _closed_loop(client, row_shape, *, threads, reps, max_rows):
    """Lockstep request/response workers — saturated-latency numbers."""
    lat, errors = [], []
    lock = threading.Lock()
    rows_cycle = [1, 2, max(1, max_rows // 2), max_rows]

    def worker(wid):
        rng = np.random.default_rng(wid)
        mine = []
        for i in range(reps):
            x = rng.normal(size=(rows_cycle[i % len(rows_cycle)],)
                           + row_shape).astype(np.float32)
            t0 = time.perf_counter()
            try:
                client.predict("bench", x, binary=True)
                mine.append((time.perf_counter() - t0) * 1e3)
            except Exception as e:  # noqa: BLE001 — count, keep measuring
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
        with lock:
            lat.extend(mine)

    ts = [threading.Thread(target=worker, args=(w,)) for w in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    elapsed = time.perf_counter() - t0
    rec = {"threads": threads, "requests": len(lat),
           "throughput_rps": round(len(lat) / elapsed, 1), **_percentiles(lat)}
    if errors:
        rec["errors"] = len(errors)
        rec["first_error"] = errors[0]
    return rec


def _open_loop(client, row_shape, *, target_rps, duration_s, slo_ms):
    """Fixed arrival rate, unbounded concurrency — requests are launched on
    schedule whether or not earlier ones returned, so slow responses can't
    slow the arrival process (no coordinated omission)."""
    lat, errors = [], []
    lock = threading.Lock()
    threads = []
    rng = np.random.default_rng(42)
    n = int(target_rps * duration_s)
    xs = [rng.normal(size=(1,) + row_shape).astype(np.float32)
          for _ in range(min(n, 16))]

    def fire(i):
        t0 = time.perf_counter()
        try:
            client.predict("bench", xs[i % len(xs)], binary=True)
            with lock:
                lat.append((time.perf_counter() - t0) * 1e3)
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append(f"{type(e).__name__}: {e}")

    interval = 1.0 / target_rps
    start = time.perf_counter()
    for i in range(n):
        due = start + i * interval
        delay = due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=fire, args=(i,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - start
    done = len(lat)
    within = sum(1 for x in lat if x <= slo_ms)
    rec = {"target_rps": target_rps,
           "achieved_rps": round(done / elapsed, 1),
           "slo_ms": slo_ms,
           "slo_hit_rate": round(within / n, 4) if n else 0.0,
           "goodput_rps": round(within / elapsed, 1)}
    if lat:
        rec.update(_percentiles(lat))
    if errors:
        rec["errors"] = len(errors)
    return rec


def _dispatch_micro(row_shape=(2048,), reps=2000):
    """The host-side coalesce+pad tax, isolated: four 6-row requests
    assembled into a 32-bucket batch, preallocated pad buffer vs the old
    concatenate-then-pad-concatenate (which allocates AND copies the full
    padded batch twice). ``_assemble`` is timed directly because the full
    ``output()`` round-trip (queue handoff, device transfer, forward,
    result materialization) is ~0.5 ms of fixed cost that swamps the
    ~30 µs copy delta into run-to-run noise; ``roundtrip_ms_per_req`` is
    reported once as that context."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    class _Identity:
        def output(self, x):
            return np.asarray(x)

    class _Rows:
        def __init__(self, x):
            self.x = x

    pi = ParallelInference(_Identity(), max_batch_size=32, buckets=[32],
                           mode="sequential")
    rng = np.random.default_rng(9)
    batch = [_Rows(rng.normal(size=(6,) + row_shape).astype(np.float32))
             for _ in range(4)]
    out = {"rows": 24, "bucket": 32,
           "row_floats": int(np.prod(row_shape))}
    for label, reuse in (("assemble_reuse_us", True),
                         ("assemble_concat_us", False)):
        pi.reuse_pad_buffer = reuse
        for _ in range(max(50, reps // 10)):  # warm the path
            pi._assemble(batch, 24, 32)
        t0 = time.perf_counter()
        for _ in range(reps):
            pi._assemble(batch, 24, 32)
        out[label] = round((time.perf_counter() - t0) / reps * 1e6, 2)
    pi.shutdown()

    bpi = ParallelInference(_Identity(), max_batch_size=32, buckets=[32],
                            wait_ms=0.0)
    x = np.concatenate([r.x for r in batch], axis=0)
    for _ in range(20):
        bpi.output(x)
    t0 = time.perf_counter()
    for _ in range(200):
        bpi.output(x)
    out["roundtrip_ms_per_req"] = round((time.perf_counter() - t0) / 200
                                        * 1e3, 4)
    bpi.shutdown()
    return out


def _compile_count():
    from deeplearning4j_tpu.observe import trace as _trace
    tracer = _trace.get_active_tracer()
    return tracer.compile_count if tracer is not None else 0


def _bench_config(name, spec, *, int8=False):
    buckets = spec["buckets"]
    rec = {"config": spec["desc"] + (" + int8 weights" if int8 else ""),
           "buckets": buckets}

    # cold: no warmup — the first request pays the compile spike
    model = spec["make"](seed=3)
    registry, server, client = _stack(model, buckets, warmup="off")
    rec["cold_first_request_ms"] = round(
        _first_request_ms(client, max(buckets), spec["row_shape"]), 2)
    _teardown(registry, server, client)

    # warm: AOT bucket warmup at registration; fresh model object so its
    # jit cache is genuinely cold at register time
    model = spec["make"](seed=3)
    kw = {}
    if int8:
        sample = np.random.default_rng(5).normal(
            size=(max(buckets),) + spec["row_shape"]).astype(np.float32)
        kw = dict(dtype_policy="int8", sample_input=sample)
    from deeplearning4j_tpu.serving import MetricsRegistry, ModelRegistry
    from deeplearning4j_tpu.serving import ModelServer, ModelServingClient
    m = MetricsRegistry()
    registry = ModelRegistry(metrics=m, buckets=buckets, warmup="sync",
                             max_batch_size=max(buckets))
    registry.register("bench", model, **kw)
    state = registry.warmup_state("bench")
    rec["warmup_seconds"] = state["seconds"]
    assert state["status"] == "warm", state
    server = ModelServer(registry, metrics=m, max_inflight=256)
    server.start()
    client = ModelServingClient(server.url)

    c0 = _compile_count()
    rec["warm_first_request_ms"] = round(
        _first_request_ms(client, max(buckets), spec["row_shape"]), 2)
    rec["closed_loop"] = _closed_loop(
        client, spec["row_shape"], threads=spec["closed_threads"],
        reps=spec["closed_reps"], max_rows=max(buckets))
    rec["open_loop"] = _open_loop(
        client, spec["row_shape"], target_rps=spec["open_rps"],
        duration_s=spec["open_s"], slo_ms=spec["slo_ms"])
    rec["steady_state_compiles"] = _compile_count() - c0

    if int8:
        from deeplearning4j_tpu.serving.quantize import param_nbytes
        served = registry.get("bench")
        mv = served.versions[served.current_version]
        rec["quant_error"] = mv.quant_error
        rec["param_bytes_float32"] = param_nbytes(model.params)
        rec["param_bytes_int8"] = mv.model.param_nbytes
    _teardown(registry, server, client)
    return rec


def run_full():
    import jax
    from deeplearning4j_tpu.observe import (Tracer, disable_tracing,
                                            enable_tracing)
    enable_tracing(Tracer())  # compile counting only; ring buffer bounded
    try:
        record = {"series": "BENCH_SERVING", "round": 1,
                  "backend": jax.default_backend(),
                  "devices": len(jax.devices())}
        configs = {}
        for name, spec in CONFIGS.items():
            try:
                configs[name] = _bench_config(name, spec)
            except Exception as e:  # noqa: BLE001 — isolate per config
                configs[name] = {"error": f"{type(e).__name__}: {e}"}
        try:
            # int8 dequantizes per forward — on the CPU bench host that is
            # pure overhead (the byte win pays off on HBM-bound devices),
            # so drive it at a rate it can absorb; the stable comparators
            # are quant_error and the 3.8x weight-byte cut
            int8_spec = dict(CONFIGS["mlp_ff"], open_rps=30.0)
            configs["mlp_ff_int8"] = _bench_config(
                "mlp_ff_int8", int8_spec, int8=True)
        except Exception as e:  # noqa: BLE001
            configs["mlp_ff_int8"] = {"error": f"{type(e).__name__}: {e}"}
        record["configs"] = configs
        try:
            record["dispatch_micro"] = _dispatch_micro()
        except Exception as e:  # noqa: BLE001
            record["dispatch_micro"] = {"error": f"{type(e).__name__}: {e}"}
        return record
    finally:
        disable_tracing()


# --------------------------------------------------------------------- chaos
CHAOS_SCHEMA_KEYS = ("config", "requests", "successes", "availability",
                     "availability_floor", "errors_5xx_after_trip",
                     "breaker_opened_total", "breaker_closed_again",
                     "dispatcher_restarts", "degraded_requests",
                     "recovery_requests", "recovery_wall_ms",
                     "client_retries",
                     "observability_reachable_during_quarantine")

CHAOS_AVAILABILITY_FLOOR = 0.99


def run_chaos():
    """Drive the serving-resilience choreography end to end over real
    HTTP and record what the CLIENT observed. Control time (breaker
    cooldown, restart backoff) lives on a manual clock; only the HTTP
    round-trips are wall time."""
    import jax

    from deeplearning4j_tpu.parallel.elastic import BackoffPolicy
    from deeplearning4j_tpu.parallel.time_source import ManualTimeSource
    from deeplearning4j_tpu.serving import (MetricsRegistry, ModelRegistry,
                                            ModelServer, ModelServingClient,
                                            RetryPolicy)
    from deeplearning4j_tpu.util import faultinject

    ts = ManualTimeSource()
    m = MetricsRegistry()
    registry = ModelRegistry(
        metrics=m, buckets=[2, 4], max_batch_size=4,
        max_dispatcher_restarts=5,
        restart_backoff=BackoffPolicy(base_s=1.0, jitter=0.0),
        breaker=dict(failure_threshold=2, window_s=60.0, cooldown_s=10.0,
                     half_open_probes=1),
        time_source=ts)
    registry.register("bench", _tiny(seed=3))
    registry.register("bench", _tiny(seed=4))   # v2 goes live
    registry.set_fallback("bench", ["previous"])
    server = ModelServer(registry, metrics=m, max_inflight=64)
    server.start()
    cm = MetricsRegistry()
    client = ModelServingClient(
        server.url, metrics=cm,
        retry=RetryPolicy(max_retries=3, jitter=0.0),
        sleep=lambda s: None)  # backoff is advice here, not wall time
    # the live client is serial, so HTTP request seq == dispatch seq;
    # seqs 0-1 are the healthy baseline, 2-4 the crash storm
    plan = {"faults": [
        {"type": "crash_forward", "model": "bench", "step": s}
        for s in (2, 3, 4)]}
    faultinject.set_plan(faultinject.FaultPlan.parse(plan))
    x = np.zeros((2, 8), np.float32)
    outcomes = []          # (ok, after_trip)
    tripped = False
    recovery_requests = None
    t_first_crash = None

    def drive(n=1):
        nonlocal tripped, recovery_requests, t_first_crash
        for _ in range(n):
            try:
                client.predict("bench", x, binary=True)
                ok = True
            except Exception:  # noqa: BLE001 — the record counts these
                ok = False
            brk = registry.get("bench").breakers.get(2)
            if brk is not None and brk.opened_total and not tripped:
                tripped = True
            outcomes.append((ok, tripped))

    try:
        drive(2)                      # seqs 0-1: healthy baseline on v2
        t_first_crash = time.perf_counter()
        drive(1)                      # seq 2: crash -> failover to v1
        drive(1)                      # restart pending -> failover
        ts.advance(seconds=2)         # past restart backoff #1
        drive(1)                      # seq 3: crash #2 -> breaker OPENS
        drive(2)                      # open: quarantined, fallback serves
        # the observability plane must survive the data-plane death:
        # /livez answers (degraded, not down) and /metrics scrapes while
        # the live version is quarantined and the dispatcher is down
        import urllib.request
        observability_ok = True
        for probe in ("/livez", "/metrics"):
            try:
                with urllib.request.urlopen(server.url + probe,
                                            timeout=5) as r:
                    observability_ok &= r.status == 200
            except Exception:  # noqa: BLE001 — recorded, not raised
                observability_ok = False
        ts.advance(seconds=15)        # past cooldown AND backoff #2
        drive(1)                      # half-open probe: seq 4 crash ->
        #                               re-open; the request still serves
        ts.advance(seconds=15)
        drive(1)                      # probe succeeds -> breaker CLOSES
        brk = registry.get("bench").breakers[2]
        closed_again = brk.state == "closed"
        for i in range(3):            # primary serves again
            drive(1)
        recovery_wall_ms = (time.perf_counter() - t_first_crash) * 1e3
        # first post-crash request served by the PRIMARY again
        recovery_requests = 8         # by construction of the schedule
        pi = registry.get("bench").inference
        successes = sum(1 for ok, _ in outcomes if ok)
        record = {
            "config": "tiny MLP 8-16-4, v2 live + v1 fallback, "
                      "crash_forward storm at dispatch seqs 2-4",
            "plan": plan,
            "requests": len(outcomes),
            "successes": successes,
            "availability": round(successes / len(outcomes), 4),
            "availability_floor": CHAOS_AVAILABILITY_FLOOR,
            "errors_5xx_after_trip": sum(
                1 for ok, after in outcomes if after and not ok),
            "breaker_opened_total": brk.opened_total,
            "breaker_closed_again": closed_again,
            "dispatcher_restarts": pi.restarts_used,
            "degraded_requests": int(
                m.get("serving_degraded_requests_total").total()),
            "recovery_requests": recovery_requests,
            "recovery_wall_ms": round(recovery_wall_ms, 1),
            "client_retries": int(cm.get("client_retries_total").total()),
            "observability_reachable_during_quarantine": observability_ok,
        }
        return {"series": "BENCH_SERVING", "round": 2,
                "backend": jax.default_backend(),
                "devices": len(jax.devices()),
                "chaos": record}
    finally:
        faultinject.set_plan(None)
        client.close()
        server.stop(drain=False)
        registry.shutdown()


def run_chaos_check(committed_path):
    """Deterministic chaos oracles for the smoke tier: the committed r02
    record carries the schema and its invariants hold (availability at or
    above its floor, zero 5xx after the trip, breaker closed again,
    restarts within budget), and a fresh in-process chaos run reproduces
    them exactly — plus /livez and /metrics answer during quarantine."""
    failures = []
    with open(committed_path) as f:
        committed = json.load(f)
    if committed.get("series") != "BENCH_SERVING":
        failures.append(f"{committed_path}: series != BENCH_SERVING")
    chaos = committed.get("chaos")
    if not isinstance(chaos, dict):
        failures.append(f"{committed_path}: no 'chaos' record")
        chaos = {}
    for key in CHAOS_SCHEMA_KEYS:
        if key not in chaos:
            failures.append(f"{committed_path}: chaos missing {key!r}")
    if chaos.get("availability", 0) < chaos.get("availability_floor", 1):
        failures.append(f"{committed_path}: availability "
                        f"{chaos.get('availability')} below floor")
    if chaos.get("errors_5xx_after_trip", 1) != 0:
        failures.append(f"{committed_path}: recorded 5xx after the trip")
    if not chaos.get("breaker_closed_again", False):
        failures.append(f"{committed_path}: breaker never closed again")

    fresh = run_chaos()["chaos"]
    if fresh["availability"] < fresh["availability_floor"]:
        failures.append(
            f"live chaos availability {fresh['availability']} below "
            f"floor {fresh['availability_floor']}")
    if fresh["errors_5xx_after_trip"] != 0:
        failures.append(f"live chaos saw {fresh['errors_5xx_after_trip']} "
                        f"client-visible 5xx after the breaker tripped")
    if not fresh["breaker_closed_again"]:
        failures.append("live chaos breaker did not close after faults "
                        "stopped")
    if not fresh["breaker_opened_total"]:
        failures.append("live chaos breaker never opened")
    if fresh["dispatcher_restarts"] < 1:
        failures.append("live chaos dispatcher never restarted")
    if not fresh["observability_reachable_during_quarantine"]:
        failures.append("live chaos: /livez or /metrics unreachable while "
                        "the dispatcher was down")

    if failures:
        for f_ in failures:
            print(f"CHECK FAIL: {f_}", file=sys.stderr)
        return 1
    print(f"bench_serving chaos check OK against {committed_path} "
          f"(availability {fresh['availability']}, "
          f"{fresh['breaker_opened_total']} breaker trip(s), "
          f"{fresh['dispatcher_restarts']} dispatcher restart(s), "
          f"zero 5xx after trip)")
    return 0


# ----------------------------------------------------------------------- slo
SLO_SCHEMA_KEYS = ("config", "slo_spec", "compliance", "burn",
                   "alert_states", "open_loop", "steady_state_compiles",
                   "cost", "sampler", "exemplar_trace_captured")


class _ListSink:
    """In-memory keep target for the tail sampler (the bench needs the
    accounting, not the disk format)."""

    def __init__(self):
        self.spans = []

    def add(self, span):
        self.spans.append(span)


def run_slo():
    """Round 3 — the request-cost & SLO plane under open-loop load.

    Open-loop traffic (fixed arrival rate) with one injected
    ``slow_forward`` stall runs against a server carrying a latency SLO
    whose threshold sits below the lowest histogram bucket — every
    request is a deterministic budget violation, so the burn-rate
    choreography (fire exactly once, resolve on silence) is exact on a
    ``ManualTimeSource`` with zero control-path sleeps. The record
    captures what the plane promises: compliance + burn at fire time,
    the cost ledger's conservation invariant (attributed + unattributed
    == total device ms, compile time separate), the tail sampler's
    keep/drop accounting, and that the latency histogram's tail-bucket
    exemplar names a trace ``capture_bundle`` can actually return."""
    import jax

    from deeplearning4j_tpu.observe import (AlertManager, CallbackSink,
                                            MetricsRegistry, TailSampler,
                                            Tracer, disable_tracing,
                                            enable_tracing, load_slos,
                                            parse_prometheus_text)
    from deeplearning4j_tpu.observe.incident import capture_bundle
    from deeplearning4j_tpu.parallel.time_source import ManualTimeSource
    from deeplearning4j_tpu.serving import (ModelRegistry, ModelServer,
                                            ModelServingClient)
    from deeplearning4j_tpu.util import faultinject

    m = MetricsRegistry()
    sampler = TailSampler(_ListSink(), default_slow_ms=150.0, metrics=m)
    tracer = enable_tracing(Tracer(sampler), metrics=m)
    slo_set = load_slos({"slos": [{
        "name": "bench-latency", "sli": "latency",
        "metric": "serving_request_latency_seconds",
        "labels": {"model": "bench"},
        "threshold_ms": 0.001, "objective": 0.99,
        "windows": [{"long_s": 3600, "short_s": 10, "factor": 2.0}]}]})
    clock = ManualTimeSource(0)
    notes = []
    mgr = AlertManager(m, slo_set.rules(), [CallbackSink(notes.append)],
                       time_source=clock)
    registry = ModelRegistry(metrics=m, buckets=[1, 2, 4], warmup="sync",
                             max_batch_size=4)
    registry.register("bench", _tiny(seed=3))
    server = ModelServer(registry, metrics=m, max_inflight=64,
                         alerts=mgr, slo=slo_set)
    server.start()
    client = ModelServingClient(server.url)
    faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
        {"type": "slow_forward", "model": "bench", "step": 5,
         "duration_s": 0.3}]}))
    try:
        mgr.evaluate_once()   # baseline sample at t=0
        c0 = tracer.compile_count
        open_loop = _open_loop(client, (8,), target_rps=40.0,
                               duration_s=2.0, slo_ms=50.0)
        leaked = tracer.compile_count - c0

        clock.advance(seconds=5)
        mgr.evaluate_once()   # the burn-rate rule fires here
        status = slo_set.status(metrics=m, alerts=mgr)
        entry = status["slos"][0]
        compliance, burn = entry["compliance"], entry["burn"][0]
        clock.advance(seconds=400)
        mgr.evaluate_once()   # traffic silence: short window drains
        states = [n.state for n in notes
                  if n.rule == "slo_burn:bench-latency"]

        # the tail-bucket exemplar must name a retrievable trace
        parsed = parse_prometheus_text(m.exposition())
        tail_le, tail_tid = -1.0, None
        for (series, labels), ex in parsed.exemplars.items():
            ld = dict(labels)
            if series != "serving_request_latency_seconds_bucket" \
                    or ld.get("model") != "bench":
                continue
            le = float(ld["le"])
            if le != float("inf") and le > tail_le:
                tail_le, tail_tid = le, ex.labels.get("trace_id")
        bundle = capture_bundle(seconds=120, metrics=m, cost=server.cost,
                                sampler=sampler, max_spans=4096)
        captured = tail_tid is not None and any(
            e.get("args", {}).get("trace_id") == tail_tid
            for e in bundle["trace"]["traceEvents"])

        cons = server.cost.conservation("bench")
        acct = sampler.describe()
        record = {
            "config": "tiny MLP 8-16-4 warm, open-loop 40 rps x 2 s, one "
                      "300 ms slow_forward stall at dispatch seq 5, "
                      "latency SLO threshold below the lowest bucket",
            "slo_spec": slo_set.describe()[0],
            "compliance": compliance,
            "burn": burn,
            "alert_states": states,
            "open_loop": open_loop,
            "steady_state_compiles": leaked,
            "cost": {
                "conservation_ok": cons["ok"],
                "error_ms": round(cons["error_ms"], 9),
                "device_ms": round(cons["device_ms"], 3),
                "attributed_device_ms": round(
                    cons["attributed_device_ms"], 3),
                "unattributed_device_ms": round(
                    cons["unattributed_device_ms"], 3),
                "compile_ms": round(cons["compile_ms"], 3),
                "requests": cons["requests"],
                "batches": cons["batches"]},
            "sampler": {
                "kept_traces": acct["kept_traces"],
                "kept_spans": acct["kept_spans"],
                "dropped_traces": acct["dropped_traces"],
                "dropped_spans": acct["dropped_spans"],
                "keep_reasons": acct["keep_reasons"],
                "bytes_written": acct["bytes_written"]},
            "exemplar_trace_captured": captured,
        }
        return {"series": "BENCH_SERVING", "round": 3,
                "backend": jax.default_backend(),
                "devices": len(jax.devices()),
                "slo": record}
    finally:
        faultinject.set_plan(None)
        client.close()
        server.stop(drain=False)
        registry.shutdown()
        disable_tracing()
        sampler.close()


def run_slo_check(committed_path):
    """Deterministic SLO/cost oracles for the smoke tier: the committed
    r03 record carries the schema and its invariants hold, and a fresh
    in-process run reproduces every one of them — fire-once/resolve
    choreography, cost conservation with zero steady-state compiles,
    tail-sampler keeps AND drops, exemplar-to-trace retrievability.
    Latency/throughput numbers are deliberately not gated."""
    failures = []
    with open(committed_path) as f:
        committed = json.load(f)
    if committed.get("series") != "BENCH_SERVING":
        failures.append(f"{committed_path}: series != BENCH_SERVING")
    rec = committed.get("slo")
    if not isinstance(rec, dict):
        failures.append(f"{committed_path}: no 'slo' record")
        rec = {}
    for key in SLO_SCHEMA_KEYS:
        if key not in rec:
            failures.append(f"{committed_path}: slo missing {key!r}")

    def _gate(r, where):
        out = []
        if r.get("alert_states") != ["firing", "resolved"]:
            out.append(f"{where}: burn alert did not fire exactly once "
                       f"and resolve (states {r.get('alert_states')})")
        if r.get("compliance", {}).get("met") is not False:
            out.append(f"{where}: sub-bucket threshold did not violate "
                       f"compliance")
        if not r.get("burn", {}).get("active", False):
            out.append(f"{where}: burn windows never went active")
        if not r.get("cost", {}).get("conservation_ok", False):
            out.append(f"{where}: cost ledger conservation broken "
                       f"(error {r.get('cost', {}).get('error_ms')} ms)")
        if r.get("cost", {}).get("requests", 0) < 1:
            out.append(f"{where}: ledger attributed no requests")
        if r.get("steady_state_compiles", 1) != 0:
            out.append(f"{where}: compiles leaked into measured traffic "
                       f"(compile exclusion untestable)")
        if r.get("sampler", {}).get("kept_traces", 0) < 1:
            out.append(f"{where}: tail sampler kept nothing (the stall "
                       f"trace must earn its keep)")
        if r.get("sampler", {}).get("dropped_traces", 0) < 1:
            out.append(f"{where}: tail sampler dropped nothing (it is "
                       f"not sampling)")
        if not r.get("exemplar_trace_captured", False):
            out.append(f"{where}: tail-bucket exemplar's trace not "
                       f"retrievable from the capture bundle")
        return out

    failures += _gate(rec, committed_path)
    fresh = run_slo()["slo"]
    failures += _gate(fresh, "live slo run")

    if failures:
        for f_ in failures:
            print(f"CHECK FAIL: {f_}", file=sys.stderr)
        return 1
    print(f"bench_serving slo check OK against {committed_path} "
          f"(fired once + resolved, conservation error "
          f"{fresh['cost']['error_ms']} ms, "
          f"{fresh['sampler']['kept_traces']} trace(s) kept / "
          f"{fresh['sampler']['dropped_traces']} dropped, "
          f"exemplar trace captured)")
    return 0


# -------------------------------------------------------------------- --check
def run_check(committed_path):
    """Deterministic regression oracles, cheap enough for the smoke tier:

    1. the committed series file parses and carries the full schema;
    2. a tiny model registered with warmup covers every declared bucket;
    3. ZERO XLA compiles while steady-state traffic spans those buckets;
    4. the keep-alive client holds one connection across requests.

    Latency numbers are deliberately NOT gated — on shared CI they flake;
    a compile leaking into the hot path is the regression that matters.
    """
    failures = []
    with open(committed_path) as f:
        committed = json.load(f)
    if committed.get("series") != "BENCH_SERVING":
        failures.append(f"{committed_path}: series != BENCH_SERVING")
    for cname, crec in committed.get("configs", {}).items():
        if "error" in crec:
            failures.append(f"{committed_path}: config {cname} recorded an "
                            f"error: {crec['error']}")
            continue
        for key in SCHEMA_CONFIG_KEYS:
            if key not in crec:
                failures.append(f"{committed_path}: {cname} missing {key!r}")
        if crec.get("steady_state_compiles", 1) != 0:
            failures.append(f"{committed_path}: {cname} recorded "
                            f"steady_state_compiles != 0")

    from deeplearning4j_tpu.observe import (Tracer, disable_tracing,
                                            enable_tracing)
    from deeplearning4j_tpu.serving import ModelServingClient
    tracer = enable_tracing(Tracer())
    try:
        buckets = [2, 4]
        registry, server, client = _stack(_tiny(), buckets, warmup="sync")
        try:
            state = registry.warmup_state("bench")
            if state["status"] != "warm" or state["warm"] != buckets:
                failures.append(f"warmup did not cover buckets: {state}")
            c0 = tracer.compile_count
            rng = np.random.default_rng(0)
            for rows in (1, 2, 3, 4, 1, 4):
                client.predict(
                    "bench", rng.normal(size=(rows, 8)).astype(np.float32),
                    binary=True)
            leaked = tracer.compile_count - c0
            if leaked:
                failures.append(
                    f"{leaked} XLA compile(s) leaked into steady-state "
                    f"serving across declared buckets")
            conn = client._connection()
            client.predict("bench", np.zeros((1, 8), np.float32),
                           binary=True)
            if client._connection() is not conn:
                failures.append("keep-alive client did not reuse its "
                                "connection")
            assert isinstance(client, ModelServingClient)
        finally:
            _teardown(registry, server, client)
    finally:
        disable_tracing()

    if failures:
        for f_ in failures:
            print(f"CHECK FAIL: {f_}", file=sys.stderr)
        return 1
    print(f"bench_serving check OK against {committed_path} "
          f"(warm buckets, zero steady-state compiles, keep-alive)")
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(prog="bench_serving.py")
    p.add_argument("--check", metavar="BENCH_SERVING_rNN.json", default=None,
                   help="regression mode: verify the committed series file "
                        "and its deterministic invariants (fast path for "
                        "r01-style records, chaos/recovery for r02, "
                        "SLO/cost plane for r03)")
    p.add_argument("--chaos", action="store_true",
                   help="record the chaos/recovery series (breaker trip, "
                        "failover, restart, availability under fault) "
                        "instead of the latency suite")
    p.add_argument("--slo", action="store_true",
                   help="record the request-cost & SLO series (burn-rate "
                        "fire/resolve, cost-ledger conservation, tail "
                        "sampling, exemplar retrievability) instead of "
                        "the latency suite")
    p.add_argument("--out", default=None,
                   help="also write the JSON record here")
    args = p.parse_args(argv)
    if args.check:
        with open(args.check) as f:
            committed = json.load(f)
        if "chaos" in committed:
            return run_chaos_check(args.check)
        if "slo" in committed:
            return run_slo_check(args.check)
        return run_check(args.check)
    if args.slo:
        record = run_slo()
    elif args.chaos:
        record = run_chaos()
    else:
        record = run_full()
    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
