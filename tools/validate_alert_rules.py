#!/usr/bin/env python
"""Alert-rule file validator: schema check + dry-run lint.

Validates a ``--alerts rules.json`` file (the ``observe.alerts``
``load_rules`` schema) the same way ``tools/validate_trace.py`` validates
traces: importable (``validate_file``/``validate_rules`` return a list of
problems, empty = valid) and runnable (``python
tools/validate_alert_rules.py RULES.json [...]``).

Two passes:

1. **schema** — the file must build through ``load_rules`` (unknown rule
   types, missing fields, bad ops/windows/objectives, duplicate names all
   surface here with the offending rule index);
2. **dry run** — every rule is evaluated once against an EMPTY metrics
   registry and once against a registry carrying one sample of each
   referenced metric, so a rule that crashes on real data (rather than
   merely staying inactive) is caught before it ships.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from deeplearning4j_tpu.observe.alerts import (  # noqa: E402
    AlertManager, BurnRateRule, load_rules)
from deeplearning4j_tpu.observe.metrics import MetricsRegistry  # noqa: E402
from deeplearning4j_tpu.parallel.time_source import (  # noqa: E402
    ManualTimeSource)


def _referenced_metrics(rules) -> List[str]:
    names = []
    for r in rules:
        if isinstance(r, BurnRateRule):
            names.append(r.slo.metric)
        else:
            names.append(getattr(r, "metric", None))
    return [n for n in names if n]


def validate_rules(spec) -> List[str]:
    """Return a list of problems (empty = valid). ``spec`` is anything
    ``load_rules`` accepts: a path, a JSON string, or a parsed dict."""
    try:
        rules = load_rules(spec)
    except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
        return [f"schema: {e}"]
    if not rules:
        return ["schema: no rules defined"]
    errors: List[str] = []
    # dry run 1: empty registry — every rule must evaluate without raising
    clock = ManualTimeSource(0)
    mgr = AlertManager(MetricsRegistry(), rules, sinks=[],
                       time_source=clock)
    try:
        mgr.evaluate_once()
        clock.advance(seconds=3600)
        mgr.evaluate_once()
    except Exception as e:  # noqa: BLE001 - report, don't crash the lint
        errors.append(f"dry-run (empty registry): {type(e).__name__}: {e}")
    # dry run 2: one counter sample per referenced metric, so label-subset
    # matching and windowed deltas execute against present series
    reg = MetricsRegistry()
    for m in _referenced_metrics(rules):
        try:
            reg.counter(m, "dry-run sample").inc()
        except ValueError:
            pass  # same metric referenced twice
    clock2 = ManualTimeSource(0)
    mgr2 = AlertManager(reg, rules, sinks=[], time_source=clock2)
    try:
        mgr2.evaluate_once()
        clock2.advance(seconds=3600)
        mgr2.evaluate_once()
    except Exception as e:  # noqa: BLE001
        errors.append(f"dry-run (sampled registry): {type(e).__name__}: {e}")
    return errors


def validate_file(path: str) -> List[str]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            spec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable rules file: {e}"]
    return validate_rules(spec)


def main(argv: List[str]) -> int:
    if not argv:
        print("usage: validate_alert_rules.py RULES.json [RULES.json ...]")
        return 2
    rc = 0
    for path in argv:
        errors = validate_file(path)
        if errors:
            rc = 1
            print(f"FAIL {path}")
            for e in errors:
                print(f"  - {e}")
        else:
            n = len(load_rules(path))
            print(f"OK   {path}: {n} rule(s)")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
