"""HLO-text analysis helpers for the TPU perf session.

Maps profiled op names back to what they COMPUTE: every instruction in the
module is indexed (name -> shape/opkind/metadata), fusions resolve to their
body instructions, conv FLOPs are computed by resolving operand shapes, and
classification uses the jax METADATA op_name (scope paths such as
``transpose(jvp(...))/conv_general_dilated``), not XLA's fusion names —
round 1's mislabeled-fusion lesson.
"""

import re

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
DTYPE_BYTES = {"bf16": 2, "f32": 4, "f16": 2, "s32": 4, "pred": 1,
               "u32": 4, "s8": 1, "u8": 1, "f64": 8, "s64": 8, "u64": 8}

# TPU HLO types carry layout/tiling annotations (e.g.
# bf16[256]{0:T(256)(128)(2,1)S(1)}) and tuple types, so the type token
# cannot be matched with a simple char class: find the opcode as the first
# lowercase word followed by '(' after '=' (dtypes are followed by '[',
# layout tokens are digits/uppercase).
_NAME_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")
_OPCODE_RE = re.compile(r"(?:^|\s)([a-z][a-z0-9\-_]*)\(")
_META_RE = re.compile(r'metadata=\{[^}]*op_name="([^"]*)"')
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")


def shape_of(tok):
    """First shape in a type token -> (elem_count, shape tuple, dtype)."""
    m = _SHAPE_RE.search(tok)
    if not m:
        return 0, (), None
    dt, dims = m.group(1), m.group(2)
    shape = tuple(int(d) for d in dims.split(",") if d) if dims else ()
    n = 1
    for d in shape:
        n *= d
    return n, shape, dt


class HloModule:
    def __init__(self, txt):
        self.instr = {}        # name -> dict (first definition wins)
        self.by_comp = {}      # computation -> {name -> dict}
        self.comp_members = {}  # computation name -> [instr names]
        self.entry = []        # instr names in ENTRY
        cur_comp = None
        in_entry = False
        for raw in txt.splitlines():
            s = raw.strip()
            if s.startswith("ENTRY"):
                in_entry = True
                cur_comp = "__entry__"
                self.comp_members[cur_comp] = []
                continue
            m_comp = re.match(r"^%?([\w.\-]+)\s+\(.*\)\s*->\s*.*\{$", s)
            if m_comp and not s.startswith("ENTRY"):
                cur_comp = m_comp.group(1)
                in_entry = False
                self.comp_members[cur_comp] = []
                continue
            if s.startswith("}"):
                cur_comp = None
                in_entry = False
                continue
            m = _NAME_RE.match(s)
            if not m or cur_comp is None:
                continue
            name, rest = m.groups()
            om = _OPCODE_RE.search(rest)
            if not om:
                continue
            outtok, opkind = rest[:om.start()].strip(), om.group(1)
            meta = _META_RE.search(s)
            cm = _CALLS_RE.search(s)
            info = {
                "out": outtok, "op": opkind, "line": s,
                "meta": meta.group(1) if meta else "",
                "calls": cm.group(1) if cm else None,
                "comp": cur_comp,
            }
            # names like param_0 repeat in every fused computation —
            # resolution must be computation-local first (a global-only
            # map silently resolves operands against the WRONG computation)
            self.by_comp.setdefault(cur_comp, {})[name] = info
            if name not in self.instr:
                self.instr[name] = info
            self.comp_members[cur_comp].append(name)
            if in_entry:
                self.entry.append(name)

    # ------------------------------------------------------------ resolve
    def body_of(self, name):
        """Instruction names inside a fusion (or [name] itself)."""
        info = self.instr.get(name)
        if info is None:
            return []
        if info["calls"] and info["calls"] in self.comp_members:
            return self.comp_members[info["calls"]]
        return [name]

    def member_infos(self, name):
        """Info dicts of a fusion's body instructions, resolved in the
        CALLED computation's namespace (param names collide globally)."""
        info = self.instr.get(name)
        if info is None:
            return []
        if info["calls"] and info["calls"] in self.comp_members:
            comp = info["calls"]
            return [self.by_comp[comp][m] for m in self.comp_members[comp]]
        return [info]

    def operand_shapes(self, line, comp=None):
        """Shapes of the operands of an instruction line. The operand list
        is the balanced paren group right after the opcode (layout
        annotations both before and inside it contain parens, so naive
        regex grouping fails); top-level commas split operands. ``comp``
        scopes name resolution to the instruction's own computation."""
        rest = line.split("=", 1)
        if len(rest) < 2:
            return []
        om = _OPCODE_RE.search(rest[1])
        if not om:
            return []
        s = rest[1]
        start = s.index("(", om.end() - 1)
        depth, toks, cur = 0, [], []
        for ch in s[start:]:
            if ch == "(":
                depth += 1
                if depth == 1:
                    continue
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    toks.append("".join(cur))
                    break
            elif ch == "," and depth == 1:
                toks.append("".join(cur))
                cur = []
                continue
            cur.append(ch)
        local = self.by_comp.get(comp, {})
        out = []
        for tok in toks:
            tok = tok.strip()
            key = tok.lstrip("%")
            ref = local.get(key) or self.instr.get(key)
            if ref:
                out.append(shape_of(ref["out"]))
            else:
                out.append(shape_of(tok))  # inline-typed operand
        return out

    # --------------------------------------------------------------- conv
    @staticmethod
    def _dim_taps(out_size, win, stride, pad_lo, lhs_dil, rhs_dil, in_size):
        """Σ over output positions of VALID window taps in one spatial dim.
        XLA canonicalizes backward convs into forms where most taps fall in
        padding or dilation holes (e.g. a 1x1 input-grad appears as a
        55x55-window conv with pad=54) — counting nominal window size
        overstates FLOPs by orders of magnitude."""
        total = 0
        for o in range(out_size):
            base = o * stride - pad_lo
            for w in range(win):
                pos = base + w * rhs_dil
                if pos % lhs_dil:
                    continue
                if 0 <= pos // lhs_dil < in_size:
                    total += 1
        return total

    def conv_flops(self, info):
        """FLOPs + out shape of one convolution instruction:
        2 * out_nonspatial * contracted * Π_d valid_taps_d."""
        if isinstance(info, str):
            info = self.instr[info]
        line = info["line"]
        _, out_shape, _ = shape_of(info["out"])
        dl = re.search(r"dim_labels=(\S+?)(,|\s|$)", line)
        ops = self.operand_shapes(line, info["comp"])
        if not out_shape or not dl or len(ops) < 2:
            return 0, out_shape
        specs = dl.group(1)
        lspec, rest = specs.split("_")
        rspec, ospec = rest.split("->")
        _, lhs_shape, _ = ops[0]
        _, rhs_shape, _ = ops[1]
        if ("i" not in rspec or len(rspec) != len(rhs_shape)
                or len(lspec) != len(lhs_shape)
                or len(ospec) != len(out_shape)):
            return 0, out_shape
        contracted = rhs_shape[rspec.index("i")]
        spatial = [ch for ch in ospec if ch.isdigit()]
        wspec = re.search(r"window=\{([^}]*)\}", line)
        wtxt = wspec.group(1) if wspec else ""
        geti = lambda key, n, dflt: (
            [int(v) for v in m.group(1).split("x")]
            if (m := re.search(key + r"=([\dx]+)", wtxt)) else [dflt] * n)
        n = len(spatial)
        sizes = geti("size", n, 1)
        strides = geti("stride", n, 1)
        lhsd = geti("lhs_dilate", n, 1)
        rhsd = geti("rhs_dilate", n, 1)
        pm = re.search(r"pad=([-\dx_]+)", wtxt)
        pads = ([tuple(int(v) for v in p.split("_"))
                 for p in pm.group(1).split("x")] if pm else [(0, 0)] * n)
        taps = 1
        for d, ch in enumerate(spatial):
            out_size = out_shape[ospec.index(ch)]
            in_size = lhs_shape[lspec.index(ch)]
            taps *= self._dim_taps(out_size, sizes[d], strides[d],
                                   pads[d][0], lhsd[d], rhsd[d], in_size)
        out_nonspatial = 1
        for i, ch in enumerate(ospec):
            if not ch.isdigit():
                out_nonspatial *= out_shape[i]
        return 2 * out_nonspatial * contracted * taps, out_shape

    # ------------------------------------------------------------ classify
    def classify(self, name, batch):
        """(category, flops) for a profiled instruction name."""
        info = self.instr.get(name)
        if info is None:
            return "unmatched", 0
        members = self.member_infos(name)
        metas = [m["meta"] for m in members] + [info["meta"]]
        ops = [m["op"] for m in members]
        flops = 0
        conv_infos = [m for m in members if m["op"] == "convolution"]
        if info["op"] == "convolution":
            conv_infos = [info]
        if conv_infos:
            cats = set()
            for ci in conv_infos:
                f, out_shape = self.conv_flops(ci)
                flops += f
                line = ci["line"]
                out_elems = 1
                for d in out_shape:
                    out_elems *= d
                op_elems = [n for (n, _, _)
                            in self.operand_shapes(line, ci["comp"]) if n]
                rev = re.search(r"rhs_reversal=([\dx]+)", line)
                lhsd = re.search(r"lhs_dilate=([\dx]+)", line)
                # filter grads contract the batch dim: their output (a
                # kernel) is far smaller than either operand
                if op_elems and out_elems * 4 < min(op_elems):
                    cats.add("conv_bwd_filter")
                elif ((rev and any(v != "0" for v in
                                   rev.group(1).split("x")))
                      or (lhsd and any(v != "1" for v in
                                       lhsd.group(1).split("x")))
                      or "transpose(" in ci["meta"]):
                    cats.add("conv_bwd_input")
                else:
                    # NOTE: 1x1 stride-1 input-grad convs with stripped
                    # metadata are structurally identical to forward convs
                    # and land here — fwd/bwd_input may blur for those
                    cats.add("conv_fwd")
            cat = (sorted(cats)[0] if len(cats) == 1
                   else "conv_mixed")
            return cat, flops
        joined = " ".join(metas)
        if "select_and_scatter" in joined or "select-and-scatter" in ops:
            return "maxpool_bwd", 0
        if "reduce_window" in joined or any(o == "reduce-window"
                                            for o in ops):
            if "transpose(" in joined or "vjp" in joined:
                return "pool_bwd", 0
            return "pool_fwd", 0
        if any(o == "dot" for o in ops):
            return "matmul", 0
        if any(o == "reduce" for o in ops):
            return "reduction", 0
        if info["op"] in ("copy", "transpose", "bitcast", "reshape",
                          "copy-start", "copy-done"):
            return "copy", 0
        if info["op"] in ("all-reduce", "all-gather", "reduce-scatter"):
            return "collective", 0
        return "elementwise", 0

    def stream_bytes(self, name):
        """Approximate bytes moved by an elementwise fusion: output plus
        every parameter of its fused computation."""
        info = self.instr.get(name)
        if info is None:
            return 0
        n, shape, dt = shape_of(info["out"])
        total = n * DTYPE_BYTES.get(dt, 4)
        members = self.member_infos(name)
        if len(members) == 1 and members[0] is info:
            # unfused op (plain copy/transpose/add): count its operand
            # reads, or the reported GB/s understates traffic ~2x
            for pn, _, pdt in self.operand_shapes(info["line"],
                                                  info["comp"]):
                total += pn * DTYPE_BYTES.get(pdt or "f32", 4)
        else:
            for mi in members:
                if mi["op"] == "parameter":
                    pn, _, pdt = shape_of(mi["out"])
                    total += pn * DTYPE_BYTES.get(pdt, 4)
        return total
