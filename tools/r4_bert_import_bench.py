"""BERT-base at real scale THROUGH the Keras import path (BASELINE
``configs[2]``), benched against the framework-native zoo
``TransformerEncoder`` — proving import adds no graph-quality tax.

Two stages (run in separate processes; Keras/TF must not share the TPU
process):

  make  — build a genuine BERT-base (12L/768/12H/3072, vocab 30522,
          T=128) in the installed Keras as a two-input functional model
          (token ids + position ids), compile, save h5 (~0.5 GB).
  bench — import the h5, bf16 compute, train B=32/T=128 on the TPU with
          PROFILED device time; then the zoo TransformerEncoder with the
          same shapes in the same session (A/B pair). Done criterion
          (round-3 verdict): imported step within 10% of the zoo step.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=. python tools/r4_bert_import_bench.py make
  PYTHONPATH=.:tools:/root/.axon_site python tools/r4_bert_import_bench.py bench
Writes R4_BERT_IMPORT_BENCH.json.
"""

import json
import os
import sys

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

H5 = os.environ.get("DL4J_TPU_BERT_H5", "/tmp/bert_base_import.h5")
T, V, D, NH, FF, L = 128, 30522, 768, 12, 3072, 12
BATCH = 32


def make():
    import keras
    from keras import layers as kl

    tok = kl.Input((T,), dtype="int32", name="tokens")
    pos = kl.Input((T,), dtype="int32", name="positions")
    e = kl.Embedding(V, D, name="tok_emb")(tok)
    p = kl.Embedding(T, D, name="pos_emb")(pos)
    x = kl.Add(name="embed_add")([e, p])
    for i in range(L):
        att = kl.MultiHeadAttention(num_heads=NH, key_dim=D // NH,
                                    name=f"mha_{i}")(x, x)
        x = kl.LayerNormalization(name=f"ln1_{i}")(
            kl.Add(name=f"add1_{i}")([x, att]))
        ff = kl.Dense(FF, activation="gelu", name=f"ff1_{i}")(x)
        ff = kl.Dense(D, name=f"ff2_{i}")(ff)
        x = kl.LayerNormalization(name=f"ln2_{i}")(
            kl.Add(name=f"add2_{i}")([x, ff]))
    g = kl.GlobalAveragePooling1D(name="pool")(x)
    out = kl.Dense(2, activation="softmax", name="cls")(g)
    m = keras.Model([tok, pos], out)
    m.compile(loss="categorical_crossentropy", optimizer="adam")
    m.save(H5)
    print("params:", m.count_params(), "->", H5,
          f"{os.path.getsize(H5) / 1e9:.2f} GB", flush=True)


def profiled_ms_per_step(fit_once, log_dir, warmup=3, steps=4):
    import shutil

    import jax

    from tpu_perf_session import parse_xplane

    for _ in range(warmup):
        fit_once()
    shutil.rmtree(log_dir, ignore_errors=True)
    jax.profiler.start_trace(log_dir)
    try:
        for _ in range(steps):
            fit_once()
    finally:
        jax.profiler.stop_trace()
    times = parse_xplane(log_dir)
    return 1e3 * sum(t for t, _ in times.values()) / steps


def bench():
    import jax

    from deeplearning4j_tpu.modelimport.keras.importer import KerasModelImport

    print("backend:", jax.default_backend(), flush=True)
    results = {}
    rng = np.random.default_rng(0)
    toks = rng.integers(0, V, size=(BATCH, T)).astype(np.float32)
    poss = np.tile(np.arange(T, dtype=np.float32), (BATCH, 1))
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, BATCH)]

    net = KerasModelImport.import_keras_model_and_weights(H5)
    net.conf.global_conf.compute_dtype = "bfloat16"
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    mds = MultiDataSet([toks, poss], [y])

    def fit_imported():
        net.fit(mds)
        return net.score_

    ms = profiled_ms_per_step(fit_imported, "/tmp/r4_bert_imported")
    results["imported_bert_base"] = {
        "device_ms_per_step": ms,
        "tokens_per_s": BATCH * T / ms * 1e3,
    }
    print(f"imported BERT-base: {ms:.2f} ms/step device "
          f"({BATCH * T / ms * 1e3:.0f} tok/s)", flush=True)
    del net

    # A/B: the framework-native zoo encoder, same shapes, same session
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.models import TransformerEncoder

    zconf = TransformerEncoder(num_labels=2, vocab_size=V, max_length=T).conf()
    zconf.global_conf.compute_dtype = "bfloat16"
    znet = ComputationGraph(zconf)
    znet.init()

    def fit_zoo():
        znet.fit(toks, y)
        return znet.score_

    ms_z = profiled_ms_per_step(fit_zoo, "/tmp/r4_bert_zoo")
    results["zoo_transformer_encoder"] = {
        "device_ms_per_step": ms_z,
        "tokens_per_s": BATCH * T / ms_z * 1e3,
    }
    print(f"zoo encoder:        {ms_z:.2f} ms/step device "
          f"({BATCH * T / ms_z * 1e3:.0f} tok/s)", flush=True)
    results["import_tax_ratio"] = ms / ms_z

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "R4_BERT_IMPORT_BENCH.json")
    with open(out, "w") as fh:
        json.dump(results, fh, indent=1)
    print("wrote", out, flush=True)


if __name__ == "__main__":
    make() if sys.argv[1] == "make" else bench()
