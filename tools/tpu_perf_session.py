"""One-session ResNet50 perf analysis on the tunneled TPU chip.

Produces the per-category roofline evidence the round-2 verdict asked for:

1. bench the headline step (same config as bench.py) — the session baseline;
2. profile a 4-step window, parse the xplane trace (``XLA Ops`` line of the
   TPU plane only), and map every profiled op back to its HLO instruction
   (fusion contents + jax metadata) so time is bucketed by what ops ACTUALLY
   compute, not by XLA's fusion names (round-1's mislabeling lesson);
3. microbench every conv-layer signature IN ISOLATION (fwd + full vjp,
   unrolled chain, runtime cotangent, PROFILED device time) plus the
   single-pass elementwise stream rate — the size-matched hardware ceiling
   for each bucket;
4. emit the table: bucket time share, achieved rate, isolated ceiling —
   written to ROOFLINE_r03.json.

Hard-won methodology notes (round 3): wall clocks lie through this tunnel
(~105 ms sync round trip; fori_loop iterations re-dispatched at ~6-7 ms),
so ALL microbench timing is profiled device time; sum(y) losses hand XLA an
all-ones cotangent that algebraically deletes the backward convolutions;
single-element consumption lets XLA narrow convs; elementwise chains fuse
into one memory pass. Absolute wall throughput drifts across sessions;
device time is bit-stable.

Run:  PYTHONPATH=.:tools:/root/.axon_site python tools/tpu_perf_session.py
"""

import json
import os
import re
import sys
import time

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from hlo_map import HloModule, shape_of

BATCH = 256


def build_net():
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.models import ResNet50

    conf = ResNet50(num_labels=1000, seed=1).conf()
    conf.global_conf.compute_dtype = "bfloat16"
    net = ComputationGraph(conf)
    net.init()
    return net


def make_batch(shape=(224, 224, 3), classes=1000):
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(BATCH,) + shape).astype(np.float32))
    y = jnp.asarray(np.eye(classes, dtype=np.float32)[
        rng.integers(0, classes, size=BATCH)])
    return DataSet(x, y)


def bench(net, ds, steps=10, warmup=3):
    for _ in range(warmup):
        net._fit_batch(ds)
    float(net.score_)
    t0 = time.perf_counter()
    for _ in range(steps):
        net._fit_batch(ds)
    float(net.score_)
    dt = time.perf_counter() - t0
    return BATCH * steps / dt, dt / steps


def lower_hlo(net, ds):
    import jax.numpy as jnp
    mds = net._to_mds(ds)
    dtype = net.conf.global_conf.jnp_dtype()
    inputs = {n: jnp.asarray(f, dtype)
              for n, f in zip(net.conf.inputs, mds.features)}
    labels = [jnp.asarray(l, dtype) for l in mds.labels]
    step = net._get_train_step()
    it = jnp.asarray(net.iteration, jnp.float32)
    ep = jnp.asarray(net.epoch, jnp.float32)
    rng = net._next_rng()
    lowered = step.lower(net.params, net.states, net.updater_states, it, ep,
                         inputs, labels, None, None, rng)
    return lowered.compile().as_text()


def profile_step(net, ds, log_dir):
    import shutil

    from deeplearning4j_tpu.optimize.listeners import ProfilerListener

    shutil.rmtree(log_dir, ignore_errors=True)  # never parse a stale trace
    prof = ProfilerListener(log_dir, start_iteration=net.iteration + 1,
                            n_iterations=4)
    net.listeners.append(prof)
    for _ in range(7):
        net._fit_batch(ds)
    float(net.score_)
    prof.close()
    net.listeners.remove(prof)
    if prof.last_error:
        raise RuntimeError(prof.last_error)
    return parse_xplane(log_dir)


def parse_xplane(log_dir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    pb = None
    for root, _, files in os.walk(log_dir):
        for f in files:
            if f.endswith(".xplane.pb"):
                pb = os.path.join(root, f)
    if pb is None:
        raise RuntimeError(f"no xplane.pb under {log_dir}")
    xs = xplane_pb2.XSpace()
    with open(pb, "rb") as fh:
        xs.ParseFromString(fh.read())
    times = {}
    for plane in xs.planes:
        if "TPU" not in plane.name:
            continue
        ev_meta = {m.id: m.name for m in plane.event_metadata.values()}
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                nm = ev_meta.get(ev.metadata_id, "?")
                dur = ev.duration_ps / 1e12
                t, c = times.get(nm, (0.0, 0))
                times[nm] = (t + dur, c + 1)
    if not times:
        raise RuntimeError("no XLA Ops events found in TPU plane")
    return times


# ---------------------------------------------------------- microbenches
def measure_dispatch_overhead():
    """Synchronous round-trip latency of a trivial dispatch through the
    tunnel (dispatch + result readback) — context for wall-vs-device gaps;
    microbenchmarks themselves use PROFILED device time, not wall clock."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros((8,), jnp.float32)
    float(f(x)[0])
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(x)[0])
        samples.append(time.perf_counter() - t0)
    return min(samples)


def profiled_device_time(run_once, log_dir="/tmp/mb_prof", n_calls=2):
    """Total on-device time (XLA Ops line) of ``n_calls`` executions of an
    async-dispatched callable — wall-clock-free timing, immune to the
    tunnel's ~100 ms sync round trips and session drift."""
    import shutil

    import jax

    shutil.rmtree(log_dir, ignore_errors=True)
    jax.profiler.start_trace(log_dir)
    try:
        last = None
        for _ in range(n_calls):
            last = run_once()
        float(last)  # one sync at the end; the trace captures device work
    finally:
        jax.profiler.stop_trace()
    times = parse_xplane(log_dir)
    return sum(t for t, _ in times.values()) / n_calls


def microbench_model_convs(net, reps=6):
    """Isolated best-case time of every conv layer in the model: each
    distinct (input shape, kernel, stride, filters) signature's forward +
    full vjp (input AND filter grads), UNROLLED ``reps`` times inside one
    jit and chained through a single input element — one dispatch total.
    (A fori_loop would be cleaner, but the tunnel backend re-dispatches
    every loop iteration at ~6-7 ms each, swamping ops this small; the
    unrolled chain keeps XLA's full conv-rewrite pipeline in a single
    dispatch, and timing is PROFILED DEVICE TIME — wall-clock plays no
    part, so no dispatch subtraction is needed.)"""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.layers.conv import ConvolutionLayer

    sigs = {}
    for name, vd in net.conf.vertices.items():
        if not vd.is_layer or not isinstance(vd.obj, ConvolutionLayer):
            continue
        in_t = net.conf.vertex_input_types[name][0]
        layer = vd.obj
        sig = (in_t.height, in_t.width, in_t.channels,
               tuple(layer.kernel_size), tuple(layer.stride), layer.n_out,
               layer.convolution_mode,
               bool(getattr(layer, "space_to_depth_stem", False)))
        if sig in sigs:
            sigs[sig]["count"] += 1
        else:
            sigs[sig] = {"count": 1, "name": name, "layer": layer}
    out = []
    cd = jnp.bfloat16
    for sig, info in sigs.items():
        h, w, c = sig[0], sig[1], sig[2]
        layer = info["layer"]
        params = jax.tree_util.tree_map(
            lambda a: a.astype(cd), dict(net.params[info["name"]]))
        x0 = jax.random.normal(jax.random.PRNGKey(0), (BATCH, h, w, c), cd)

        def loss(p, x, r, _l=layer):
            y, _ = _l.forward(p, x, state={}, train=True, rng=None)
            # RUNTIME cotangent: with sum(y) the cotangent is all-ones and
            # XLA algebraically collapses both backward convolutions into
            # cheap reductions (measured "287 TF/s", beyond peak)
            return jnp.vdot(y.astype(jnp.float32), r)

        vag = jax.value_and_grad(loss, argnums=(0, 1))
        y_shape = jax.eval_shape(
            lambda p, x: layer.forward(p, x, state={}, train=True,
                                       rng=None)[0], params, x0).shape
        r0 = jax.random.normal(jax.random.PRNGKey(1), y_shape, jnp.float32)

        @jax.jit
        def run(x, r):
            acc = jnp.float32(0.0)
            for _ in range(reps):
                v, (gp, gx) = vag(params, x, r)
                # consume EVERY gradient fully — a single-element read of
                # gx would let XLA narrow the bwd-input convolution to one
                # output position, and unread filter grads would dead-code
                # the bwd-filter convolution. The sums add one read pass
                # per tensor (a few % — conservative: overstates isolated
                # time). Serialization rides the gx sum.
                gsum = jnp.sum(gx.astype(jnp.float32))
                x = x.at[(0,) * x.ndim].add(
                    (gsum * jnp.float32(1e-12)).astype(x.dtype))
                acc = acc + v + gsum
                for g in jax.tree_util.tree_leaves(gp):
                    acc = acc + jnp.sum(g.astype(jnp.float32))
            return acc

        try:
            float(run(x0, r0))  # compile+sync
            dt = profiled_device_time(lambda: run(x0, r0)) / reps
        except Exception as e:
            print(f"  conv microbench failed for {info['name']}: "
                  f"{type(e).__name__}", flush=True)
            continue
        out.append({"sig": f"{h}x{w}x{c} k{sig[3]} s{sig[4]} "
                           f"f{sig[5]}" + (" s2d" if sig[7] else ""),
                    "count": info["count"], "time_s": dt})
    return out


def microbench_stream(shape=(256, 56, 56, 256)):
    """Elementwise add stream ceiling (2 reads + 1 write, bf16). ONE add
    per dispatch, timed by profiled device time: any chain of elementwise
    ops fuses into a single memory pass (register chaining), which made a
    chained variant report physically impossible bandwidth."""
    import jax
    import jax.numpy as jnp

    a = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), shape, jnp.bfloat16)

    @jax.jit
    def run(x, y):
        s = x + y
        # returning s materializes the write; the sum (registers, fused)
        # gives a scalar to sync on — 2 reads + 1 write total
        return s, jnp.sum(s.astype(jnp.float32))

    float(run(a, b)[1])
    dt = profiled_device_time(lambda: run(a, b)[1], n_calls=4)
    n = 1
    for d in shape:
        n *= d
    return {"time_s": dt, "gbps": 3 * n * 2 / dt / 1e9}


# ---------------------------------------------------------------- driver
def analyze(net, ds, out_path, do_roofline=True):
    print("== bench (session baseline) ==", flush=True)
    ips, per_step = bench(net, ds)
    print(f"throughput {ips:.1f} img/s  ({per_step*1e3:.2f} ms/step)",
          flush=True)

    print("== HLO lowering ==", flush=True)
    hlo_txt = lower_hlo(net, ds)
    with open("/tmp/rn50_hlo.txt", "w") as fh:
        fh.write(hlo_txt)  # kept for offline analysis
    mod = HloModule(hlo_txt)
    print(f"{len(mod.entry)} entry instructions", flush=True)

    print("== profile 4 steps ==", flush=True)
    times = profile_step(net, ds, "/tmp/rn50_prof")
    total = sum(t for t, _ in times.values())
    print(f"profiled device time {total/4*1e3:.2f} ms/step", flush=True)

    buckets = {}
    per_op = []
    for nm, (t, c) in times.items():
        # profiler event names are full HLO lines; the instruction name is
        # the token before ' = '
        key = nm.split(" = ")[0].strip().lstrip("%")
        cat, flops = mod.classify(key, BATCH)
        b = buckets.setdefault(cat, {"time": 0.0, "flops": 0})
        b["time"] += t
        b["flops"] += flops * c
        per_op.append({"name": key, "t": t, "cat": cat, "flops": flops,
                       "count": c})
    per_op.sort(key=lambda d: -d["t"])

    print("\n== bucket table ==", flush=True)
    for cat, b in sorted(buckets.items(), key=lambda kv: -kv[1]["time"]):
        rate = b["flops"] / b["time"] / 1e12 if b["flops"] else 0
        print(f"  {cat:18s} {b['time']/total*100:5.1f}%  "
              f"{b['time']/4*1e3:7.2f} ms/step  "
              + (f"{rate:6.1f} TFLOP/s" if rate else ""), flush=True)

    print("\n== top ops ==", flush=True)
    for d in per_op[:15]:
        r = d["flops"] * d["count"] / d["t"] / 1e12 if d["flops"] else 0
        print(f"  {d['t']/total*100:5.1f}%  {d['cat']:16s} {d['name'][:58]}"
              + (f"  {r:5.1f} TF/s" if r else ""), flush=True)

    roof = []
    if do_roofline:
        disp = measure_dispatch_overhead()
        print(f"\n(dispatch overhead per call: {disp*1e3:.2f} ms)",
              flush=True)
        print("== conv roofline: isolated fwd+vjp per layer signature ==",
              flush=True)
        roof = microbench_model_convs(net)
        iso_total = sum(r["count"] * r["time_s"] for r in roof) * 1e3
        step_conv_ms = sum(buckets.get(c, {"time": 0})["time"]
                           for c in ("conv_fwd", "conv_bwd_input",
                                     "conv_bwd_filter",
                                     "conv_mixed")) / 4 * 1e3
        for r in roof:
            print(f"  {r['sig']:52s} x{r['count']}  "
                  f"{r['time_s']*1e3:7.2f} ms isolated fwd+bwd", flush=True)
        print(f"  isolated conv total (fwd+bwd all layers): "
              f"{iso_total:.1f} ms/step", flush=True)
        if iso_total > 0:
            print(f"  in-step conv bucket time:                 "
                  f"{step_conv_ms:.1f} ms/step  "
                  f"(ratio {step_conv_ms/iso_total:.2f})", flush=True)
        else:
            print("  (no conv microbenches succeeded — ratio unavailable; "
                  "bench+profile results still written)", flush=True)

        print("\n== bandwidth-bound buckets vs HBM ==", flush=True)
        # v5e HBM is ~819 GB/s; each elementwise/copy op's achieved GB/s
        # comes from its fused computation's operand+output bytes
        bw_rows = []
        for d in per_op:
            if d["cat"] not in ("elementwise", "copy", "maxpool_bwd"):
                continue
            bts = mod.stream_bytes(d["name"])
            if not bts or d["t"] <= 0:
                continue
            gbps = bts * d["count"] / d["t"] / 1e9
            bw_rows.append({"name": d["name"], "cat": d["cat"],
                            "share_pct": d["t"] / total * 100,
                            "bytes": bts, "gbps": gbps})
        for r in bw_rows[:12]:
            print(f"  {r['name'][:40]:40s} {r['cat']:12s} share "
                  f"{r['share_pct']:4.1f}%  {r['gbps']:6.1f} GB/s",
                  flush=True)
        st = microbench_stream()
        print(f"  chained-add microbench: {st['gbps']:.1f} GB/s", flush=True)

        # combined compute/bandwidth roofline per op: model time =
        # max(flops / isolated-conv rate, bytes / stream rate). Round-3
        # result: every top op is HBM-bound and the aggregate runs at
        # 1.09x the model — the step is at its bandwidth roofline, and
        # the isolated-conv gap is fused-epilogue BYTES, not inefficiency.
        peak_tf = 192.3e12  # measured isolated ResNet conv rate, this chip
        stream = st["gbps"] * 1e9 if st["gbps"] else 690e9
        print("\n== combined roofline (top ops) ==", flush=True)
        comb = []
        tot_a = tot_m = 0.0
        for dd in per_op[:25]:
            t_step = dd["t"] / 4
            bts = mod.stream_bytes(dd["name"])
            t_model = max(dd["flops"] / peak_tf, bts / stream)
            if t_model <= 0:
                continue
            comb.append({"name": dd["name"], "cat": dd["cat"],
                         "actual_ms": t_step * 1e3,
                         "model_ms": t_model * 1e3,
                         "ratio": t_step / t_model,
                         "bound": ("MXU" if dd["flops"] / peak_tf
                                   > bts / stream else "HBM")})
            tot_a += t_step
            tot_m += t_model
        if tot_m:
            print(f"  top-{len(comb)} ops: actual {tot_a*1e3:.1f} ms vs "
                  f"roofline model {tot_m*1e3:.1f} ms "
                  f"(ratio {tot_a/tot_m:.2f}); "
                  f"{sum(1 for c in comb if c['bound']=='HBM')}/{len(comb)}"
                  " HBM-bound", flush=True)
    else:
        st, bw_rows, comb = {"gbps": None}, [], []

    out = {
        "session_throughput_img_s": ips,
        "ms_per_step": per_step * 1e3,
        "profiled_ms_per_step": total / 4 * 1e3,
        # what the same step would sustain without per-dispatch tunnel
        # overhead (locally-attached hardware): batch / device-time
        "device_time_throughput_img_s": BATCH / (total / 4),
        "dispatch_overhead_ms_per_step": per_step * 1e3 - total / 4 * 1e3,
        "bandwidth_rows": bw_rows[:20],
        "buckets": {k: {"share_pct": v["time"] / total * 100,
                        "ms_per_step": v["time"] / 4 * 1e3,
                        "tflops": (v["flops"] / v["time"] / 1e12
                                   if v["flops"] else None)}
                    for k, v in buckets.items()},
        "top_ops": [{k: v for k, v in d.items()} for d in per_op[:25]],
        "conv_roofline": roof,
        "combined_roofline": comb,
        "stream_gbps": st["gbps"],
    }
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(out, fh, indent=1)
        print(f"\nwrote {out_path}", flush=True)
    return out


def device_loop_smoke():
    """Compile-and-run lock for ``fit_batches_on_device`` on the REAL chip
    (round-2 verdict item 10): a 3-step window at tiny batch. The axon
    tunnel streams the stacked window per step (~50 s/step measured in
    round 2), so this is a correctness smoke, NOT a benchmark — results are
    recorded in BASELINE.md."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                              OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    convolution_mode="same",
                                    activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    from deeplearning4j_tpu.datasets.dataset import DataSet

    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    window = [DataSet(rng.normal(size=(8, 8, 8, 1)).astype(np.float32),
                      np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)])
              for _ in range(3)]
    t0 = time.perf_counter()
    net.fit_batches_on_device(window)
    loss = float(net.score_)
    dt = time.perf_counter() - t0
    print(f"device-loop smoke: 3-step window ran, loss {loss:.4f}, "
          f"{dt:.1f}s wall (compile+run through tunnel)", flush=True)
    return {"loss": loss, "wall_s": dt}


def main():
    import jax
    print("devices:", jax.devices(), flush=True)
    net = build_net()
    ds = make_batch()
    out = analyze(net, ds, "ROOFLINE_r03.json")
    try:
        out["device_loop_smoke"] = device_loop_smoke()
        with open("ROOFLINE_r03.json", "w") as fh:
            json.dump(out, fh, indent=1)
    except Exception as e:
        print(f"device-loop smoke FAILED: {type(e).__name__}: {e}",
              flush=True)


if __name__ == "__main__":
    main()
