#!/usr/bin/env python
"""SLO config validator: schema check + dry-run lint.

Validates a ``--slo SLO.json`` file (the ``observe.slo`` ``load_slos``
schema) the same way ``tools/validate_alert_rules.py`` validates alert
rules: importable (``validate_file``/``validate_slos`` return a list of
problems, empty = valid) and runnable (``python
tools/validate_slo_config.py SLO.json [...]``).

Two passes:

1. **schema** — the file must build through ``load_slos`` (unknown SLI
   kinds, objectives outside (0, 1), a latency SLO without
   ``threshold_ms``, an availability SLO without ``error_labels``, bad
   windows and duplicate names all surface here with the offending SLO
   index);
2. **dry run** — every compiled burn-rate rule is evaluated once
   against an EMPTY metrics registry and once against a registry
   carrying one sample of each referenced metric (a histogram
   observation for latency SLOs so the bucket math executes, a labeled
   counter increment for availability SLOs), so a config that crashes
   on real series — rather than merely staying inactive — is caught
   before it ships.  ``SLOSet.status()`` runs over the sampled
   exposition too: the /slo payload must assemble cleanly.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from deeplearning4j_tpu.observe.alerts import AlertManager  # noqa: E402
from deeplearning4j_tpu.observe.metrics import MetricsRegistry  # noqa: E402
from deeplearning4j_tpu.observe.slo import load_slos  # noqa: E402
from deeplearning4j_tpu.parallel.time_source import (  # noqa: E402
    ManualTimeSource)


def _seed_registry(slo_set) -> MetricsRegistry:
    """One sample per referenced metric, shaped for its SLI: latency
    SLOs get a real histogram observation (bucket series must exist for
    the good/total split to execute), availability SLOs get a counter
    increment carrying the SLO's error labels."""
    reg = MetricsRegistry()
    for s in slo_set.slos:
        labels = dict(s.labels or {})
        if s.sli == "latency":
            try:
                h = reg.histogram(s.metric, "dry-run sample",
                                  tuple(labels.keys()))
            except ValueError:
                continue  # same metric referenced twice, other shape
            h.observe(0.001, **labels)
        else:
            err = dict(labels)
            err.update(s.error_labels or {})
            try:
                c = reg.counter(s.metric, "dry-run sample",
                                tuple(err.keys()))
            except ValueError:
                continue
            c.inc(**err)
    return reg


def _dry_run(slo_set, reg: MetricsRegistry, tag: str) -> List[str]:
    errors: List[str] = []
    clock = ManualTimeSource(0)
    mgr = AlertManager(reg, slo_set.rules(), sinks=[], time_source=clock)
    try:
        mgr.evaluate_once()
        clock.advance(seconds=3600)
        mgr.evaluate_once()
    except Exception as e:  # noqa: BLE001 - report, don't crash the lint
        errors.append(f"dry-run ({tag}): {type(e).__name__}: {e}")
    # the /slo payload must assemble over the same registry + manager
    try:
        status = slo_set.status(metrics=reg, alerts=mgr)
        if len(status["slos"]) != len(slo_set.slos):
            errors.append(f"dry-run ({tag}): status() reported "
                          f"{len(status['slos'])} of "
                          f"{len(slo_set.slos)} slo(s)")
    except Exception as e:  # noqa: BLE001
        errors.append(f"dry-run ({tag}): status(): "
                      f"{type(e).__name__}: {e}")
    return errors


def validate_slos(spec) -> List[str]:
    """Return a list of problems (empty = valid). ``spec`` is anything
    ``load_slos`` accepts: a path, a JSON string, or a parsed
    dict/list."""
    try:
        slo_set = load_slos(spec)
    except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
        return [f"schema: {e}"]
    if not slo_set.slos:
        return ["schema: no slos defined"]
    errors: List[str] = []
    errors += _dry_run(slo_set, MetricsRegistry(), "empty registry")
    errors += _dry_run(slo_set, _seed_registry(slo_set),
                       "sampled registry")
    return errors


def validate_file(path: str) -> List[str]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            spec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable slo file: {e}"]
    return validate_slos(spec)


def main(argv: List[str]) -> int:
    if not argv:
        print("usage: validate_slo_config.py SLO.json [SLO.json ...]")
        return 2
    rc = 0
    for path in argv:
        errors = validate_file(path)
        if errors:
            rc = 1
            print(f"FAIL {path}")
            for e in errors:
                print(f"  - {e}")
        else:
            n = len(load_slos(path).slos)
            print(f"OK   {path}: {n} slo(s)")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
