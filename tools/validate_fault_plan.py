#!/usr/bin/env python
"""Fault-plan file validator: schema check + dry-run lint.

Validates a ``DL4J_TPU_FAULT_PLAN`` file (the ``util.faultinject``
``FaultPlan`` schema) the same way ``tools/validate_alert_rules.py``
validates alert rules: importable (``validate_file``/``validate_plan``
return a list of problems, empty = valid) and runnable
(``python tools/validate_fault_plan.py PLAN.json [...]``).

Two passes:

1. **schema** — the file must build through ``FaultPlan.parse`` (unknown
   fault types, bad workers/steps/modes/signals all surface here with
   the offending fault index);
2. **dry run** — ``FaultPlan.lint`` flags plans that parse but cannot
   behave as written: duplicate triggers, and faults shadowed by an
   earlier kill/stall of the same worker. No fault is executed.

``--workers N`` additionally checks that every integer worker slot is
inside the job's initial world; ``--hosts H`` does the same for the
host-scoped fault kinds (``kill_host`` / ``partition`` — and plans that
use them against a job with no host grouping are flagged);
``--models NAME,NAME`` does the same for the serving-scoped kinds
(``crash_forward`` / ``slow_forward`` / ``reject_admission`` /
``drop_response``) — a fault naming a model the server never registers
would silently never fire.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from deeplearning4j_tpu.util.faultinject import FaultPlan  # noqa: E402


def validate_plan(spec, num_workers: Optional[int] = None,
                  num_hosts: Optional[int] = None,
                  models: Optional[List[str]] = None) -> List[str]:
    """Return a list of problems (empty = valid). ``spec`` is a parsed
    dict, a JSON string, or a path."""
    try:
        if isinstance(spec, dict):
            plan = FaultPlan.parse(spec)
        else:
            plan = FaultPlan.load(spec)
    except (ValueError, KeyError, TypeError, OSError,
            json.JSONDecodeError) as e:
        return [f"schema: {e}"]
    if not plan.faults:
        return ["schema: no faults defined"]
    errors = [f"lint: {p}" for p in plan.lint()]
    if models is not None:
        for i, f in enumerate(plan.faults):
            if f.model is not None and f.model != "*" \
                    and f.model not in models:
                errors.append(
                    f"lint: fault[{i}] targets model {f.model!r} but the "
                    f"server registers {sorted(models)} — it would "
                    f"silently never fire")
    if num_workers is not None:
        for i, f in enumerate(plan.faults):
            if isinstance(f.worker, int) and f.worker >= num_workers:
                errors.append(
                    f"lint: fault[{i}] targets worker {f.worker} but the "
                    f"job starts with {num_workers} workers "
                    f"(slots 0..{num_workers - 1})")
    if num_hosts is not None:
        for i, f in enumerate(plan.faults):
            if isinstance(f.host, int) and f.host >= num_hosts:
                errors.append(
                    f"lint: fault[{i}] targets host {f.host} but the "
                    f"job starts with {num_hosts} host groups "
                    f"(hosts 0..{num_hosts - 1})")
    elif num_workers is not None:
        # a job validated without --hosts has no host grouping: its
        # host-scoped faults would silently never fire
        for i, f in enumerate(plan.faults):
            if f.host is not None:
                errors.append(
                    f"lint: fault[{i}] is host-scoped ({f.type}) but the "
                    f"job has no host grouping (pass --hosts H)")
    return errors


def validate_file(path: str,
                  num_workers: Optional[int] = None,
                  num_hosts: Optional[int] = None,
                  models: Optional[List[str]] = None) -> List[str]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            spec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable plan file: {e}"]
    return validate_plan(spec, num_workers, num_hosts, models)


def main(argv: List[str]) -> int:
    num_workers = None
    num_hosts = None
    models = None
    if "--workers" in argv:
        i = argv.index("--workers")
        try:
            num_workers = int(argv[i + 1])
        except (IndexError, ValueError):
            print("--workers needs an integer")
            return 2
        argv = argv[:i] + argv[i + 2:]
    if "--hosts" in argv:
        i = argv.index("--hosts")
        try:
            num_hosts = int(argv[i + 1])
        except (IndexError, ValueError):
            print("--hosts needs an integer")
            return 2
        argv = argv[:i] + argv[i + 2:]
    if "--models" in argv:
        i = argv.index("--models")
        try:
            models = [m for m in argv[i + 1].split(",") if m]
        except IndexError:
            models = []
        if not models:
            print("--models needs a comma-separated name list")
            return 2
        argv = argv[:i] + argv[i + 2:]
    if not argv:
        print("usage: validate_fault_plan.py [--workers N] [--hosts H] "
              "[--models NAME,NAME] PLAN.json [PLAN.json ...]")
        return 2
    rc = 0
    for path in argv:
        errors = validate_file(path, num_workers, num_hosts, models)
        if errors:
            rc = 1
            print(f"FAIL {path}")
            for e in errors:
                print(f"  - {e}")
        else:
            n = len(FaultPlan.load(path).faults)
            print(f"OK   {path}: {n} fault(s)")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
