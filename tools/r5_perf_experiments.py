"""Round-5 ResNet50 floor-proof experiments (VERDICT r4 Weak #2 / Next #3).

The r3 roofline left three over-model buckets (~3-4 ms of the 94.7 ms
step): maxpool backward (select_and_scatter, 200 GB/s vs the 690 GB/s
stream ceiling), BN-backward reductions at 1.55x model, one conv
bwd-input fusion at 1.70x — plus one unmeasured lever, "bf16 storage of
activations re-read by BN/conv backward" (bounded 5-8% IF such f32
activation bytes exist). This script measures each bucket AT ITS OWN
CEILING so the 94.7 ms floor claim is airtight, and A/Bs the one
reformulation with a plausible byte win:

  f32_residual_audit   — parse the optimized train-step HLO and list every
                         f32 tensor >= 8 MB: if the only big f32 buffers
                         are updater slots (whose split was measured
                         no-win in r4), the bf16-saved-activations lever
                         has NO bytes left to shave and its bound is 0.
  maxpool_isolated     — the stem maxpool fwd+vjp in isolation (profiled
                         device time): its achieved GB/s vs its byte
                         floor. If the ISOLATED op also runs ~200 GB/s,
                         that rate is select_and_scatter's own ceiling on
                         this chip, not a fusion artifact.
  maxpool_eq_backward  — custom-vjp reformulation routing gradients by
                         value equality (tie-sharing subgradient):
                         dx = sum over covering windows of
                         (x == y_w) * g_w / ties_w, built from strided
                         slices + repeats that fuse into streaming passes.
                         A/B vs select_and_scatter at the stem shape.
  bn_reduce_isolated   — the exact BN-backward reduction pair
                         (sum(dy), sum(dy*xhat) over NHW) in isolation:
                         achieved GB/s vs the 2-read byte floor.

Run: PYTHONPATH=.:tools:/root/.axon_site python tools/r5_perf_experiments.py
Writes R5_PERF_EXPERIMENTS.json.
"""

import json
import os
import re
import sys
import time

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

STEM = (256, 112, 112, 64)  # ResNet50 maxpool input, bf16, batch 256


def f32_residual_audit(results):
    import jax

    from tpu_perf_session import build_net, lower_hlo, make_batch

    net = build_net()
    ds = make_batch()
    txt = lower_hlo(net, ds)
    # only ENTRY-computation instructions allocate HBM buffers; f32 values
    # inside fusion bodies live in registers and must not be counted
    entry = re.search(r"\nENTRY [^\n]*\{\n(.*?)\n\}", txt, re.S)
    body = entry.group(1) if entry else txt
    sizes = {}
    for line in body.splitlines():
        m = re.match(r"\s*%?([\w.\-]+) = (.*?) (\w+)\(", line)
        if not m:
            continue
        name, result_t = m.group(1), m.group(2)
        n = 0
        for shp in re.finditer(r"f32\[([\d,]*)\]", result_t):
            sz = 4
            for d in shp.group(1).split(","):
                if d:
                    sz *= int(d)
            n += sz
        if n >= 8 << 20:
            sizes[name] = n
    top = sorted(sizes.items(), key=lambda kv: -kv[1])[:25]
    results["f32_residual_audit"] = {
        "materialized_f32_buffers_over_8mb": [
            {"name": k, "mb": round(v / 2**20, 1)} for k, v in top],
        "total_mb_over_8mb": round(sum(sizes.values()) / 2**20, 1),
    }
    print("f32 audit:", results["f32_residual_audit"]["total_mb_over_8mb"],
          "MB materialized f32 >=8MB;", len(sizes), "buffers", flush=True)


def _maxpool_fwd(x):
    from jax import lax

    # python-float init: a TRACED init array hides the max monoid from
    # jax's reduce_window autodiff rule (fails only under jit on tpu)
    return lax.reduce_window(x, -float("inf"), lax.max,
                             (1, 3, 3, 1), (1, 2, 2, 1),
                             [(0, 0), (1, 1), (1, 1), (0, 0)])


def maxpool_isolated(results):
    import jax
    import jax.numpy as jnp

    from tpu_perf_session import profiled_device_time

    x = jax.random.normal(jax.random.PRNGKey(0), STEM, jnp.bfloat16)
    r = jax.random.normal(jax.random.PRNGKey(1),
                          (STEM[0], 56, 56, STEM[3]), jnp.bfloat16)

    @jax.jit
    def vjp_run(x, r):
        y, pull = jax.vjp(_maxpool_fwd, x)
        (dx,) = pull(r)
        # scalar sync target; the dx write is materialized by returning it
        return dx, jnp.sum(dx.astype(jnp.float32))

    float(vjp_run(x, r)[1])
    dt = profiled_device_time(lambda: vjp_run(x, r)[1],
                              "/tmp/r5_mp_iso", n_calls=4)
    elem = 1
    for d in STEM:
        elem *= d
    out_elem = elem // 4
    # fwd reads x + writes y; bwd reads x,y,g + writes dx (bf16)
    byte_floor = 2 * (elem + out_elem) + 2 * (elem + 2 * out_elem + elem)
    results["maxpool_isolated"] = {
        "device_ms": round(dt * 1e3, 3),
        "gbps_at_byte_floor": round(byte_floor / dt / 1e9, 1),
        "byte_floor_mb": round(byte_floor / 2**20, 1),
    }
    print("maxpool fwd+vjp isolated:", results["maxpool_isolated"], flush=True)


def _eq_maxpool(x):
    """Maxpool 3x3/s2/p1 with an equality-routed custom backward."""
    import jax
    import jax.numpy as jnp

    @jax.custom_vjp
    def pool(x):
        return _maxpool_fwd(x)

    def fwd(x):
        y = _maxpool_fwd(x)
        return y, (x, y)

    def bwd(res, g):
        x, y = res
        n, h, w, c = x.shape
        oh, ow = y.shape[1], y.shape[2]
        hp, wp = h + 2, w + 2  # padded grid (pad=1 both sides)
        xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)),
                     constant_values=-jnp.inf)
        # tie counts per window from 9 strided patch views of xp
        cnt = None
        for di in range(3):
            for dj in range(3):
                p = jax.lax.slice(xp, (0, di, dj, 0),
                                  (n, di + 2 * oh - 1, dj + 2 * ow - 1, c),
                                  (1, 2, 2, 1))
                e = (p == y).astype(jnp.bfloat16)
                cnt = e if cnt is None else cnt + e
        share = (g / cnt).astype(jnp.float32)

        # Padded position ip is covered by window p iff 2p <= ip <= 2p+2:
        # term A: p = ip // 2           (any ip, when p < oh)
        # term B: p = ip // 2 - 1       (EVEN ip only, when p >= 0)
        # Build each term as a repeat of the out grid onto [0, 2*oh) then
        # pad/shift onto the padded grid; parity masks kill invalid B
        # contributions. Everything is slices/repeats/where — one fused
        # streaming pass per term under XLA.
        def up(a, fill):
            a2 = jnp.repeat(jnp.repeat(a, 2, axis=1), 2, axis=2)
            return jnp.pad(a2, ((0, 0), (0, hp - 2 * oh), (0, wp - 2 * ow),
                                (0, 0)), constant_values=fill)

        yA, sA = up(y, jnp.inf), up(share, 0.0)  # indexed by ip directly

        def shift2(a, axis, fill):
            # b[ip] = a[ip-2]: term-B alignment along one axis
            pad = [(0, 0)] * 4
            pad[axis] = (2, 0)
            out = jnp.pad(a, pad, constant_values=fill)
            return (out[:, :hp, :, :] if axis == 1 else out[:, :, :wp, :])

        even_h = (jnp.arange(hp) % 2 == 0)[None, :, None, None]
        even_w = (jnp.arange(wp) % 2 == 0)[None, None, :, None]

        acc = jnp.zeros((n, hp, wp, c), jnp.float32)
        for bh in (False, True):
            for bw_ in (False, True):
                yt, st = yA, sA
                ok = None
                if bh:
                    yt, st = shift2(yt, 1, jnp.inf), shift2(st, 1, 0.0)
                    ok = even_h if ok is None else (ok & even_h)
                if bw_:
                    yt, st = shift2(yt, 2, jnp.inf), shift2(st, 2, 0.0)
                    ok = even_w if ok is None else (ok & even_w)
                hit = (xp == yt)
                if ok is not None:
                    hit = hit & ok
                acc = acc + jnp.where(hit, st, 0.0)
        dx = acc[:, 1:-1, 1:-1, :].astype(x.dtype)
        return (dx,)

    pool.defvjp(fwd, bwd)
    return pool(x)


def maxpool_eq_backward(results):
    import jax
    import jax.numpy as jnp

    from tpu_perf_session import profiled_device_time

    x = jax.random.normal(jax.random.PRNGKey(0), STEM, jnp.bfloat16)
    r = jax.random.normal(jax.random.PRNGKey(1),
                          (STEM[0], 56, 56, STEM[3]), jnp.bfloat16)

    @jax.jit
    def vjp_run(x, r):
        y, pull = jax.vjp(_eq_maxpool, x)
        (dx,) = pull(r)
        return dx, jnp.sum(dx.astype(jnp.float32))

    # numeric sanity on a tiny tie-free input before timing
    xt = jnp.asarray(np.random.default_rng(0).permutation(
        np.arange(2 * 8 * 8 * 3, dtype=np.float32)).reshape(2, 8, 8, 3))
    rt = jnp.ones((2, 4, 4, 3), jnp.float32)
    ref = jax.vjp(_maxpool_fwd, xt)[1](rt)[0]
    got = jax.vjp(_eq_maxpool, xt)[1](rt)[0]
    err = float(jnp.max(jnp.abs(ref - got)))
    float(vjp_run(x, r)[1])
    dt = profiled_device_time(lambda: vjp_run(x, r)[1],
                              "/tmp/r5_mp_eq", n_calls=4)
    results["maxpool_eq_backward"] = {
        "device_ms": round(dt * 1e3, 3),
        "tie_free_max_abs_err_vs_xla": err,
    }
    print("maxpool equality-routed:", results["maxpool_eq_backward"],
          flush=True)


def bn_reduce_isolated(results):
    import jax
    import jax.numpy as jnp

    from tpu_perf_session import profiled_device_time

    shape = (256, 56, 56, 256)  # representative BN-backward operand

    dy = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.bfloat16)
    xh = jax.random.normal(jax.random.PRNGKey(1), shape, jnp.bfloat16)

    @jax.jit
    def run(dy, xh):
        s1 = jnp.sum(dy, axis=(0, 1, 2), dtype=jnp.float32)
        s2 = jnp.sum((dy * xh).astype(jnp.float32), axis=(0, 1, 2),
                     dtype=jnp.float32)
        return jnp.sum(s1) + jnp.sum(s2)

    float(run(dy, xh))
    dt = profiled_device_time(lambda: run(dy, xh), "/tmp/r5_bn", n_calls=4)
    n = 1
    for d in shape:
        n *= d
    bytes_moved = 2 * n * 2  # two bf16 reads; outputs are [C]-tiny
    results["bn_reduce_isolated"] = {
        "device_ms": round(dt * 1e3, 3),
        "gbps": round(bytes_moved / dt / 1e9, 1),
    }
    print("BN backward reduction pair isolated:",
          results["bn_reduce_isolated"], flush=True)


def main():
    results = {}
    t0 = time.time()
    for fn in (f32_residual_audit, maxpool_isolated, maxpool_eq_backward,
               bn_reduce_isolated):
        try:
            fn(results)
        except Exception as e:  # noqa: BLE001 - record and continue
            results[fn.__name__] = {"error": f"{type(e).__name__}: {e}"}
            print(f"{fn.__name__} FAILED: {e}", flush=True)
    results["wall_s_total"] = time.time() - t0
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "R5_PERF_EXPERIMENTS.json")
    with open(out, "w") as fh:
        json.dump(results, fh, indent=1)
    print("wrote", out, flush=True)


if __name__ == "__main__":
    main()
