"""Round-4 ResNet50 A/B experiments on PROFILED device time.

The round-3 verdict's open perf question: in-step conv buckets run 1.61x
their isolated fwd+vjp time because BN-backward reductions, residual
grads, and updater epilogues ride the conv fusions (~28 ms/step of
fused-epilogue BYTES on a bandwidth-bound step). Attacks, all measured
with the trusted device-time methodology (wall clocks lie through the
tunnel — see tpu_perf_session.py header):

A. batch sweep 256/384/512 — the round-1/2 "batch doesn't help"
   conclusion predates the methodology fix;
B. activation rematerialization (gradient_checkpointing) — the textbook
   HBM-for-FLOPs trade on a bandwidth-bound step;
C. updater-outside-fusion — a separate jitted apply isolates the updater
   epilogue traffic from the conv backward fusions.

Run:  PYTHONPATH=.:tools:/root/.axon_site python tools/r4_perf_experiments.py
Writes R4_PERF_EXPERIMENTS.json.
"""

import json
import os
import sys
import time

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from tpu_perf_session import parse_xplane


def build_net(remat=False):
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.models import ResNet50

    conf = ResNet50(num_labels=1000, seed=1).conf()
    conf.global_conf.compute_dtype = "bfloat16"
    if remat:
        conf.global_conf.gradient_checkpointing = True
    net = ComputationGraph(conf)
    net.init()
    return net


def make_batch(batch, shape=(224, 224, 3), classes=1000):
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch,) + shape).astype(np.float32))
    y = jnp.asarray(np.eye(classes, dtype=np.float32)[
        rng.integers(0, classes, size=batch)])
    return DataSet(x, y)


def profiled_ms_per_step(net, ds, log_dir, warmup=3, steps=4):
    import shutil

    import jax

    for _ in range(warmup):
        net._fit_batch(ds)
    float(net.score_)
    shutil.rmtree(log_dir, ignore_errors=True)
    jax.profiler.start_trace(log_dir)
    try:
        for _ in range(steps):
            net._fit_batch(ds)
        float(net.score_)
    finally:
        jax.profiler.stop_trace()
    times = parse_xplane(log_dir)
    return 1e3 * sum(t for t, _ in times.values()) / steps


def experiment_batch_sweep(results, batches=(256, 384, 512)):
    for batch in batches:
        net = build_net()
        ds = make_batch(batch)
        ms = profiled_ms_per_step(net, ds, f"/tmp/r4_b{batch}")
        results[f"batch_{batch}"] = {
            "device_ms_per_step": ms,
            "device_img_per_s": batch / ms * 1e3,
        }
        print(f"batch {batch}: {ms:.2f} ms/step device = "
              f"{batch / ms * 1e3:.1f} img/s", flush=True)
        del net, ds


def experiment_remat(results, batches=(256,)):
    # measured: remat at b=256 is 1830 img/s vs 2702 stock — the step is
    # bandwidth-bound AT its roofline, so recompute adds reads without
    # removing any; b=512+remat OOMs outright. One batch size suffices.
    for batch in batches:
        net = build_net(remat=True)
        ds = make_batch(batch)
        ms = profiled_ms_per_step(net, ds, f"/tmp/r4_remat{batch}")
        results[f"remat_batch_{batch}"] = {
            "device_ms_per_step": ms,
            "device_img_per_s": batch / ms * 1e3,
        }
        print(f"remat batch {batch}: {ms:.2f} ms/step device = "
              f"{batch / ms * 1e3:.1f} img/s", flush=True)
        del net, ds


def experiment_updater_outside(results, batch=256):
    """Two-jit step: grads in one donated jit, updater apply in a second.
    Isolates the updater epilogue bytes from the conv backward fusions —
    if the fused epilogues were mispriced, the split step's conv buckets
    should drop toward their isolated times (at the cost of materializing
    the gradient pytree once)."""
    import jax
    import jax.numpy as jnp

    net = build_net()
    ds = make_batch(batch)

    mds = net._to_mds(ds)
    dtype = net.conf.global_conf.jnp_dtype()
    inputs = {n: jnp.asarray(f, dtype)
              for n, f in zip(net.conf.inputs, mds.features)}
    labels = [jnp.asarray(l, dtype) for l in mds.labels]

    def grad_step(params, states, it, ep, inputs, labels, rng):
        rng_use, rng_next = jax.random.split(rng)

        def lf(p):
            return net._loss_fn(p, states, inputs, labels, rng_use,
                                None, None, train=True, carries=None)
        (loss, (new_states, _)), grads = jax.value_and_grad(
            lf, has_aux=True)(params)
        return grads, new_states, loss, rng_next

    def apply_step(params, grads, upd_states, it, ep):
        new_params, new_upd = net._apply_updates(params, grads, upd_states,
                                                 it, ep)
        return new_params, new_upd, it + 1.0

    jg = jax.jit(grad_step, donate_argnums=(1,))
    ja = jax.jit(apply_step, donate_argnums=(0, 2))

    params, states, upd = net.params, net.states, net.updater_states
    it = jnp.asarray(0.0, jnp.float32)
    ep = jnp.asarray(0.0, jnp.float32)
    rng = jax.random.PRNGKey(0)

    def one_step():
        nonlocal params, states, upd, it, rng
        grads, states, loss, rng = jg(params, states, it, ep, inputs, labels,
                                      rng)
        params, upd, it = ja(params, grads, upd, it, ep)
        return loss

    for _ in range(3):
        loss = one_step()
    float(loss)
    import shutil
    shutil.rmtree("/tmp/r4_split", ignore_errors=True)
    jax.profiler.start_trace("/tmp/r4_split")
    try:
        for _ in range(4):
            loss = one_step()
        float(loss)
    finally:
        jax.profiler.stop_trace()
    times = parse_xplane("/tmp/r4_split")
    ms = 1e3 * sum(t for t, _ in times.values()) / 4
    results["updater_outside_batch_256"] = {
        "device_ms_per_step": ms,
        "device_img_per_s": batch / ms * 1e3,
    }
    print(f"updater-outside batch {batch}: {ms:.2f} ms/step device = "
          f"{batch / ms * 1e3:.1f} img/s", flush=True)


def main():
    import jax
    print("backend:", jax.default_backend(), jax.devices(), flush=True)
    results = {}
    t0 = time.time()
    only = set(sys.argv[1:])
    for name, fn in (("sweep", experiment_batch_sweep),
                     ("remat", experiment_remat),
                     ("split", experiment_updater_outside)):
        if only and name not in only:
            continue
        try:
            fn(results)
        except Exception as e:  # noqa: BLE001 - record and continue (OOMs)
            results[f"{name}_error"] = f"{type(e).__name__}: {str(e)[:300]}"
            print(f"{name} FAILED: {type(e).__name__}", flush=True)
    results["wall_s_total"] = time.time() - t0
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "R4_PERF_EXPERIMENTS.json")
    with open(out, "w") as fh:
        json.dump(results, fh, indent=1)
    print("wrote", out, flush=True)


if __name__ == "__main__":
    main()
