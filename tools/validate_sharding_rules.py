#!/usr/bin/env python
"""Sharding-rule file validator: schema check + dry-run lint.

Validates a ``--sharding-rules`` file (the ``parallel.sharding``
``load_sharding_rules`` schema) the same way
``tools/validate_fault_plan.py`` validates fault plans: importable
(``validate_file``/``validate_rules`` return a list of problems,
empty = valid) and runnable
(``python tools/validate_sharding_rules.py RULES.json [...]``).

Two passes:

1. **schema** — the file must build through ``load_sharding_rules``
   (non-list rules, uncompilable regexes, bad spec arrays all surface
   here with the offending rule index);
2. **dry run** — ``lint_partition_rules`` matches the rules against a
   sample model's param tree and flags rules that parse but cannot
   behave as written: params NO rule matches (``match_partition_rules``
   would raise at placement time), dead rules (match nothing in the
   sample), and shadowed rules (every leaf they match is claimed by an
   earlier rule — first match wins). Nothing is placed on devices.

The default sample model is a tiny ``TransformerLM`` (the vertex-name
universe the shipped Megatron rule set targets: ``embed/W``, ``Wqkv``,
``ff1``/``ff2``, ``out/W``); ``--sample-model PATH`` lints against a
serialized model of your own instead. ``--mesh data=4,model=2``
additionally checks every spec axis against the mesh's axis names — a
typo'd axis would raise at placement, not here, without it.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from deeplearning4j_tpu.parallel.sharding import (  # noqa: E402
    lint_partition_rules, load_sharding_rules, normalize_rules)


def _sample_params(sample_model: Optional[str] = None):
    """Param pytree to lint against: a saved model's, or the tiny LM."""
    if sample_model is not None:
        from deeplearning4j_tpu.util.model_guesser import load_model_guess
        return load_model_guess(sample_model).params
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.models import TransformerLM
    net = ComputationGraph(TransformerLM(
        vocab_size=32, max_length=8, n_layers=1, d_model=8, n_heads=2,
        d_ff=16, seed=0).conf()).init()
    return net.params


def validate_rules(spec, sample_params=None,
                   mesh_axes: Optional[dict] = None) -> List[str]:
    """Return a list of problems (empty = valid). ``spec`` is a parsed
    dict, a file object, or a path. ``sample_params`` is the param
    pytree the dry run matches against (default: the tiny LM's)."""
    try:
        rules = load_sharding_rules(spec)
        normalize_rules(rules)
    except (ValueError, KeyError, TypeError, OSError,
            json.JSONDecodeError) as e:
        return [f"schema: {e}"]
    if not rules:
        return ["schema: no rules defined"]
    errors: List[str] = []
    if mesh_axes is not None:
        for i, (pattern, p) in enumerate(rules):
            for dim in p:  # a dim entry is an axis name, a tuple of
                # axis names, or None (replicated)
                for axis in (dim if isinstance(dim, tuple) else (dim,)):
                    if axis is not None and axis not in mesh_axes:
                        errors.append(
                            f"schema: rule[{i}] ({pattern!r}) names mesh "
                            f"axis {axis!r} but the mesh has "
                            f"{sorted(mesh_axes)} — placement would raise")
    if sample_params is None:
        sample_params = _sample_params()
    errors += [f"lint: {w}"
               for w in lint_partition_rules(rules, sample_params)]
    return errors


def validate_file(path: str, sample_params=None,
                  mesh_axes: Optional[dict] = None) -> List[str]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            spec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable rules file: {e}"]
    return validate_rules(spec, sample_params, mesh_axes)


def main(argv: List[str]) -> int:
    sample_model = None
    mesh_axes = None
    if "--sample-model" in argv:
        i = argv.index("--sample-model")
        try:
            sample_model = argv[i + 1]
        except IndexError:
            print("--sample-model needs a model path")
            return 2
        argv = argv[:i] + argv[i + 2:]
    if "--mesh" in argv:
        i = argv.index("--mesh")
        from deeplearning4j_tpu.parallel.mesh import parse_mesh_axes
        try:
            mesh_axes = parse_mesh_axes(argv[i + 1])
        except (IndexError, ValueError) as e:
            print(f"--mesh: {e}")
            return 2
        argv = argv[:i] + argv[i + 2:]
    if not argv:
        print("usage: validate_sharding_rules.py [--sample-model PATH] "
              "[--mesh data=4,model=2] RULES.json [RULES.json ...]")
        return 2
    sample_params = _sample_params(sample_model)
    rc = 0
    for path in argv:
        errors = validate_file(path, sample_params, mesh_axes)
        if errors:
            rc = 1
            print(f"FAIL {path}")
            for e in errors:
                print(f"  - {e}")
        else:
            n = len(load_sharding_rules(path))
            print(f"OK   {path}: {n} rule(s)")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
