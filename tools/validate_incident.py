#!/usr/bin/env python
"""Incident-bundle validator: schema check + bounds lint.

Validates an ``incident_<generation>_<seq>/`` directory written by
``observe.incident.IncidentRecorder`` the same way
``tools/validate_fault_plan.py`` validates fault plans: importable
(``validate_bundle`` returns a list of problems, empty = valid) and
runnable (``python tools/validate_incident.py BUNDLE_DIR [...]``).

Two passes:

1. **schema** — ``incident.json`` must exist, parse, and carry every
   required field with the right shape (schema version, decision action
   from the known set, victim/world/worker records, decision ladder,
   declared bounds and files);
2. **bounds lint** — the bundle must honor its own declared bounds
   (span files ≤ ``max_spans`` span lines each, ``logs.jsonl`` ≤
   ``max_log_lines``, victim log tails ≤ ``max_log_bytes``) and every
   declared file must actually exist — a flight recorder that silently
   truncates or dangles references is lying to the operator.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from deeplearning4j_tpu.observe.incident import (  # noqa: E402
    DECISIONS,
    KIND,
    SCHEMA_VERSION,
)


def _is_int(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _check_manifest(m: Any) -> List[str]:
    errors: List[str] = []
    if not isinstance(m, dict):
        return ["incident.json: top level is not an object"]
    if m.get("schema") != SCHEMA_VERSION:
        errors.append(f"schema: expected version {SCHEMA_VERSION}, "
                      f"got {m.get('schema')!r}")
    if m.get("kind") != KIND:
        errors.append(f"schema: kind must be {KIND!r}, got {m.get('kind')!r}")
    for field, typ in (("job_id", str), ("generation", int), ("seq", int),
                      ("ts_ms", int)):
        v = m.get(field)
        if not isinstance(v, typ) or isinstance(v, bool):
            errors.append(f"schema: {field} missing or not {typ.__name__}")

    dec = m.get("decision")
    if not isinstance(dec, dict):
        errors.append("schema: decision missing")
    else:
        if dec.get("action") not in DECISIONS:
            errors.append(f"schema: decision.action {dec.get('action')!r} "
                          f"not in {DECISIONS}")
        if not isinstance(dec.get("reason"), str) or not dec.get("reason"):
            errors.append("schema: decision.reason missing/empty")
        if not isinstance(dec.get("ladder"), list) or not dec.get("ladder"):
            errors.append("schema: decision.ladder missing/empty")
        else:
            for i, rung in enumerate(dec["ladder"]):
                if not isinstance(rung, dict) or "rung" not in rung \
                        or "taken" not in rung:
                    errors.append(f"schema: ladder[{i}] needs rung/taken")

    victim = m.get("victim")
    if not isinstance(victim, dict) or not _is_int(victim.get("slot")):
        errors.append("schema: victim.slot missing or not an int")

    world = m.get("world")
    if not isinstance(world, dict) \
            or not isinstance(world.get("before"), list) \
            or not isinstance(world.get("after"), list):
        errors.append("schema: world.before/world.after missing")

    if not isinstance(m.get("dead_slots"), list):
        errors.append("schema: dead_slots missing")

    workers = m.get("workers")
    if not isinstance(workers, list) or not workers:
        errors.append("schema: workers missing/empty")
    else:
        for i, w in enumerate(workers):
            if not isinstance(w, dict) or not _is_int(w.get("slot")):
                errors.append(f"schema: workers[{i}].slot missing")
            elif "last_step" not in w:
                errors.append(f"schema: workers[{i}].last_step missing "
                              "(null is fine; absence is not)")

    ckpt = m.get("checkpoint")
    if not isinstance(ckpt, dict) or "restore_step" not in ckpt:
        errors.append("schema: checkpoint.restore_step missing")

    bounds = m.get("bounds")
    if not isinstance(bounds, dict) or not all(
            _is_int(bounds.get(k)) and bounds.get(k) > 0
            for k in ("max_spans", "max_log_lines", "max_log_bytes")):
        errors.append("schema: bounds.max_spans/max_log_lines/"
                      "max_log_bytes missing or non-positive")

    if not isinstance(m.get("files"), dict):
        errors.append("schema: files missing")
    return errors


def _count_lines(path: str, *, span_lines: bool = False) -> int:
    n = 0
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            if span_lines and '"meta"' in line:
                continue  # the anchor/meta header is not a span
            n += 1
    return n


def validate_bundle(path: str) -> List[str]:
    """Return a list of problems (empty = valid) for one bundle dir."""
    manifest_path = os.path.join(path, "incident.json")
    try:
        with open(manifest_path, encoding="utf-8") as fh:
            m = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{manifest_path}: unreadable manifest: {e}"]
    errors = _check_manifest(m)
    if errors:
        return errors

    bounds = m["bounds"]
    files = m["files"]

    metrics = files.get("metrics")
    if metrics is not None and not os.path.exists(
            os.path.join(path, metrics)):
        errors.append(f"files: declared metrics file {metrics!r} missing")

    spans_dir = files.get("spans_dir")
    if spans_dir is not None:
        full = os.path.join(path, spans_dir)
        if not os.path.isdir(full):
            errors.append(f"files: declared spans dir {spans_dir!r} missing")
        else:
            for name in sorted(os.listdir(full)):
                n = _count_lines(os.path.join(full, name), span_lines=True)
                if n > bounds["max_spans"]:
                    errors.append(
                        f"bounds: {spans_dir}/{name} has {n} spans "
                        f"> max_spans={bounds['max_spans']}")

    logs = files.get("logs")
    if logs is not None:
        full = os.path.join(path, logs)
        if not os.path.exists(full):
            errors.append(f"files: declared log file {logs!r} missing")
        else:
            n = _count_lines(full)
            if n > bounds["max_log_lines"]:
                errors.append(f"bounds: {logs} has {n} lines "
                              f"> max_log_lines={bounds['max_log_lines']}")

    tails = files.get("log_tail_dir")
    if tails is not None:
        full = os.path.join(path, tails)
        if not os.path.isdir(full):
            errors.append(f"files: declared log-tail dir {tails!r} missing")
        else:
            for name in sorted(os.listdir(full)):
                size = os.path.getsize(os.path.join(full, name))
                if size > bounds["max_log_bytes"]:
                    errors.append(
                        f"bounds: {tails}/{name} is {size} bytes "
                        f"> max_log_bytes={bounds['max_log_bytes']}")
    return errors


def main(argv: List[str]) -> int:
    if not argv:
        print("usage: validate_incident.py BUNDLE_DIR [BUNDLE_DIR ...]")
        return 2
    rc = 0
    for path in argv:
        errors = validate_bundle(path)
        if errors:
            rc = 1
            print(f"FAIL {path}")
            for e in errors:
                print(f"  - {e}")
        else:
            with open(os.path.join(path, "incident.json"),
                      encoding="utf-8") as fh:
                m = json.load(fh)
            print(f"OK   {path}: generation {m['generation']} "
                  f"{m['decision']['action']} "
                  f"(victim slot {m['victim']['slot']})")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
