#!/usr/bin/env python
"""Chrome trace-event schema validator.

Checks a trace JSON (the ``observe.export`` output, or any Trace Event
Format file) against the rules ``chrome://tracing`` / Perfetto actually
enforce, so a trace that passes here loads there:

- top level: an object with a ``traceEvents`` list (the "JSON Object
  Format"), or a bare event list (the "JSON Array Format");
- every event: a dict with a string ``ph`` from the known phase set and
  integer-like ``pid``/``tid``;
- timed phases (everything except metadata ``M``): a finite, non-negative
  numeric ``ts`` in microseconds;
- complete events (``X``): a finite, non-negative ``dur``;
- duration events: ``B``/``E`` balanced per (pid, tid), never negative
  nesting;
- flow events (``s``/``t``/``f``): an ``id``; every flow has a start;
- ``args``, when present, a JSON object.

Used three ways: ``python tools/validate_trace.py trace.json [...]`` by
humans/CI, ``validate_file``/``validate_events`` by the tests, and by
``examples/25_tracing_and_profiling.py`` on its own output.
"""

from __future__ import annotations

import json
import math
import sys
from typing import Any, Dict, List

# the trace-event format's phase table
KNOWN_PHASES = {"B", "E", "X", "I", "i", "C", "b", "n", "e", "s", "t", "f",
                "P", "N", "O", "D", "M", "S", "T", "p", "F", "v", "V", "R",
                "c", "a"}


def _is_int_like(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _is_finite_number(v: Any) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and math.isfinite(v))


def validate_events(obj: Any) -> List[str]:
    """Return a list of schema violations (empty = valid)."""
    errors: List[str] = []
    if isinstance(obj, dict):
        events = obj.get("traceEvents")
        if not isinstance(events, list):
            return ["top-level object has no 'traceEvents' list"]
    elif isinstance(obj, list):
        events = obj
    else:
        return [f"top level must be an object or list, got {type(obj).__name__}"]

    open_durations: Dict[tuple, int] = {}
    flow_starts = set()
    flow_ends = []
    for i, ev in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str) or ph not in KNOWN_PHASES:
            errors.append(f"{where}: bad phase {ph!r}")
            continue
        where = f"event[{i}] ({ph} {ev.get('name', '?')!r})"
        for key in ("pid", "tid"):
            if not _is_int_like(ev.get(key)):
                errors.append(f"{where}: missing/non-integer {key!r}")
        if ph != "M":
            ts = ev.get("ts")
            if not _is_finite_number(ts) or ts < 0:
                errors.append(f"{where}: bad ts {ts!r}")
        if ph in ("X", "B", "E", "I", "i", "M", "C", "s", "t", "f") \
                and not isinstance(ev.get("name"), str):
            errors.append(f"{where}: missing name")
        if ph == "X":
            dur = ev.get("dur")
            if not _is_finite_number(dur) or dur < 0:
                errors.append(f"{where}: bad dur {dur!r}")
        if ph in ("B", "E"):
            key = (ev.get("pid"), ev.get("tid"))
            depth = open_durations.get(key, 0) + (1 if ph == "B" else -1)
            if depth < 0:
                errors.append(f"{where}: E without matching B on {key}")
                depth = 0
            open_durations[key] = depth
        if ph in ("s", "t", "f"):
            if "id" not in ev:
                errors.append(f"{where}: flow event without id")
            elif ph == "s":
                flow_starts.add(ev["id"])
            else:
                flow_ends.append((where, ev["id"]))
        if "args" in ev:
            if not isinstance(ev["args"], dict):
                errors.append(f"{where}: args is not an object")
            else:
                for k, v in ev["args"].items():
                    # Python's json tolerates NaN/Infinity; strict JSON
                    # (and chrome://tracing) does not
                    if isinstance(v, float) and not math.isfinite(v):
                        errors.append(
                            f"{where}: non-finite args[{k!r}] "
                            f"(not strict JSON)")
    for key, depth in open_durations.items():
        if depth:
            errors.append(f"{depth} unclosed B event(s) on pid/tid {key}")
    for where, fid in flow_ends:
        if fid not in flow_starts:
            errors.append(f"{where}: flow end id {fid!r} has no start")
    return errors


def validate_file(path: str) -> List[str]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            obj = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable trace: {e}"]
    return validate_events(obj)


def main(argv: List[str]) -> int:
    if not argv:
        print("usage: validate_trace.py TRACE.json [TRACE.json ...]")
        return 2
    rc = 0
    for path in argv:
        errors = validate_file(path)
        if errors:
            rc = 1
            print(f"FAIL {path}")
            for e in errors:
                print(f"  - {e}")
        else:
            with open(path, "r", encoding="utf-8") as fh:
                obj = json.load(fh)
            n = len(obj["traceEvents"] if isinstance(obj, dict) else obj)
            print(f"OK   {path}: {n} events")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
