#!/usr/bin/env python
"""Pipeline-config validator: schema check + dry-run lint.

Validates a continuous-training pipeline config (the
``deeplearning4j_tpu.pipeline.PipelineConfig`` schema the ``pipeline``
CLI subcommand consumes) the same way ``tools/validate_alert_rules.py``
and ``tools/validate_fault_plan.py`` validate their files: importable
(``validate_file``/``validate_config`` return a list of problems, empty
= valid) and runnable
(``python tools/validate_pipeline_config.py CONFIG.json [...]``).

Two passes:

1. **schema** — the file must build through ``PipelineConfig.parse``
   (unknown sections/keys, bad types, malformed canary schedules and
   gate metrics all surface here with the offending field);
2. **dry run** — ``PipelineConfig.lint`` flags configs that parse but
   cannot behave as written: a shadow-divergence budget with shadow
   sampling off, a schedule that holds nothing, a strict gate with no
   earlier watchdog signal.  Nothing is executed.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from deeplearning4j_tpu.pipeline import PipelineConfig  # noqa: E402


def validate_config(spec) -> List[str]:
    """Return a list of problems (empty = valid). ``spec`` is a parsed
    dict, a JSON string, or a path."""
    try:
        cfg = PipelineConfig.parse(spec)
    except (ValueError, KeyError, TypeError, OSError,
            json.JSONDecodeError) as e:
        return [f"schema: {e}"]
    return [f"lint: {p}" for p in cfg.lint()]


def validate_file(path: str) -> List[str]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            spec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable config file: {e}"]
    return validate_config(spec)


def main(argv: List[str]) -> int:
    if not argv:
        print("usage: validate_pipeline_config.py CONFIG.json "
              "[CONFIG.json ...]")
        return 2
    rc = 0
    for path in argv:
        errors = validate_file(path)
        if errors:
            rc = 1
            print(f"FAIL {path}")
            for e in errors:
                print(f"  - {e}")
        else:
            cfg = PipelineConfig.parse(path)
            print(f"OK   {path}: pipeline {cfg.name!r}, "
                  f"{len(cfg.canary['schedule'])} canary step(s), "
                  f"gate metric {cfg.gate['metric']}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
