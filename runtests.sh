#!/usr/bin/env bash
# Test runner (role of the reference's runtests.sh): full suite on the
# virtual 8-device CPU mesh, then the benchmark if a device is available.
set -euo pipefail
cd "$(dirname "$0")"
python -m pytest tests/ -q "$@"  # incl. the examples smoke tier (DL4J_TPU_SKIP_EXAMPLES=1 to skip)
