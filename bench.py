"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: **ResNet50 ImageNet-shape training throughput (images/sec) on one
chip** — the tracked metric in BASELINE.json ("zoo ResNet50 images/sec/chip").
Training step = full forward/backward/update on 224x224x3 synthetic batches
via the zoo ResNet50 graph, mixed precision (f32 master weights, bfloat16
compute — the TPU-idiomatic configuration; the reference has no published
number to compare against, BASELINE.md "published: {}").

``vs_baseline`` is the ratio against the first value this framework recorded
on the target hardware (below), or 1.0 until one exists.
"""

import json
import time

import numpy as np

# First recorded value on the round-1 bench hardware (TPU v5e lite, batch 256,
# mixed bf16/f32; matches BASELINE.md). Update when the framework improves.
BASELINE_IMAGES_PER_SEC = 2035.4


def main():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.models import ResNet50

    batch = 256
    steps = 10
    warmup = 3

    conf = ResNet50(num_labels=1000, seed=1).conf()
    conf.global_conf.compute_dtype = "bfloat16"
    net = ComputationGraph(conf)
    net.init()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3)).astype(np.float32))
    y = jnp.asarray(
        np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, size=batch)])
    ds = DataSet(x, y)  # resident on device for the whole run

    for _ in range(warmup):
        net._fit_batch(ds)
    float(net.score_)  # materialize: a data read is the only reliable sync
    # through tunneled backends where block_until_ready can no-op

    t0 = time.perf_counter()
    for _ in range(steps):
        net._fit_batch(ds)
    float(net.score_)  # drain the whole queue before stopping the clock
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    vs = ips / BASELINE_IMAGES_PER_SEC if BASELINE_IMAGES_PER_SEC else 1.0
    record = {
        "metric": "resnet50_train_throughput_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 4),
    }
    # Device-time companion numbers: wall throughput through the tunneled
    # link drifts by session (2095-2440 img/s observed for the identical
    # program) while profiled on-device step time is bit-stable; report
    # both so the stable number rides along (tools/tpu_perf_session.py
    # methodology). Omitted silently where the profiler is unavailable.
    try:
        import os
        import sys
        os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                              "python")
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        from tpu_perf_session import profile_step
        times = profile_step(net, ds, "/tmp/bench_prof")
        dev = sum(t for t, _ in times.values()) / 4
        if dev > 0:  # CPU hosts have no TPU plane -> omit, don't report 0
            record["device_ms_per_step"] = round(dev * 1e3, 2)
            record["device_time_images_per_sec"] = round(batch / dev, 1)
            record["dispatch_overhead_ms_per_step"] = round(
                dt / steps * 1e3 - dev * 1e3, 2)
    except Exception:
        pass
    print(json.dumps(record))


if __name__ == "__main__":
    # one retry IN A FRESH PROCESS: the tunneled TPU link occasionally
    # drops a request mid-compile, and jax's cached PJRT client stays
    # broken for the life of the process — only a re-exec gets a new
    # connection. The env flag stops a second failure from looping.
    import os
    import sys
    try:
        main()
    except Exception as e:  # noqa: BLE001 - any transient backend error
        import traceback
        traceback.print_exc()
        if os.environ.get("DL4J_TPU_BENCH_RETRY") == "1":
            raise
        print(f"bench attempt 1 failed ({type(e).__name__}); "
              f"retrying in a fresh process", file=sys.stderr, flush=True)
        env = dict(os.environ, DL4J_TPU_BENCH_RETRY="1")
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)], env)
