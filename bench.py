"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: **ResNet50 ImageNet-shape training throughput (images/sec) on one
chip** — the tracked metric in BASELINE.json ("zoo ResNet50 images/sec/chip").
Training step = full forward/backward/update on 224x224x3 synthetic batches
via the zoo ResNet50 graph, mixed precision (f32 master weights, bfloat16
compute — the TPU-idiomatic configuration; the reference has no published
number to compare against, BASELINE.md "published: {}").

``vs_baseline`` is the ratio against the first value this framework recorded
on the target hardware (below), or 1.0 until one exists.

The single JSON line also carries a ``suite`` object covering the other four
BASELINE.json configs (round-5: per-round regression coverage of the whole
headline suite, VERDICT r4 Weak #1), each with wall AND profiled device time
(the only session-stable number through the tunneled chip —
``tools/tpu_perf_session.py`` methodology):

- ``lenet_mnist``          — configs[0], zoo LeNet, B=512 f32
- ``graveslstm_char_rnn``  — configs[3], 2x512 GravesLSTM, B=64 T=128 bf16
                             (re-measured with device time; the round-1
                             725k char/s wall number was tunnel-distorted)
- ``bert_base_import``     — configs[2], genuine Keras BERT-base through the
                             import path when the fixture exists (falls back
                             to the zoo TransformerEncoder at identical
                             shapes, recorded as ``path: zoo_fallback``;
                             r4 measured the import tax at 0.92x so the two
                             track each other)
- ``vgg16``                — configs[4]'s single-chip half, zoo VGG16 B=64
                             bf16 (the ICI-scaling half is exercised by
                             ``__graft_entry__.dryrun_multichip``)

Each suite entry is individually guarded: a failure records ``error`` for
that entry and never blocks the headline line.

``--trace DIR`` (or ``DL4J_TPU_BENCH_TRACE_DIR``) records each config —
headline included — with the observe tracer and writes one Chrome-trace
JSON per config into DIR (``<name>.trace.json``): per-step spans with the
XLA compile spans attributed to the steps that paid for them.

``--pod-scaling [OUT.json]`` runs the pod-scale elastic series instead of
the headline (MULTICHIP_r06: step time vs world size on the mesh, and
the per-step checkpoint save stall sync vs async — the async overlapped
path must beat the blocking one). ``--save-mode sync|async`` restricts
the save-stall half to one mode.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# First recorded value on the round-1 bench hardware (TPU v5e lite, batch 256,
# mixed bf16/f32; matches BASELINE.md). Update when the framework improves.
BASELINE_IMAGES_PER_SEC = 2035.4

BERT_H5 = "/tmp/bert_base_import.h5"


def _trace_dir():
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace")
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return os.environ.get("DL4J_TPU_BENCH_TRACE_DIR") or None


def _with_trace(name, fn):
    """Run one bench config, optionally recording it as its own trace."""
    out_dir = _trace_dir()
    if not out_dir:
        return fn()
    from deeplearning4j_tpu.observe import (Tracer, disable_tracing,
                                            enable_tracing)
    os.makedirs(out_dir, exist_ok=True)
    tracer = enable_tracing(Tracer())  # fresh recorder per config
    try:
        with tracer.span(f"bench:{name}", category="bench"):
            return fn()
    finally:
        disable_tracing()
        path = os.path.join(out_dir, f"{name}.trace.json")
        print(f"bench trace: {path} ({tracer.flush(path)} spans)",
              file=sys.stderr)


def _profiled_device_ms(net, ds):
    """Profiled on-device ms/step, or None where no TPU plane exists."""
    try:
        os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                              "python")
        tools = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        from tpu_perf_session import profile_step
        times = profile_step(net, ds, "/tmp/bench_prof")
        dev = sum(t for t, _ in times.values()) / 4
        return dev * 1e3 if dev > 0 else None
    except Exception:
        return None


def _measure(net, ds, items_per_batch, steps=8, warmup=3):
    """Wall + device per-step timings for one config; items/s from both."""
    from deeplearning4j_tpu.observe import trace as _trace
    with _trace.span("warmup", attrs={"steps": warmup}):
        for _ in range(warmup):
            net._fit_batch(ds)
        float(net.score_)  # materialize: a data read is the only reliable sync
    t0 = time.perf_counter()
    with _trace.span("measure", attrs={"steps": steps}):
        for _ in range(steps):
            net._fit_batch(ds)
        float(net.score_)  # drain the whole queue before stopping the clock
    wall_ms = (time.perf_counter() - t0) / steps * 1e3
    rec = {"wall_ms_per_step": round(wall_ms, 2),
           "wall_items_per_sec": round(items_per_batch / wall_ms * 1e3, 1)}
    dev_ms = _profiled_device_ms(net, ds)
    if dev_ms is not None:
        rec["device_ms_per_step"] = round(dev_ms, 2)
        rec["device_items_per_sec"] = round(items_per_batch / dev_ms * 1e3, 1)
    return rec


def _resnet50_headline():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.models import ResNet50

    batch = 256
    steps = 10
    warmup = 3

    conf = ResNet50(num_labels=1000, seed=1).conf()
    conf.global_conf.compute_dtype = "bfloat16"
    net = ComputationGraph(conf)
    net.init()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3)).astype(np.float32))
    y = jnp.asarray(
        np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, size=batch)])
    ds = DataSet(x, y)  # resident on device for the whole run

    from deeplearning4j_tpu.observe import trace as _trace
    with _trace.span("warmup", attrs={"steps": warmup}):
        for _ in range(warmup):
            net._fit_batch(ds)
        float(net.score_)

    t0 = time.perf_counter()
    with _trace.span("measure", attrs={"steps": steps}):
        for _ in range(steps):
            net._fit_batch(ds)
        float(net.score_)
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    vs = ips / BASELINE_IMAGES_PER_SEC if BASELINE_IMAGES_PER_SEC else 1.0
    record = {
        "metric": "resnet50_train_throughput_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 4),
    }
    dev_ms = _profiled_device_ms(net, ds)
    if dev_ms is not None:
        record["device_ms_per_step"] = round(dev_ms, 2)
        record["device_time_images_per_sec"] = round(batch / dev_ms * 1e3, 1)
        record["dispatch_overhead_ms_per_step"] = round(
            dt / steps * 1e3 - dev_ms, 2)
    return record


def _bench_lenet():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.zoo.models import LeNet

    batch = 512
    net = MultiLayerNetwork(LeNet(num_labels=10, seed=1).conf())
    net.init()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(batch, 28, 28, 1)).astype(np.float32))
    y = jnp.asarray(
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)])
    rec = _measure(net, DataSet(x, y), batch)
    rec["config"] = "zoo LeNet, B=512, f32"
    return rec


def _bench_graveslstm():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import GravesLSTMLayer, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    batch, t, vocab, width = 64, 128, 77, 512
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
            .list()
            .layer(GravesLSTMLayer(n_in=vocab, n_out=width,
                                   activation="tanh"))
            .layer(GravesLSTMLayer(n_in=width, n_out=width,
                                   activation="tanh"))
            .layer(RnnOutputLayer(n_in=width, n_out=vocab,
                                  activation="softmax",
                                  loss="negativeloglikelihood"))
            .set_input_type(InputType.recurrent(vocab, t))
            .build())
    conf.global_conf.compute_dtype = "bfloat16"
    net = MultiLayerNetwork(conf)
    net.init()
    rng = np.random.default_rng(2)
    ids = rng.integers(0, vocab, size=(batch, t))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        np.roll(ids, -1, axis=1)])
    rec = _measure(net, DataSet(x, y), batch * t)  # items = characters
    rec["config"] = "2x512 GravesLSTM char-RNN, B=64 T=128 V=77, bf16"
    return rec


def _bench_bert_import():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet

    batch, t = 32, 128
    rng = np.random.default_rng(3)

    if not os.path.exists(BERT_H5):
        # the make stage needs keras, which must not share the TPU process.
        # A timed-out/killed make must not leave a truncated h5 that
        # poisons every later run: build to a temp name, rename on success.
        tmp_h5 = BERT_H5 + ".part"
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       DL4J_TPU_BERT_H5=tmp_h5)
            subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tools", "r4_bert_import_bench.py"), "make"],
                env=env, timeout=900, check=True,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            os.replace(tmp_h5, BERT_H5)
        except Exception:
            if os.path.exists(tmp_h5):
                os.remove(tmp_h5)

    net = None
    import_error = None
    if os.path.exists(BERT_H5):
        try:
            from deeplearning4j_tpu.datasets.dataset import MultiDataSet
            from deeplearning4j_tpu.modelimport.keras.importer import (
                KerasModelImport)
            net = KerasModelImport.import_keras_model_and_weights(BERT_H5)
        except Exception as e:  # noqa: BLE001 - record, fall back to zoo
            # the fixture is written atomically, so an import failure is
            # more likely an importer/backend issue than corruption — keep
            # the file (rebuilding costs ~15 min) and surface the reason
            import_error = f"{type(e).__name__}: {e}"
            net = None
    if net is not None:
        net.conf.global_conf.compute_dtype = "bfloat16"
        tok = rng.integers(0, 30522, size=(batch, t)).astype(np.float32)
        pos = np.tile(np.arange(t, dtype=np.float32), (batch, 1))
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=batch)]
        ds = MultiDataSet([jnp.asarray(tok), jnp.asarray(pos)],
                          [jnp.asarray(y)])
        path = "import"
    else:
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.zoo.models import TransformerEncoder

        conf = TransformerEncoder(num_labels=2, seed=1).conf()
        conf.global_conf.compute_dtype = "bfloat16"
        net = ComputationGraph(conf)
        net.init()
        tok = rng.integers(0, 30522, size=(batch, t)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=batch)]
        ds = DataSet(jnp.asarray(tok), jnp.asarray(y))
        path = "zoo_fallback"

    rec = _measure(net, ds, batch * t)  # items = tokens
    rec["path"] = path
    if import_error is not None:
        rec["import_error"] = import_error
    rec["config"] = "BERT-base shape 12L/768/12H/3072, B=32 T=128, bf16"
    return rec


def _bench_vgg16():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.zoo.models import VGG16

    batch = 64
    conf = VGG16(num_labels=1000, seed=1).conf()
    conf.global_conf.compute_dtype = "bfloat16"
    net = MultiLayerNetwork(conf)
    net.init()
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3)).astype(np.float32))
    y = jnp.asarray(
        np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, size=batch)])
    rec = _measure(net, DataSet(x, y), batch, steps=6)
    rec["config"] = "zoo VGG16, B=64, 224x224x3, bf16, single chip"
    return rec


SUITE = {
    "lenet_mnist": _bench_lenet,
    "graveslstm_char_rnn": _bench_graveslstm,
    "bert_base_import": _bench_bert_import,
    "vgg16": _bench_vgg16,
}


# -- pod-scale elastic series (MULTICHIP_r06) --------------------------------

def _scaling_net(seed=1, width=512):
    """A model big enough that its checkpoint write is measurable (~1M
    params ≈ 4 MB of f32 + updater state) but cheap to step on CPU."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=width, activation="relu"))
            .layer(DenseLayer(n_out=width, activation="relu"))
            .layer(OutputLayer(n_out=10))
            .set_input_type(InputType.feed_forward(width)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(seed)
    batch = 128
    x = jnp.asarray(rng.normal(size=(batch, width)).astype(np.float32))
    y = jnp.asarray(
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)])
    return net, DataSet(x, y), batch


def _pod_scaling_worlds(steps=8, warmup=3):
    """Step time vs data-parallel world size on the local mesh — the
    scaling half of the curve."""
    import jax

    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    from deeplearning4j_tpu.parallel import (DistributedMultiLayerNetwork,
                                             SharedTrainingMaster)
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    worlds = []
    for w in (1, 2, 4, 8):
        if w > len(devices):
            break
        net, ds, batch = _scaling_net()
        mesh = make_mesh({"data": w}, devices=devices[:w])
        master = SharedTrainingMaster(batch_size_per_worker=batch // w,
                                      threshold=1e-3, mesh=mesh)
        front = DistributedMultiLayerNetwork(net, master)
        x = np.asarray(ds.features)
        y = np.asarray(ds.labels)
        it = lambda: ListDataSetIterator(DataSet(x, y), batch)  # noqa: E731
        front.fit(it(), epochs=warmup)  # compile + warm
        t0 = time.perf_counter()
        front.fit(it(), epochs=steps)
        wall_ms = (time.perf_counter() - t0) / steps * 1e3
        worlds.append({"world": w,
                       "wall_ms_per_step": round(wall_ms, 2),
                       "items_per_sec": round(batch / wall_ms * 1e3, 1)})
    return worlds


def _pod_save_stall(mode, tmp_dir, steps=6):
    """Per-step checkpoint stall for one save mode: the wall time the
    TRAINING thread loses to each per-step checkpoint. sync = full
    orbax save + finalize on the step path; async = snapshot + bounded
    submit (AsyncCheckpointSession), commit runs behind the next
    steps."""
    import shutil

    from deeplearning4j_tpu.parallel.elastic import (AsyncCheckpointSession,
                                                     ElasticWorkerContext)
    from deeplearning4j_tpu.util.orbax_checkpoint import (
        OrbaxCheckpointManager)

    net, ds, batch = _scaling_net()
    d = os.path.join(tmp_dir, f"save_{mode}")
    shutil.rmtree(d, ignore_errors=True)
    for _ in range(3):
        net._fit_batch(ds)
    float(net.score_)
    stalls = []
    mgr = OrbaxCheckpointManager(d, max_to_keep=2)
    session = None
    committed = 0
    if mode == "async":
        ctx = ElasticWorkerContext(
            coordinator="", num_processes=1, process_id=0, slot=0,
            generation=1, token="bench", ckpt_dir=d,
            heartbeat_path=os.path.join(d, "hb"), restore_step=None)
        session = AsyncCheckpointSession(ctx, manager=mgr,
                                         max_in_flight=2)
    t_train0 = time.perf_counter()
    for step in range(1, steps + 1):
        net._fit_batch(ds)
        float(net.score_)
        t0 = time.perf_counter()
        if session is not None:
            session.submit(step, net)
        else:
            if mgr.save(step, net, overwrite_existing=True):
                committed += 1
            mgr.wait_until_finished()
        stalls.append(time.perf_counter() - t0)
    total_wall = time.perf_counter() - t_train0
    if session is not None:
        flushed = session.close(timeout=300)
        committed = len(session.committed)
    else:
        flushed = True
    # a timed-out flush means the saver thread may still be inside a
    # manager call — do NOT close the manager under it (same rule as
    # run_elastic_worker); process exit reclaims it, and the record
    # reports flushed=false
    if flushed:
        mgr.close()
    return {"mode": mode,
            "save_stall_ms_per_step": round(
                sum(stalls) / len(stalls) * 1e3, 2),
            "save_stall_ms_max": round(max(stalls) * 1e3, 2),
            "wall_ms_per_step_with_saves": round(
                total_wall / steps * 1e3, 2),
            "steps": steps, "flushed": flushed,
            "committed_steps": committed}


def _pod_scaling_main(out_path, save_mode):
    import tempfile

    import jax
    record = {
        "metric": "pod_scale_elastic",
        "series": "MULTICHIP_r06",
        "config": "3-layer 512-wide MLP (~790k params, Adam), B=128 f32, "
                  "per-step orbax checkpoint rotation",
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "note": "worlds = step time vs data-axis size on the local "
                "device mesh (on the virtual CPU mesh collective overhead "
                "dominates at this model size, so the curve RISES — the "
                "series exists to track the shape run-over-run and on "
                "real ICI); save = per-step checkpoint stall on the "
                "training thread, sync vs async commit path",
        "worlds": _pod_scaling_worlds(),
        "save": {},
    }
    modes = ("sync", "async") if save_mode is None else (save_mode,)
    with tempfile.TemporaryDirectory(prefix="pod_bench_") as td:
        for mode in modes:
            record["save"][mode] = _pod_save_stall(mode, td)
    if {"sync", "async"} <= set(record["save"]):
        sync_ms = record["save"]["sync"]["save_stall_ms_per_step"]
        async_ms = record["save"]["async"]["save_stall_ms_per_step"]
        record["async_stall_vs_sync"] = round(async_ms / sync_ms, 4) \
            if sync_ms > 0 else None
    line = json.dumps(record, indent=2)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
        print(f"wrote {out_path}", file=sys.stderr)
    print(line)


def main():
    record = _with_trace("resnet50_headline", _resnet50_headline)
    if os.environ.get("DL4J_TPU_BENCH_HEADLINE_ONLY") != "1":
        suite = {}
        for name, fn in SUITE.items():
            try:
                suite[name] = _with_trace(name, fn)
            except Exception as e:  # noqa: BLE001 - isolate per-config failures
                suite[name] = {"error": f"{type(e).__name__}: {e}"}
        record["suite"] = suite
    print(json.dumps(record))


def _parse_pod_args():
    """(--pod-scaling out_path_or_None, --save-mode or None); returns
    (False, None, None) when --pod-scaling is absent. Unknown flags
    (--trace etc.) belong to the headline path and pass through."""
    if "--pod-scaling" not in sys.argv[1:]:
        return False, None, None
    import argparse
    ap = argparse.ArgumentParser("bench --pod-scaling", add_help=False)
    ap.add_argument("--pod-scaling", nargs="?", default=None,
                    metavar="OUT.json", dest="out")
    ap.add_argument("--save-mode", choices=("sync", "async"),
                    default=None, dest="mode")
    args, _unknown = ap.parse_known_args(sys.argv[1:])
    return True, args.out, args.mode


if __name__ == "__main__":
    pod, _pod_out, _pod_mode = _parse_pod_args()
    if pod:
        _pod_scaling_main(_pod_out, _pod_mode)
        raise SystemExit(0)
    # one retry IN A FRESH PROCESS: the tunneled TPU link occasionally
    # drops a request mid-compile, and jax's cached PJRT client stays
    # broken for the life of the process — only a re-exec gets a new
    # connection. The env flag stops a second failure from looping.
    try:
        main()
    except Exception as e:  # noqa: BLE001 - any transient backend error
        import traceback
        traceback.print_exc()
        if os.environ.get("DL4J_TPU_BENCH_RETRY") == "1":
            raise
        print(f"bench attempt 1 failed ({type(e).__name__}); "
              f"retrying in a fresh process", file=sys.stderr, flush=True)
        env = dict(os.environ, DL4J_TPU_BENCH_RETRY="1")
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)], env)
