"""Benchmark driver — prints ONE JSON line with the headline metric.

Round-1 headline: LeNet-MNIST training throughput (images/sec) on one chip,
measured with the PerformanceListener methodology
(`PerformanceListener.java:87-88` samples/sec). The reference publishes no
numbers (BASELINE.md), so ``vs_baseline`` is the ratio against the first
value this framework recorded (stored below), or 1.0 until one exists.
"""

import json
import time

import numpy as np

# First recorded value for this benchmark on the target hardware (updated as
# the framework improves; BASELINE.md "published" is empty in the reference).
BASELINE_IMAGES_PER_SEC = None  # set after first TPU run


def main():
    from __graft_entry__ import _lenet
    from deeplearning4j_tpu.datasets.dataset import DataSet

    import jax

    batch = 512
    steps = 30
    warmup = 5

    import jax.numpy as jnp

    net = _lenet()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)]
    ds = DataSet(jnp.asarray(x), jnp.asarray(y))  # place on device once

    for _ in range(warmup):
        net._fit_batch(ds)
    jax.block_until_ready(net.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        net._fit_batch(ds)
    jax.block_until_ready(net.params)
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    vs = ips / BASELINE_IMAGES_PER_SEC if BASELINE_IMAGES_PER_SEC else 1.0
    print(json.dumps({
        "metric": "lenet_mnist_train_throughput",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
