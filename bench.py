"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: **ResNet50 ImageNet-shape training throughput (images/sec) on one
chip** — the tracked metric in BASELINE.json ("zoo ResNet50 images/sec/chip").
Training step = full forward/backward/update on 224x224x3 synthetic batches
via the zoo ResNet50 graph, mixed precision (f32 master weights, bfloat16
compute — the TPU-idiomatic configuration; the reference has no published
number to compare against, BASELINE.md "published: {}").

``vs_baseline`` is the ratio against the first value this framework recorded
on the target hardware (below), or 1.0 until one exists.

The single JSON line also carries a ``suite`` object covering the other four
BASELINE.json configs (round-5: per-round regression coverage of the whole
headline suite, VERDICT r4 Weak #1), each with wall AND profiled device time
(the only session-stable number through the tunneled chip —
``tools/tpu_perf_session.py`` methodology):

- ``lenet_mnist``          — configs[0], zoo LeNet, B=512 f32
- ``graveslstm_char_rnn``  — configs[3], 2x512 GravesLSTM, B=64 T=128 bf16
                             (re-measured with device time; the round-1
                             725k char/s wall number was tunnel-distorted)
- ``bert_base_import``     — configs[2], genuine Keras BERT-base through the
                             import path when the fixture exists (falls back
                             to the zoo TransformerEncoder at identical
                             shapes, recorded as ``path: zoo_fallback``;
                             r4 measured the import tax at 0.92x so the two
                             track each other)
- ``vgg16``                — configs[4]'s single-chip half, zoo VGG16 B=64
                             bf16 (the ICI-scaling half is exercised by
                             ``__graft_entry__.dryrun_multichip``)

Each suite entry is individually guarded: a failure records ``error`` for
that entry and never blocks the headline line.

``--trace DIR`` (or ``DL4J_TPU_BENCH_TRACE_DIR``) records each config —
headline included — with the observe tracer and writes one Chrome-trace
JSON per config into DIR (``<name>.trace.json``): per-step spans with the
XLA compile spans attributed to the steps that paid for them.

``--pod-scaling [OUT.json]`` runs the pod-scale elastic series instead of
the headline (MULTICHIP_r06: step time vs world size on the mesh, and
the per-step checkpoint save stall sync vs async — the async overlapped
path must beat the blocking one). ``--save-mode sync|async`` restricts
the save-stall half to one mode.

``--train-pipeline [OUT.json]`` runs the training input-pipeline + fused
updater series (BENCH_TRAIN_r01): step time over an ETL-bound iterator
with prefetch off vs on (the ``fit(prefetch_depth=...)`` async wrap must
hide the host work), host_wait per step, transfer bytes, steady-state
compile counts, and the fused Pallas optimizer step vs the stock
per-param chain (timing + numerical agreement + kernel-launch count).
``--train-pipeline --check COMMITTED.json`` validates a committed record
(prefetch-on faster, zero steady-state compiles) plus LIVE oracles on
this machine: fused-vs-stock agreement ≤2e-5, exactly one pallas_call
per fusable tensor in the train-step jaxpr, none with the seam clear,
zero steady-state compiles — exits non-zero on any violation.

``--sharding-2d [OUT.json]`` runs the GSPMD 2-D parallelism series
(MULTICHIP_r07) on the virtual 8-device CPU mesh: DP-only vs DP×MP
(Megatron rule-based placement) step time plus per-config collective
counts from the compiled train-step and forward HLO. The record fails
outright if a 2-D forward contains an all-gather — the zero-all-gather
vocab path (row-sharded embedding take, column-sharded logits + LSE
loss) is the series' invariant. ``--sharding-2d --check COMMITTED.json``
validates a committed record and re-proves the invariant live, before
and after a train step (placement pinning regression).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# First recorded value on the round-1 bench hardware (TPU v5e lite, batch 256,
# mixed bf16/f32; matches BASELINE.md). Update when the framework improves.
BASELINE_IMAGES_PER_SEC = 2035.4

BERT_H5 = "/tmp/bert_base_import.h5"


def _trace_dir():
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace")
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return os.environ.get("DL4J_TPU_BENCH_TRACE_DIR") or None


def _with_trace(name, fn):
    """Run one bench config, optionally recording it as its own trace."""
    out_dir = _trace_dir()
    if not out_dir:
        return fn()
    from deeplearning4j_tpu.observe import (Tracer, disable_tracing,
                                            enable_tracing)
    os.makedirs(out_dir, exist_ok=True)
    tracer = enable_tracing(Tracer())  # fresh recorder per config
    try:
        with tracer.span(f"bench:{name}", category="bench"):
            return fn()
    finally:
        disable_tracing()
        path = os.path.join(out_dir, f"{name}.trace.json")
        print(f"bench trace: {path} ({tracer.flush(path)} spans)",
              file=sys.stderr)


def _profiled_device_ms(net, ds):
    """Profiled on-device ms/step, or None where no TPU plane exists."""
    try:
        os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                              "python")
        tools = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        from tpu_perf_session import profile_step
        times = profile_step(net, ds, "/tmp/bench_prof")
        dev = sum(t for t, _ in times.values()) / 4
        return dev * 1e3 if dev > 0 else None
    except Exception:
        return None


def _measure(net, ds, items_per_batch, steps=8, warmup=3):
    """Wall + device per-step timings for one config; items/s from both."""
    from deeplearning4j_tpu.observe import trace as _trace
    with _trace.span("warmup", attrs={"steps": warmup}):
        for _ in range(warmup):
            net._fit_batch(ds)
        float(net.score_)  # materialize: a data read is the only reliable sync
    t0 = time.perf_counter()
    with _trace.span("measure", attrs={"steps": steps}):
        for _ in range(steps):
            net._fit_batch(ds)
        float(net.score_)  # drain the whole queue before stopping the clock
    wall_ms = (time.perf_counter() - t0) / steps * 1e3
    rec = {"wall_ms_per_step": round(wall_ms, 2),
           "wall_items_per_sec": round(items_per_batch / wall_ms * 1e3, 1)}
    dev_ms = _profiled_device_ms(net, ds)
    if dev_ms is not None:
        rec["device_ms_per_step"] = round(dev_ms, 2)
        rec["device_items_per_sec"] = round(items_per_batch / dev_ms * 1e3, 1)
    return rec


def _resnet50_headline():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.models import ResNet50

    batch = 256
    steps = 10
    warmup = 3

    conf = ResNet50(num_labels=1000, seed=1).conf()
    conf.global_conf.compute_dtype = "bfloat16"
    net = ComputationGraph(conf)
    net.init()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3)).astype(np.float32))
    y = jnp.asarray(
        np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, size=batch)])
    ds = DataSet(x, y)  # resident on device for the whole run

    from deeplearning4j_tpu.observe import trace as _trace
    with _trace.span("warmup", attrs={"steps": warmup}):
        for _ in range(warmup):
            net._fit_batch(ds)
        float(net.score_)

    t0 = time.perf_counter()
    with _trace.span("measure", attrs={"steps": steps}):
        for _ in range(steps):
            net._fit_batch(ds)
        float(net.score_)
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    vs = ips / BASELINE_IMAGES_PER_SEC if BASELINE_IMAGES_PER_SEC else 1.0
    record = {
        "metric": "resnet50_train_throughput_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 4),
    }
    dev_ms = _profiled_device_ms(net, ds)
    if dev_ms is not None:
        record["device_ms_per_step"] = round(dev_ms, 2)
        record["device_time_images_per_sec"] = round(batch / dev_ms * 1e3, 1)
        record["dispatch_overhead_ms_per_step"] = round(
            dt / steps * 1e3 - dev_ms, 2)
    return record


def _bench_lenet():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.zoo.models import LeNet

    batch = 512
    net = MultiLayerNetwork(LeNet(num_labels=10, seed=1).conf())
    net.init()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(batch, 28, 28, 1)).astype(np.float32))
    y = jnp.asarray(
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)])
    rec = _measure(net, DataSet(x, y), batch)
    rec["config"] = "zoo LeNet, B=512, f32"
    return rec


def _bench_graveslstm():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import GravesLSTMLayer, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    batch, t, vocab, width = 64, 128, 77, 512
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
            .list()
            .layer(GravesLSTMLayer(n_in=vocab, n_out=width,
                                   activation="tanh"))
            .layer(GravesLSTMLayer(n_in=width, n_out=width,
                                   activation="tanh"))
            .layer(RnnOutputLayer(n_in=width, n_out=vocab,
                                  activation="softmax",
                                  loss="negativeloglikelihood"))
            .set_input_type(InputType.recurrent(vocab, t))
            .build())
    conf.global_conf.compute_dtype = "bfloat16"
    net = MultiLayerNetwork(conf)
    net.init()
    rng = np.random.default_rng(2)
    ids = rng.integers(0, vocab, size=(batch, t))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        np.roll(ids, -1, axis=1)])
    rec = _measure(net, DataSet(x, y), batch * t)  # items = characters
    rec["config"] = "2x512 GravesLSTM char-RNN, B=64 T=128 V=77, bf16"
    return rec


def _bench_bert_import():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet

    batch, t = 32, 128
    rng = np.random.default_rng(3)

    if not os.path.exists(BERT_H5):
        # the make stage needs keras, which must not share the TPU process.
        # A timed-out/killed make must not leave a truncated h5 that
        # poisons every later run: build to a temp name, rename on success.
        tmp_h5 = BERT_H5 + ".part"
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       DL4J_TPU_BERT_H5=tmp_h5)
            subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tools", "r4_bert_import_bench.py"), "make"],
                env=env, timeout=900, check=True,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            os.replace(tmp_h5, BERT_H5)
        except Exception:
            if os.path.exists(tmp_h5):
                os.remove(tmp_h5)

    net = None
    import_error = None
    if os.path.exists(BERT_H5):
        try:
            from deeplearning4j_tpu.datasets.dataset import MultiDataSet
            from deeplearning4j_tpu.modelimport.keras.importer import (
                KerasModelImport)
            net = KerasModelImport.import_keras_model_and_weights(BERT_H5)
        except Exception as e:  # noqa: BLE001 - record, fall back to zoo
            # the fixture is written atomically, so an import failure is
            # more likely an importer/backend issue than corruption — keep
            # the file (rebuilding costs ~15 min) and surface the reason
            import_error = f"{type(e).__name__}: {e}"
            net = None
    if net is not None:
        net.conf.global_conf.compute_dtype = "bfloat16"
        tok = rng.integers(0, 30522, size=(batch, t)).astype(np.float32)
        pos = np.tile(np.arange(t, dtype=np.float32), (batch, 1))
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=batch)]
        ds = MultiDataSet([jnp.asarray(tok), jnp.asarray(pos)],
                          [jnp.asarray(y)])
        path = "import"
    else:
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.zoo.models import TransformerEncoder

        conf = TransformerEncoder(num_labels=2, seed=1).conf()
        conf.global_conf.compute_dtype = "bfloat16"
        net = ComputationGraph(conf)
        net.init()
        tok = rng.integers(0, 30522, size=(batch, t)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=batch)]
        ds = DataSet(jnp.asarray(tok), jnp.asarray(y))
        path = "zoo_fallback"

    rec = _measure(net, ds, batch * t)  # items = tokens
    rec["path"] = path
    if import_error is not None:
        rec["import_error"] = import_error
    rec["config"] = "BERT-base shape 12L/768/12H/3072, B=32 T=128, bf16"
    return rec


def _bench_vgg16():
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.zoo.models import VGG16

    batch = 64
    conf = VGG16(num_labels=1000, seed=1).conf()
    conf.global_conf.compute_dtype = "bfloat16"
    net = MultiLayerNetwork(conf)
    net.init()
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3)).astype(np.float32))
    y = jnp.asarray(
        np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, size=batch)])
    rec = _measure(net, DataSet(x, y), batch, steps=6)
    rec["config"] = "zoo VGG16, B=64, 224x224x3, bf16, single chip"
    return rec


SUITE = {
    "lenet_mnist": _bench_lenet,
    "graveslstm_char_rnn": _bench_graveslstm,
    "bert_base_import": _bench_bert_import,
    "vgg16": _bench_vgg16,
}


# -- pod-scale elastic series (MULTICHIP_r06) --------------------------------

def _scaling_net(seed=1, width=512):
    """A model big enough that its checkpoint write is measurable (~1M
    params ≈ 4 MB of f32 + updater state) but cheap to step on CPU."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=width, activation="relu"))
            .layer(DenseLayer(n_out=width, activation="relu"))
            .layer(OutputLayer(n_out=10))
            .set_input_type(InputType.feed_forward(width)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(seed)
    batch = 128
    x = jnp.asarray(rng.normal(size=(batch, width)).astype(np.float32))
    y = jnp.asarray(
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)])
    return net, DataSet(x, y), batch


def _pod_scaling_worlds(steps=8, warmup=3):
    """Step time vs data-parallel world size on the local mesh — the
    scaling half of the curve."""
    import jax

    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    from deeplearning4j_tpu.parallel import (DistributedMultiLayerNetwork,
                                             SharedTrainingMaster)
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    worlds = []
    for w in (1, 2, 4, 8):
        if w > len(devices):
            break
        net, ds, batch = _scaling_net()
        mesh = make_mesh({"data": w}, devices=devices[:w])
        master = SharedTrainingMaster(batch_size_per_worker=batch // w,
                                      threshold=1e-3, mesh=mesh)
        front = DistributedMultiLayerNetwork(net, master)
        x = np.asarray(ds.features)
        y = np.asarray(ds.labels)
        it = lambda: ListDataSetIterator(DataSet(x, y), batch)  # noqa: E731
        front.fit(it(), epochs=warmup)  # compile + warm
        t0 = time.perf_counter()
        front.fit(it(), epochs=steps)
        wall_ms = (time.perf_counter() - t0) / steps * 1e3
        worlds.append({"world": w,
                       "wall_ms_per_step": round(wall_ms, 2),
                       "items_per_sec": round(batch / wall_ms * 1e3, 1)})
    return worlds


def _pod_save_stall(mode, tmp_dir, steps=6):
    """Per-step checkpoint stall for one save mode: the wall time the
    TRAINING thread loses to each per-step checkpoint. sync = full
    orbax save + finalize on the step path; async = snapshot + bounded
    submit (AsyncCheckpointSession), commit runs behind the next
    steps."""
    import shutil

    from deeplearning4j_tpu.parallel.elastic import (AsyncCheckpointSession,
                                                     ElasticWorkerContext)
    from deeplearning4j_tpu.util.orbax_checkpoint import (
        OrbaxCheckpointManager)

    net, ds, batch = _scaling_net()
    d = os.path.join(tmp_dir, f"save_{mode}")
    shutil.rmtree(d, ignore_errors=True)
    for _ in range(3):
        net._fit_batch(ds)
    float(net.score_)
    stalls = []
    mgr = OrbaxCheckpointManager(d, max_to_keep=2)
    session = None
    committed = 0
    if mode == "async":
        ctx = ElasticWorkerContext(
            coordinator="", num_processes=1, process_id=0, slot=0,
            generation=1, token="bench", ckpt_dir=d,
            heartbeat_path=os.path.join(d, "hb"), restore_step=None)
        session = AsyncCheckpointSession(ctx, manager=mgr,
                                         max_in_flight=2)
    t_train0 = time.perf_counter()
    for step in range(1, steps + 1):
        net._fit_batch(ds)
        float(net.score_)
        t0 = time.perf_counter()
        if session is not None:
            session.submit(step, net)
        else:
            if mgr.save(step, net, overwrite_existing=True):
                committed += 1
            mgr.wait_until_finished()
        stalls.append(time.perf_counter() - t0)
    total_wall = time.perf_counter() - t_train0
    if session is not None:
        flushed = session.close(timeout=300)
        committed = len(session.committed)
    else:
        flushed = True
    # a timed-out flush means the saver thread may still be inside a
    # manager call — do NOT close the manager under it (same rule as
    # run_elastic_worker); process exit reclaims it, and the record
    # reports flushed=false
    if flushed:
        mgr.close()
    return {"mode": mode,
            "save_stall_ms_per_step": round(
                sum(stalls) / len(stalls) * 1e3, 2),
            "save_stall_ms_max": round(max(stalls) * 1e3, 2),
            "wall_ms_per_step_with_saves": round(
                total_wall / steps * 1e3, 2),
            "steps": steps, "flushed": flushed,
            "committed_steps": committed}


def _pod_scaling_main(out_path, save_mode):
    import tempfile

    import jax
    record = {
        "metric": "pod_scale_elastic",
        "series": "MULTICHIP_r06",
        "config": "3-layer 512-wide MLP (~790k params, Adam), B=128 f32, "
                  "per-step orbax checkpoint rotation",
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "note": "worlds = step time vs data-axis size on the local "
                "device mesh (on the virtual CPU mesh collective overhead "
                "dominates at this model size, so the curve RISES — the "
                "series exists to track the shape run-over-run and on "
                "real ICI); save = per-step checkpoint stall on the "
                "training thread, sync vs async commit path",
        "worlds": _pod_scaling_worlds(),
        "save": {},
    }
    modes = ("sync", "async") if save_mode is None else (save_mode,)
    with tempfile.TemporaryDirectory(prefix="pod_bench_") as td:
        for mode in modes:
            record["save"][mode] = _pod_save_stall(mode, td)
    if {"sync", "async"} <= set(record["save"]):
        sync_ms = record["save"]["sync"]["save_stall_ms_per_step"]
        async_ms = record["save"]["async"]["save_stall_ms_per_step"]
        record["async_stall_vs_sync"] = round(async_ms / sync_ms, 4) \
            if sync_ms > 0 else None
    line = json.dumps(record, indent=2)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
        print(f"wrote {out_path}", file=sys.stderr)
    print(line)


# -- GSPMD 2-D parallelism series (MULTICHIP_r07) ----------------------------

_COLLECTIVES = ("all-gather", "all-reduce", "reduce-scatter",
                "collective-permute", "all-to-all")


def _force_cpu_mesh(n=8):
    """This series is DEFINED on the virtual 8-device CPU mesh (same
    substrate as the test tier) — must run before the first jax import."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()


def _collective_counts(hlo_text):
    import re as _re
    return {c.replace("-", "_"):
            len(_re.findall(r"\b%s\b" % c, hlo_text))
            for c in _COLLECTIVES}


def _lm_2d_net(mesh=None, rules=None, vocab=512, d_model=64, n_heads=4,
               n_layers=2, d_ff=128, t=16, seed=7):
    """Tiny-but-real TransformerLM + LM batch; sharded when mesh given.
    n_heads must be divisible by the model-axis size (head-major QKV
    reshape propagation keeps the layout; a non-dividing head count
    forces GSPMD to re-gather activations)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.parallel.sharding import shard_model_with_rules
    from deeplearning4j_tpu.zoo.models import TransformerLM, lm_labels

    net = TransformerLM(vocab_size=vocab, d_model=d_model, n_heads=n_heads,
                        n_layers=n_layers, d_ff=d_ff, max_length=t,
                        seed=seed).init()
    if mesh is not None:
        shard_model_with_rules(net, mesh, rules)
    rng = np.random.default_rng(seed)
    batch = 32
    toks = rng.integers(0, vocab, size=(batch, t))
    x = toks.astype(np.float32)
    y = np.asarray(lm_labels(jnp.asarray(toks), vocab))
    return net, DataSet(x, y), batch


def _lm_step_hlo(net, ds, mesh):
    """Compiled HLO of the graph train step on mesh-placed args."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel.sharding import place_batch

    step = net._get_train_step()
    it, ep, rng_k = net._device_tick()
    xj = place_batch(jnp.asarray(np.asarray(ds.features)), mesh) \
        if mesh is not None else jnp.asarray(np.asarray(ds.features))
    yj = place_batch(jnp.asarray(np.asarray(ds.labels)), mesh) \
        if mesh is not None else jnp.asarray(np.asarray(ds.labels))
    return step.lower(net.params, net.states, net.updater_states, it, ep,
                      {"tokens": xj}, [yj], None, None,
                      rng_k).compile().as_text()


def _lm_forward_hlo(net, ds, mesh):
    """Compiled HLO of the forward (the vocab-path oracle surface:
    row-sharded embedding take in, column-sharded logits out)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel.sharding import place_batch

    ofn = net._output_fn()
    xj = place_batch(jnp.asarray(np.asarray(ds.features)), mesh) \
        if mesh is not None else jnp.asarray(np.asarray(ds.features))
    return ofn.lower(net.params, net.states,
                     {"tokens": xj}, None).compile().as_text()


def _sharding_2d_config(name, axes, steps=8, warmup=3):
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(dict(axes)) if axes else None
    net, ds, batch = _lm_2d_net(mesh=mesh)
    for _ in range(warmup):
        net.fit(ds)
    float(net.score_)
    t0 = time.perf_counter()
    for _ in range(steps):
        net.fit(ds)
    float(net.score_)
    wall_ms = (time.perf_counter() - t0) / steps * 1e3
    return {"mesh": dict(axes) if axes else {"data": 1},
            "wall_ms_per_step": round(wall_ms, 2),
            "items_per_sec": round(batch / wall_ms * 1e3, 1),
            "train_step": _collective_counts(_lm_step_hlo(net, ds, mesh)),
            # forward AFTER training: placement pinning must have kept
            # the params where the rules put them (sharding drift would
            # show up here as all-gathers)
            "forward": _collective_counts(_lm_forward_hlo(net, ds, mesh))}


def _sharding_2d_main(out_path):
    import jax

    configs = {
        "dp8": {"data": 8},
        "dp4_mp2": {"data": 4, "model": 2},
        "dp2_mp4": {"data": 2, "model": 4},
    }
    record = {
        "metric": "sharding_2d",
        "series": "MULTICHIP_r07",
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "config": "TransformerLM 2L/64d/4h/512V T=16 B=32 f32 Adam, "
                  "rule-based GSPMD placement (DEFAULT_2D_RULES)",
        "note": "dp8 = data-parallel only; dp4_mp2/dp2_mp4 = Megatron "
                "2-D over the same 8 virtual CPU devices (collective "
                "overhead dominates at this size on CPU — the series "
                "tracks the collective COUNTS and the shape run-over-"
                "run; on real ICI the model axis buys memory headroom). "
                "forward.all_gather == 0 is the zero-all-gather vocab-"
                "path invariant: row-sharded embedding take + column-"
                "sharded logits with LSE cross-entropy never "
                "re-assemble the vocab dimension",
        "configs": {name: _sharding_2d_config(name, axes)
                    for name, axes in configs.items()},
    }
    for name in ("dp4_mp2", "dp2_mp4"):
        ag = record["configs"][name]["forward"]["all_gather"]
        if ag != 0:
            print(f"sharding-2d: {name} forward has {ag} all-gather(s) — "
                  f"the vocab-path invariant is BROKEN", file=sys.stderr)
            raise SystemExit(1)
    line = json.dumps(record, indent=2)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
        print(f"wrote {out_path}", file=sys.stderr)
    print(line)


def _sharding_2d_check(path):
    """Validate a committed MULTICHIP_r07 record + live vocab-path
    oracle. Timing is checked against the committed record only (live
    timing on CI is noise); the zero-all-gather invariant is re-proven
    live on this machine, before AND after a train step."""
    errors = []

    def expect(cond, msg):
        if not cond:
            errors.append(msg)

    with open(path, encoding="utf-8") as fh:
        rec = json.load(fh)
    expect(rec.get("metric") == "sharding_2d", "metric != sharding_2d")
    cfgs = rec.get("configs") or {}
    for name in ("dp8", "dp4_mp2", "dp2_mp4"):
        expect(name in cfgs, f"configs.{name} missing")
    for name in ("dp4_mp2", "dp2_mp4"):
        if name in cfgs:
            expect(cfgs[name]["forward"].get("all_gather") == 0,
                   f"committed record: {name} forward all-gathers != 0 "
                   f"(vocab path re-assembles the vocab dim)")
            expect(cfgs[name]["train_step"].get("all_reduce", 0) > 0,
                   f"committed record: {name} train step has no "
                   f"all-reduce (gradient exchange missing?)")
        if name in cfgs and "dp8" in cfgs:
            expect(cfgs[name].get("wall_ms_per_step", 0) > 0
                   and cfgs["dp8"].get("wall_ms_per_step", 0) > 0,
                   f"committed record: {name}/dp8 timing missing")

    # live oracle — the invariant, re-proven on this machine every run
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 4, "model": 2})
    net, ds, _ = _lm_2d_net(mesh=mesh)
    ag0 = _collective_counts(_lm_forward_hlo(net, ds, mesh))["all_gather"]
    expect(ag0 == 0, f"live: fresh placement forward has {ag0} "
                     f"all-gather(s)")
    net.fit(ds)  # one optimizer step: updated params must stay pinned
    float(net.score_)
    ag1 = _collective_counts(_lm_forward_hlo(net, ds, mesh))["all_gather"]
    expect(ag1 == 0, f"live: post-step forward has {ag1} all-gather(s) — "
                     f"train-step output shardings drifted off the rules")

    if errors:
        for e in errors:
            print(f"sharding-2d check FAILED: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"sharding-2d check OK: {path} (committed collective counts "
          f"consistent; zero-all-gather vocab path holds live, before "
          f"and after a train step)")


# -- training input pipeline + fused updater series (BENCH_TRAIN_r01) --------

class _OneHotETLIterator:
    """Transfer-bound input source: every batch costs an ingest latency
    (``io_ms`` of GIL-released wait — the remote-storage read profile) plus
    real numpy decode work (one-hot encode), the stall the async prefetch
    wrap exists to hide behind the running step. Yields fresh numpy-backed
    DataSets, so it is safe to device_put/mutate downstream."""

    def __init__(self, n_batches, batch, t, vocab, n_labels=10, seed=0,
                 io_ms=15.0):
        self.n_batches = int(n_batches)
        self.batch, self.t, self.vocab = int(batch), int(t), int(vocab)
        self.n_labels = int(n_labels)
        self.seed = int(seed)
        self.io_ms = float(io_ms)

    def reset(self):
        pass

    def __iter__(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.default_rng(self.seed)
        eye = np.eye(self.vocab, dtype=np.float32)
        for _ in range(self.n_batches):
            time.sleep(self.io_ms / 1e3)  # the read we are hiding
            ids = rng.integers(0, self.vocab, size=(self.batch, self.t))
            x = eye[ids].reshape(self.batch, self.t * self.vocab)
            y = np.eye(self.n_labels, dtype=np.float32)[
                rng.integers(0, self.n_labels, size=self.batch)]
            yield DataSet(x, y)


def _pipeline_net(n_in, width=128, n_labels=10, seed=1):
    """Small dense model over wide one-hot input: the step is cheap enough
    that an unhidden ETL stage dominates the loop."""
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=width, activation="relu"))
            .layer(OutputLayer(n_out=n_labels))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _prefetch_run(net, depth, n_batches, batch, t, vocab, seed):
    """One epoch over the ETL-bound iterator at one prefetch depth, with the
    production observability attached — the TraceListener reads the score
    every step, the per-iteration sync every monitored training run pays.
    Returns wall/step, host_wait/step (from the fit loop's trace spans),
    transfer MB (from the exported counter) and compiles on this thread."""
    from deeplearning4j_tpu.observe import (Tracer, disable_tracing,
                                            enable_tracing)
    from deeplearning4j_tpu.observe.listener import TraceListener
    from deeplearning4j_tpu.observe.metrics import MetricsRegistry

    it = _OneHotETLIterator(n_batches, batch, t, vocab, seed=seed)
    metrics = MetricsRegistry()
    tracer = enable_tracing(Tracer(metrics=metrics))
    listener = TraceListener(tracer, metrics, model_name="bench")
    net.listeners.append(listener)
    try:
        t0 = time.perf_counter()
        net.fit(it, epochs=1, prefetch_depth=depth)
        float(net.score_)  # drain the dispatch queue before stopping the clock
        dt = time.perf_counter() - t0
        compiles = tracer.thread_compile_count()
    finally:
        net.listeners.remove(listener)
        disable_tracing()
    host_wait_ms = sum(s.end_ns - s.start_ns
                       for s in tracer.recorder.spans()
                       if s.name == "host_wait" and s.end_ns) / 1e6
    xfer = metrics.get("training_transfer_bytes_total")
    return {
        "prefetch_depth": depth,
        "wall_ms_per_step": round(dt / n_batches * 1e3, 2),
        "host_wait_ms_per_step": round(host_wait_ms / n_batches, 2),
        "transfer_mb_total": round(
            (xfer.value(model="bench") if xfer is not None else 0) / 2**20, 2),
        "steady_state_compiles": int(compiles),
    }


def _max_param_diff(a, b):
    """max |Δ| over every parameter tensor of two same-structure nets."""
    import jax
    return max(float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
               for x, y in zip(jax.tree_util.tree_leaves(a.params),
                               jax.tree_util.tree_leaves(b.params)))


def _count_pallas_eqns(jaxpr):
    """pallas_call equations in a jaxpr, recursing into sub-jaxprs (pjit
    bodies, scan/cond branches)."""
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            n += 1
        for v in eqn.params.values():
            for u in (v if isinstance(v, (tuple, list)) else (v,)):
                inner = getattr(u, "jaxpr", u)
                if hasattr(inner, "eqns"):
                    n += _count_pallas_eqns(inner)
    return n


def _pallas_call_counts(net, ds):
    """(pallas_call eqns in the traced train step, fusable param tensors).
    With the fused updater registered the two must be EQUAL — one kernel
    launch per parameter's read-modify-write, no per-param op chain."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.pallas_kernels import PallasUpdaterHelper

    fn = net._get_train_step(False)
    closed = jax.make_jaxpr(fn)(
        net.params, net.states, net.updater_states,
        jnp.float32(0.0), jnp.float32(0.0),
        jnp.asarray(np.asarray(ds.features), jnp.float32),
        jnp.asarray(np.asarray(ds.labels), jnp.float32),
        None, None, jax.random.PRNGKey(0), None)
    probe = PallasUpdaterHelper()
    fusable = sum(1 for i, layer_params in enumerate(net.params)
                  for n, p in layer_params.items()
                  if probe.supports(net._updaters[i][n], p, p))
    return _count_pallas_eqns(closed.jaxpr), fusable


def _fused_updater_bench():
    """Fused Pallas optimizer step vs the stock per-param chain on twin
    nets (same seed, same data): wall time each way, post-run numerical
    agreement, and the kernel-launch oracle."""
    import jax

    from deeplearning4j_tpu.nn import helpers as _helpers
    from deeplearning4j_tpu.nn.pallas_kernels import PallasUpdaterHelper

    net_a, ds, batch = _scaling_net(seed=7)
    net_b, _, _ = _scaling_net(seed=7)
    _helpers.clear_helper("updater")
    try:
        rec = {"config": "3-layer 512-wide MLP (~790k params, Adam), "
                         "B=128 f32 (the pod-scaling net)"}
        # per-update agreement contract first: fresh twins, 3 identical
        # steps each way — the tolerance is per update, not compounded
        # over a long chaotic trajectory
        tw_a, tw_ds, _ = _scaling_net(seed=11, width=64)
        tw_b, _, _ = _scaling_net(seed=11, width=64)
        for _ in range(3):
            tw_a._fit_batch(tw_ds)
        _helpers.set_helper("updater", PallasUpdaterHelper())
        for _ in range(3):
            tw_b._fit_batch(tw_ds)
        rec["max_abs_param_diff"] = float(_max_param_diff(tw_a, tw_b))
        rec["agreement_steps"] = 3
        _helpers.clear_helper("updater")
        rec["stock"] = _measure(net_a, ds, batch)
        _helpers.set_helper("updater", PallasUpdaterHelper())
        rec["fused"] = _measure(net_b, ds, batch)
        stock_ms = rec["stock"]["wall_ms_per_step"]
        fused_ms = rec["fused"]["wall_ms_per_step"]
        rec["fused_vs_stock"] = round(fused_ms / stock_ms, 4) \
            if stock_ms > 0 else None
        pallas, fusable = _pallas_call_counts(net_b, ds)
        rec["pallas_calls_in_train_step"] = pallas
        rec["fusable_tensors"] = fusable
        if jax.default_backend() != "tpu":
            rec["note"] = ("interpret-mode Pallas off-TPU: the fused timing "
                           "measures the seam, not the kernel — the "
                           "correctness/launch-count oracles are the "
                           "backend-portable signal")
        return rec
    finally:
        _helpers.clear_helper("updater")


def _train_pipeline_main(out_path):
    import jax

    vocab, t, batch, n_batches = 256, 32, 64, 24
    net = _pipeline_net(t * vocab)
    # compile outside the measured windows (identical shapes throughout)
    net.fit(_OneHotETLIterator(2, batch, t, vocab, seed=99), epochs=1,
            prefetch_depth=0)
    float(net.score_)

    prefetch = {
        "off": _prefetch_run(net, 0, n_batches, batch, t, vocab, seed=5),
        "on": _prefetch_run(net, 2, n_batches, batch, t, vocab, seed=6),
    }
    on_ms = prefetch["on"]["wall_ms_per_step"]
    record = {
        "metric": "train_pipeline",
        "series": "BENCH_TRAIN_r01",
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "config": f"dense-128 over {t * vocab}-wide one-hot, B={batch}, "
                  f"{n_batches} batches/epoch, 15ms ingest latency + numpy "
                  "decode per batch, Adam, f32, TraceListener attached "
                  "(per-step score sync)",
        "note": "prefetch off = the fit thread pays ingest + decode + "
                "transfer between steps; on = AsyncDataSetIterator producer "
                "+ device_put stage hides them behind the running step, so "
                "host_wait collapses",
        "prefetch": prefetch,
        "prefetch_speedup": round(
            prefetch["off"]["wall_ms_per_step"] / on_ms, 4) if on_ms else None,
        "fused_updater": _fused_updater_bench(),
    }
    line = json.dumps(record, indent=2)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
        print(f"wrote {out_path}", file=sys.stderr)
    print(line)


def _train_check(path):
    """Validate a committed BENCH_TRAIN record + live functional oracles.
    Timing claims are checked against the COMMITTED record (live timing on
    an arbitrary CI box is noise); correctness claims are re-proven live."""
    errors = []

    def expect(cond, msg):
        if not cond:
            errors.append(msg)

    with open(path, encoding="utf-8") as fh:
        rec = json.load(fh)
    expect(rec.get("metric") == "train_pipeline", "metric != train_pipeline")
    pre = rec.get("prefetch") or {}
    expect("off" in pre and "on" in pre, "prefetch.off/on missing")
    if "off" in pre and "on" in pre:
        expect(pre["on"]["wall_ms_per_step"] < pre["off"]["wall_ms_per_step"],
               "committed record: prefetch-on not faster than prefetch-off")
        expect(pre["on"]["host_wait_ms_per_step"]
               <= pre["off"]["host_wait_ms_per_step"],
               "committed record: prefetch did not reduce host_wait")
        for k in ("off", "on"):
            expect(pre[k].get("steady_state_compiles") == 0,
                   f"committed record: prefetch.{k} recompiled in steady "
                   f"state")
            expect(pre[k].get("transfer_mb_total", 0) > 0,
                   f"committed record: prefetch.{k} transfer counter empty")
    fu = rec.get("fused_updater") or {}
    expect(fu.get("max_abs_param_diff", 1.0) <= 2e-5,
           "committed record: fused/stock divergence > 2e-5")
    expect(fu.get("fusable_tensors", 0) > 0
           and fu.get("pallas_calls_in_train_step")
           == fu.get("fusable_tensors"),
           "committed record: kernel launches != fusable tensors")

    # live oracles — re-proven on this machine, every run
    from deeplearning4j_tpu.nn import helpers as _helpers
    from deeplearning4j_tpu.nn.pallas_kernels import PallasUpdaterHelper
    from deeplearning4j_tpu.observe import (Tracer, disable_tracing,
                                            enable_tracing)

    net_a, ds, _ = _scaling_net(seed=3, width=64)
    net_b, _, _ = _scaling_net(seed=3, width=64)
    _helpers.clear_helper("updater")
    try:
        for _ in range(3):
            net_a._fit_batch(ds)
        pallas0, _ = _pallas_call_counts(net_a, ds)
        expect(pallas0 == 0,
               f"live: {pallas0} pallas_call(s) with the updater seam clear")
        _helpers.set_helper("updater", PallasUpdaterHelper())
        for _ in range(3):
            net_b._fit_batch(ds)
        diff = _max_param_diff(net_a, net_b)
        expect(diff <= 2e-5,
               f"live: fused diverged from stock by {diff:.2e} > 2e-5")
        pallas, fusable = _pallas_call_counts(net_b, ds)
        expect(fusable > 0 and pallas == fusable,
               f"live: {pallas} pallas_call(s) for {fusable} fusable tensors")
        tracer = enable_tracing(Tracer())
        try:
            for _ in range(3):
                net_b._fit_batch(ds)
            float(net_b.score_)
            live_compiles = tracer.thread_compile_count()
            expect(live_compiles == 0,
                   f"live: {live_compiles} steady-state compile(s) on the "
                   f"fused path")
        finally:
            disable_tracing()
    finally:
        _helpers.clear_helper("updater")

    if errors:
        for e in errors:
            print(f"train-pipeline check FAILED: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"train-pipeline check OK: {path} (prefetch speedup "
          f"{rec.get('prefetch_speedup')}x committed; fused updater agrees "
          f"live, one kernel per tensor, zero steady-state compiles)")


def main():
    record = _with_trace("resnet50_headline", _resnet50_headline)
    if os.environ.get("DL4J_TPU_BENCH_HEADLINE_ONLY") != "1":
        suite = {}
        for name, fn in SUITE.items():
            try:
                suite[name] = _with_trace(name, fn)
            except Exception as e:  # noqa: BLE001 - isolate per-config failures
                suite[name] = {"error": f"{type(e).__name__}: {e}"}
        record["suite"] = suite
    print(json.dumps(record))


def _parse_train_args():
    """(--train-pipeline present, out path or None, --check path or None);
    (False, None, None) when the flag is absent. Unknown flags pass
    through, mirroring _parse_pod_args."""
    if "--train-pipeline" not in sys.argv[1:]:
        return False, None, None
    import argparse
    ap = argparse.ArgumentParser("bench --train-pipeline", add_help=False)
    ap.add_argument("--train-pipeline", nargs="?", default=None,
                    metavar="OUT.json", dest="out")
    ap.add_argument("--check", default=None, metavar="COMMITTED.json")
    args, _unknown = ap.parse_known_args(sys.argv[1:])
    return True, args.out, args.check


def _parse_sharding_args():
    """(--sharding-2d present, out path or None, --check path or None);
    (False, None, None) when the flag is absent."""
    if "--sharding-2d" not in sys.argv[1:]:
        return False, None, None
    import argparse
    ap = argparse.ArgumentParser("bench --sharding-2d", add_help=False)
    ap.add_argument("--sharding-2d", nargs="?", default=None,
                    metavar="OUT.json", dest="out")
    ap.add_argument("--check", default=None, metavar="COMMITTED.json")
    args, _unknown = ap.parse_known_args(sys.argv[1:])
    return True, args.out, args.check


def _parse_pod_args():
    """(--pod-scaling out_path_or_None, --save-mode or None); returns
    (False, None, None) when --pod-scaling is absent. Unknown flags
    (--trace etc.) belong to the headline path and pass through."""
    if "--pod-scaling" not in sys.argv[1:]:
        return False, None, None
    import argparse
    ap = argparse.ArgumentParser("bench --pod-scaling", add_help=False)
    ap.add_argument("--pod-scaling", nargs="?", default=None,
                    metavar="OUT.json", dest="out")
    ap.add_argument("--save-mode", choices=("sync", "async"),
                    default=None, dest="mode")
    args, _unknown = ap.parse_known_args(sys.argv[1:])
    return True, args.out, args.mode


if __name__ == "__main__":
    train, _train_out, _train_check_path = _parse_train_args()
    if train:
        if _train_check_path:
            _train_check(_train_check_path)
        else:
            _train_pipeline_main(_train_out)
        raise SystemExit(0)
    pod, _pod_out, _pod_mode = _parse_pod_args()
    if pod:
        _pod_scaling_main(_pod_out, _pod_mode)
        raise SystemExit(0)
    sh2d, _sh_out, _sh_check = _parse_sharding_args()
    if sh2d:
        _force_cpu_mesh()  # BEFORE the first jax import
        if _sh_check:
            _sharding_2d_check(_sh_check)
        else:
            _sharding_2d_main(_sh_out)
        raise SystemExit(0)
    # one retry IN A FRESH PROCESS: the tunneled TPU link occasionally
    # drops a request mid-compile, and jax's cached PJRT client stays
    # broken for the life of the process — only a re-exec gets a new
    # connection. The env flag stops a second failure from looping.
    try:
        main()
    except Exception as e:  # noqa: BLE001 - any transient backend error
        import traceback
        traceback.print_exc()
        if os.environ.get("DL4J_TPU_BENCH_RETRY") == "1":
            raise
        print(f"bench attempt 1 failed ({type(e).__name__}); "
              f"retrying in a fresh process", file=sys.stderr, flush=True)
        env = dict(os.environ, DL4J_TPU_BENCH_RETRY="1")
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)], env)
