"""Datasets / iterators / normalizers / listeners / ModelSerializer tests.

Mirrors the reference tiers: iterator unit tests
(`deeplearning4j-core/.../datasets/iterator/`), normalizer behavior, the
serialization regression pattern (`regressiontest/RegressionTest*.java` locks
the checkpoint format), and CheckpointListener rotation
(`TestCheckpointListener.java`).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    AsyncDataSetIterator,
    CifarDataSetIterator,
    DataSet,
    DataSetIteratorSplitter,
    EarlyTerminationDataSetIterator,
    ImagePreProcessingScaler,
    IrisDataSetIterator,
    IteratorDataSetIterator,
    ListDataSetIterator,
    MnistDataSetIterator,
    MultipleEpochsIterator,
    Normalizer,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    SamplingDataSetIterator,
    UciSequenceDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.optimize import (
    CheckpointListener,
    CollectScoresIterationListener,
    EvaluativeListener,
    PerformanceListener,
    ScoreIterationListener,
)
from deeplearning4j_tpu.util.model_serializer import (
    add_normalizer_to_model,
    restore_computation_graph,
    restore_model,
    restore_multi_layer_network,
    restore_normalizer,
    write_model,
)


def small_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


class TestFetchers:
    def test_mnist_shapes(self):
        it = MnistDataSetIterator(32, train=True)
        ds = next(iter(it))
        assert ds.features.shape == (32, 28, 28, 1)
        assert ds.labels.shape == (32, 10)
        assert 0.0 <= float(ds.features.min()) and float(ds.features.max()) <= 1.0

    def test_iris_real_data(self):
        it = IrisDataSetIterator(150)
        assert not it.synthetic  # sklearn's bundled real iris
        ds = next(iter(it))
        assert ds.features.shape == (150, 4)
        assert ds.labels.shape == (150, 3)
        # class counts are 50/50/50 in the real dataset
        np.testing.assert_array_equal(ds.labels.sum(0), [50, 50, 50])

    def test_cifar_shapes(self):
        ds = next(iter(CifarDataSetIterator(16)))
        assert ds.features.shape == (16, 32, 32, 3)

    def test_uci_sequences(self):
        it = UciSequenceDataSetIterator(60, train=True)
        ds = next(iter(it))
        assert ds.features.shape == (60, 60, 1)
        assert ds.labels.shape == (60, 6)

    def test_mnist_learnable(self):
        """The synthetic stand-in must actually be learnable (sanity of the
        fetcher-based examples)."""
        it = MnistDataSetIterator(64, train=True, synthetic_size=512)
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=784, n_out=64, activation="relu"))
                .layer(OutputLayer(n_in=64, n_out=10, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(
                    __import__("deeplearning4j_tpu.nn.conf.inputs",
                               fromlist=["InputType"]).InputType.convolutional_flat(28, 28, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, epochs=8)
        acc = net.evaluate(MnistDataSetIterator(256, train=True,
                                                synthetic_size=512,
                                                shuffle=False)).accuracy()
        assert acc > 0.9


class TestIterators:
    def _base(self, n=64, batch=16):
        x = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
        y = np.zeros((n, 2), np.float32)
        return ListDataSetIterator(DataSet(x, y), batch)

    def test_async_matches_sync(self):
        base = self._base()
        sync = [np.asarray(d.features) for d in base]
        async_ = [np.asarray(d.features) for d in AsyncDataSetIterator(self._base())]
        assert len(sync) == len(async_)
        for a, b in zip(sync, async_):
            np.testing.assert_array_equal(a, b)

    def test_async_propagates_error(self):
        class Bad:
            def reset(self):
                pass

            def __iter__(self):
                yield DataSet(np.zeros((2, 2)), np.zeros((2, 2)))
                raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            list(AsyncDataSetIterator(Bad()))

    def test_multiple_epochs(self):
        it = MultipleEpochsIterator(self._base(32, 16), 3)
        assert len(list(it)) == 6

    def test_early_termination(self):
        it = EarlyTerminationDataSetIterator(self._base(64, 16), 2)
        assert len(list(it)) == 2

    def test_sampling(self):
        ds = DataSet(np.zeros((10, 2), np.float32), np.zeros((10, 2), np.float32))
        it = SamplingDataSetIterator(ds, 8, 5)
        batches = list(it)
        assert len(batches) == 5 and batches[0].features.shape == (8, 2)

    def test_splitter(self):
        split = DataSetIteratorSplitter(self._base(64, 16), 4, 0.75)
        assert len(list(split.train)) == 3
        assert len(list(split.test)) == 1

    def test_rebatching(self):
        small = ListDataSetIterator(
            DataSet(np.zeros((50, 2), np.float32), np.zeros((50, 2), np.float32)), 10)
        out = list(IteratorDataSetIterator(small, 20))
        assert [d.num_examples() for d in out] == [20, 20, 10]


class TestNormalizers:
    def test_standardize_roundtrip(self, rng):
        x = rng.normal(5.0, 3.0, size=(100, 4)).astype(np.float32)
        ds = DataSet(x, np.zeros((100, 2), np.float32))
        n = NormalizerStandardize().fit(ds)
        t = n.transform(ds)
        assert abs(float(t.features.mean())) < 1e-4
        assert abs(float(t.features.std()) - 1.0) < 1e-2
        r = n.revert(t)
        np.testing.assert_allclose(r.features, x, rtol=1e-4, atol=1e-4)

    def test_minmax(self, rng):
        x = rng.uniform(-7, 13, size=(50, 3)).astype(np.float32)
        ds = DataSet(x, np.zeros((50, 1), np.float32))
        n = NormalizerMinMaxScaler().fit(ds)
        t = n.transform(ds)
        assert float(t.features.min()) >= -1e-6
        assert float(t.features.max()) <= 1 + 1e-6

    def test_image_scaler(self):
        x = np.full((4, 2, 2, 1), 255.0, np.float32)
        t = ImagePreProcessingScaler().transform(DataSet(x, x))
        assert float(t.features.max()) == 1.0

    def test_serde(self, rng):
        x = rng.normal(size=(30, 4)).astype(np.float32)
        n = NormalizerStandardize().fit(DataSet(x, x))
        n2 = Normalizer.from_json(n.to_json())
        np.testing.assert_allclose(n.mean, n2.mean)
        np.testing.assert_allclose(n.std, n2.std)


class TestListeners:
    def test_score_and_collect(self, rng):
        net = small_net()
        scores = CollectScoresIterationListener()
        printed = []
        net.set_listeners(scores, ScoreIterationListener(1, printed.append),
                          PerformanceListener(1, printer=printed.append))
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        it = ListDataSetIterator(DataSet(x, y), 8)
        net.fit(it, epochs=2)
        assert len(scores.scores) == 8
        assert any("Score at iteration" in p for p in printed)
        assert any("batches/sec" in p for p in printed)

    def test_evaluative_listener(self, rng):
        net = small_net()
        x = rng.normal(size=(24, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 24)]
        ev = EvaluativeListener(ListDataSetIterator(DataSet(x, y), 24),
                                frequency=1, printer=lambda s: None)
        net.set_listeners(ev)
        net.fit(ListDataSetIterator(DataSet(x, y), 8), epochs=3)
        assert len(ev.evaluations) == 3

    def test_evaluative_listener_custom_evaluations(self, rng):
        # evalWith(IEvaluation...) parity: stream held-out predictions
        # through custom evaluators (calibration + ROCMultiClass here)
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        net = small_net()
        x = rng.normal(size=(24, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 24)]
        printed = []
        ev = EvaluativeListener(
            ListDataSetIterator(DataSet(x, y), 24), frequency=1,
            printer=printed.append,
            evaluations=[lambda: EvaluationCalibration(histogram_bins=20),
                         lambda: ROCMultiClass()])
        net.set_listeners(ev)
        net.fit(ListDataSetIterator(DataSet(x, y), 8), epochs=2)
        assert len(ev.evaluations) == 2
        cal, roc = ev.evaluations[-1]
        assert 0.0 <= cal.expected_calibration_error() <= 1.0
        assert cal.num_classes == 3
        assert any("ECE" in p for p in printed)


class TestModelSerializer:
    def test_mln_roundtrip(self, rng, tmp_path):
        net = small_net()
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(x, y)
        p = tmp_path / "model.zip"
        write_model(net, p)
        net2 = restore_multi_layer_network(p)
        np.testing.assert_allclose(np.asarray(net.output(x)),
                                   np.asarray(net2.output(x)), rtol=1e-6)
        assert net2.iteration == net.iteration
        # updater state restored → identical continued training
        net.fit(x, y)
        net2.fit(x, y)
        for a, b in zip(net.params, net2.params):
            for n in a:
                np.testing.assert_allclose(np.asarray(a[n]), np.asarray(b[n]),
                                           rtol=1e-6, atol=1e-7)

    def test_graph_roundtrip(self, rng, tmp_path):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.vertices import ElementWiseVertex
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=4, n_out=4, activation="tanh"), "in")
                .add_vertex("res", ElementWiseVertex("add"), "d", "in")
                .add_layer("out", OutputLayer(n_in=4, n_out=2, activation="softmax",
                                              loss="mcxent"), "res")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf).init()
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        g.fit(DataSet(x, y))
        p = tmp_path / "graph.zip"
        write_model(g, p)
        g2 = restore_computation_graph(p)
        np.testing.assert_allclose(np.asarray(g.output(x)),
                                   np.asarray(g2.output(x)), rtol=1e-6)

    def test_wrong_type_raises(self, rng, tmp_path):
        net = small_net()
        p = tmp_path / "m.zip"
        write_model(net, p)
        with pytest.raises(ValueError):
            restore_computation_graph(p)
        assert restore_model(p) is not None

    def test_normalizer_in_zip(self, rng, tmp_path):
        net = small_net()
        p = tmp_path / "m.zip"
        write_model(net, p)
        x = rng.normal(size=(20, 4)).astype(np.float32)
        n = NormalizerStandardize().fit(DataSet(x, x))
        add_normalizer_to_model(p, n)
        n2 = restore_normalizer(p)
        np.testing.assert_allclose(n.mean, n2.mean)


class TestQkvLayoutMigration:
    """Round-5 breaking-change coverage: fused attention weights moved to
    HEAD-MAJOR column order. A checkpoint saved before the change (no
    ``qkv_layout`` stamp, block-major [3,H,Dh] columns) must repack on
    restore and reproduce the producer's outputs exactly."""

    def _attn_net(self):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import (RnnOutputLayer,
                                                  SelfAttentionLayer)
        conf = (NeuralNetConfiguration.builder().seed(9).updater(Adam(0.01))
                .list()
                .layer(SelfAttentionLayer(n_out=8, n_heads=2, head_size=4))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(8, 5))
                .build())
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()

    @staticmethod
    def _to_legacy(arr, parts, h, dh):
        """Inverse of the head-major repack: what an old save contains."""
        a = np.asarray(arr)
        if a.ndim == 1:
            return a.reshape(h, parts, dh).transpose(1, 0, 2).reshape(-1)
        d = a.shape[0]
        return a.reshape(d, h, parts, dh).transpose(0, 2, 1, 3).reshape(d, -1)

    def test_unstamped_checkpoint_repacks_to_same_outputs(self, rng,
                                                          tmp_path):
        import io
        import json
        import zipfile

        net = self._attn_net()
        x = rng.normal(size=(4, 5, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 5))]
        net.fit(x, y)
        ref_out = np.asarray(net.output(x))

        p = tmp_path / "legacy.zip"
        write_model(net, p)
        # forge a pre-round-5 checkpoint: params in block-major order,
        # meta without the qkv_layout stamp
        with zipfile.ZipFile(p) as z:
            entries = {n: z.read(n) for n in z.namelist()}
        params = dict(np.load(io.BytesIO(entries["params.npz"])))
        params["0/Wqkv"] = self._to_legacy(params["0/Wqkv"], 3, 2, 4)
        params["0/bqkv"] = self._to_legacy(params["0/bqkv"], 3, 2, 4)
        buf = io.BytesIO()
        np.savez(buf, **params)
        entries["params.npz"] = buf.getvalue()
        meta = json.loads(entries["meta.json"])
        del meta["qkv_layout"]
        entries["meta.json"] = json.dumps(meta).encode()
        with zipfile.ZipFile(p, "w") as z:
            for n, b in entries.items():
                z.writestr(n, b)

        again = restore_multi_layer_network(p)
        np.testing.assert_allclose(np.asarray(again.output(x)), ref_out,
                                   rtol=1e-5, atol=1e-6)

    def test_stamped_checkpoint_not_repacked(self, rng, tmp_path):
        net = self._attn_net()
        x = rng.normal(size=(4, 5, 8)).astype(np.float32)
        p = tmp_path / "new.zip"
        write_model(net, p)
        again = restore_multi_layer_network(p)
        np.testing.assert_allclose(np.asarray(again.output(x)),
                                   np.asarray(net.output(x)), rtol=1e-6)

    def test_orbax_unstamped_checkpoint_repacks(self, rng, tmp_path):
        import json
        import os

        import jax.numpy as jnp

        from deeplearning4j_tpu.util import orbax_checkpoint as orx

        net = self._attn_net()
        x = rng.normal(size=(4, 5, 8)).astype(np.float32)
        ref_out = np.asarray(net.output(x))
        d = str(tmp_path / "ckpt")
        # forge legacy: swap the params to block-major BEFORE saving, then
        # strip the stamp from the meta file
        net.params[0]["Wqkv"] = jnp.asarray(
            self._to_legacy(net.params[0]["Wqkv"], 3, 2, 4))
        net.params[0]["bqkv"] = jnp.asarray(
            self._to_legacy(net.params[0]["bqkv"], 3, 2, 4))
        orx.save_model(net, d)
        cfg_path = os.path.join(d, orx._CONFIG_FILE)
        meta = json.loads(open(cfg_path).read())
        del meta["qkv_layout"]
        open(cfg_path, "w").write(json.dumps(meta))
        again = orx.restore_model(d)
        np.testing.assert_allclose(np.asarray(again.output(x)), ref_out,
                                   rtol=1e-5, atol=1e-6)


class TestCheckpointListener:
    def test_rotation_keep_last(self, rng, tmp_path):
        net = small_net()
        cp = CheckpointListener(tmp_path, save_every_n_iterations=2, keep_last=2)
        net.set_listeners(cp)
        x = rng.normal(size=(40, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 40)]
        net.fit(ListDataSetIterator(DataSet(x, y), 4))  # 10 iterations
        files = list(tmp_path.glob("checkpoint_*.zip"))
        assert len(files) == 2
        restored = restore_multi_layer_network(cp.last_checkpoint())
        assert restored.iteration == 10

    def test_keep_every_n(self, rng, tmp_path):
        net = small_net()
        cp = CheckpointListener(tmp_path, save_every_n_iterations=1,
                                keep_last=1, keep_every_n=3)
        net.set_listeners(cp)
        x = rng.normal(size=(24, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 24)]
        net.fit(ListDataSetIterator(DataSet(x, y), 4))  # 6 iterations/saves
        nums = sorted(int(p.name.split("_")[1])
                      for p in tmp_path.glob("checkpoint_*.zip"))
        assert nums == [3, 6]


class TestMultiNormalizer:
    def test_per_input_standardize_and_revert(self):
        from deeplearning4j_tpu.datasets import MultiNormalizer
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        rng = np.random.default_rng(0)
        f1 = rng.normal(5.0, 3.0, size=(64, 4)).astype(np.float32)
        f2 = rng.normal(-2.0, 0.5, size=(64, 6)).astype(np.float32)
        y = rng.normal(size=(64, 2)).astype(np.float32)
        mds = MultiDataSet([f1, f2], [y])
        norm = MultiNormalizer("standardize").fit(mds)
        out = norm.transform(mds)
        for f in out.features:
            assert abs(float(np.mean(f))) < 0.1
            assert abs(float(np.std(f)) - 1.0) < 0.1
        back = norm.revert(out)
        np.testing.assert_allclose(back.features[0], f1, atol=1e-4)
        np.testing.assert_allclose(back.features[1], f2, atol=1e-4)

    def test_serde_round_trip(self):
        from deeplearning4j_tpu.datasets import MultiNormalizer
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        rng = np.random.default_rng(1)
        mds = MultiDataSet([rng.normal(size=(16, 3)).astype(np.float32)],
                           [rng.normal(size=(16, 1)).astype(np.float32)])
        norm = MultiNormalizer("minmax").fit(mds)
        d = norm.to_dict()
        norm2 = MultiNormalizer.from_dict(d)
        a = norm.transform(mds).features[0]
        b = norm2.transform(mds).features[0]
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_checkpoint_static_loaders(tmp_path):
    """CheckpointListener.loadCheckpointMLN / availableCheckpoints parity."""
    import numpy as np
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.optimize.listeners import CheckpointListener

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.listeners.append(CheckpointListener(
        tmp_path, save_every_n_iterations=1))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    for _ in range(3):
        net.fit(x, y)
    cps = CheckpointListener.available_checkpoints(tmp_path)
    assert [c["number"] for c in cps] == [1, 2, 3]
    assert cps[-1]["iteration"] == 3
    latest = CheckpointListener.load_checkpoint(tmp_path)
    np.testing.assert_allclose(np.asarray(latest.params[0]["W"]),
                               np.asarray(net.params[0]["W"]), rtol=1e-6)
    second = CheckpointListener.load_checkpoint(tmp_path, number=2)
    assert second.params is not None
    import pytest as _pytest
    with _pytest.raises(FileNotFoundError, match="no checkpoint number 9"):
        CheckpointListener.load_checkpoint(tmp_path, number=9)


def test_collect_scores_export(tmp_path):
    from deeplearning4j_tpu.optimize.listeners import (
        CollectScoresIterationListener)
    l = CollectScoresIterationListener()
    class M:  # minimal model stand-in
        score_ = 0.5
    for i in range(1, 4):
        M.score_ = 1.0 / i
        l.iteration_done(M, i, 0)
    p = tmp_path / "scores.csv"
    l.export_scores(p)
    lines = p.read_text().strip().splitlines()
    assert lines[0] == "iteration,score" and len(lines) == 4
    assert lines[1].startswith("1,")
