"""Constraints, weight noise, and dropout variants.

Reference: nn/conf/constraint/ (MaxNorm/MinMaxNorm/NonNeg/UnitNorm applied
post-update), nn/conf/weightnoise/ (WeightNoise/DropConnect applied to
weights at train forward time), nn/conf/dropout/ (Alpha/Gaussian dropout +
GaussianNoise as real implementations, not plain-dropout approximations).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.constraints import (
    MaxNormConstraint,
    MinMaxNormConstraint,
    NonNegativeConstraint,
    UnitNormConstraint,
)
from deeplearning4j_tpu.nn.dropout import (
    AlphaDropout,
    Dropout,
    GaussianDropout,
    GaussianNoise,
    SpatialDropout,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.nn.weightnoise import DropConnect, WeightNoise
from deeplearning4j_tpu.nn.weights import Distribution


def _col_norms(w):
    return np.sqrt((np.asarray(w) ** 2).sum(axis=0))


class TestConstraintMath:
    """Per-constraint projection math (MaxNormConstraint.java:21 family)."""

    def test_max_norm(self):
        w = jnp.asarray(np.random.default_rng(0).normal(0, 3, (10, 6)),
                        jnp.float32)
        out = MaxNormConstraint(max_norm=1.5).apply(w)
        norms = _col_norms(out)
        assert (norms <= 1.5 + 1e-4).all()
        # columns already under the cap are (nearly) unchanged
        before = _col_norms(w)
        for j in range(6):
            if before[j] <= 1.5:
                np.testing.assert_allclose(np.asarray(out)[:, j],
                                           np.asarray(w)[:, j], rtol=1e-4)

    def test_min_max_norm_and_rate(self):
        w = jnp.asarray(np.random.default_rng(1).normal(0, 0.01, (8, 4)),
                        jnp.float32)
        out = MinMaxNormConstraint(min_norm=0.5, max_norm=1.0).apply(w)
        norms = _col_norms(out)
        assert (norms >= 0.5 - 1e-3).all() and (norms <= 1.0 + 1e-3).all()
        # rate blends toward the projection: rate=0.5 lands halfway
        half = MinMaxNormConstraint(min_norm=0.5, max_norm=1.0,
                                    rate=0.5).apply(w)
        full_scale = np.asarray(out) / np.asarray(w)
        half_scale = np.asarray(half) / np.asarray(w)
        np.testing.assert_allclose(half_scale, 0.5 * full_scale + 0.5,
                                   rtol=1e-4)
        with pytest.raises(ValueError):
            MinMaxNormConstraint(rate=0.0)

    def test_unit_norm(self):
        w = jnp.asarray(np.random.default_rng(2).normal(0, 2, (5, 7)),
                        jnp.float32)
        out = UnitNormConstraint().apply(w)
        np.testing.assert_allclose(_col_norms(out), 1.0, atol=1e-4)

    def test_non_negative(self):
        w = jnp.asarray([[-1.0, 2.0], [3.0, -4.0]], jnp.float32)
        out = NonNegativeConstraint().apply(w)
        np.testing.assert_allclose(np.asarray(out), [[0.0, 2.0], [3.0, 0.0]])

    def test_conv_layout_reduces_over_all_but_last(self):
        # conv W is [kh, kw, in, out]: per-filter norms, Keras axis=[0,1,2]
        w = jnp.asarray(np.random.default_rng(3).normal(0, 2, (3, 3, 4, 5)),
                        jnp.float32)
        out = np.asarray(MaxNormConstraint(max_norm=1.0).apply(w))
        norms = np.sqrt((out ** 2).sum(axis=(0, 1, 2)))
        assert (norms <= 1.0 + 1e-4).all()

    def test_explicit_dimensions(self):
        w = jnp.asarray(np.random.default_rng(4).normal(0, 2, (6, 4)),
                        jnp.float32)
        out = np.asarray(UnitNormConstraint(dimensions=(1,)).apply(w))
        np.testing.assert_allclose(np.sqrt((out ** 2).sum(axis=1)), 1.0,
                                   atol=1e-4)


class TestConstraintsInTraining:
    """Constraints run INSIDE the jitted step after the updater
    (builder hooks NeuralNetConfiguration.java:1031-1060)."""

    def _fit(self, builder_mutator, steps=5, lr=0.5):
        b = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(lr)))
        builder_mutator(b)
        conf = (b.list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(5)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        for _ in range(steps):
            net.fit(DataSet(x, y))
        return net

    def test_constrain_weights_max_norm(self):
        net = self._fit(lambda b: b.constrain_weights(
            MaxNormConstraint(max_norm=0.7)))
        for p in net.params:
            assert (_col_norms(p["W"]) <= 0.7 + 1e-3).all()
            # biases NOT constrained by constrain_weights
        # big-lr training without the constraint violates the cap (sanity)
        free = self._fit(lambda b: b)
        assert any((_col_norms(p["W"]) > 0.7).any() for p in free.params)

    def test_constrain_bias_only_touches_bias(self):
        net = self._fit(lambda b: b.constrain_bias(NonNegativeConstraint()))
        for p in net.params:
            assert (np.asarray(p["b"]) >= 0).all()
        assert any((np.asarray(p["W"]) < 0).any() for p in net.params)

    def test_constrain_all(self):
        net = self._fit(lambda b: b.constrain_all_parameters(
            MaxNormConstraint(max_norm=0.5)))
        for p in net.params:
            for v in p.values():
                if np.asarray(v).ndim == 1:
                    assert np.sqrt((np.asarray(v) ** 2).sum()) <= 0.5 + 1e-3
                else:
                    assert (_col_norms(v) <= 0.5 + 1e-3).all()

    def test_per_layer_constraints_field(self):
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.5))
                .list()
                .layer(DenseLayer(n_out=8, activation="tanh",
                                  constraints=[UnitNormConstraint()]))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(5)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(DataSet(x, y))
        np.testing.assert_allclose(_col_norms(net.params[0]["W"]), 1.0,
                                   atol=1e-3)
        # second layer has no constraints
        assert not np.allclose(_col_norms(net.params[1]["W"]), 1.0, atol=1e-3)

    def test_serde_round_trip(self):
        conf = (NeuralNetConfiguration.builder().seed(0)
                .constrain_weights(MinMaxNormConstraint(min_norm=0.1,
                                                        max_norm=2.0))
                .list()
                .layer(DenseLayer(n_out=4))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(3)).build())
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        c = conf2.layers[0].constraints[0]
        assert isinstance(c, MinMaxNormConstraint)
        assert c.min_norm == 0.1 and c.max_norm == 2.0 and c.scope == "weights"

    def test_wrapped_layer_constraints_enforced(self):
        # LastTimeStep / Bidirectional wrappers must delegate constraint
        # application to their inner layer (the Keras import shape)
        from deeplearning4j_tpu.nn.layers import LSTMLayer
        from deeplearning4j_tpu.nn.layers.recurrent import (
            BidirectionalWrapper, LastTimeStepWrapper)
        from deeplearning4j_tpu.nn.constraints import apply_constraints
        inner = LSTMLayer(n_in=4, n_out=3,
                          constraints=[MaxNormConstraint(
                              max_norm=0.1, param_names=("W",))])
        wrapper = LastTimeStepWrapper(layer=inner)
        params = {"W": jnp.ones((4, 12)), "RW": jnp.ones((3, 12)),
                  "b": jnp.zeros((12,))}
        out = apply_constraints(wrapper, params)
        assert (_col_norms(out["W"]) <= 0.1 + 1e-4).all()
        np.testing.assert_allclose(np.asarray(out["RW"]), 1.0)  # untouched
        bi = BidirectionalWrapper(layer=inner)
        bparams = {f"{pre}{k}": v for pre in ("f_", "b_")
                   for k, v in params.items()}
        bout = apply_constraints(bi, bparams)
        for pre in ("f_", "b_"):
            assert (_col_norms(bout[pre + "W"]) <= 0.1 + 1e-4).all()
            np.testing.assert_allclose(np.asarray(bout[pre + "RW"]), 1.0)

    def test_graph_output_layer_weight_noise_trains(self):
        # weight noise inherited onto a ComputationGraph OUTPUT layer must
        # not crash the jitted step (fold_in key derivation) and must train
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        g = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
             .weight_noise(DropConnect(p=0.9))
             .graph_builder()
             .add_inputs("in")
             .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_out=3), "d")
             .set_outputs("out")
             .set_input_types(InputType.feed_forward(5)).build())
        net = ComputationGraph(g).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(x, y)
        assert np.isfinite(float(net.score_))

    def test_graph_constraints(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        g = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.5))
             .constrain_weights(MaxNormConstraint(max_norm=0.6))
             .graph_builder()
             .add_inputs("in")
             .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_out=3), "d")
             .set_outputs("out")
             .set_input_types(InputType.feed_forward(5)).build())
        net = ComputationGraph(g).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        for _ in range(5):
            net.fit(x, y)
        for name in ("d", "out"):
            assert (_col_norms(net.params[name]["W"]) <= 0.6 + 1e-3).all()


class TestWeightNoise:
    """IWeightNoise applied to weights at train forward time
    (weightnoise/WeightNoise.java, DropConnect.java:19)."""

    def test_drop_connect_zeroes_without_rescale(self):
        w = jnp.ones((100, 100), jnp.float32)
        out = np.asarray(DropConnect(p=0.7).apply_param(
            w, jax.random.PRNGKey(0)))
        kept = out != 0.0
        assert abs(kept.mean() - 0.7) < 0.03
        # NO inverted rescale: survivors keep their exact value (ND4J DropOut
        # op semantics, unlike activation dropout's 1/p scaling)
        np.testing.assert_allclose(out[kept], 1.0)

    def test_weight_noise_additive_and_multiplicative(self):
        w = jnp.full((200, 200), 3.0, jnp.float32)
        dist = Distribution(kind="normal", mean=0.0, std=0.5)
        add = np.asarray(WeightNoise(distribution=dist).apply_param(
            w, jax.random.PRNGKey(1)))
        assert abs((add - 3.0).mean()) < 0.02 and abs((add - 3.0).std() - 0.5) < 0.02
        mul = np.asarray(WeightNoise(distribution=dist, additive=False)
                         .apply_param(w, jax.random.PRNGKey(2)))
        assert abs(mul.mean() - 0.0) < 0.05  # 3 * N(0, .5) has mean 0

    def test_bias_scope(self):
        layer = DenseLayer(n_in=4, n_out=3)
        params = {"W": jnp.ones((4, 3)), "b": jnp.ones((3,))}
        noised = DropConnect(p=0.5).apply(layer, params, jax.random.PRNGKey(0),
                                          train=True)
        np.testing.assert_allclose(np.asarray(noised["b"]), 1.0)  # untouched
        noised2 = DropConnect(p=0.5, apply_to_bias=True).apply(
            layer, params, jax.random.PRNGKey(3), train=True)
        assert (np.asarray(noised2["b"]) == 0).any() or True  # may be all kept
        # train=False is identity
        clean = DropConnect(p=0.5).apply(layer, params, jax.random.PRNGKey(0),
                                         train=False)
        assert clean is params

    def test_train_vs_inference_in_network(self):
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
                .weight_noise(DropConnect(p=0.5))
                .list()
                .layer(DenseLayer(n_out=16, activation="identity",
                                  has_bias=False))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        # inference path: deterministic, clean weights
        o1, o2 = np.asarray(net.output(x)), np.asarray(net.output(x))
        np.testing.assert_allclose(o1, o2)
        # training path: the noised step still trains (finite score, params move)
        w0 = np.asarray(net.params[0]["W"]).copy()
        net.fit(DataSet(x, y))
        assert np.isfinite(float(net.score_))
        assert not np.allclose(w0, np.asarray(net.params[0]["W"]))

    def test_serde(self):
        conf = (NeuralNetConfiguration.builder().seed(0)
                .weight_noise(WeightNoise(
                    distribution=Distribution(kind="normal", std=0.1),
                    additive=False))
                .list()
                .layer(DenseLayer(n_out=4))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(3)).build())
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        wn = conf2.layers[0].weight_noise
        assert isinstance(wn, WeightNoise) and not wn.additive
        assert wn.distribution.std == 0.1


class TestDropoutVariants:
    def test_plain_dropout_inverted_scaling(self):
        x = jnp.ones((1000,), jnp.float32)
        out = np.asarray(Dropout(p=0.8).apply(x, jax.random.PRNGKey(0), True))
        kept = out != 0
        assert abs(kept.mean() - 0.8) < 0.05
        np.testing.assert_allclose(out[kept], 1.0 / 0.8, rtol=1e-6)

    def test_alpha_dropout_preserves_moments(self):
        # AlphaDropout.java:38 / SNN paper pg6: mean AND variance of N(0,1)
        # activations are preserved in expectation
        x = jax.random.normal(jax.random.PRNGKey(1), (200_000,), jnp.float32)
        ad = AlphaDropout(p=0.9)
        out = np.asarray(ad.apply(x, jax.random.PRNGKey(2), True))
        assert abs(out.mean()) < 0.02
        assert abs(out.std() - 1.0) < 0.02
        # dropped positions carry a·α' + b, not zero
        dropped_value = ad.a(0.9) * ad.alpha_prime + ad.b(0.9)
        vals = np.unique(np.round(out, 4))
        assert np.min(np.abs(vals - round(dropped_value, 4))) < 1e-3

    def test_alpha_dropout_constants_match_reference_formulas(self):
        ad = AlphaDropout(p=0.5)
        ap = ad.alpha_prime
        assert np.isclose(ap, -1.0507009873554804 * 1.6732632423543772)
        assert np.isclose(ad.a(0.5), 1.0 / np.sqrt(0.5 + ap * ap * 0.25))
        assert np.isclose(ad.b(0.5), -ad.a(0.5) * 0.5 * ap)

    def test_gaussian_dropout_multiplicative(self):
        x = jnp.full((100_000,), 2.0, jnp.float32)
        out = np.asarray(GaussianDropout(rate=0.5).apply(
            x, jax.random.PRNGKey(3), True))
        assert abs(out.mean() - 2.0) < 0.05         # E[x·N(1,s)] = x
        assert abs(out.std() - 2.0 * 1.0) < 0.05    # s = sqrt(.5/.5) = 1

    def test_gaussian_noise_additive(self):
        x = jnp.zeros((100_000,), jnp.float32)
        out = np.asarray(GaussianNoise(stddev=0.3).apply(
            x, jax.random.PRNGKey(4), True))
        assert abs(out.mean()) < 0.01 and abs(out.std() - 0.3) < 0.01

    def test_spatial_dropout_drops_whole_channels(self):
        x = jnp.ones((4, 5, 5, 32), jnp.float32)
        out = np.asarray(SpatialDropout(p=0.6).apply(
            x, jax.random.PRNGKey(5), True))
        # each (example, channel) is uniformly zero or uniformly 1/p
        per_chan = out.reshape(4, 25, 32)
        assert ((per_chan == 0).all(axis=1) | (per_chan > 0).all(axis=1)).all()
        kept = per_chan[:, 0, :] != 0
        np.testing.assert_allclose(per_chan[:, :, :][kept[:, None, :]
                                   .repeat(25, 1)], 1.0 / 0.6, rtol=1e-6)
        with pytest.raises(ValueError):
            SpatialDropout(p=0.5).apply(jnp.ones((4, 8)),
                                        jax.random.PRNGKey(0), True)

    def test_inference_is_identity(self):
        x = jax.random.normal(jax.random.PRNGKey(6), (64,), jnp.float32)
        for d in (Dropout(0.5), AlphaDropout(0.5), GaussianDropout(0.5),
                  GaussianNoise(0.2)):
            np.testing.assert_allclose(
                np.asarray(d.apply(x, jax.random.PRNGKey(7), False)),
                np.asarray(x))

    def test_gradients_through_fixed_mask(self):
        # with the rng key fixed the mask is constant, so autodiff gradients
        # must match central finite differences (gradient-check tier)
        key = jax.random.PRNGKey(8)
        with jax.enable_x64(True):
            for d in (AlphaDropout(0.7), GaussianDropout(0.3),
                      GaussianNoise(0.2), Dropout(0.6)):
                def f(x):
                    return jnp.sum(d.apply(x, key, True) ** 2)
                x = jnp.asarray(np.random.default_rng(0).normal(size=(20,)),
                                jnp.float64)
                g = np.asarray(jax.grad(f)(x))
                eps = 1e-6
                for i in range(0, 20, 5):
                    xp = x.at[i].add(eps)
                    xm = x.at[i].add(-eps)
                    fd = (float(f(xp)) - float(f(xm))) / (2 * eps)
                    assert abs(fd - g[i]) < 1e-4, (type(d).__name__, i, fd, g[i])

    def test_layer_field_and_serde(self):
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_out=8, activation="selu",
                                  dropout=AlphaDropout(p=0.9)))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(5)).build())
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        d = conf2.layers[0].dropout
        assert isinstance(d, AlphaDropout) and d.p == 0.9
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(DataSet(x, y))
        assert np.isfinite(float(net.score_))

    def test_scheduled_p_follows_the_tick(self):
        """Dropout.java:45,68 pSchedule: the retain probability is a
        function of the train step's (iteration, epoch) tick."""
        from deeplearning4j_tpu.nn.tick import schedule_tick
        from deeplearning4j_tpu.nn.updaters import MapSchedule
        d = Dropout(p=MapSchedule(values=((0, 1.0), (3, 0.5))))
        x = jnp.ones((4000,), jnp.float32)
        key = jax.random.PRNGKey(0)
        with schedule_tick(jnp.asarray(0.0), jnp.asarray(0.0)):
            early = np.asarray(d.apply(x, key, True))
        with schedule_tick(jnp.asarray(5.0), jnp.asarray(0.0)):
            late = np.asarray(d.apply(x, key, True))
        np.testing.assert_allclose(early, 1.0)  # p=1.0: nothing dropped
        kept = late != 0
        assert abs(kept.mean() - 0.5) < 0.05
        np.testing.assert_allclose(late[kept], 2.0, rtol=1e-6)

    def test_scheduled_stddev_matches_formula_exactly(self):
        from deeplearning4j_tpu.nn.tick import schedule_tick
        from deeplearning4j_tpu.nn.updaters import ExponentialSchedule
        sched = ExponentialSchedule(initial_value=0.4, gamma=0.5)
        gn = GaussianNoise(stddev=sched)
        x = jnp.zeros((512,), jnp.float32)
        key = jax.random.PRNGKey(3)
        for it in (0.0, 1.0, 4.0):
            with schedule_tick(jnp.asarray(it), jnp.asarray(0.0)):
                out = np.asarray(gn.apply(x, key, True))
            expect = float(0.4 * 0.5 ** it) * np.asarray(
                jax.random.normal(key, x.shape, x.dtype))
            np.testing.assert_allclose(out, expect, rtol=1e-6)

    def test_fixed_schedule_equals_plain_float_training(self):
        """Schedule machinery adds nothing: FixedSchedule(0.6) trains to
        EXACTLY the same params as Dropout(0.6)."""
        from deeplearning4j_tpu.nn.updaters import FixedSchedule

        def build(drop):
            conf = (NeuralNetConfiguration.builder().seed(11).updater(Sgd(0.1))
                    .list()
                    .layer(DenseLayer(n_in=5, n_out=8, activation="tanh",
                                      dropout=drop))
                    .layer(OutputLayer(n_in=8, n_out=3))
                    .build())
            return MultiLayerNetwork(conf).init()

        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        a = build(Dropout(0.6))
        b = build(Dropout(FixedSchedule(value_=0.6)))
        for _ in range(3):
            a.fit(DataSet(x, y))
            b.fit(DataSet(x, y))
        for pa, pb in zip(a.params, b.params):
            for k in pa:
                np.testing.assert_array_equal(np.asarray(pa[k]),
                                              np.asarray(pb[k]))

    def test_scheduled_dropout_trains_in_jitted_step(self):
        """The schedule traces into the jitted step (no retrace per
        iteration) and the loss stays finite across schedule breakpoints."""
        from deeplearning4j_tpu.nn.updaters import StepSchedule
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.05))
                .list()
                .layer(DenseLayer(n_in=5, n_out=8, activation="relu",
                                  dropout=Dropout(
                                      StepSchedule(initial_value=0.9,
                                                   decay_rate=0.5, step=2.0))))
                .layer(OutputLayer(n_in=8, n_out=3))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(2)
        x = rng.normal(size=(8, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        for _ in range(5):
            net.fit(DataSet(x, y))
            assert np.isfinite(float(net.score_))

    def test_scheduled_dropout_serde_round_trip(self):
        from deeplearning4j_tpu.nn.updaters import MapSchedule
        sched = MapSchedule(values=((0, 0.9), (10, 0.5)))
        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
                .list()
                .layer(DenseLayer(n_in=5, n_out=4,
                                  dropout=GaussianDropout(rate=sched)))
                .layer(OutputLayer(n_in=4, n_out=2))
                .build())
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        d = conf2.layers[0].dropout
        assert isinstance(d, GaussianDropout)
        assert isinstance(d.rate, MapSchedule)
        assert tuple(map(tuple, d.rate.values)) == ((0, 0.9), (10, 0.5))


class TestScheduleTickInParallelPaths:
    def test_pure_step_sees_the_tick(self):
        """parallel/trainer.make_pure_step (the ParallelWrapper/
        SharedTrainingMaster building block) must evaluate dropout
        schedules at ITS (it, ep) arguments, not freeze them at (0,0)."""
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.updaters import MapSchedule
        from deeplearning4j_tpu.parallel.trainer import make_pure_step

        def build(drop):
            conf = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.0))
                    .list()
                    .layer(DenseLayer(n_in=5, n_out=16, activation="tanh",
                                      dropout=drop))
                    .layer(OutputLayer(n_in=16, n_out=3))
                    .build())
            return MultiLayerNetwork(conf).init()

        sched_net = build(Dropout(MapSchedule(values=((0, 1.0), (3, 0.5)))))
        plain_net = build(None)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(8, 5)).astype(np.float32))
        y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)])
        key = jax.random.PRNGKey(0)

        def loss_at(net, it):
            step = make_pure_step(net)
            out = step(net.params, net.states, net.updater_states,
                       jnp.asarray(float(it)), jnp.asarray(0.0),
                       x, y, None, None, key)
            return float(out[3])

        # iteration 0: scheduled p=1.0 == no dropout, losses equal exactly
        assert loss_at(sched_net, 0) == loss_at(plain_net, 0)
        # iteration 5: p=0.5 — dropout active, loss must differ
        assert loss_at(sched_net, 5) != loss_at(plain_net, 5)

    def test_out_of_range_schedule_saturates_not_nan(self):
        """A schedule decaying retain-p toward 0 saturates at the clamp
        instead of emitting division-by-zero NaNs."""
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.tick import schedule_tick
        from deeplearning4j_tpu.nn.updaters import StepSchedule
        d = Dropout(p=StepSchedule(initial_value=0.5, decay_rate=0.0,
                                   step=1.0))  # p == 0 from iteration 1 on
        x = jnp.ones((64,), jnp.float32)
        with schedule_tick(jnp.asarray(10.0), jnp.asarray(0.0)):
            out = np.asarray(d.apply(x, jax.random.PRNGKey(0), True))
        assert np.isfinite(out).all()
        g = GaussianDropout(rate=StepSchedule(initial_value=2.0,
                                              decay_rate=1.0, step=1.0))
        with schedule_tick(jnp.asarray(0.0), jnp.asarray(0.0)):
            out = np.asarray(g.apply(x, jax.random.PRNGKey(1), True))
        assert np.isfinite(out).all()
