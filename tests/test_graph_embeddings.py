"""Graph embeddings tests — mirrors the reference's deeplearning4j-graph test
suite (TestGraphLoading, TestGraphHuffman, DeepWalkGradientCheck, TestDeepWalk)."""

import numpy as np
import pytest

from deeplearning4j_tpu.graph import (
    DeepWalk,
    Edge,
    Graph,
    GraphHuffman,
    GraphLoader,
    GraphVectorSerializer,
    NoEdgeHandling,
    NoEdgesException,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)


def _ring_graph(n=10):
    g = Graph(n)
    for i in range(n):
        g.add_edge(i, (i + 1) % n)
    return g


class TestGraphStructure:
    def test_add_edge_undirected_both_sides(self):
        g = Graph(4)
        g.add_edge(0, 1)
        assert g.get_vertex_degree(0) == 1
        assert g.get_vertex_degree(1) == 1
        assert list(g.get_connected_vertex_indices(1)) == [0]

    def test_directed_edge_one_side(self):
        g = Graph(3)
        g.add_edge(0, 1, directed=True)
        assert g.get_vertex_degree(0) == 1
        assert g.get_vertex_degree(1) == 0

    def test_no_multiple_edges(self):
        g = Graph(3, allow_multiple_edges=False)
        g.add_edge(0, 1)
        g.add_edge(0, 1)
        assert g.get_vertex_degree(0) == 1

    def test_loader_edge_list(self, tmp_path):
        p = tmp_path / "edges.txt"
        p.write_text("0,1\n1,2\n2,3\n3,0\n")
        g = GraphLoader.load_undirected_graph_edge_list_file(str(p), 4)
        assert g.num_vertices() == 4
        for v in range(4):
            assert g.get_vertex_degree(v) == 2

    def test_loader_weighted(self, tmp_path):
        p = tmp_path / "edges.txt"
        p.write_text("# comment\n0,1,1.5\n1,2,2.5\n")
        g = GraphLoader.load_weighted_edge_list_file(str(p), 3, directed=True)
        edges = g.get_edges_out(0)
        assert len(edges) == 1 and edges[0].weight() == 1.5
        assert g.get_vertex_degree(2) == 0

    def test_vertex_and_edge_files(self, tmp_path):
        vp, ep = tmp_path / "v.txt", tmp_path / "e.txt"
        vp.write_text("0:alpha\n1:beta\n2:gamma\n")
        ep.write_text("0,1\n1,2\n")
        g = GraphLoader.load_graph_from_vertex_and_edge_files(str(vp), str(ep))
        assert g.num_vertices() == 3
        assert g.get_vertex(1).get_value() == "beta"


class TestRandomWalks:
    def test_walk_length_and_edges(self):
        g = _ring_graph(12)
        it = RandomWalkIterator(g, walk_length=5, seed=7)
        count = 0
        starts = set()
        for seq in it:
            idx = seq.indices()
            assert len(idx) == 6
            starts.add(idx[0])
            for a, b in zip(idx, idx[1:]):
                assert b in set(g.get_connected_vertex_indices(a))
            count += 1
        # one walk starting at each vertex exactly once
        assert count == 12 and starts == set(range(12))

    def test_disconnected_exception(self):
        g = Graph(3)
        g.add_edge(0, 1)
        it_args = dict(walk_length=3, seed=1)
        with pytest.raises(NoEdgesException):
            RandomWalkIterator(g, mode=NoEdgeHandling.EXCEPTION_ON_DISCONNECTED, **it_args)

    def test_disconnected_self_loop(self):
        g = Graph(3)
        g.add_edge(0, 1)
        it = RandomWalkIterator(g, walk_length=3, seed=1,
                                mode=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED)
        for seq in it:
            idx = seq.indices()
            if idx[0] == 2:  # isolated vertex self-loops
                assert idx == [2, 2, 2, 2]

    def test_weighted_walk_avoids_zero_weight(self):
        # vertex 0 connects to 1 (weight 0) and 2 (weight 5): never walk to 1
        g = Graph(3)
        g.add_edge(0, 1, value=0.0, directed=True)
        g.add_edge(0, 2, value=5.0, directed=True)
        g.add_edge(2, 0, value=1.0, directed=True)
        it = WeightedRandomWalkIterator(g, walk_length=20, seed=3,
                                        mode=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED)
        for seq in it:
            assert 1 not in seq.indices()[1:] or seq.indices()[0] == 1


class TestGraphHuffman:
    def test_prefix_free_and_degree_ordering(self):
        degrees = [1, 50, 3, 2, 1, 100, 2, 1]
        gh = GraphHuffman(len(degrees)).build_tree(degrees)
        codes = [gh.get_code_string(i) for i in range(len(degrees))]
        # prefix-free
        for i, c1 in enumerate(codes):
            for j, c2 in enumerate(codes):
                if i != j:
                    assert not c2.startswith(c1)
        # highest-degree vertex gets the shortest code
        lens = [gh.get_code_length(i) for i in range(len(degrees))]
        assert lens[5] == min(lens)
        assert lens[1] <= lens[0]

    def test_path_inner_nodes_consistent(self):
        degrees = [4, 2, 7, 1, 9, 3]
        gh = GraphHuffman(len(degrees)).build_tree(degrees)
        for v in range(len(degrees)):
            path = gh.get_path_inner_nodes(v)
            assert len(path) == gh.get_code_length(v)
            assert path[0] == 0  # root is inner node 0
            assert all(0 <= p < len(degrees) - 1 for p in path)

    def test_path_arrays_match_scalar_api(self):
        degrees = [4, 2, 7, 1, 9, 3]
        gh = GraphHuffman(len(degrees)).build_tree(degrees)
        nodes, bits, mask = gh.path_arrays()
        for v in range(len(degrees)):
            cl = gh.get_code_length(v)
            assert mask[v].sum() == cl
            assert list(nodes[v][:cl]) == gh.get_path_inner_nodes(v)
            for i in range(cl):
                assert bits[v, i] == ((gh.get_code(v) >> i) & 1)


class TestDeepWalk:
    def test_probabilities_sum_to_one(self):
        g = _ring_graph(8)
        dw = DeepWalk(vector_size=6, window_size=1, learning_rate=0.05, seed=1)
        dw.initialize(g)
        total = sum(dw.lookup_table.calculate_prob(2, j) for j in range(8))
        assert abs(total - 1.0) < 1e-6

    def test_gradient_check(self):
        """vectorsAndGradients vs central finite differences of
        score = -log P(second|first) — DeepWalkGradientCheck parity."""
        g = _ring_graph(7)
        dw = DeepWalk(vector_size=5, window_size=1, seed=3)
        dw.initialize(g)
        table = dw.lookup_table
        first, second = 1, 4
        vectors, grads = table.vectors_and_gradients(first, second)
        eps = 1e-5
        base_vec = np.array(table.get_vector(first))
        for d in range(5):
            vv = np.asarray(table.get_vertex_vectors()).copy()
            vv[first, d] = base_vec[d] + eps
            table.set_vertex_vectors(vv)
            s_plus = table.calculate_score(first, second)
            vv[first, d] = base_vec[d] - eps
            table.set_vertex_vectors(vv)
            s_minus = table.calculate_score(first, second)
            vv[first, d] = base_vec[d]
            table.set_vertex_vectors(vv)
            numeric = (s_plus - s_minus) / (2 * eps)
            assert abs(numeric - grads[0][d]) < 1e-4, f"dim {d}"

    def test_fit_improves_neighbor_probability(self):
        g = _ring_graph(10)
        dw = DeepWalk(vector_size=8, window_size=1, learning_rate=0.1, seed=5)
        dw.initialize(g)
        before = np.mean([dw.lookup_table.calculate_prob(i, (i + 1) % 10)
                          for i in range(10)])
        dw.fit(g, walk_length=8, epochs=30)
        after = np.mean([dw.lookup_table.calculate_prob(i, (i + 1) % 10)
                         for i in range(10)])
        assert after > before

    def test_two_cluster_similarity(self):
        # two dense clusters joined by one edge: intra-cluster similarity must
        # exceed inter-cluster after training (TestDeepWalk pattern)
        g = Graph(10)
        for c in (0, 5):
            for i in range(c, c + 5):
                for j in range(i + 1, c + 5):
                    g.add_edge(i, j)
        g.add_edge(4, 5)
        dw = DeepWalk(vector_size=16, window_size=2, learning_rate=0.05, seed=11)
        dw.fit(g, walk_length=10, epochs=40)
        intra = np.mean([dw.similarity(0, j) for j in range(1, 5)])
        inter = np.mean([dw.similarity(0, j) for j in range(5, 10)])
        assert intra > inter

    def test_vertices_nearest(self):
        g = _ring_graph(6)
        dw = DeepWalk(vector_size=4, seed=2)
        dw.initialize(g)
        near = dw.vertices_nearest(0, 3)
        assert len(near) == 3 and 0 not in near

    def test_builder(self):
        dw = (DeepWalk.Builder().vector_size(32).window_size(3)
              .learning_rate(0.2).seed(9).build())
        assert dw.get_vector_size() == 32
        assert dw.get_window_size() == 3
        assert dw.get_learning_rate() == 0.2

    def test_serializer_round_trip(self, tmp_path):
        g = _ring_graph(5)
        dw = DeepWalk(vector_size=4, seed=8)
        dw.initialize(g)
        path = str(tmp_path / "vecs.txt")
        GraphVectorSerializer.write_graph_vectors(dw, path)
        loaded = GraphVectorSerializer.load_txt_vectors(path)
        assert loaded.num_vertices() == 5
        assert loaded.get_vector_size() == 4
        np.testing.assert_allclose(loaded.get_vertex_vector(3),
                                   dw.get_vertex_vector(3), rtol=1e-6)
