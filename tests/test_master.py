"""TrainingMaster tests on the virtual 8-device CPU mesh.

Mirrors the reference's local-mode Spark equivalence strategy
(TestCompareParameterAveragingSparkVsSingleMachine: distributed result must
match single-machine SGD)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.parallel import (
    DistributedMultiLayerNetwork,
    ParameterAveragingTrainingMaster,
    SharedTrainingMaster,
)
from deeplearning4j_tpu.parallel.mesh import make_mesh


def _net(seed=7, lr=0.1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr)).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    y_idx = rng.integers(0, 3, n)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    x[np.arange(n), y_idx] += 2.5
    y = np.eye(3, dtype=np.float32)[y_idx]
    return DataSet(x, y)


class TestParameterAveragingMaster:
    def test_matches_single_machine_sgd(self):
        """averaging_frequency=1 + plain SGD: averaging params after one step
        per worker == one step on the averaged gradient == single-machine
        step on the full batch (the reference's Spark-vs-local lock)."""
        ds = _data(64)
        mesh = make_mesh({"data": 8})

        local = _net(seed=3)
        local.fit(ds)  # one full-batch step

        dist_net = _net(seed=3)
        master = ParameterAveragingTrainingMaster(
            batch_size_per_worker=8, averaging_frequency=1, mesh=mesh)
        DistributedMultiLayerNetwork(dist_net, master).fit([ds])

        for pl, pd in zip(local.params, dist_net.params):
            for k in pl:
                np.testing.assert_allclose(np.asarray(pl[k]), np.asarray(pd[k]),
                                           rtol=2e-4, atol=2e-5)

    def test_split_sizing_and_training(self):
        ds = _data(300)
        mesh = make_mesh({"data": 8})
        net = _net()
        master = (ParameterAveragingTrainingMaster.Builder(8)
                  .averaging_frequency(3).build())
        master.mesh = mesh
        master.num_workers = 8
        front = DistributedMultiLayerNetwork(net, master)
        front.fit([ds], epochs=10)
        ev = net.evaluate(ListDataSetIterator(ds, 128))
        assert ev.accuracy() > 0.85
        stats = front.get_training_stats().as_dict()
        assert "fit" in stats and "split" in stats

    def test_worker_divisible_tail_split(self):
        """96 examples with per_round=64 leaves a 32-example tail that divides
        the worker count: must train, not crash on stacking mixed shapes."""
        ds = _data(96)
        mesh = make_mesh({"data": 8})
        net = _net()
        master = ParameterAveragingTrainingMaster(
            batch_size_per_worker=8, averaging_frequency=5, mesh=mesh)
        DistributedMultiLayerNetwork(net, master).fit([ds], epochs=2)
        assert net.iteration > 0

    def test_export_and_replay(self, tmp_path):
        ds = _data(64)
        master = ParameterAveragingTrainingMaster(
            batch_size_per_worker=8, export_directory=str(tmp_path),
            mesh=make_mesh({"data": 8}))
        master._repartition([ds])
        loaded = ParameterAveragingTrainingMaster.load_exported(str(tmp_path))
        assert loaded and loaded[0].features.shape == (64, 6)


class TestSharedTrainingMaster:
    def test_trains_with_threshold_compression(self):
        ds = _data(512)
        mesh = make_mesh({"data": 8})
        net = _net(lr=0.05)
        master = SharedTrainingMaster(batch_size_per_worker=16,
                                      threshold=1e-3, mesh=mesh)
        front = DistributedMultiLayerNetwork(net, master)
        it = ListDataSetIterator(ds, 128, shuffle=True, seed=1)
        front.fit(it, epochs=15)
        ev = net.evaluate(ListDataSetIterator(ds, 256))
        assert ev.accuracy() > 0.85

    def test_residual_preserved_between_steps(self):
        """Gradient mass below the threshold must accumulate in the residual,
        not vanish (EncodedGradientsAccumulator residual semantics)."""
        ds = _data(64)
        mesh = make_mesh({"data": 8})
        net = _net(lr=0.05)
        master = SharedTrainingMaster(batch_size_per_worker=8, threshold=1e6,
                                      mesh=mesh)  # nothing passes threshold
        p0 = [{k: np.asarray(v).copy() for k, v in layer.items()}
              for layer in net.params]
        DistributedMultiLayerNetwork(net, master).fit([ds])
        # params unchanged (no update passed the threshold)...
        for pl, pd in zip(p0, net.params):
            for k in pl:
                np.testing.assert_allclose(pl[k], np.asarray(pd[k]))
        # ...but the residual holds the pending update mass
        total = sum(float(np.abs(np.asarray(r)).sum())
                    for layer in master._residual for r in layer.values())
        assert total > 0

    def test_threshold_adapts(self):
        master = SharedTrainingMaster(batch_size_per_worker=8, threshold=1e-3,
                                      threshold_step=1e-4, step_delay=0,
                                      mesh=make_mesh({"data": 8}))
        t0 = master.threshold
        master._adapt_threshold(0.0)  # nothing transmitted → decay
        assert master.threshold < t0
        t1 = master.threshold
        master._adapt_threshold(0.5)  # too dense → raise
        assert master.threshold > t1

    def test_builder(self):
        m = (SharedTrainingMaster.Builder(32).update_threshold(5e-4)
             .min_update_threshold(1e-6).build())
        assert m.batch_size_per_worker == 32
        assert m.threshold == 5e-4
        assert m.min_threshold == 1e-6


class TestEarlyStoppingParallel:
    def test_parallel_early_stopping(self):
        """EarlyStoppingParallelTrainer: epochs run sharded over the mesh
        (EarlyStoppingParallelTrainer.java role)."""
        from deeplearning4j_tpu.optimize import (
            DataSetLossCalculator,
            EarlyStoppingConfiguration,
            EarlyStoppingParallelTrainer,
            InMemoryModelSaver,
            MaxEpochsTerminationCondition,
        )
        ds = _data(256)
        valid = _data(128, seed=9)
        net = _net()
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(
                ListDataSetIterator(valid, 64)),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(8)],
            model_saver=InMemoryModelSaver())
        trainer = EarlyStoppingParallelTrainer(
            cfg, net, ListDataSetIterator(ds, 64, shuffle=True),
            mesh=make_mesh({"data": 8}))
        result = trainer.fit()
        assert result.total_epochs <= 8
        ev = result.best_model.evaluate(ListDataSetIterator(valid, 128))
        assert ev.accuracy() > 0.8
        # the user's model was never mutated (no instance-attribute fit)
        assert "fit" not in net.__dict__


class TestTimeSource:
    """NTP-corrected clock (dl4j-spark time/NTPTimeSource.java parity):
    SNTP protocol against a local fake server; system-clock fallback."""

    def _fake_ntp_server(self, offset_s):
        import socket, struct, threading, time
        _DELTA = 2208988800
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]

        def serve():
            data, addr = sock.recvfrom(48)
            now = time.time() + offset_s       # server clock runs ahead
            resp = bytearray(48)
            resp[0] = 0x1C                     # LI=0 VN=3 Mode=4 (server)
            for off in (32, 40):               # receive + transmit stamps
                sec = int(now + _DELTA)
                frac = int(((now + _DELTA) % 1) * 2 ** 32)
                struct.pack_into(">II", resp, off, sec, frac)
            sock.sendto(bytes(resp), addr)
            sock.close()

        threading.Thread(target=serve, daemon=True).start()
        return port

    def test_offset_measured_from_fake_server(self):
        from deeplearning4j_tpu.parallel.time_source import NTPTimeSource
        port = self._fake_ntp_server(offset_s=5.0)
        ts = NTPTimeSource(server="127.0.0.1", port=port, timeout=3.0,
                           eager=False)
        assert ts.sync()
        assert 4000 < ts.offset_millis < 6000   # ~5 s, minus round trip
        import time
        assert abs(ts.current_time_millis()
                   - (time.time() + 5.0) * 1000) < 1500

    def test_unreachable_server_falls_back_to_system_clock(self):
        import time
        from deeplearning4j_tpu.parallel.time_source import NTPTimeSource
        ts = NTPTimeSource(server="127.0.0.1", port=9, timeout=0.2,
                           eager=False)
        assert not ts.sync()
        assert ts.last_error is not None
        assert ts.offset_millis == 0.0
        assert abs(ts.current_time_millis() - time.time() * 1000) < 1500

    def test_current_time_millis_never_blocks(self):
        # an expired window must NOT pay the SNTP round trip on the stamp
        # path (ADVICE r1): the refresh happens on a background thread
        import time
        from deeplearning4j_tpu.parallel.time_source import NTPTimeSource
        ts = NTPTimeSource(server="127.0.0.1", port=9, timeout=1.5,
                           update_frequency=0.0,   # every call is "expired"
                           eager=False)
        t0 = time.time()
        ts.current_time_millis()
        assert time.time() - t0 < 0.5              # returned before timeout
        # the background refresh does run and records its failure
        deadline = time.time() + 5.0
        while ts.last_error is None and time.time() < deadline:
            time.sleep(0.05)
        assert ts.last_error is not None

    def test_training_stats_events_use_time_source(self):
        from deeplearning4j_tpu.parallel.master import TrainingStats
        from deeplearning4j_tpu.parallel.time_source import TimeSource

        class Fixed(TimeSource):
            def current_time_millis(self):
                return 1_000_000

        st = TrainingStats(time_source=Fixed())
        st.add("fit", 2.0)
        phase, start, dur = st.events[0]
        assert phase == "fit" and dur == 2000 and start == 1_000_000 - 2000
        assert st.total("fit") == 2.0


class TestMasterStateCheckpoint:
    def test_save_load_state_resume_equality(self, tmp_path):
        """Compression state (adaptive threshold + residuals) saved at a
        step boundary and restored into a FRESH master resumes training
        bit-identically — the preemption-exact-resume contract the model
        checkpoint alone cannot satisfy (residuals would re-accumulate)."""
        ds = _data(128)
        mesh = make_mesh({"data": 8})

        # run A: 6 uninterrupted fit calls
        net_a = _net(lr=0.05)
        m_a = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                   step_delay=0, threshold_step=1e-4,
                                   mesh=mesh)
        fa = DistributedMultiLayerNetwork(net_a, m_a)
        for _ in range(6):
            fa.fit([ds])

        # run B: 3 fit calls, checkpoint (model + master state), fresh
        # master + restored state, 3 more
        net_b = _net(lr=0.05)
        m_b = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                   step_delay=0, threshold_step=1e-4,
                                   mesh=mesh)
        fb = DistributedMultiLayerNetwork(net_b, m_b)
        for _ in range(3):
            fb.fit([ds])
        state_path = str(tmp_path / "master.npz")
        m_b.save_state(state_path)
        m_b2 = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                    step_delay=0, threshold_step=1e-4,
                                    mesh=mesh)
        m_b2.load_state(state_path)
        assert m_b2.threshold == m_b.threshold
        assert m_b2._steps_done == m_b._steps_done
        fb2 = DistributedMultiLayerNetwork(net_b, m_b2)
        for _ in range(3):
            fb2.fit([ds])

        for pa, pb in zip(net_a.params, net_b.params):
            for k in pa:
                np.testing.assert_array_equal(np.asarray(pa[k]),
                                              np.asarray(pb[k]))

    def test_load_state_worker_count_reshape_trains(self, tmp_path):
        """Since round 10 (elastic shrink) a checkpoint from a DIFFERENT
        worker count loads and trains: the saved per-worker residual
        stack is summed and spread over the new stack, conserving the
        un-transmitted gradient mass (exact-mass + adapted-threshold
        semantics locked in tests/test_elastic.py; an ARCHITECTURE
        mismatch still fails loudly there too)."""
        ds = _data(64)
        m = SharedTrainingMaster(batch_size_per_worker=8, threshold=1e-3,
                                 mesh=make_mesh({"data": 8}))
        net = _net(lr=0.05)
        DistributedMultiLayerNetwork(net, m).fit([ds])
        path = str(tmp_path / "m.npz")
        m.save_state(path)
        m4 = SharedTrainingMaster(batch_size_per_worker=8, threshold=1e-3,
                                  mesh=make_mesh({"data": 4}))
        m4.load_state(path)
        assert m4.threshold == m.threshold
        net4 = _net(lr=0.05)
        DistributedMultiLayerNetwork(net4, m4).fit([ds])
        assert net4.iteration > 0

    def test_orbax_restored_model_trains_under_master(self, tmp_path):
        """Orbax-restored params arrive COMMITTED to one device; the
        sharded step must re-place them over the mesh (regression: this
        raised 'incompatible devices' before round 4)."""
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager)
        ds = _data(128)
        mesh = make_mesh({"data": 8})
        net = _net(lr=0.05)
        m = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                 mesh=mesh)
        DistributedMultiLayerNetwork(net, m).fit([ds])
        with OrbaxCheckpointManager(str(tmp_path / "ck")) as mgr:
            mgr.save(1, net)
            mgr.wait_until_finished()
        with OrbaxCheckpointManager(str(tmp_path / "ck")) as mgr:
            restored = mgr.restore()
        m2 = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                  mesh=mesh)
        DistributedMultiLayerNetwork(restored, m2).fit([ds])
        assert np.isfinite(float(restored.score_))
