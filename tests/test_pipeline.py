"""Pipeline parallelism tests: staged execution over the 'pipe' mesh axis
must equal running the stages sequentially on one device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import PipelineParallel, PIPE_AXIS

WIDTH = 16


def stage_init(rng):
    k1, k2 = jax.random.split(rng)
    lim = float(np.sqrt(6.0 / (2 * WIDTH)))
    return {"W": jax.random.uniform(k1, (WIDTH, WIDTH), minval=-lim, maxval=lim),
            "b": jnp.zeros((WIDTH,))}


def stage_fn(p, x):
    return jnp.tanh(x @ p["W"] + p["b"])


def loss_fn(y, target):
    return jnp.mean((y - target) ** 2)


def _sequential_forward(stacked_params, micro_x):
    """Reference: apply the S stages one after another on one device."""
    S = stacked_params["W"].shape[0]
    out = []
    for m in range(micro_x.shape[0]):
        h = micro_x[m]
        for s in range(S):
            h = stage_fn({"W": stacked_params["W"][s],
                          "b": stacked_params["b"][s]}, h)
        out.append(h)
    return jnp.stack(out)


class TestPipelineParallel:
    @pytest.fixture
    def mesh(self):
        return make_mesh({PIPE_AXIS: 4})

    def test_forward_matches_sequential(self, mesh, rng):
        pp = PipelineParallel(mesh, stage_init, stage_fn, loss_fn, seed=3)
        micro_x = jnp.asarray(rng.normal(size=(6, 8, WIDTH)).astype(np.float32))
        got = pp.forward(micro_x)
        expect = _sequential_forward(pp.params, micro_x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-5, atol=2e-6)

    def test_train_step_matches_sequential_gradients(self, mesh, rng):
        pp = PipelineParallel(mesh, stage_init, stage_fn, loss_fn,
                              learning_rate=0.1, seed=5)
        micro_x = jnp.asarray(rng.normal(size=(4, 8, WIDTH)).astype(np.float32))
        micro_y = jnp.asarray(rng.normal(size=(4, 8, WIDTH)).astype(np.float32))
        p0 = jax.tree_util.tree_map(jnp.array, pp.params)  # copy

        # single-device reference step
        def ref_loss(stacked):
            outs = _sequential_forward(stacked, micro_x)
            return jnp.mean(jax.vmap(loss_fn)(outs, micro_y))

        ref_val, ref_grads = jax.value_and_grad(ref_loss)(p0)
        ref_new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, p0, ref_grads)

        loss = pp.fit_step(micro_x, micro_y)
        assert abs(float(loss) - float(ref_val)) < 1e-5
        for k in ("W", "b"):
            np.testing.assert_allclose(np.asarray(pp.params[k]),
                                       np.asarray(ref_new[k]),
                                       rtol=2e-4, atol=2e-6)

    def test_training_reduces_loss(self, mesh, rng):
        pp = PipelineParallel(mesh, stage_init, stage_fn, loss_fn,
                              learning_rate=0.2, seed=7)
        micro_x = jnp.asarray(rng.normal(size=(4, 16, WIDTH)).astype(np.float32))
        micro_y = jnp.tanh(micro_x * 0.5)
        first = float(pp.fit_step(micro_x, micro_y))
        for _ in range(30):
            last = float(pp.fit_step(micro_x, micro_y))
        assert last < first * 0.5
