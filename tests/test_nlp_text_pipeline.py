"""Sentence/document iterator family, text utils, moving windows.

Reference behaviors: text/sentenceiterator/*.java, text/documentiterator/*.java,
text/inputsanitation/InputHomogenization.java, text/stopwords/StopWords.java,
text/movingwindow/*.java (deeplearning4j-nlp).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.sentence import (
    AggregatingSentenceIterator,
    BasicLabelAwareIterator,
    CollectionSentenceIterator,
    DocumentIterator,
    FileDocumentIterator,
    FileLabelAwareIterator,
    FilenamesLabelAwareIterator,
    LabelsSource,
    LineSentenceIterator,
    MutipleEpochsSentenceIterator,
    PrefetchingSentenceIterator,
    StreamLineIterator,
    SynchronizedSentenceIterator,
)
from deeplearning4j_tpu.nlp.text_utils import (
    InMemoryInvertedIndex,
    InputHomogenization,
    StopWords,
)
from deeplearning4j_tpu.nlp import movingwindow as mw


class TestSentenceIterators:
    def test_pre_processor_applied_on_iteration(self):
        it = CollectionSentenceIterator(["Hello World", "BYE"])
        it.set_pre_processor(str.lower)
        assert list(it) == ["hello world", "bye"]

    def test_pre_processor_applied_on_explicit_protocol(self):
        # nextSentence() itself applies it, as in the reference
        it = CollectionSentenceIterator(["Hello", "WORLD"])
        it.set_pre_processor(str.lower)
        it.reset()
        out = []
        while it.has_next():
            out.append(it.next_sentence())
        assert out == ["hello", "world"]

    def test_prefetching_propagates_source_error(self):
        class Exploding(CollectionSentenceIterator):
            def next_sentence(self):
                if self._pos >= 2:
                    raise IOError("disk on fire")
                return super().next_sentence()

        it = PrefetchingSentenceIterator(Exploding(["a", "b", "c", "d"]), 1)
        got, err = [], None
        try:
            while it.has_next():
                got.append(it.next_sentence())
        except IOError as e:
            err = e
        assert got == ["a", "b"]
        assert err is not None  # no deadlock, error surfaced

    def test_line_sentence_iterator(self, tmp_path):
        p = tmp_path / "f.txt"
        p.write_text("one\ntwo\nthree\n")
        it = LineSentenceIterator(str(p))
        assert list(it) == ["one", "two", "three"]
        # reset() restarts
        assert list(it) == ["one", "two", "three"]

    def test_stream_line_iterator_from_documents(self, tmp_path):
        (tmp_path / "a.txt").write_text("l1\nl2")
        (tmp_path / "b.txt").write_text("l3")
        docs = FileDocumentIterator(str(tmp_path))
        it = StreamLineIterator(docs)
        assert list(it) == ["l1", "l2", "l3"]

    def test_aggregating_builder_chains_and_preprocesses(self):
        agg = (AggregatingSentenceIterator.builder()
               .add_sentence_iterator(CollectionSentenceIterator(["A", "B"]))
               .add_sentence_iterator(CollectionSentenceIterator(["C"]))
               .add_sentence_pre_processor(str.lower)
               .build())
        assert list(agg) == ["a", "b", "c"]

    def test_multiple_epochs(self):
        it = MutipleEpochsSentenceIterator(
            CollectionSentenceIterator(["x", "y"]), 3)
        assert list(it) == ["x", "y"] * 3
        with pytest.raises(ValueError):
            MutipleEpochsSentenceIterator(CollectionSentenceIterator([]), 0)

    def test_prefetching_matches_and_resets(self):
        src = [str(i) for i in range(100)]
        it = PrefetchingSentenceIterator(CollectionSentenceIterator(src), 8)
        assert list(it) == src
        assert list(it) == src  # reset spawns a fresh producer

    def test_synchronized_concurrent_consumers(self):
        import threading
        src = [str(i) for i in range(500)]
        it = SynchronizedSentenceIterator(CollectionSentenceIterator(src))
        it.reset()
        seen = []
        lock = threading.Lock()

        def consume():
            while True:
                s = it.next_sentence()
                if s is None:
                    return
                with lock:
                    seen.append(s)

        threads = [threading.Thread(target=consume) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(seen, key=int) == src  # each sentence exactly once


class TestDocumentIterators:
    def test_file_document_iterator(self, tmp_path):
        (tmp_path / "1.txt").write_text("first doc")
        (tmp_path / "2.txt").write_text("second doc")
        docs = list(FileDocumentIterator(str(tmp_path)))
        assert docs == ["first doc", "second doc"]

    def test_labels_source_template_and_formatter(self):
        ls = LabelsSource("DOC_%d")
        assert ls.next_label() == "DOC_0"
        assert ls.next_label() == "DOC_1"
        assert ls.get_labels() == ["DOC_0", "DOC_1"]
        plain = LabelsSource("SENT_")
        assert plain.next_label() == "SENT_0"

    def test_labels_source_template_store_does_not_flip_mode(self):
        ls = LabelsSource("DOC_%d")
        ls.store_label("extra")          # stored, but template still drives
        assert ls.next_label() == "DOC_0"
        assert ls.next_label() == "DOC_1"

    def test_stream_line_iterator_from_generator(self):
        it = StreamLineIterator(iter(["a\nb", "c"]))  # one-shot source
        assert list(it) == ["a", "b", "c"]
        assert list(it) == ["a", "b", "c"]  # snapshot makes reset() work

    def test_labels_source_list_and_store(self):
        ls = LabelsSource(["a", "b"])
        assert ls.next_label() == "a"
        ls.store_label("c")
        ls.store_label("c")  # dedupe
        assert ls.get_labels() == ["a", "b", "c"]
        assert ls.index_of("c") == 2

    def test_basic_label_aware_iterator(self):
        it = BasicLabelAwareIterator(
            CollectionSentenceIterator(["d0", "d1"]),
            LabelsSource("DOC_%d"))
        docs = list(it)
        assert [d.content for d in docs] == ["d0", "d1"]
        assert [d.labels for d in docs] == [["DOC_0"], ["DOC_1"]]
        assert it.labels_source.get_labels() == ["DOC_0", "DOC_1"]

    def test_file_label_aware_iterator(self, tmp_path):
        for label, texts in [("pos", ["good", "great"]), ("neg", ["bad"])]:
            d = tmp_path / label
            d.mkdir()
            for i, t in enumerate(texts):
                (d / f"{i}.txt").write_text(t)
        it = FileLabelAwareIterator.builder().add_source_folder(str(tmp_path)).build()
        docs = list(it)
        assert {(d.content, d.labels[0]) for d in docs} == {
            ("good", "pos"), ("great", "pos"), ("bad", "neg")}
        assert sorted(it.labels_source.get_labels()) == ["neg", "pos"]

    def test_filenames_label_aware_iterator(self, tmp_path):
        (tmp_path / "x.txt").write_text("content x")
        it = FilenamesLabelAwareIterator(str(tmp_path))
        docs = list(it)
        assert docs[0].labels == ["x.txt"]
        assert docs[0].content == "content x"


class TestTextUtils:
    def test_input_homogenization(self):
        # digits -> d, lowercase, punctuation stripped, ! runs collapsed
        assert InputHomogenization("Hello, World!!! 42").transform() == \
            "hello world! dd"
        assert InputHomogenization("ABC", preserve_case=True).transform() == "ABC"
        out = InputHomogenization("a.b", ignore_characters_containing=["."]).transform()
        assert out == "a.b"  # ignored chars survive the punctuation strip
        assert InputHomogenization("a.b").transform() == "ab"

    def test_stop_words(self):
        words = StopWords.get_stop_words()
        assert "the" in words and "and" in words
        assert len(words) > 100
        assert StopWords.get_stop_words() is words  # cached

    def test_inverted_index(self):
        idx = InMemoryInvertedIndex()
        idx.add_words_to_doc(0, ["the", "cat"])
        idx.add_words_to_doc(1, ["the", "dog"])
        assert idx.documents("the") == [0, 1]
        assert idx.documents("cat") == [0]
        assert idx.document(1) == ["the", "dog"]
        assert idx.num_documents() == 2
        assert idx.total_words() == 4
        assert idx.words() == {"the", "cat", "dog"}
        batches = list(idx.batch_iter(1))
        assert batches == [[["the", "cat"]], [["the", "dog"]]]


class TestMovingWindow:
    def test_windows_padding_and_focus(self):
        ws = mw.windows("the quick brown", 3)
        assert [w.focus_word() for w in ws] == ["the", "quick", "brown"]
        assert ws[0].words == ["<s>", "the", "quick"]
        assert ws[-1].words == ["quick", "brown", "</s>"]

    def test_window_label_detection(self):
        w = mw.Window(["<LOC>", "york", "</LOC>"], 3, 0, 3)
        assert w.label == "LOC"
        assert w.begin_label and w.end_label

    def test_as_example_array_concats_vectors(self):
        class Vecs:
            def vector(self, w):
                return {"a": [1.0, 0.0], "b": [0.0, 2.0]}.get(w)
        w = mw.Window(["a", "b", "a"], 3, 0, 3)
        arr = mw.as_example_array(w, Vecs())
        np.testing.assert_allclose(arr, [1, 0, 0, 2, 1, 0])
        # normalized
        arr_n = mw.as_example_array(w, Vecs(), normalize=True)
        np.testing.assert_allclose(arr_n, [1, 0, 0, 1, 1, 0])

    def test_as_example_matrix_zeros_unknown(self):
        class Vecs:
            def vector(self, w):
                return [3.0] if w == "a" else None
        w = mw.Window(["a", "zz", "a"], 3, 0, 3)
        np.testing.assert_allclose(mw.as_example_matrix(w, Vecs()), [3, 0, 3])

    def test_string_with_labels(self):
        s, spans = mw.string_with_labels("i live in <LOC> new york </LOC> now")
        assert s == "i live in new york now"
        assert spans == {(3, 5): "LOC"}
        with pytest.raises(ValueError):
            mw.string_with_labels("broken </LOC> here")
