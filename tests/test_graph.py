"""ComputationGraph tests — DAG execution, vertices, serde, gradient checks.

Models the reference's graph test tier: vertex behavior tests
(`nn/graph/ComputationGraphTestRNN.java`, `TestComputationGraphNetwork.java`)
and the comp-graph gradient-check suite
(`gradientcheck/GradientCheckTestsComputationGraph.java`).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator, MultiDataSet
from deeplearning4j_tpu.nn.conf import (
    ComputationGraphConfiguration,
    InputType,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import (
    DenseLayer,
    LSTMLayer,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.vertices import (
    ElementWiseVertex,
    L2NormalizeVertex,
    L2Vertex,
    LastTimeStepVertex,
    MergeVertex,
    ReshapeVertex,
    ScaleVertex,
    ShiftVertex,
    StackVertex,
    SubsetVertex,
    UnstackVertex,
)


def residual_graph(seed=3):
    """x -> dense -> (+x skip) -> out : exercises ElementWiseVertex."""
    return (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_in=10, n_out=10, activation="tanh"), "in")
            .add_vertex("res", ElementWiseVertex("add"), "d1", "in")
            .add_layer("out", OutputLayer(n_in=10, n_out=3, activation="softmax",
                                          loss="mcxent"), "res")
            .set_outputs("out")
            .build())


class TestGraphBasics:
    def test_topo_order_and_params(self):
        conf = residual_graph()
        assert conf.topo_order.index("d1") < conf.topo_order.index("res")
        assert conf.topo_order.index("res") < conf.topo_order.index("out")
        g = ComputationGraph(conf).init()
        assert g.num_params() == (10 * 10 + 10) + (10 * 3 + 3)

    def test_cycle_detection(self):
        b = (NeuralNetConfiguration.builder().graph_builder()
             .add_inputs("in")
             .add_layer("a", DenseLayer(n_in=4, n_out=4), "b")
             .add_layer("b", DenseLayer(n_in=4, n_out=4), "a")
             .set_outputs("b"))
        with pytest.raises(ValueError, match="cycle"):
            b.build()

    def test_unknown_input_rejected(self):
        b = (NeuralNetConfiguration.builder().graph_builder()
             .add_inputs("in")
             .add_layer("a", DenseLayer(n_in=4, n_out=4), "nope")
             .set_outputs("a"))
        with pytest.raises(ValueError, match="unknown input"):
            b.build()

    def test_fit_learns(self, rng):
        n = 256
        x = rng.normal(size=(n, 10)).astype(np.float32)
        w = rng.normal(size=(10, 3)).astype(np.float32)
        y_idx = np.argmax(x @ w, axis=1)
        y = np.eye(3, dtype=np.float32)[y_idx]
        g = ComputationGraph(residual_graph()).init()
        it = ListDataSetIterator(DataSet(x, y), 64, shuffle=True)
        g.fit(it, epochs=30)
        acc = g.evaluate(ListDataSetIterator(DataSet(x, y), 128)).accuracy()
        assert acc > 0.9

    def test_output_and_predict(self, rng):
        g = ComputationGraph(residual_graph()).init()
        x = rng.normal(size=(5, 10)).astype(np.float32)
        out = g.output(x)
        assert out.shape == (5, 3)
        np.testing.assert_allclose(np.asarray(jnp.sum(out, -1)), 1.0, rtol=1e-5)
        assert g.predict(x).shape == (5,)


class TestMultiInputOutput:
    def graph(self):
        return (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
                .graph_builder()
                .add_inputs("a", "b")
                .add_layer("da", DenseLayer(n_in=6, n_out=8, activation="relu"), "a")
                .add_layer("db", DenseLayer(n_in=4, n_out=8, activation="relu"), "b")
                .add_vertex("merge", MergeVertex(), "da", "db")
                .add_layer("out1", OutputLayer(n_in=16, n_out=3, activation="softmax",
                                               loss="mcxent"), "merge")
                .add_layer("out2", OutputLayer(n_in=16, n_out=1, activation="identity",
                                               loss="mse"), "merge")
                .set_outputs("out1", "out2")
                .build())

    def test_two_in_two_out(self, rng):
        g = ComputationGraph(self.graph()).init()
        xa = rng.normal(size=(12, 6)).astype(np.float32)
        xb = rng.normal(size=(12, 4)).astype(np.float32)
        y1 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 12)]
        y2 = rng.normal(size=(12, 1)).astype(np.float32)
        mds = MultiDataSet([xa, xb], [y1, y2])
        g.fit([mds], epochs=3)
        o1, o2 = g.output(xa, xb)
        assert o1.shape == (12, 3) and o2.shape == (12, 1)
        assert np.isfinite(g.score_)


class TestVertices:
    def test_elementwise_ops(self):
        a = jnp.asarray([[1.0, 2.0]])
        b = jnp.asarray([[3.0, 5.0]])
        assert ElementWiseVertex("add").forward([a, b]).tolist() == [[4.0, 7.0]]
        assert ElementWiseVertex("subtract").forward([a, b]).tolist() == [[-2.0, -3.0]]
        assert ElementWiseVertex("product").forward([a, b]).tolist() == [[3.0, 10.0]]
        assert ElementWiseVertex("max").forward([a, b]).tolist() == [[3.0, 5.0]]
        assert ElementWiseVertex("average").forward([a, b]).tolist() == [[2.0, 3.5]]

    def test_stack_unstack_subset(self):
        a = jnp.ones((2, 4))
        b = jnp.zeros((2, 4))
        s = StackVertex().forward([a, b])
        assert s.shape == (4, 4)
        u = UnstackVertex(from_index=1, stack_size=2).forward([s])
        assert float(jnp.sum(u)) == 0.0
        sub = SubsetVertex(from_index=1, to_index=2).forward([s])
        assert sub.shape == (4, 2)

    def test_scale_shift_reshape_l2(self):
        x = jnp.asarray([[3.0, 4.0]])
        assert ScaleVertex(2.0).forward([x]).tolist() == [[6.0, 8.0]]
        assert ShiftVertex(1.0).forward([x]).tolist() == [[4.0, 5.0]]
        r = ReshapeVertex(shape=(2, 1)).forward([x])
        assert r.shape == (1, 2, 1)
        n = L2NormalizeVertex().forward([x])
        np.testing.assert_allclose(np.asarray(n), [[0.6, 0.8]], rtol=1e-5)
        d = L2Vertex().forward([x, jnp.zeros_like(x)])
        np.testing.assert_allclose(np.asarray(d), [[5.0]], rtol=1e-4)

    def test_last_time_step_with_mask(self):
        x = jnp.arange(24, dtype=jnp.float32).reshape(2, 4, 3)
        mask = jnp.asarray([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
        out = LastTimeStepVertex().forward([x], [mask])
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(x[0, 1]))
        np.testing.assert_allclose(np.asarray(out[1]), np.asarray(x[1, 3]))


class TestRnnGraph:
    def test_lstm_graph_with_last_step(self, rng):
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("lstm", LSTMLayer(n_in=5, n_out=8), "in")
                .add_vertex("last", LastTimeStepVertex(), "lstm")
                .add_layer("out", OutputLayer(n_in=8, n_out=2, activation="softmax",
                                              loss="mcxent"), "last")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf).init()
        x = rng.normal(size=(4, 7, 5)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        g.fit(DataSet(x, y), epochs=2)
        assert g.output(x).shape == (4, 2)

    def test_rnn_time_step_stateful(self, rng):
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("lstm", LSTMLayer(n_in=3, n_out=6), "in")
                .add_layer("out", RnnOutputLayer(n_in=6, n_out=3, activation="softmax",
                                                 loss="mcxent"), "lstm")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf).init()
        x = rng.normal(size=(2, 6, 3)).astype(np.float32)
        full = np.asarray(g.output(x))
        g.rnn_clear_previous_state()
        step_outs = []
        for t in range(6):
            step_outs.append(np.asarray(g.rnn_time_step(x[:, t, :])))
        np.testing.assert_allclose(np.stack(step_outs, 1), full, rtol=1e-4,
                                   atol=1e-5)


class TestGraphSerde:
    def test_json_roundtrip(self):
        conf = residual_graph()
        j = conf.to_json()
        conf2 = ComputationGraphConfiguration.from_json(j)
        assert conf2.topo_order == conf.topo_order
        assert conf2.to_json() == j

    def test_roundtrip_same_outputs(self, rng):
        conf = residual_graph()
        g = ComputationGraph(conf).init()
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        g2 = ComputationGraph(conf2).init()
        x = rng.normal(size=(3, 10)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(g.output(x)), np.asarray(g2.output(x)),
                                   rtol=1e-6)


class TestGraphGradients:
    def test_residual_graph_gradients(self, rng):
        """Finite differences vs jax.grad through the DAG (comp-graph
        gradient-check suite parity)."""
        from deeplearning4j_tpu.util.gradient_check import check_graph_gradients
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_in=4, n_out=4, activation="tanh"), "in")
                .add_vertex("res", ElementWiseVertex("add"), "d1", "in")
                .add_layer("out", OutputLayer(n_in=4, n_out=2, activation="softmax",
                                              loss="mcxent"), "res")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf).init()
        x = rng.normal(size=(3, 4))
        y = np.eye(2)[rng.integers(0, 2, 3)]
        assert check_graph_gradients(g, x, y, print_results=True)

    def test_multi_output_gradients(self, rng):
        from deeplearning4j_tpu.util.gradient_check import check_graph_gradients
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1))
                .graph_builder()
                .add_inputs("a", "b")
                .add_layer("da", DenseLayer(n_in=3, n_out=4, activation="tanh"), "a")
                .add_layer("db", DenseLayer(n_in=3, n_out=4, activation="sigmoid"), "b")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_layer("o1", OutputLayer(n_in=8, n_out=2, activation="softmax",
                                             loss="mcxent"), "m")
                .add_layer("o2", OutputLayer(n_in=8, n_out=1, activation="identity",
                                             loss="mse"), "m")
                .set_outputs("o1", "o2")
                .build())
        g = ComputationGraph(conf).init()
        xa = rng.normal(size=(3, 3))
        xb = rng.normal(size=(3, 3))
        y1 = np.eye(2)[rng.integers(0, 2, 3)]
        y2 = rng.normal(size=(3, 1))
        assert check_graph_gradients(g, [xa, xb], [y1, y2], print_results=True)


class TestCrossAttentionGraph:
    def test_cross_attention_gradients(self):
        import numpy as np
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import (CrossAttentionLayer,
                                                  LossLayer)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.util.gradient_check import check_graph_gradients

        g = (NeuralNetConfiguration.builder().seed(3).graph_builder()
             .add_inputs("q", "kv")
             .set_input_types(InputType.recurrent(6, 4), InputType.recurrent(6, 5)))
        g.add_layer("xatt", CrossAttentionLayer(n_heads=2, head_size=3), "q", "kv")
        g.add_layer("out", LossLayer(loss="mse", activation="identity"), "xatt")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        rng = np.random.default_rng(5)
        xq = rng.normal(size=(2, 4, 6))
        xkv = rng.normal(size=(2, 5, 6))
        y = rng.normal(size=(2, 4, 6))
        assert check_graph_gradients(net, [xq, xkv], [y], subset=40,
                                     print_results=True)

    def test_cross_attention_single_input_mask_matches_self_attention(self):
        # regression: the single-input path must apply the mask to the keys
        import jax
        import numpy as np
        from deeplearning4j_tpu.nn.layers import CrossAttentionLayer
        from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
        import jax.numpy as jnp

        layer = CrossAttentionLayer(n_in=8, k_in=8, v_in=8, n_out=8,
                                    n_heads=2, head_size=4)
        p = layer.init_params(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 5, 8)).astype(np.float32))
        mask = jnp.asarray(np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]],
                                    np.float32))
        y_masked, _ = layer.forward(p, x, mask=mask)
        y_plain, _ = layer.forward(p, x)
        # masked example differs from unmasked; fully-valid example matches
        assert not np.allclose(np.asarray(y_masked)[0, :3],
                               np.asarray(y_plain)[0, :3])
        np.testing.assert_allclose(np.asarray(y_masked)[1],
                                   np.asarray(y_plain)[1], rtol=1e-5, atol=1e-6)


class TestGraphTBPTT:
    """Truncated BPTT on the DAG (the reference dispatches TBPTT inside
    ComputationGraph.fit the same way MultiLayerNetwork does)."""

    def _lstm_graph(self, tbptt=None):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import LSTMLayer, RnnOutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd

        g = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1))
             .graph_builder().add_inputs("in")
             .set_input_types(InputType.recurrent(3, 12)))
        g.add_layer("lstm", LSTMLayer(n_out=8), "in")
        g.add_layer("out", RnnOutputLayer(n_out=2, loss="mcxent",
                                          activation="softmax"), "lstm")
        g.set_outputs("out")
        if tbptt:
            g.t_bptt_length(tbptt)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return ComputationGraph(g.build()).init()

    def test_single_chunk_tbptt_equals_standard_step(self):
        import numpy as np
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 12, 3)).astype(np.float32)
        y = np.zeros((4, 12, 2), np.float32)
        y[..., 0] = 1
        a = self._lstm_graph()               # standard BPTT
        b = self._lstm_graph(tbptt=12)       # one chunk spanning the sequence
        a.fit(x, y)
        b.fit(x, y)
        for name in a.params:
            for k in a.params[name]:
                np.testing.assert_allclose(
                    np.asarray(a.params[name][k]),
                    np.asarray(b.params[name][k]), atol=1e-6,
                    err_msg=f"{name}/{k}")

    def test_chunked_tbptt_trains_and_counts_iterations(self):
        import numpy as np
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 12, 3)).astype(np.float32)
        cls = (x.mean(axis=2) > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[cls]
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net = self._lstm_graph(tbptt=4)      # 3 chunks per batch
        s0 = net.score(DataSet(x, y))
        for _ in range(30):
            net.fit(x, y)
        assert net.iteration == 30 * 3       # one iteration per chunk
        assert float(net.score_) < s0

    def test_backprop_type_aliases_normalize(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import LSTMLayer, RnnOutputLayer

        g = (NeuralNetConfiguration.builder().graph_builder()
             .add_inputs("in").set_input_types(InputType.recurrent(3, 8))
             .backprop_type("TBPTT"))
        g.add_layer("l", LSTMLayer(n_out=4), "in")
        g.add_layer("o", RnnOutputLayer(n_out=2, loss="mcxent",
                                        activation="softmax"), "l")
        g.set_outputs("o")
        assert g.build().backprop_type == "truncated_bptt"

        lb = (NeuralNetConfiguration.builder().list()
              .layer(LSTMLayer(n_in=3, n_out=4))
              .layer(RnnOutputLayer(n_out=2, loss="mcxent",
                                    activation="softmax"))
              .backprop_type("TruncatedBPTT")
              .set_input_type(InputType.recurrent(3, 8)))
        assert lb.build().backprop_type == "truncated_bptt"

    def test_transformer_lm_tbptt_chunks(self):
        # causal attention + positional offsets carry across graph TBPTT
        # chunks (transformer-XL-style): must run and train
        import numpy as np
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.zoo.models import TransformerLM, lm_labels

        m = TransformerLM(vocab_size=11, max_length=16, n_layers=1,
                          d_model=16, n_heads=2, d_ff=32, seed=3)
        conf = m.conf()
        conf.backprop_type = "truncated_bptt"
        conf.tbptt_fwd_length = 8
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        x = ((rng.integers(0, 11, (8, 1)) + np.arange(16)[None, :]) % 11
             ).astype(np.float32)
        y = lm_labels(x, 11)
        for _ in range(3):
            net.fit(x, y)
        assert np.isfinite(float(net.score_))
        assert net.iteration == 3 * 2  # two chunks per batch

    def test_tbptt_with_2d_sequence_labels(self):
        # per-sequence (2D) labels must still dispatch TBPTT (the temporal
        # input decides, not the label rank) and train each chunk on the
        # same label, like the sequential network does
        import numpy as np
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 12, 3)).astype(np.float32)
        cls = (x.mean(axis=(1, 2)) > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[cls]          # [N, 2] — no time axis

        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.layers import (
            GlobalPoolingLayer, LSTMLayer, OutputLayer)

        g = (NeuralNetConfiguration.builder().seed(5).graph_builder()
             .add_inputs("in").set_input_types(InputType.recurrent(3, 12))
             .t_bptt_length(4))
        g.add_layer("lstm", LSTMLayer(n_out=8), "in")
        g.add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), "lstm")
        g.add_layer("out", OutputLayer(n_out=2, loss="mcxent",
                                       activation="softmax"), "pool")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        net.fit(x, y)
        assert net.iteration == 3  # 12/4 chunks — TBPTT DID dispatch
        assert np.isfinite(float(net.score_))

    def test_normalization_covers_from_dict(self):
        from deeplearning4j_tpu.nn.conf.network import (
            MultiLayerConfiguration, normalize_backprop_type)
        assert normalize_backprop_type("TBPTT") == "truncated_bptt"
        assert normalize_backprop_type("TruncatedBPTT") == "truncated_bptt"
        assert normalize_backprop_type("standard") == "standard"
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        conf = (NeuralNetConfiguration.builder().list()
                .layer(DenseLayer(n_in=4, n_out=4))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        d = conf.to_dict()
        d["backprop_type"] = "TruncatedBPTT"   # DL4J-dialect spelling
        conf2 = MultiLayerConfiguration.from_dict(d)
        assert conf2.backprop_type == "truncated_bptt"


class TestFitBatchesOnDevice:
    """Device-loop training window (lax.scan over stacked batches): one
    dispatch == K sequential fit steps, same math."""

    def _parts(self, seed=3):
        import numpy as np
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd
        g = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
             .graph_builder().add_inputs("in")
             .add_layer("d", DenseLayer(n_out=12, activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_out=3), "d")
             .set_outputs("out").set_input_types(InputType.feed_forward(6)))
        return g.build()

    def test_matches_sequential_fit(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        rng = np.random.default_rng(0)
        batches = []
        for i in range(5):
            yc = rng.integers(0, 3, 16)
            x = rng.normal(size=(16, 6)).astype(np.float32)
            x[np.arange(16), yc] += 2.0
            batches.append(DataSet(x, np.eye(3, dtype=np.float32)[yc]))

        seq = ComputationGraph(self._parts()).init()
        for ds in batches:
            seq.fit(ds)
        dev = ComputationGraph(self._parts()).init()
        dev.fit_batches_on_device(batches)
        assert dev.iteration == seq.iteration == 5
        for name in seq.params:
            for k in seq.params[name]:
                np.testing.assert_allclose(
                    np.asarray(dev.params[name][k]),
                    np.asarray(seq.params[name][k]), rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(float(dev.score_), float(seq.score_),
                                   rtol=1e-4)

    def test_rejects_masks_and_tbptt(self):
        import numpy as np
        import pytest
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        net = ComputationGraph(self._parts()).init()
        x = np.ones((4, 6), np.float32)
        y = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
        with pytest.raises(ValueError, match="mask"):
            net.fit_batches_on_device(
                [DataSet(x, y, features_mask=np.ones((4, 1), np.float32))])


def test_graph_evaluate_topn_and_metadata(tmp_path):
    """ComputationGraph.evaluate carries top_n and record metadata through
    like MultiLayerNetwork.evaluate."""
    import numpy as np
    from deeplearning4j_tpu.datasets.records import (
        CollectionRecordReader, RecordReaderDataSetIterator)
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.updaters import Adam

    g = (NeuralNetConfiguration.builder().seed(3).updater(Adam(0.05))
         .graph_builder().add_inputs("in"))
    g.add_layer("h", DenseLayer(n_in=4, n_out=16, activation="relu"), "in")
    g.add_layer("out", OutputLayer(n_in=16, n_out=3), "h")
    net = ComputationGraph(g.set_outputs("out").build()).init()
    rng = np.random.default_rng(0)
    recs = []
    for i in range(60):
        cls = i % 3
        f = rng.normal(0, 0.3, 4)
        f[cls] += 2.0
        recs.append(list(f) + [cls])
    it = RecordReaderDataSetIterator(
        CollectionRecordReader(recs), 16, label_index=4,
        num_possible_labels=3)
    for _ in range(15):
        net.fit(it)
    eval_it = RecordReaderDataSetIterator(
        CollectionRecordReader(recs), 16, label_index=4,
        num_possible_labels=3, collect_meta_data=True)
    e = net.evaluate(eval_it, top_n=2)
    assert e.accuracy() > 0.9
    assert e.top_n_accuracy() >= e.accuracy()
    assert e.get_predictions_by_actual_class(0) is not None


def test_graph_pretrain_layer():
    """ComputationGraph.pretrainLayer on an autoencoder vertex."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import (AutoEncoderLayer, DenseLayer,
                                              OutputLayer)
    from deeplearning4j_tpu.nn.updaters import Adam

    g = (NeuralNetConfiguration.builder().seed(2).updater(Adam(0.01))
         .graph_builder().add_inputs("in"))
    g.add_layer("ae", AutoEncoderLayer(n_in=8, n_out=4,
                                       activation="sigmoid"), "in")
    g.add_layer("out", OutputLayer(n_in=4, n_out=2), "ae")
    net = ComputationGraph(g.set_outputs("out").build()).init()
    rng = np.random.default_rng(1)
    x = (rng.random((64, 8)) < 0.3).astype(np.float32)
    ae = net.conf.vertices["ae"].obj
    l0 = float(jax.jit(ae.pretrain_loss)(net.params["ae"], jnp.asarray(x),
                                         jax.random.PRNGKey(0)))
    net.pretrain(x, epochs=30)
    l1 = float(jax.jit(ae.pretrain_loss)(net.params["ae"], jnp.asarray(x),
                                         jax.random.PRNGKey(0)))
    assert l1 < l0 * 0.9
    import pytest as _pytest
    with _pytest.raises(ValueError, match="pretrainable"):
        net.pretrain_layer("out", x)


def test_graph_surface_methods():
    """evaluateROC, scoreExamples, setLearningRate, outputSingle,
    layerSize, getVertex on ComputationGraph."""
    import numpy as np
    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    g = (NeuralNetConfiguration.builder().seed(3).updater(Adam(0.05))
         .graph_builder().add_inputs("in"))
    g.add_layer("h", DenseLayer(n_in=4, n_out=16, activation="relu"), "in")
    g.add_layer("out", OutputLayer(n_in=16, n_out=2), "h")
    net = ComputationGraph(g.set_outputs("out").build()).init()
    assert net.layer_size("h") == 16
    assert net.get_vertex("h").is_layer
    rng = np.random.default_rng(1)
    cls = rng.integers(0, 2, 64)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    x[np.arange(64), cls] += 2.0
    y = np.eye(2, dtype=np.float32)[cls]
    for _ in range(20):
        net.fit(x, y)
    assert net.output_single(x).shape == (64, 2)
    roc = net.evaluate_roc(ListDataSetIterator(DataSet(x, y), 32))
    assert roc.calculate_auc() > 0.9
    scores = net.score_examples(DataSet(x, y))
    assert scores.shape == (64,)
    assert np.isfinite(scores).all()
    net.set_learning_rate(0.0)
    w = np.asarray(net.params["h"]["W"]).copy()
    net.fit(x, y)
    np.testing.assert_allclose(np.asarray(net.params["h"]["W"]), w)


def test_graph_masked_evaluation_matches_mln():
    """Padded sequence batches: graph evaluate must thread the feature
    mask into the forward pass and the label mask into eval — identical
    confusion to the same layers evaluated as a MultiLayerNetwork (the
    round-3 review's mask-dropping regression)."""
    import numpy as np
    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import LSTMLayer, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    rng = np.random.default_rng(0)
    N, T, F, C = 12, 7, 4, 3
    x = rng.normal(size=(N, T, F)).astype(np.float32)
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, (N, T))]
    lengths = rng.integers(2, T + 1, N)
    m = (np.arange(T)[None, :] < lengths[:, None]).astype(np.float32)
    ds = DataSet(x, y, m, m)

    mconf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(0.01))
             .list()
             .layer(LSTMLayer(n_in=F, n_out=8))
             .layer(RnnOutputLayer(n_in=8, n_out=C))
             .build())
    mln = MultiLayerNetwork(mconf).init()

    g = (NeuralNetConfiguration.builder().seed(5).updater(Adam(0.01))
         .graph_builder().add_inputs("in")
         .set_input_types(InputType.recurrent(F)))
    g.add_layer("lstm", LSTMLayer(n_in=F, n_out=8), "in")
    g.add_layer("out", RnnOutputLayer(n_in=8, n_out=C), "lstm")
    cg = ComputationGraph(g.set_outputs("out").build()).init()
    # identical params
    cg.params["lstm"] = dict(mln.params[0])
    cg.params["out"] = dict(mln.params[1])

    it = ListDataSetIterator(ds, 6)
    em = mln.evaluate(it)
    eg = cg.evaluate(ListDataSetIterator(ds, 6))
    np.testing.assert_array_equal(eg.confusion, em.confusion)
    # total scored predictions == number of VALID timesteps, not N*T
    assert em.confusion.sum() == int(m.sum())
