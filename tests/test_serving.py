"""Production serving subsystem tests (`serving/`): metrics core, versioned
registry, admission control, and the HTTP front-end driven end to end over
ephemeral ports — concurrent load with metric reconciliation, hot-swap under
load with a no-torn-responses oracle, deadline expiry (504, never
dispatched), queue overflow (429 + Retry-After), dispatcher-crash
containment (503), and graceful drain. Everything runs on CPU with port-0
binds and no sleeps beyond the ~50 ms deadline windows under test.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import (AdmissionController,
                                        AdmissionRejected, Draining,
                                        MetricsRegistry, ModelNotFound,
                                        ModelRegistry, ModelServer,
                                        ModelServingClient, ServingError,
                                        parse_prometheus_text)


def small_net(seed=7, n_in=12, n_out=4):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=n_out, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    return MultiLayerNetwork(conf).init()


class _GateModel:
    """Stub model whose forward blocks until released — deterministic
    control over dispatcher timing without sleeps. Duck-types the only
    method ParallelInference calls."""

    def __init__(self, n_out=2):
        self.gate = threading.Event()
        self.entered = threading.Event()
        self.calls = 0
        self._lock = threading.Lock()
        self.n_out = n_out

    def output(self, x):
        with self._lock:
            self.calls += 1
        self.entered.set()
        assert self.gate.wait(10.0), "test forgot to release the gate"
        x = np.asarray(x)
        return np.zeros((x.shape[0], self.n_out), np.float32)


@pytest.fixture
def stack():
    """(metrics, registry, server, client) with everything torn down."""
    metrics = MetricsRegistry()
    registry = ModelRegistry(metrics=metrics)
    server = ModelServer(registry, metrics=metrics, max_inflight=32)
    server.start()
    client = ModelServingClient(server.url)
    yield metrics, registry, server, client
    server.stop(drain=False)
    registry.shutdown()


# --------------------------------------------------------------- metrics core
class TestMetricsCore:
    def test_counter_gauge_labels_and_exposition(self):
        m = MetricsRegistry()
        c = m.counter("reqs_total", "requests", ("model", "status"))
        c.inc(model="a", status="200")
        c.inc(2, model="a", status="500")
        g = m.gauge("depth", "queue depth")
        g.set(3)
        g.dec()
        text = m.exposition()
        parsed = parse_prometheus_text(text)
        assert parsed["reqs_total"][
            (("model", "a"), ("status", "200"))] == 1
        assert parsed["reqs_total"][
            (("model", "a"), ("status", "500"))] == 2
        assert parsed["depth"][()] == 2
        assert "# TYPE reqs_total counter" in text
        assert "# TYPE depth gauge" in text

    def test_histogram_cumulative_buckets(self):
        m = MetricsRegistry()
        h = m.histogram("lat", "latency", buckets=[0.1, 1.0])
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        parsed = parse_prometheus_text(m.exposition())
        assert parsed["lat_bucket"][(("le", "0.1"),)] == 1
        assert parsed["lat_bucket"][(("le", "1"),)] == 2
        assert parsed["lat_bucket"][(("le", "+Inf"),)] == 3
        assert parsed["lat_count"][()] == 3
        assert parsed["lat_sum"][()] == pytest.approx(5.55)
        assert h.count() == 3

    def test_get_or_create_identity_and_mismatch(self):
        m = MetricsRegistry()
        a = m.counter("x_total", label_names=("k",))
        assert m.counter("x_total", label_names=("k",)) is a
        with pytest.raises(ValueError):
            m.counter("x_total", label_names=("other",))
        with pytest.raises(ValueError):
            m.gauge("x_total")
        with pytest.raises(ValueError):
            a.inc(wrong="label")
        with pytest.raises(ValueError):
            a.inc(-1, k="v")

    def test_label_escaping_round_trip(self):
        m = MetricsRegistry()
        c = m.counter("esc_total", label_names=("p",))
        weird = 'a"b\\c\nd'
        c.inc(p=weird)
        parsed = parse_prometheus_text(m.exposition())
        assert parsed["esc_total"][(("p", weird),)] == 1


# ------------------------------------------------------------------- registry
class TestModelRegistry:
    def test_register_from_zip_path_and_object(self, tmp_path):
        from deeplearning4j_tpu.util.model_serializer import write_model
        net = small_net(seed=3)
        zip_path = tmp_path / "m.zip"
        write_model(net, zip_path)
        reg = ModelRegistry()
        try:
            v1 = reg.register("m", path=str(zip_path))
            assert v1 == 1
            v2 = reg.register("m", small_net(seed=4))
            assert v2 == 2
            assert reg.get("m").current_version == 2
            listing = reg.list_models()
            assert listing[0]["name"] == "m"
            assert [v["version"] for v in listing[0]["versions"]] == [1, 2]
            assert listing[0]["versions"][0]["source"] == str(zip_path)
            # the zip-restored v1 still serves, pinned
            x = np.zeros((2, 12), np.float32)
            pinned = reg.predict("m", x, version=1)
            np.testing.assert_allclose(pinned, np.asarray(net.output(x)),
                                       rtol=1e-5, atol=1e-6)
        finally:
            reg.shutdown()

    def test_activate_rollback_and_swap_metrics(self):
        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics)
        try:
            a, b = small_net(seed=1), small_net(seed=2)
            reg.register("m", a)
            reg.register("m", b)          # auto-activates v2
            x = np.ones((2, 12), np.float32)
            np.testing.assert_allclose(reg.predict("m", x),
                                       np.asarray(b.output(x)),
                                       rtol=1e-5, atol=1e-6)
            assert reg.rollback("m") == 1
            np.testing.assert_allclose(reg.predict("m", x),
                                       np.asarray(a.output(x)),
                                       rtol=1e-5, atol=1e-6)
            assert reg.get("m").current_version == 1
            # one counter increment per swap EVENT: register v1, activate
            # v2, rollback — summing over kinds == number of swaps
            swaps = metrics.get("serving_model_swaps_total")
            assert swaps.value(model="m", kind="register") == 1
            assert swaps.value(model="m", kind="activate") == 1
            assert swaps.value(model="m", kind="rollback") == 1
            assert swaps.total() == 3
            assert metrics.get("serving_model_version").value(model="m") == 1
        finally:
            reg.shutdown()

    def test_unknowns_raise(self):
        reg = ModelRegistry()
        try:
            with pytest.raises(ModelNotFound):
                reg.get("ghost")
            reg.register("m", small_net())
            with pytest.raises(ModelNotFound):
                reg.activate("m", 9)
            with pytest.raises(ModelNotFound):
                reg.rollback("m")  # no previous version yet
            with pytest.raises(ValueError):
                reg.register("m")  # neither model nor path
        finally:
            reg.shutdown()


# ------------------------------------------------------------------ admission
class TestAdmission:
    def test_overflow_and_release(self):
        ctrl = AdmissionController(2, retry_after_s=0.25)
        s1, s2 = ctrl.admit(), ctrl.admit()
        with pytest.raises(AdmissionRejected) as ei:
            ctrl.admit()
        assert ei.value.retry_after_s == 0.25
        s1.release()
        with ctrl.admit():
            pass
        s2.release()
        assert ctrl.inflight == 0

    def test_drain(self):
        ctrl = AdmissionController(4)
        slot = ctrl.admit()
        ctrl.begin_drain()
        with pytest.raises(Draining):
            ctrl.admit()
        assert not ctrl.wait_idle(timeout=0.05)
        slot.release()
        assert ctrl.wait_idle(timeout=1.0)


# ----------------------------------------------------------------- HTTP tier
class TestModelServerEndpoints:
    def test_health_ready_listing_and_404(self, stack):
        metrics, registry, server, client = stack
        assert client.healthy()
        assert not client.ready()          # empty registry → not ready
        registry.register("m", small_net())
        assert client.ready()
        assert [m["name"] for m in client.models()] == ["m"]
        assert client.model("m")["current_version"] == 1
        with pytest.raises(ServingError) as ei:
            client.predict("ghost", np.zeros((1, 12), np.float32))
        assert ei.value.status == 404
        with pytest.raises(ServingError) as ei:
            client.predict("m", np.zeros((1, 12), np.float32), version=9)
        assert ei.value.status == 404

    def test_json_and_binary_predict_agree(self, stack):
        metrics, registry, server, client = stack
        net = small_net(seed=5)
        registry.register("m", net)
        x = np.random.default_rng(0).normal(size=(6, 12)).astype(np.float32)
        want = np.asarray(net.output(x))
        got_json = client.predict("m", x)
        got_bin = client.predict("m", x, binary=True)
        np.testing.assert_allclose(got_json, want, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got_bin, want, rtol=1e-5, atol=1e-6)
        # binary response is the exact codec frame (float32, no JSON loss)
        assert got_bin.dtype == want.dtype

    def test_bad_requests_400(self, stack):
        metrics, registry, server, client = stack
        registry.register("m", small_net())
        url = f"{server.url}/v1/models/m/predict"

        def post(body, ctype="application/json"):
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type": ctype})
            try:
                with urllib.request.urlopen(req, timeout=5) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code

        assert post(b"not json") == 400
        assert post(json.dumps({"nope": 1}).encode()) == 400
        assert post(json.dumps({"inputs": 3.0}).encode()) == 400  # 0-d
        assert post(b"\x00\x00\x00\xffgarbage",
                    "application/octet-stream") == 400
        # truncated binary frame (< 4-byte header → struct.error, not 500)
        assert post(b"\x00", "application/octet-stream") == 400

    def test_concurrent_load_metrics_reconcile(self, stack):
        """N client threads × M models; every per-status counter must
        reconcile with what the clients observed, and the batch-size
        histogram count must equal the number of dispatched batches."""
        metrics, registry, server, client = stack
        nets = {"alpha": small_net(seed=1), "beta": small_net(seed=2)}
        for name, net in nets.items():
            registry.register(name, net)
        x = np.random.default_rng(1).normal(size=(3, 12)).astype(np.float32)
        want = {n: np.asarray(net.output(x)) for n, net in nets.items()}
        observed = []   # (model, status) per request, client-side
        obs_lock = threading.Lock()

        def worker(name, reps):
            local = []
            for i in range(reps):
                target = name if i % 5 else "ghost"   # sprinkle 404s
                try:
                    out = client.predict(target, x)
                    np.testing.assert_allclose(out, want[target],
                                               rtol=1e-4, atol=1e-5)
                    local.append((target, "200"))
                except ServingError as e:
                    local.append((target, str(e.status)))
            with obs_lock:
                observed.extend(local)

        threads = [threading.Thread(target=worker, args=(name, 10))
                   for name in nets for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(observed) == 60
        parsed = parse_prometheus_text(client.metrics_text())
        series = parsed["serving_requests_total"]
        # per-(model,status) totals reconcile exactly with the client view;
        # unknown names land under the bounded "_unknown" sentinel label
        from collections import Counter as C
        client_view = C((m if m in nets else "_unknown", s)
                        for m, s in observed)
        server_view = {(k[0][1], k[1][1]): int(v) for k, v in series.items()}
        assert server_view == dict(client_view)
        assert sum(series.values()) == 60
        # batch-size histogram count == dispatched batches, per model
        for name in nets:
            dispatched = registry.get(name).inference.batches_dispatched
            assert parsed["inference_batch_size_count"][
                (("model", name),)] == dispatched
            # every request row is accounted for inside the batches
            assert parsed["inference_batch_size_sum"][
                (("model", name),)] == sum(
                    3 for m, s in observed if m == name and s == "200")

    def test_hot_swap_under_load_no_torn_responses(self, stack):
        """Serve concurrently while v2 activates and then rolls back: every
        successful response equals EITHER version's output exactly — never a
        mixture — and the swap counter records the events."""
        metrics, registry, server, client = stack
        a, b = small_net(seed=11), small_net(seed=22)
        registry.register("m", a)
        x = np.random.default_rng(2).normal(size=(4, 12)).astype(np.float32)
        want_a = np.asarray(a.output(x))
        want_b = np.asarray(b.output(x))
        assert np.abs(want_a - want_b).max() > 1e-2  # distinguishable
        failures = []

        def worker(reps):
            for _ in range(reps):
                out = client.predict("m", x)
                da = np.abs(out - want_a).max()
                db = np.abs(out - want_b).max()
                if min(da, db) > 1e-4:
                    failures.append((da, db))

        threads = [threading.Thread(target=worker, args=(25,))
                   for _ in range(4)]
        for t in threads:
            t.start()
        registry.register("m", b)            # hot-swap to v2 mid-load
        registry.rollback("m")               # and back, still under load
        for t in threads:
            t.join(timeout=60)
        assert not failures, failures
        swaps = metrics.get("serving_model_swaps_total")
        assert swaps.value(model="m", kind="activate") >= 1
        assert swaps.value(model="m", kind="rollback") == 1
        assert registry.get("m").current_version == 1

    def test_deadline_expiry_504_and_never_dispatched(self, stack):
        metrics, registry, server, client = stack
        gate = _GateModel()
        registry.register("slow", gate)
        results = {}

        def blocked():
            results["a"] = client.predict("slow", np.zeros((1, 3)))

        t = threading.Thread(target=blocked)
        t.start()
        assert gate.entered.wait(5.0)        # dispatcher now stuck in batch 1
        t0 = time.perf_counter()
        with pytest.raises(ServingError) as ei:
            client.predict("slow", np.zeros((1, 3)), deadline_ms=50)
        elapsed = time.perf_counter() - t0
        assert ei.value.status == 504
        assert elapsed < 5.0                 # failed at the deadline, not the gate
        gate.gate.set()                      # release batch 1
        t.join(timeout=10)
        assert results["a"].shape == (1, 2)
        # the expired request was never dispatched: a fresh request lands in
        # batch 2, so the gate saw exactly 2 forward calls in total
        client.predict("slow", np.zeros((1, 3)))
        assert gate.calls == 2
        assert metrics.get("serving_requests_total").value(
            model="slow", status="504") == 1

    def test_queue_overflow_429_with_retry_after(self):
        metrics = MetricsRegistry()
        registry = ModelRegistry(metrics=metrics)
        server = ModelServer(registry, metrics=metrics, max_inflight=2,
                             retry_after_s=0.125)
        server.start()
        client = ModelServingClient(server.url)
        gate = _GateModel()
        registry.register("slow", gate)
        done = []
        try:
            def worker():
                done.append(client.predict("slow", np.zeros((1, 3))).shape)

            threads = [threading.Thread(target=worker) for _ in range(2)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 5.0
            while (server.admission.inflight < 2
                   and time.monotonic() < deadline):
                time.sleep(0.002)
            assert server.admission.inflight == 2
            with pytest.raises(ServingError) as ei:
                client.predict("slow", np.zeros((1, 3)))
            assert ei.value.status == 429
            assert ei.value.retry_after_s == pytest.approx(0.125)
            gate.gate.set()
            for t in threads:
                t.join(timeout=10)
            assert done == [(1, 2), (1, 2)]
            assert metrics.get("serving_admission_rejections_total").value(
                reason="overflow") == 1
            assert metrics.get("serving_requests_total").value(
                model="slow", status="429") == 1
        finally:
            gate.gate.set()
            server.stop(drain=False)
            registry.shutdown()

    def test_dispatcher_crash_contained_as_503(self, stack):
        """A dispatcher-thread crash must fail in-flight AND future requests
        with 503 — no hung clients — and flip /readyz."""
        metrics, registry, server, client = stack
        registry.register("m", small_net())
        pi = registry.get("m").inference

        def boom(batch, n):
            raise RuntimeError("device fell over")

        pi._dispatch = boom
        with pytest.raises(ServingError) as ei:
            client.predict("m", np.zeros((2, 12), np.float32))
        assert ei.value.status == 503        # in-flight request unblocked
        with pytest.raises(ServingError) as ei:
            client.predict("m", np.zeros((2, 12), np.float32))
        assert ei.value.status == 503        # fast-fail, dispatcher is gone
        assert not pi.healthy
        assert not client.ready()
        assert not registry.healthy()
        assert metrics.get("inference_dispatcher_up").value(model="m") == 0

    def test_keep_alive_connection_survives_reject_paths(self, stack):
        """HTTP/1.1 keep-alive: a rejected POST (404) must still drain the
        request body, or the next request on the same socket would parse
        the stale body as its request line."""
        import http.client
        metrics, registry, server, client = stack
        registry.register("m", small_net())
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
        try:
            body = json.dumps(
                {"inputs": np.zeros((2, 12)).tolist()}).encode()
            conn.request("POST", "/v1/models/ghost/predict", body,
                         {"Content-Type": "application/json"})
            r1 = conn.getresponse()
            assert r1.status == 404
            r1.read()
            # SAME socket: must parse cleanly after the rejected request
            conn.request("POST", "/v1/models/m/predict", body,
                         {"Content-Type": "application/json"})
            r2 = conn.getresponse()
            assert r2.status == 200
            assert json.loads(r2.read())["version"] == 1
        finally:
            conn.close()

    def test_predict_reports_the_version_that_served(self, stack):
        """The response's version field comes from the model object that
        actually served the batch — not a post-hoc registry read."""
        metrics, registry, server, client = stack
        a, b = small_net(seed=1), small_net(seed=2)
        registry.register("m", a)
        registry.register("m", b)
        out, ver = registry.predict_versioned(
            "m", np.zeros((2, 12), np.float32))
        np.testing.assert_allclose(
            out, np.asarray(b.output(np.zeros((2, 12), np.float32))),
            rtol=1e-5, atol=1e-6)
        assert ver == 2
        out1, ver1 = registry.predict_versioned(
            "m", np.zeros((2, 12), np.float32), version=1)
        assert ver1 == 1

    def test_graceful_drain_shutdown(self):
        registry = ModelRegistry()
        server = ModelServer(registry)
        server.start()
        client = ModelServingClient(server.url)
        registry.register("m", small_net())
        assert client.predict("m", np.zeros((1, 12), np.float32)).shape == (1, 4)
        server.stop(drain=True, shutdown_registry=True)
        assert not client.ready()            # listener closed → not ready
        assert not client.healthy()
        with pytest.raises(RuntimeError):
            registry.predict("m", np.zeros((1, 12), np.float32))


# ------------------------------------------------- shared observability core
class TestSharedMetricsCore:
    def test_knn_server_reports_through_shared_registry(self, rng):
        from deeplearning4j_tpu.clustering.server import (
            NearestNeighborsClient, NearestNeighborsServer)
        metrics = MetricsRegistry()
        srv = NearestNeighborsServer(
            rng.normal(size=(16, 4)).astype(np.float32), port=0,
            metrics=metrics)
        port = srv.start()
        try:
            c = NearestNeighborsClient(f"http://127.0.0.1:{port}")
            c.knn(0, 3)
            c.knn_new(np.zeros(4, np.float32), 2)
            reqs = metrics.get("http_requests_total")

            # the mixin records AFTER the response bytes are written; the
            # client can observe the body first — poll briefly (the same
            # discipline the UI-server test below applies)
            def _poll(path, want):
                for _ in range(200):
                    if reqs.value(server="knn", path=path,
                                  status="200") == want:
                        break
                    time.sleep(0.005)
                assert reqs.value(server="knn", path=path,
                                  status="200") == want
            _poll("/knn", 1)
            _poll("/knnnew", 1)
            assert metrics.get("http_request_latency_seconds").count(
                server="knn", path="/knn") == 1
            # a malformed request line (rejected before self.path is set)
            # must not crash the instrumented handler...
            import socket
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=5) as s:
                s.sendall(b"GET /x HTTP/garbage\r\n\r\n")
                assert s.recv(64)  # error reply, not a dropped connection
            # ...and the server keeps serving afterwards
            c.knn(0, 1)
            _poll("/knn", 2)
        finally:
            srv.stop()

    def test_ui_server_reports_through_shared_registry(self):
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        metrics = MetricsRegistry()
        ui = UIServer(port=0, metrics=metrics)
        ui.attach(InMemoryStatsStorage())
        port = ui.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/train/sessions",
                    timeout=5) as r:
                assert r.status == 200
            reqs = metrics.get("http_requests_total")
            # the mixin records AFTER the response bytes are written; the
            # client can observe the body first — poll briefly
            for _ in range(200):
                if reqs.value(server="ui", path="/train/sessions",
                              status="200") == 1:
                    break
                time.sleep(0.005)
            assert reqs.value(server="ui", path="/train/sessions",
                              status="200") == 1
        finally:
            ui.stop()
