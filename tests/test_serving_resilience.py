"""Serving resilience tests (round 13): dispatcher supervision, per-version
circuit breakers, fallback-chain failover, resilient client policy, brownout
degradation — all proven under DETERMINISTIC injected chaos.

Every timing-sensitive path runs on injectable clocks (``ManualTimeSource``
for breakers/brownout/restart backoff, recorded ``sleep`` for client
backoff): no test sleeps to make time pass. Forward crashes come either
from the ``crash_forward`` fault kind (``util/faultinject.py``, keyed on
(model, dispatch seq) — replayable from ``DL4J_TPU_FAULT_PLAN``) or from a
``BaseException``-raising stub model (the same containment seam). The
acceptance proof at the bottom is the ISSUE's CI chaos bar: a crash storm
trips the breaker, traffic fails over with zero client-visible 5xx after
the trip, the dispatcher restarts under budget, the breaker half-opens and
closes once faults stop, availability holds its floor, and the
observability plane answers at every phase.
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.elastic import BackoffPolicy
from deeplearning4j_tpu.parallel.inference import (DispatcherCrashed,
                                                   ParallelInference)
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource
from deeplearning4j_tpu.serving import (BrownoutController, CircuitBreaker,
                                        MetricsRegistry, ModelRegistry,
                                        ModelServer, ModelServingClient,
                                        RetryPolicy, ServingError,
                                        VersionQuarantined)
from deeplearning4j_tpu.serving import breaker as breaker_mod
from deeplearning4j_tpu.util import faultinject

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_net(seed=7, n_in=8, n_out=2):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=n_out, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    return MultiLayerNetwork(conf).init()


class _Boom(BaseException):
    """Escapes the dispatcher's per-request Exception handler — the same
    seam crash_forward uses, without needing a fault plan."""


class _CrashingModel:
    """Duck model whose Nth forward calls kill the dispatcher thread."""

    def __init__(self, crash_calls=(), n_out=2):
        self.crash_calls = set(crash_calls)
        self.calls = 0
        self.n_out = n_out
        self._lock = threading.Lock()

    def output(self, x):
        with self._lock:
            i = self.calls
            self.calls += 1
        if i in self.crash_calls:
            raise _Boom(f"injected crash at forward call {i}")
        x = np.asarray(x)
        return np.zeros((x.shape[0], self.n_out), np.float32)


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faultinject.set_plan(None)


def manual_clocked_pi(model, *, max_restarts=0, base_s=1.0, **kw):
    """(pi, clock_list): a batched PI whose restart clock is clock[0]."""
    clock = [0.0]
    pi = ParallelInference(
        model, max_batch_size=4, buckets=[4], wait_ms=0.5,
        max_restarts=max_restarts,
        restart_backoff=BackoffPolicy(base_s=base_s, jitter=0.0),
        restart_clock=lambda: clock[0], **kw)
    return pi, clock


# ----------------------------------------------------- serving fault kinds
class TestServingFaultPlan:
    def test_serving_kinds_need_model(self):
        with pytest.raises(ValueError, match="needs a 'model'"):
            faultinject.FaultPlan.parse(
                {"faults": [{"type": "crash_forward", "step": 1}]})

    def test_serving_kinds_reject_worker_host_phase(self):
        for bad in ({"worker": 0}, {"host": 1}, {"phase": "pre_write"}):
            with pytest.raises(ValueError, match="not valid on the serving"):
                faultinject.FaultPlan.parse(
                    {"faults": [dict({"type": "crash_forward", "model": "m",
                                      "step": 1}, **bad)]})

    def test_model_field_rejected_on_training_kinds(self):
        with pytest.raises(ValueError, match="'model' is only valid"):
            faultinject.FaultPlan.parse(
                {"faults": [{"type": "kill", "worker": 0, "step": 1,
                             "model": "m"}]})

    def test_lint_reject_admission_shadows_drop_response(self):
        plan = faultinject.FaultPlan.parse({"faults": [
            {"type": "reject_admission", "model": "m", "step": 3},
            {"type": "drop_response", "model": "m", "step": 3}]})
        assert any("can never fire" in p for p in plan.lint())

    def test_lint_crash_shadows_slow_forward_same_seq(self):
        plan = faultinject.FaultPlan.parse({"faults": [
            {"type": "crash_forward", "model": "m", "step": 2},
            {"type": "slow_forward", "model": "m", "step": 2}]})
        assert any("crashes that dispatch first" in p for p in plan.lint())

    def test_validator_models_bound(self):
        sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
        try:
            from validate_fault_plan import validate_plan
        finally:
            sys.path.pop(0)
        spec = {"faults": [{"type": "crash_forward", "model": "ghost",
                            "step": 1}]}
        assert validate_plan(spec) == []
        errors = validate_plan(spec, models=["mnist"])
        assert any("ghost" in e and "never fire" in e for e in errors)

    def test_hooks_are_noops_without_plan(self):
        faultinject.set_plan(None)
        faultinject.on_forward("m", 0)  # no raise
        assert faultinject.on_admission("m", 0)
        assert faultinject.on_response("m", 0)

    def test_on_forward_crash_and_slow(self, monkeypatch):
        slept = []
        monkeypatch.setattr(faultinject, "_sleep", slept.append)
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "crash_forward", "model": "m", "step": 1},
            {"type": "slow_forward", "model": "m", "step": 2,
             "duration_s": 0.25}]}))
        faultinject.on_forward("m", 0)
        with pytest.raises(faultinject.InjectedDispatcherCrash):
            faultinject.on_forward("m", 1)
        assert not isinstance(faultinject.InjectedDispatcherCrash("x"),
                              Exception)
        faultinject.on_forward("m", 2)
        assert slept == [0.25]
        faultinject.on_forward("other", 1)  # other models untouched


# ------------------------------------------------- dispatcher supervision
class TestDispatcherSupervision:
    def test_crash_restart_and_recover(self):
        metrics = MetricsRegistry()
        model = _CrashingModel(crash_calls={1})
        pi, clock = manual_clocked_pi(model, max_restarts=2,
                                      metrics=metrics)
        try:
            x = np.zeros((2, 3), np.float32)
            assert pi.output(x).shape == (2, 2)
            with pytest.raises(DispatcherCrashed) as ei:
                pi.output(x)
            assert ei.value.dispatched       # its forward took the thread
            assert ei.value.retry_after_s == pytest.approx(1.0)
            # fast-fail while the backoff runs: NOT breaker evidence
            with pytest.raises(DispatcherCrashed) as ei:
                pi.output(x)
            assert not ei.value.dispatched
            assert ei.value.retry_after_s == pytest.approx(1.0)
            state = pi.restart_state()
            assert state["crashed"] and state["restart_pending"]
            assert not state["terminal"]
            clock[0] = 1.5
            assert pi.output(x).shape == (2, 2)   # restarted in place
            assert pi.healthy
            assert pi.restarts_used == 1
            assert metrics.get(
                "serving_dispatcher_restarts_total").value(
                    model="default") == 1
            assert metrics.get("inference_dispatcher_up").value(
                model="default") == 1
        finally:
            pi.shutdown()

    def test_budget_exhaustion_is_terminal(self):
        model = _CrashingModel(crash_calls={0, 1})
        pi, clock = manual_clocked_pi(model, max_restarts=1)
        try:
            x = np.zeros((1, 3), np.float32)
            with pytest.raises(DispatcherCrashed):
                pi.output(x)                      # crash 1
            clock[0] = 10.0
            with pytest.raises(DispatcherCrashed):
                pi.output(x)                      # restart 1, crash 2
            with pytest.raises(DispatcherCrashed) as ei:
                pi.output(x)                      # budget gone: terminal
            assert ei.value.retry_after_s is None
            assert "budget" in str(ei.value)
            assert pi.restart_state()["terminal"]
            assert not pi.healthy
        finally:
            pi.shutdown()

    def test_unsupervised_crash_keeps_old_contract(self):
        pi, _ = manual_clocked_pi(_CrashingModel(crash_calls={0}))
        try:
            x = np.zeros((1, 3), np.float32)
            with pytest.raises(DispatcherCrashed):
                pi.output(x)
            with pytest.raises(DispatcherCrashed) as ei:
                pi.output(x)
            assert ei.value.retry_after_s is None
            assert not pi.healthy
        finally:
            pi.shutdown()

    def test_exponential_backoff_between_restarts(self):
        model = _CrashingModel(crash_calls={0, 1})
        pi, clock = manual_clocked_pi(model, max_restarts=3, base_s=1.0)
        try:
            x = np.zeros((1, 3), np.float32)
            with pytest.raises(DispatcherCrashed) as ei:
                pi.output(x)
            assert ei.value.retry_after_s == pytest.approx(1.0)
            clock[0] = 1.0
            with pytest.raises(DispatcherCrashed) as ei:
                pi.output(x)                      # restart 1 -> crash 2
            assert ei.value.retry_after_s == pytest.approx(2.0)  # 2nd rung
        finally:
            pi.shutdown()

    def test_crash_forward_fault_drives_supervision(self):
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "crash_forward", "model": "default", "step": 0}]}))
        pi, clock = manual_clocked_pi(_CrashingModel(), max_restarts=1)
        try:
            with pytest.raises(DispatcherCrashed) as ei:
                pi.output(np.zeros((1, 3), np.float32))
            assert ei.value.dispatched
            clock[0] = 5.0
            assert pi.output(np.zeros((1, 3), np.float32)).shape == (1, 2)
        finally:
            pi.shutdown()


# ------------------------------------------------------- circuit breaker
class TestCircuitBreaker:
    def test_trips_at_threshold_within_window(self):
        ts = ManualTimeSource()
        br = CircuitBreaker(failure_threshold=3, window_s=10.0,
                            time_source=ts)
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"
        br.record_failure()
        assert br.state == "open"
        assert br.opened_total == 1
        assert br.allow() == breaker_mod.FALLBACK

    def test_old_failures_age_out_of_window(self):
        ts = ManualTimeSource()
        br = CircuitBreaker(failure_threshold=2, window_s=5.0,
                            time_source=ts)
        br.record_failure()
        ts.advance(seconds=6)
        br.record_failure()                # the first one aged out
        assert br.state == "closed"
        br.record_failure()
        assert br.state == "open"

    def test_half_open_probe_closes_after_successes(self):
        ts = ManualTimeSource()
        br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                            half_open_probes=2, time_source=ts)
        br.record_failure()
        assert br.state == "open"
        assert br.allow() == breaker_mod.FALLBACK
        ts.advance(seconds=6)
        assert br.allow() == breaker_mod.PROBE    # cooldown elapsed
        assert br.state == "half_open"
        assert br.allow() == breaker_mod.FALLBACK  # one probe at a time
        br.record_success(probe=True)
        assert br.state == "half_open"            # needs 2 successes
        assert br.allow() == breaker_mod.PROBE
        br.record_success(probe=True)
        assert br.state == "closed"
        assert br.allow() == breaker_mod.ALLOW

    def test_probe_failure_reopens_with_fresh_cooldown(self):
        ts = ManualTimeSource()
        br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                            time_source=ts)
        br.record_failure()
        ts.advance(seconds=6)
        assert br.allow() == breaker_mod.PROBE
        br.record_failure(probe=True)
        assert br.state == "open"
        assert br.opened_total == 2
        assert br.allow() == breaker_mod.FALLBACK
        assert br.retry_after_s() == pytest.approx(5.0)
        ts.advance(seconds=6)
        assert br.allow() == breaker_mod.PROBE

    def test_abort_probe_releases_the_slot(self):
        ts = ManualTimeSource()
        br = CircuitBreaker(failure_threshold=1, cooldown_s=1.0,
                            time_source=ts)
        br.record_failure()
        ts.advance(seconds=2)
        assert br.allow() == breaker_mod.PROBE
        br.abort_probe()                          # no verdict
        assert br.state == "half_open"
        assert br.allow() == breaker_mod.PROBE    # slot free again

    def test_interleaved_successes_do_not_reset_the_window(self):
        """A version crashing on 1-in-N requests (poison input) must
        still trip: each crash burns a shared dispatcher restart, so
        only TIME ages failures out of the window — not successes."""
        ts = ManualTimeSource()
        br = CircuitBreaker(failure_threshold=3, window_s=100.0,
                            time_source=ts)
        for _ in range(2):
            br.record_failure()
            br.record_success()
            ts.advance(seconds=1)
            assert br.state == "closed"
        br.record_failure()
        assert br.state == "open"                 # 3 crashes in-window

    def test_transition_log_and_describe(self):
        ts = ManualTimeSource()
        br = CircuitBreaker(failure_threshold=1, cooldown_s=1.0,
                            time_source=ts, name="m:v2")
        br.record_failure()
        ts.advance(seconds=2)
        br.allow()
        br.record_success(probe=True)
        states = [(t["from"], t["to"]) for t in br.describe()["transitions"]]
        assert states == [("closed", "open"), ("open", "half_open"),
                          ("half_open", "closed")]

    def test_validates_knobs(self):
        with pytest.raises(ValueError):
            CircuitBreaker(failure_threshold=0)
        with pytest.raises(ValueError):
            CircuitBreaker(half_open_probes=0)


# ------------------------------------------------ fallback chain resolution
class TestFallbackResolution:
    def _registry(self, ts=None, **kw):
        return ModelRegistry(metrics=MetricsRegistry(), buckets=[4],
                             max_batch_size=4, time_source=ts, **kw)

    def test_chain_order_and_previous(self):
        reg = self._registry()
        try:
            reg.register("m", small_net(1))
            reg.register("m", small_net(2))
            reg.register("m", small_net(3), activate=False)
            reg.set_fallback("m", [3, "previous"])
            assert reg.resolve_fallback("m", exclude=2) == 3
            reg.set_fallback("m", ["previous", 3])
            assert reg.resolve_fallback("m", exclude=2) == 1  # previous
            assert reg.resolve_fallback("m", exclude=1) == 3
        finally:
            reg.shutdown()

    def test_unknown_version_rejected_previous_always_ok(self):
        reg = self._registry()
        try:
            reg.register("m", small_net(1))
            with pytest.raises(KeyError):
                reg.set_fallback("m", [9])
            reg.set_fallback("m", ["previous"])   # resolves to None now
            assert reg.resolve_fallback("m") is None
        finally:
            reg.shutdown()

    def test_open_breaker_version_is_skipped(self):
        ts = ManualTimeSource()
        reg = self._registry(ts=ts, breaker=dict(failure_threshold=1))
        try:
            reg.register("m", small_net(1))
            reg.register("m", small_net(2))
            reg.register("m", small_net(3), activate=False)
            reg.set_fallback("m", ["previous", 3])
            reg.get("m").breakers[1].record_failure()  # quarantine v1
            assert reg.resolve_fallback("m", exclude=2) == 3
        finally:
            reg.shutdown()

    def test_cold_version_is_skipped(self):
        reg = ModelRegistry(metrics=MetricsRegistry(), buckets=[4],
                            max_batch_size=4, warmup="async")
        try:
            reg.register("m", small_net(1))
            # v2's async warmup may still be pending: force a cold state
            reg.register("m", small_net(2), activate=False)
            served = reg.get("m")
            served.warmup_state[2] = {"status": "warming", "buckets": [4],
                                      "warm": [], "seconds": 0,
                                      "reason": None}
            reg.set_fallback("m", [2])
            assert reg.resolve_fallback("m") is None
        finally:
            reg.shutdown()

    def test_unregister_prunes_chain_and_breaker(self):
        reg = self._registry(breaker=dict(failure_threshold=1))
        try:
            reg.register("m", small_net(1))
            reg.register("m", small_net(2))
            reg.register("m", small_net(3), activate=False)
            reg.set_fallback("m", [3, "previous"])
            reg.unregister("m", 3)
            assert reg.get_fallback("m") == ["previous"]
            assert 3 not in reg.breaker_states("m")
        finally:
            reg.shutdown()


# ----------------------------------------------- registry failover choreo
class TestRegistryFailover:
    def _stack(self, *, fallback=True, breaker=True, max_restarts=5):
        ts = ManualTimeSource()
        metrics = MetricsRegistry()
        reg = ModelRegistry(
            metrics=metrics, buckets=[4], max_batch_size=4,
            max_dispatcher_restarts=max_restarts,
            restart_backoff=BackoffPolicy(base_s=1.0, jitter=0.0),
            breaker=dict(failure_threshold=2, window_s=60.0,
                         cooldown_s=10.0, half_open_probes=1)
            if breaker else None,
            time_source=ts)
        reg.register("m", small_net(1))
        crashy = _CrashingModel(crash_calls={0, 1, 2})
        reg.register("m", crashy)        # v2 live, crashes 3 forwards
        if fallback:
            reg.set_fallback("m", ["previous"])
        return ts, metrics, reg, crashy

    def test_crash_fails_over_and_breaker_trips(self):
        ts, metrics, reg, crashy = self._stack()
        x = np.zeros((2, 8), np.float32)
        try:
            out, v = reg.predict_versioned("m", x)     # crash 0 -> failover
            assert v == 1
            assert reg.breaker_state("m") == "closed"  # 1 of 2 failures
            out, v = reg.predict_versioned("m", x)     # restart pending
            assert v == 1
            ts.advance(seconds=2)
            out, v = reg.predict_versioned("m", x)     # crash 1 -> OPEN
            assert v == 1
            assert reg.breaker_state("m") == "open"
            out, v = reg.predict_versioned("m", x)     # quarantined
            assert v == 1
            g = metrics.get("serving_breaker_state")
            assert g.value(model="m", version="2") == 1
            deg = metrics.get("serving_degraded_requests_total")
            # crash 0, the restart-pending fast-fail, crash 1: all three
            # failed over (the fast-fail is a failover too — the client
            # must not eat a 503 the chain can absorb)
            assert deg.value(model="m", reason="crash_failover") == 3
            assert deg.value(model="m", reason="breaker_open") >= 1
        finally:
            reg.shutdown()

    def test_half_open_probe_reopens_then_closes(self):
        ts, metrics, reg, crashy = self._stack()
        x = np.zeros((2, 8), np.float32)
        try:
            reg.predict_versioned("m", x)              # crash 0
            ts.advance(seconds=2)
            reg.predict_versioned("m", x)              # crash 1 -> open
            ts.advance(seconds=15)                     # cooldown + backoff
            out, v = reg.predict_versioned("m", x)     # probe: crash 2
            assert v == 1                              # still served
            assert reg.breaker_state("m") == "open"    # re-opened
            ts.advance(seconds=15)
            out, v = reg.predict_versioned("m", x)     # probe: healthy now
            assert v == 2                              # primary serves
            assert reg.breaker_state("m") == "closed"
            out, v = reg.predict_versioned("m", x)
            assert v == 2
            assert metrics.get("serving_breaker_state").value(
                model="m", version="2") == 0
        finally:
            reg.shutdown()

    def test_open_breaker_without_fallback_raises_quarantined(self):
        ts, metrics, reg, crashy = self._stack(fallback=False)
        x = np.zeros((2, 8), np.float32)
        try:
            with pytest.raises(DispatcherCrashed):
                reg.predict_versioned("m", x)          # crash 0 surfaces
            ts.advance(seconds=2)
            with pytest.raises(DispatcherCrashed):
                reg.predict_versioned("m", x)          # crash 1 -> open
            with pytest.raises(VersionQuarantined) as ei:
                reg.predict_versioned("m", x)
            assert ei.value.retry_after_s == pytest.approx(10.0)
        finally:
            reg.shutdown()

    def test_pinned_requests_bypass_breaker_and_failover(self):
        ts, metrics, reg, crashy = self._stack()
        x = np.zeros((2, 8), np.float32)
        try:
            reg.predict_versioned("m", x)              # crash 0
            ts.advance(seconds=2)
            reg.predict_versioned("m", x)              # crash 1 -> open
            # pinned to a NON-live version: sync path, breaker ignored —
            # the caller named the version, they get exactly it
            out, v = reg.predict_versioned("m", x, version=1)
            assert v == 1
            # pinned to the LIVE version rides the dispatcher (that is
            # where the live version serves) and does NOT fail over: a
            # pinned caller asked for v2 or nothing
            with pytest.raises(DispatcherCrashed):
                reg.predict_versioned("m", x, version=2)
        finally:
            reg.shutdown()

    def test_failover_without_breaker_still_serves(self):
        ts, metrics, reg, crashy = self._stack(breaker=False)
        x = np.zeros((2, 8), np.float32)
        try:
            out, v = reg.predict_versioned("m", x)     # crash 0 -> failover
            assert v == 1
            assert reg.breaker_state("m") is None
        finally:
            reg.shutdown()


# ----------------------------------------------------- HTTP front-end tier
class TestServerResilience:
    def test_dispatcher_crash_503_carries_retry_after(self):
        """Satellite: the dispatcher-crash 503 sends Retry-After even
        with supervision OFF (terminal crash, default hint)."""
        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics)
        server = ModelServer(reg, metrics=metrics)
        server.start()
        client = ModelServingClient(server.url)
        try:
            reg.register("m", small_net())
            pi = reg.get("m").inference

            def boom(batch, n):
                raise RuntimeError("device fell over")

            pi._dispatch = boom
            with pytest.raises(ServingError) as ei:
                client.predict("m", np.zeros((2, 8), np.float32))
            assert ei.value.status == 503
            assert ei.value.retry_after_s is not None
            with pytest.raises(ServingError) as ei:
                client.predict("m", np.zeros((2, 8), np.float32))
            assert ei.value.status == 503
            assert ei.value.retry_after_s is not None
        finally:
            client.close()
            server.stop(drain=False)
            reg.shutdown()

    def test_supervised_crash_503_hints_the_backoff(self):
        ts = ManualTimeSource()
        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics, buckets=[4], max_batch_size=4,
                            max_dispatcher_restarts=2,
                            restart_backoff=BackoffPolicy(base_s=2.0,
                                                          jitter=0.0),
                            time_source=ts)
        server = ModelServer(reg, metrics=metrics)
        server.start()
        client = ModelServingClient(server.url)
        x = np.zeros((1, 8), np.float32)
        try:
            reg.register("m", _CrashingModel(crash_calls={0}))
            with pytest.raises(ServingError) as ei:
                client.predict("m", x)
            assert ei.value.status == 503
            with pytest.raises(ServingError) as ei:
                client.predict("m", x)         # restart pending
            assert ei.value.status == 503
            assert ei.value.retry_after_s == pytest.approx(2.0, abs=0.1)
            ts.advance(seconds=3)
            assert client.predict("m", x).shape == (1, 2)  # healed
        finally:
            client.close()
            server.stop(drain=False)
            reg.shutdown()

    def test_degraded_header_on_breaker_failover(self):
        ts = ManualTimeSource()
        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics, buckets=[4], max_batch_size=4,
                            max_dispatcher_restarts=5,
                            restart_backoff=BackoffPolicy(base_s=1.0,
                                                          jitter=0.0),
                            breaker=dict(failure_threshold=1,
                                         cooldown_s=10.0),
                            time_source=ts)
        server = ModelServer(reg, metrics=metrics)
        server.start()
        x = np.zeros((1, 8), np.float32)
        try:
            reg.register("m", small_net(1))
            reg.register("m", _CrashingModel(crash_calls={0}))
            reg.set_fallback("m", ["previous"])
            body = json.dumps({"inputs": x.tolist()}).encode()

            def post():
                return urllib.request.urlopen(urllib.request.Request(
                    f"{server.url}/v1/models/m/predict", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=10)

            r = post()                         # crash -> failover (closed
            d = json.loads(r.read())           # -> open at threshold 1)
            assert d["version"] == 1
            r = post()                         # breaker open now
            assert r.headers.get("X-Degraded") == "breaker"
            assert json.loads(r.read())["version"] == 1
        finally:
            server.stop(drain=False)
            reg.shutdown()

    def test_injected_admission_rejection_and_drop(self):
        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics, buckets=[4], max_batch_size=4)
        server = ModelServer(reg, metrics=metrics)
        server.start()
        cm = MetricsRegistry()
        client = ModelServingClient(server.url, metrics=cm)
        x = np.zeros((1, 8), np.float32)
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "reject_admission", "model": "m", "step": 1},
            {"type": "drop_response", "model": "m", "step": 3}]}))
        try:
            reg.register("m", small_net())
            assert client.predict("m", x).shape == (1, 2)   # seq 0
            with pytest.raises(ServingError) as ei:
                client.predict("m", x)                      # seq 1: shed
            assert ei.value.status == 429
            assert ei.value.retry_after_s is not None
            assert client.predict("m", x).shape == (1, 2)   # seq 2
            # seq 3: the response is computed then the connection severed;
            # the keep-alive client reconnects and retries transparently
            assert client.predict("m", x).shape == (1, 2)
            assert cm.get("client_reconnects_total").total() == 1
            assert metrics.get("serving_dropped_responses_total").value(
                model="m") == 1
        finally:
            client.close()
            server.stop(drain=False)
            reg.shutdown()


# ------------------------------------------------------- resilient client
class _FlakyHTTPStack:
    """Server whose model works; flakiness injected via fault plan."""

    def __init__(self, faults, **client_kw):
        self.metrics = MetricsRegistry()
        self.registry = ModelRegistry(metrics=self.metrics, buckets=[4],
                                      max_batch_size=4)
        self.registry.register("m", small_net())
        self.server = ModelServer(self.registry, metrics=self.metrics)
        self.server.start()
        self.client_metrics = MetricsRegistry()
        self.sleeps = []
        self.client = ModelServingClient(
            self.server.url, metrics=self.client_metrics,
            sleep=self.sleeps.append, **client_kw)
        if faults:
            faultinject.set_plan(faultinject.FaultPlan.parse(
                {"faults": faults}))

    def close(self):
        faultinject.set_plan(None)
        self.client.close()
        self.server.stop(drain=False)
        self.registry.shutdown()


class TestResilientClient:
    def test_retries_429_with_deterministic_backoff(self):
        s = _FlakyHTTPStack(
            [{"type": "reject_admission", "model": "m", "step": i}
             for i in (0, 1)],
            retry=RetryPolicy(max_retries=3, base_s=0.05, factor=2.0,
                              jitter=0.0))
        try:
            out = s.client.predict("m", np.zeros((1, 8), np.float32))
            assert out.shape == (1, 2)
            # two 429s -> two backoffs; Retry-After (0.05 default) is a
            # floor under the computed exponential delays
            assert s.sleeps == [pytest.approx(0.05), pytest.approx(0.1)]
            assert s.client_metrics.get("client_retries_total").value(
                reason="429") == 2
        finally:
            s.close()

    def test_retry_after_floors_the_backoff(self):
        pol = RetryPolicy(base_s=0.001, factor=2.0, jitter=0.0)
        assert pol.delay(1, retry_after_s=0.5) == pytest.approx(0.5)
        assert pol.delay(1) == pytest.approx(0.001)

    def test_jitter_is_deterministic(self):
        pol = RetryPolicy(jitter=0.2)
        a = pol.delay(2, seed="/v1/models/m/predict")
        b = pol.delay(2, seed="/v1/models/m/predict")
        c = pol.delay(2, seed="/v1/models/other/predict")
        assert a == b
        assert a != c

    def test_budget_drain_stops_retries(self):
        # every request rejected; budget starts at 1 token -> exactly one
        # retry fires across the whole storm, then errors surface raw
        s = _FlakyHTTPStack(
            [{"type": "reject_admission", "model": "m", "step": i}
             for i in range(12)],
            retry=RetryPolicy(max_retries=5, jitter=0.0,
                              budget_initial=1.0, budget_ratio=0.0))
        try:
            for _ in range(4):
                with pytest.raises(ServingError):
                    s.client.predict("m", np.zeros((1, 8), np.float32))
            assert s.client_metrics.get(
                "client_retries_total").total() == 1
            assert s.client.retry_budget == pytest.approx(0.0)
        finally:
            s.close()

    def test_non_retryable_statuses_surface_immediately(self):
        s = _FlakyHTTPStack([], retry=RetryPolicy(max_retries=3))
        try:
            with pytest.raises(ServingError) as ei:
                s.client.predict("ghost", np.zeros((1, 8), np.float32))
            assert ei.value.status == 404
            assert s.sleeps == []
        finally:
            s.close()

    def test_reconnect_failure_preserves_cause(self):
        s = _FlakyHTTPStack([])
        try:
            x = np.zeros((1, 8), np.float32)
            assert s.client.predict("m", x).shape == (1, 2)
            s.server.stop(drain=False)   # severs the keep-alive socket
            with pytest.raises(OSError) as ei:
                s.client.predict("m", x)
            # the retry's ConnectionRefused chains back to the original
            # dead-socket failure — postmortems see both
            assert ei.value.__cause__ is not None
            assert s.client_metrics.get(
                "client_reconnects_total").total() == 1
        finally:
            s.close()

    def test_hedged_request_wins_on_slow_primary(self):
        class _SlowFirstCall:
            """First forward blocks until released; later calls are
            instant — the hedge overtakes the stuck primary."""

            def __init__(self):
                self.gate = threading.Event()
                self.calls = 0
                self._lock = threading.Lock()

            def output(self, x):
                with self._lock:
                    self.calls += 1
                    first = self.calls == 1
                if first:
                    assert self.gate.wait(10.0)
                x = np.asarray(x)
                return np.zeros((x.shape[0], 2), np.float32)

        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics)
        model = _SlowFirstCall()
        reg.register("m", model)
        server = ModelServer(reg, metrics=metrics)
        server.start()
        cm = MetricsRegistry()
        client = ModelServingClient(
            server.url, metrics=cm,
            retry=RetryPolicy(hedge_after_s=0.05, jitter=0.0))
        try:
            out = client.predict("m", np.zeros((1, 8), np.float32))
            assert out.shape == (1, 2)
            assert cm.get("client_hedges_total").total() == 1
            assert cm.get("client_hedge_wins_total").total() == 1
            model.gate.set()             # release the stuck primary
        finally:
            model.gate.set()
            client.close()
            server.stop(drain=False)
            reg.shutdown()


# ------------------------------------------------------------- brownout
class _StubAdmission:
    def __init__(self, inflight=0, max_inflight=10):
        self.inflight = inflight
        self.max_inflight = max_inflight


class _StubAlerts:
    def __init__(self):
        self.names = []

    def firing(self):
        return list(self.names)


class TestBrownout:
    def test_sustained_saturation_enters_and_exits(self):
        ts = ManualTimeSource()
        adm = _StubAdmission(inflight=10)
        metrics = MetricsRegistry()
        b = BrownoutController(admission=adm, saturation=0.9,
                               enter_after_s=2.0, exit_after_s=3.0,
                               time_source=ts, metrics=metrics)
        assert not b.observe()            # pressure starts the clock
        ts.advance(seconds=1)
        assert not b.observe()            # not sustained yet
        ts.advance(seconds=1.5)
        assert b.observe()                # sustained -> engaged
        assert metrics.get("serving_brownout_active").value() == 1
        adm.inflight = 0
        assert b.observe()                # clear starts the exit clock
        ts.advance(seconds=2)
        assert b.observe()                # not clear long enough
        ts.advance(seconds=2)
        assert not b.observe()            # lifted
        assert metrics.get("serving_brownout_active").value() == 0
        kinds = [(t["active"]) for t in b.describe()["transitions"]]
        assert kinds == [True, False]

    def test_pressure_flap_resets_the_entry_clock(self):
        ts = ManualTimeSource()
        adm = _StubAdmission(inflight=10)
        b = BrownoutController(admission=adm, enter_after_s=5.0,
                               time_source=ts)
        b.observe()
        ts.advance(seconds=4)
        adm.inflight = 0
        b.observe()                        # pressure dropped: clock resets
        adm.inflight = 10
        ts.advance(seconds=4)
        assert not b.observe()             # 4s < 5s since the NEW onset
        ts.advance(seconds=6)
        assert b.observe()

    def test_alert_rule_pressure(self):
        ts = ManualTimeSource()
        alerts = _StubAlerts()
        b = BrownoutController(alerts=alerts,
                               watch_rules=("latency_burn",),
                               enter_after_s=0.0, time_source=ts)
        assert not b.observe()
        alerts.names = ["latency_burn"]
        assert b.observe()
        assert "latency_burn" in b.describe()["last_reason"]

    def test_shed_policy(self):
        b = BrownoutController(time_source=ManualTimeSource(),
                               shed_below=1)
        b.active = True
        assert b.should_shed(0)
        assert b.should_shed(1)
        assert not b.should_shed(2)
        b.active = False
        assert not b.should_shed(0)

    def test_server_sheds_low_priority_and_degrades_unpinned(self):
        ts = ManualTimeSource()
        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics, buckets=[4], max_batch_size=4)
        server = ModelServer(
            reg, metrics=metrics, max_inflight=100,
            brownout=dict(enter_after_s=0.0, exit_after_s=2.0,
                          time_source=ts))
        server.start()
        client = ModelServingClient(server.url)
        x = np.zeros((1, 8), np.float32)
        try:
            reg.register("m", small_net(1))
            reg.register("m", small_net(2))
            reg.set_fallback("m", ["previous"])
            # force pressure without real load: shrink the stub-side view
            server.brownout.admission = _StubAdmission(inflight=100,
                                                       max_inflight=100)
            with pytest.raises(ServingError) as ei:
                client.predict("m", x, priority=0)      # shed at the door
            assert ei.value.status == 429
            assert ei.value.retry_after_s is not None
            # high-priority serves, degraded onto the fallback chain
            body = json.dumps({"inputs": x.tolist()}).encode()
            r = urllib.request.urlopen(urllib.request.Request(
                f"{server.url}/v1/models/m/predict", data=body,
                headers={"Content-Type": "application/json",
                         "X-Priority": "2"}), timeout=10)
            assert r.headers.get("X-Degraded") == "brownout"
            assert json.loads(r.read())["version"] == 1
            assert metrics.get("serving_degraded_requests_total").value(
                model="m", reason="brownout") == 1
            assert metrics.get(
                "serving_admission_rejections_total").value(
                    reason="brownout") == 1
            # pinned requests are never degraded
            out, v = json.loads(urllib.request.urlopen(
                urllib.request.Request(
                    f"{server.url}/v1/models/m:2/predict", data=body,
                    headers={"Content-Type": "application/json"}),
                timeout=10).read()), None
            assert out["version"] == 2
            # pressure clears -> brownout lifts only after the exit
            # window has been CLEAR for exit_after_s (hysteresis)
            server.brownout.admission = _StubAdmission(inflight=0,
                                                       max_inflight=100)
            assert server.brownout.observe()    # clear clock starts
            ts.advance(seconds=3)
            assert client.predict("m", x, priority=0).shape == (1, 2)
            assert not server.brownout.active
        finally:
            client.close()
            server.stop(drain=False)
            reg.shutdown()


# ---------------------------------------- observability plane availability
class TestObservabilityPlaneSurvives:
    def _probe_all(self, server):
        """(path -> status) for the whole observability surface; raises
        only if a probe HANGS or the connection dies."""
        out = {}
        for path in ("/healthz", "/readyz", "/livez", "/metrics"):
            try:
                with urllib.request.urlopen(server.url + path,
                                            timeout=10) as r:
                    out[path] = r.status
            except urllib.error.HTTPError as e:
                out[path] = e.code
        return out

    def test_plane_survives_terminal_dispatcher_death(self):
        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics, buckets=[4], max_batch_size=4)
        server = ModelServer(reg, metrics=metrics)
        server.start()
        client = ModelServingClient(server.url)
        try:
            reg.register("m", _CrashingModel(crash_calls={0}))
            with pytest.raises(ServingError):
                client.predict("m", np.zeros((1, 8), np.float32))
            st = self._probe_all(server)
            assert st["/healthz"] == 200
            assert st["/metrics"] == 200
            assert st["/readyz"] == 503        # honest: data plane down
            assert st["/livez"] == 503         # terminal -> restart-worthy
        finally:
            client.close()
            server.stop(drain=False)
            reg.shutdown()

    def test_plane_survives_supervised_crash_and_restart(self):
        ts = ManualTimeSource()
        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics, buckets=[4], max_batch_size=4,
                            max_dispatcher_restarts=2,
                            restart_backoff=BackoffPolicy(base_s=5.0,
                                                          jitter=0.0),
                            breaker=dict(failure_threshold=3),
                            time_source=ts)
        server = ModelServer(reg, metrics=metrics)
        server.start()
        client = ModelServingClient(server.url)
        x = np.zeros((1, 8), np.float32)
        try:
            reg.register("m", _CrashingModel(crash_calls={0}))
            with pytest.raises(ServingError):
                client.predict("m", x)
            # crashed, restart pending: liveness must NOT ask for a
            # process restart — the supervisor will heal in place
            st = self._probe_all(server)
            assert st["/healthz"] == 200
            assert st["/metrics"] == 200
            assert st["/readyz"] == 503
            assert st["/livez"] == 200
            with urllib.request.urlopen(server.url + "/livez?verbose=1",
                                        timeout=10) as r:
                report = json.loads(r.read())
            assert report["status"] == "degraded"
            disp = [c for c in report["checks"]
                    if c["name"] == "dispatcher:m"][0]
            assert not disp["healthy"] and not disp["critical"]
            assert "restart" in disp["detail"]
            ts.advance(seconds=6)
            assert client.predict("m", x).shape == (1, 2)   # healed
            st = self._probe_all(server)
            assert st["/readyz"] == 200 and st["/livez"] == 200
            with urllib.request.urlopen(server.url + "/livez?verbose=1",
                                        timeout=10) as r:
                report = json.loads(r.read())
            disp = [c for c in report["checks"]
                    if c["name"] == "dispatcher:m"][0]
            assert disp["healthy"] and "restarted 1x" in disp["detail"]
        finally:
            client.close()
            server.stop(drain=False)
            reg.shutdown()

    def test_livez_reports_breaker_state(self):
        ts = ManualTimeSource()
        metrics = MetricsRegistry()
        reg = ModelRegistry(metrics=metrics, buckets=[4], max_batch_size=4,
                            breaker=dict(failure_threshold=1,
                                         cooldown_s=60.0),
                            time_source=ts)
        server = ModelServer(reg, metrics=metrics)
        server.start()
        try:
            reg.register("m", small_net())
            reg.get("m").breakers[1].record_failure()   # quarantine v1
            with urllib.request.urlopen(server.url + "/livez?verbose=1",
                                        timeout=10) as r:
                report = json.loads(r.read())
            brk = [c for c in report["checks"] if c["name"] == "breaker:m"]
            assert brk and not brk[0]["healthy"]
            assert "v1=open" in brk[0]["detail"]
            assert report["status"] == "degraded"
            # and /v1/models carries the quarantine for operators
            with urllib.request.urlopen(server.url + "/v1/models",
                                        timeout=10) as r:
                listing = json.loads(r.read())["models"]
            assert listing[0]["breakers"] == {"1": "open"}
        finally:
            server.stop(drain=False)
            reg.shutdown()


# --------------------------------------------------- the acceptance proof
class TestChaosAcceptance:
    def test_crash_storm_breaker_failover_restart_recovery(self):
        """The ISSUE's CI chaos bar, end to end over real HTTP on manual
        clocks: crash storm -> breaker opens -> un-pinned traffic fails
        over with ZERO client-visible 5xx after the trip -> dispatcher
        restarts under budget -> breaker half-opens, closes once faults
        stop -> availability >= 0.90 for the WHOLE run (1.0 after the
        trip), /livez + /metrics reachable at every phase."""
        ts = ManualTimeSource()
        metrics = MetricsRegistry()
        reg = ModelRegistry(
            metrics=metrics, buckets=[4], max_batch_size=4,
            max_dispatcher_restarts=5,
            restart_backoff=BackoffPolicy(base_s=1.0, jitter=0.0),
            breaker=dict(failure_threshold=2, window_s=60.0,
                         cooldown_s=10.0, half_open_probes=1),
            time_source=ts)
        server = ModelServer(reg, metrics=metrics)
        server.start()
        cm = MetricsRegistry()
        client = ModelServingClient(
            server.url, metrics=cm,
            retry=RetryPolicy(max_retries=3, jitter=0.0),
            sleep=lambda s: None)
        # non-trivial input: with an all-zeros batch both nets emit the
        # uniform softmax and the output-equality version oracle is blind
        x = np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32)
        net_a, net_b = small_net(1), small_net(2)
        want_a = np.asarray(net_a.output(x))
        want_b = np.asarray(net_b.output(x))
        assert np.abs(want_a - want_b).max() > 1e-3   # distinguishable
        reg.register("m", net_a)
        reg.register("m", net_b)            # v2 live
        reg.set_fallback("m", ["previous"])
        # the version under attack is v2: its dispatcher forwards 2-4
        # crash (0-1 are the healthy baseline; serial client => HTTP
        # request order == dispatch order)
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "crash_forward", "model": "m", "step": s}
            for s in (2, 3, 4)]}))
        outcomes = []                       # (ok, version, after_trip)

        def drive(n=1):
            for _ in range(n):
                try:
                    out = client.predict("m", x)
                    # identify the serving version by output equality
                    ver = 1 if np.abs(out - want_a).max() < 1e-5 else 2
                    ok = True
                except ServingError:
                    ok, ver = False, None
                tripped = reg.get("m").breakers[2].opened_total > 0
                outcomes.append((ok, ver, tripped))

        def probe_plane():
            for path in ("/livez", "/metrics"):
                with urllib.request.urlopen(server.url + path,
                                            timeout=10) as r:
                    assert r.status == 200, path

        try:
            drive(2)                        # phase 0: baseline on v2
            assert [v for _, v, _ in outcomes] == [2, 2]
            probe_plane()
            drive(1)                        # crash #1 -> failover to v1
            assert outcomes[-1] == (True, 1, False)
            drive(1)                        # restart pending -> failover
            assert outcomes[-1][0] and outcomes[-1][1] == 1
            probe_plane()
            ts.advance(seconds=2)           # backoff #1 elapses
            drive(1)                        # crash #2 -> breaker OPENS
            assert outcomes[-1] == (True, 1, True)
            assert reg.breaker_state("m") == "open"
            drive(3)                        # quarantined: fallback serves
            probe_plane()
            ts.advance(seconds=15)          # cooldown + backoff #2
            drive(1)                        # probe -> crash #3 -> re-open
            assert outcomes[-1] == (True, 1, True)
            assert reg.breaker_state("m") == "open"
            probe_plane()
            ts.advance(seconds=15)
            drive(1)                        # probe succeeds -> CLOSED
            assert outcomes[-1] == (True, 2, True)
            assert reg.breaker_state("m") == "closed"
            drive(3)                        # primary serves again
            assert [v for _, v, _ in outcomes[-3:]] == [2, 2, 2]
            probe_plane()

            # ---- acceptance numbers -------------------------------------
            successes = sum(1 for ok, _, _ in outcomes if ok)
            availability = successes / len(outcomes)
            assert availability >= 0.90
            assert availability == 1.0      # failover made it perfect
            after_trip = [(ok, v) for ok, v, t in outcomes if t]
            assert after_trip and all(ok for ok, _ in after_trip), \
                "client-visible failure AFTER the breaker tripped"
            pi = reg.get("m").inference
            assert 1 <= pi.restarts_used <= pi.max_restarts
            assert metrics.get(
                "serving_dispatcher_restarts_total").value(model="m") \
                == pi.restarts_used
            brk = reg.get("m").breakers[2]
            assert brk.opened_total == 2    # trip + probe re-open
            assert brk.state == "closed"
            transitions = [(t["from"], t["to"])
                           for t in brk.describe()["transitions"]]
            assert transitions == [
                ("closed", "open"), ("open", "half_open"),
                ("half_open", "open"), ("open", "half_open"),
                ("half_open", "closed")]
            deg = metrics.get("serving_degraded_requests_total")
            # crash #1, the restart-pending fast-fail, crash #2, and the
            # crashing half-open probe all failed over; the 3 requests
            # during quarantine served under breaker_open
            assert deg.value(model="m", reason="crash_failover") == 4
            assert deg.value(model="m", reason="breaker_open") == 3
            # zero 5xx EVER recorded by the front-end in this run
            reqs = metrics.get("serving_requests_total")
            assert reqs.value(model="m", status="503") == 0
            assert reqs.value(model="m", status="500") == 0
        finally:
            faultinject.set_plan(None)
            client.close()
            server.stop(drain=False)
            reg.shutdown()


# ------------------------------------------------------------ bench --chaos
@pytest.mark.smoke
class TestBenchServingChaosCheck:
    def test_chaos_check_mode_passes_against_committed_series(self):
        """The r02 chaos record's invariants re-prove themselves on every
        CI run: breaker trip + close, restart under budget, zero 5xx
        after the trip, availability at the floor, observability plane
        reachable during quarantine."""
        committed = os.path.join(REPO_ROOT, "BENCH_SERVING_r02.json")
        assert os.path.exists(committed), \
            "BENCH_SERVING_r02.json must be committed with the series"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "bench_serving.py"),
             "--check", committed],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, \
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        assert "chaos check OK" in proc.stdout

    def test_committed_chaos_series_records_acceptance_numbers(self):
        with open(os.path.join(REPO_ROOT, "BENCH_SERVING_r02.json")) as f:
            rec = json.load(f)
        assert rec["series"] == "BENCH_SERVING" and rec["round"] == 2
        chaos = rec["chaos"]
        assert chaos["availability"] >= chaos["availability_floor"]
        assert chaos["errors_5xx_after_trip"] == 0
        assert chaos["breaker_opened_total"] >= 1
        assert chaos["breaker_closed_again"] is True
        assert chaos["dispatcher_restarts"] >= 1
        assert chaos["observability_reachable_during_quarantine"] is True
