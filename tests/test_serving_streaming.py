"""Tests for the NN REST server + CLI, language-pack tokenizers, streaming,
and cloud tooling (reference modules: nearestneighbor-server/-client,
ParallelWrapperMain, nlp-chinese/-japanese/-korean/-uima, dl4j-streaming,
deeplearning4j-aws)."""

import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.clustering.server import (
    NearestNeighborsClient,
    NearestNeighborsServer,
)


class TestNearestNeighborsServer:
    @pytest.fixture
    def corpus(self, rng):
        return rng.normal(size=(50, 8)).astype(np.float32)

    def test_knn_by_index_and_vector(self, corpus):
        server = NearestNeighborsServer(corpus, port=0)
        port = server.start()
        try:
            client = NearestNeighborsClient(f"http://127.0.0.1:{port}")
            res = client.knn(3, 5)
            assert len(res["results"]) == 5
            assert all(r["index"] != 3 for r in res["results"])  # self excluded
            res2 = client.knn_new(corpus[3].tolist(), 1)
            assert res2["results"][0]["index"] == 3  # itself is nearest
            assert res2["results"][0]["distance"] < 1e-4
        finally:
            server.stop()

    def test_labels_and_errors(self, corpus):
        labels = [f"item{i}" for i in range(50)]
        server = NearestNeighborsServer(corpus, labels=labels, port=0)
        port = server.start()
        try:
            client = NearestNeighborsClient(f"http://127.0.0.1:{port}")
            res = client.knn(0, 2)
            assert len(res["labels"]) == 2
            import urllib.error
            with pytest.raises(urllib.error.HTTPError):
                client.knn(999, 2)  # out of range → 400
        finally:
            server.stop()

    def test_invert_returns_farthest(self, corpus):
        server = NearestNeighborsServer(corpus, invert=True, port=0)
        q = corpus[0]
        far = server.query(q, 3)
        near = NearestNeighborsServer(corpus, port=0).query(q, 3)
        assert far[0].distance > near[0].distance

    def test_cli_main(self, tmp_path, corpus):
        npy = tmp_path / "points.npy"
        np.save(npy, corpus)
        labels_file = tmp_path / "labels.txt"
        labels_file.write_text("\n".join(f"l{i}" for i in range(50)))
        server = NearestNeighborsServer.main(
            ["--ndarrayPath", str(npy), "--labelsPath", str(labels_file),
             "--nearestNeighborsPort", "0"])
        try:
            client = NearestNeighborsClient(f"http://127.0.0.1:{server.port}")
            assert len(client.knn(1, 3)["results"]) == 3
        finally:
            server.stop()


class TestTrainCli:
    def test_train_round_trip(self, tmp_path):
        from deeplearning4j_tpu.cli import parallel_wrapper_main
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.util import model_serializer

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        model_in = str(tmp_path / "model.zip")
        model_out = str(tmp_path / "trained.zip")
        model_serializer.write_model(net, model_in)
        rng = np.random.default_rng(0)
        y_idx = rng.integers(0, 2, 128)
        x = rng.normal(size=(128, 4)).astype(np.float32)
        x[np.arange(128), y_idx] += 2.0
        np.savez(tmp_path / "data.npz", features=x,
                 labels=np.eye(2, dtype=np.float32)[y_idx])
        trained = parallel_wrapper_main([
            "--modelPath", model_in, "--dataPath", str(tmp_path / "data.npz"),
            "--modelOutputPath", model_out, "--epochs", "5",
            "--batchSize", "32", "--workers", "8"])
        assert os.path.exists(model_out)
        assert trained.iteration > 0


class TestLanguagePacks:
    def test_chinese_char_fallback(self):
        from deeplearning4j_tpu.nlp.language_packs import ChineseTokenizerFactory
        toks = ChineseTokenizerFactory().create("我爱北京天安门").get_tokens()
        assert toks == ["我", "爱", "北", "京", "天", "安", "门"]

    def test_chinese_dictionary_matching(self):
        from deeplearning4j_tpu.nlp.language_packs import ChineseTokenizerFactory
        f = ChineseTokenizerFactory(dictionary=["北京", "天安门"])
        assert f.create("我爱北京天安门").get_tokens() == \
            ["我", "爱", "北京", "天安门"]

    def test_chinese_mixed_scripts(self):
        from deeplearning4j_tpu.nlp.language_packs import ChineseTokenizerFactory
        toks = ChineseTokenizerFactory().create("我用GPU训练 123").get_tokens()
        assert "GPU" in toks and "123" in toks

    def test_japanese_script_transitions(self):
        from deeplearning4j_tpu.nlp.language_packs import JapaneseTokenizerFactory
        toks = JapaneseTokenizerFactory().create("私はラーメンが好き").get_tokens()
        # kanji / hiragana / katakana runs separated
        assert "ラーメン" in toks
        assert "私" in toks

    def test_japanese_dictionary(self):
        from deeplearning4j_tpu.nlp.language_packs import JapaneseTokenizerFactory
        f = JapaneseTokenizerFactory(dictionary=["東京", "大学"])
        assert "東京" in f.create("東京大学").get_tokens()

    def test_korean_josa_stripping(self):
        from deeplearning4j_tpu.nlp.language_packs import KoreanTokenizerFactory
        plain = KoreanTokenizerFactory().create("나는 학교에 간다").get_tokens()
        assert plain == ["나는", "학교에", "간다"]
        stripped = KoreanTokenizerFactory(strip_josa=True).create(
            "나는 학교에 간다").get_tokens()
        assert "나" in stripped and "학교" in stripped

    def test_uima_sentence_pipeline(self):
        from deeplearning4j_tpu.nlp.language_packs import UimaTokenizerFactory
        f = UimaTokenizerFactory()
        sents = f.segment_sentences("First one. Second here! Third?")
        assert len(sents) == 3
        toks = f.create("Hello world. Bye now.").get_tokens()
        assert toks == ["Hello", "world.", "Bye", "now."]

    def test_works_with_word2vec(self):
        from deeplearning4j_tpu.nlp.language_packs import ChineseTokenizerFactory
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        sentences = ["我爱学习", "学习很好"] * 20
        w2v = Word2Vec(layer_size=8, min_word_frequency=1, epochs=1,
                       tokenizer_factory=ChineseTokenizerFactory())
        w2v.fit(sentences)
        assert w2v.has_word("学") or w2v.has_word("学习")


class TestStreaming:
    def test_array_codec_round_trip(self, rng):
        from deeplearning4j_tpu.streaming import deserialize_array, serialize_array
        a = rng.normal(size=(3, 4, 5)).astype(np.float32)
        np.testing.assert_array_equal(deserialize_array(serialize_array(a)), a)

    def test_dataset_codec_with_masks(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.streaming import (
            deserialize_dataset, serialize_dataset)
        ds = DataSet(rng.normal(size=(4, 3, 2)).astype(np.float32),
                     rng.normal(size=(4, 3, 2)).astype(np.float32),
                     np.ones((4, 3), np.float32), None)
        rt = deserialize_dataset(serialize_dataset(ds))
        np.testing.assert_array_equal(rt.features, ds.features)
        np.testing.assert_array_equal(rt.features_mask, ds.features_mask)
        assert rt.labels_mask is None

    def test_embedded_broker_groups(self):
        from deeplearning4j_tpu.streaming import EmbeddedBroker
        b = EmbeddedBroker()
        b.subscribe("t", "g1")
        b.subscribe("t", "g2")
        b.publish("t", b"msg")
        assert b.poll("t", "g1", timeout=1) == b"msg"
        assert b.poll("t", "g2", timeout=1) == b"msg"
        assert b.poll("t", "g1", timeout=0.01) is None

    def test_socket_transport(self):
        from deeplearning4j_tpu.streaming import SocketConsumer, SocketPublisher
        consumer = SocketConsumer()
        pub = SocketPublisher("127.0.0.1", consumer.port)
        try:
            pub.publish(b"hello")
            pub.publish(b"world")
            assert consumer.poll(timeout=5) == b"hello"
            assert consumer.poll(timeout=5) == b"world"
        finally:
            pub.close()
            consumer.close()

    def test_kafka_client_embedded_fallback(self, rng):
        from deeplearning4j_tpu.streaming import NDArrayKafkaClient
        client = NDArrayKafkaClient()
        a = rng.normal(size=(2, 2)).astype(np.float32)
        client.publish(a)
        np.testing.assert_array_equal(client.poll(timeout=1), a)

    def test_route_and_streaming_iterator_training(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.streaming import (
            EmbeddedBroker, Route, StreamingDataSetIterator, serialize_dataset)
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        broker = EmbeddedBroker()
        broker.subscribe("train")
        batches = []
        for _ in range(4):
            x = rng.normal(size=(16, 4)).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
            batches.append(DataSet(x, y))
        n = (Route().from_source(batches)
             .transform(serialize_dataset)
             .to_topic(broker, "train").run())
        assert n == 4
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        it = StreamingDataSetIterator(broker, "train", num_batches=4,
                                      poll_timeout=0.5)
        net.fit(it)
        assert net.iteration == 4

    def test_route_filter(self):
        from deeplearning4j_tpu.streaming import Route
        out = []
        n = (Route().from_source(range(10)).filter(lambda x: x % 2 == 0)
             .transform(lambda x: x * 10).to_list(out).run())
        assert n == 5 and out == [0, 20, 40, 60, 80]

    def test_route_on_error_skip_drops_and_records(self):
        from deeplearning4j_tpu.streaming import Route
        out = []
        bad = lambda x: 10 // x  # raises on 0
        r = (Route().from_source([5, 0, 2, 0, 1]).transform(bad)
             .to_list(out).on_error("skip"))
        assert r.run() == 3
        assert out == [2, 5, 10]
        assert [item for item, _ in r.errors] == [0, 0]
        assert all(isinstance(e, ZeroDivisionError) for _, e in r.errors)

    def test_route_on_error_stop_surfaces_sync_and_async(self):
        from deeplearning4j_tpu.streaming import Route, RouteError
        out = []
        bad = lambda x: 10 // x
        # synchronous: raises with the offending item attached
        r = Route().from_source([5, 0, 2]).transform(bad).to_list(out)
        with pytest.raises(RouteError) as ei:
            r.run()
        assert ei.value.item == 0
        assert out == [2]
        # background: the thread must not die silently — error is captured
        out2 = []
        r2 = (Route().from_source([5, 0, 2]).transform(bad)
              .to_list(out2).start())
        r2.join(timeout=5)
        assert isinstance(r2.error, RouteError)
        assert out2 == [2]  # stopped at the failure, items after dropped

    def test_route_on_error_callback_continues(self):
        from deeplearning4j_tpu.streaming import Route
        out, seen = [], []
        r = (Route().from_source([1, 0, 4]).transform(lambda x: 10 // x)
             .to_list(out)
             .on_error(lambda item, exc: seen.append((item, type(exc)))))
        assert r.run() == 2
        assert out == [10, 2]
        assert seen == [(0, ZeroDivisionError)]
        assert len(r.errors) == 1

    def test_route_on_error_raising_callback_escalates_as_route_error(self):
        from deeplearning4j_tpu.streaming import Route, RouteError

        def bad_handler(item, exc):
            raise TypeError("handler itself is broken")

        r = (Route().from_source([1, 0, 4]).transform(lambda x: 10 // x)
             .to_list([]).on_error(bad_handler))
        with pytest.raises(RouteError) as ei:   # documented 'stop' contract
            r.run()
        assert ei.value.item == 0
        assert isinstance(ei.value.__cause__, TypeError)

    def test_route_on_error_rejects_unknown_policy(self):
        from deeplearning4j_tpu.streaming import Route
        with pytest.raises(ValueError):
            Route().on_error("explode")


class TestServeCli:
    def test_serve_round_trip(self, tmp_path, capsys):
        """``serve`` subcommand: register a checkpoint zip, predict over
        HTTP, scrape /metrics, drain."""
        from deeplearning4j_tpu.cli import serve_main
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.serving import ModelServingClient
        from deeplearning4j_tpu.util.model_serializer import write_model

        conf = (NeuralNetConfiguration.builder().seed(0).list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss="negativeloglikelihood")).build())
        net = MultiLayerNetwork(conf).init()
        path = tmp_path / "clf.zip"
        write_model(net, path)
        server = serve_main(["--model", f"clf={path}", "--port", "0"],
                            block=False)
        try:
            client = ModelServingClient(server.url)
            x = np.zeros((2, 4), np.float32)
            out = client.predict("clf", x)
            np.testing.assert_allclose(out, np.asarray(net.output(x)),
                                       rtol=1e-5, atol=1e-6)
            # bare-path registration uses the file stem as the name
            assert [m["name"] for m in client.models()] == ["clf"]
            assert "serving_requests_total" in client.metrics()
            assert "registered 'clf' v1" in capsys.readouterr().out
        finally:
            server.stop(drain=True, shutdown_registry=True)


class TestCloud:
    def test_gcloud_command_builders(self):
        from deeplearning4j_tpu.cloud import TpuProvisioner
        p = TpuProvisioner("my-project", "us-central2-b")
        cmd = p.create_command("pod1", accelerator_type="v5p-32")
        assert cmd[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
        assert "--accelerator-type=v5p-32" in cmd
        assert "--zone=us-central2-b" in p.delete_command("pod1")
        ssh = p.ssh_command("pod1", "hostname")
        assert "--command=hostname" in ssh

    def test_provisioner_runner_injection(self):
        from deeplearning4j_tpu.cloud import TpuProvisioner
        calls = []
        p = TpuProvisioner("p", "z", runner=lambda cmd: calls.append(cmd) or "ok")
        assert p.create("n") == "ok"
        assert calls and calls[0][4] == "create"

    def test_file_storage_round_trip(self, tmp_path):
        from deeplearning4j_tpu.cloud import ObjectStorage
        src = tmp_path / "in.txt"
        src.write_text("payload")
        store = ObjectStorage()
        uri = f"file://{tmp_path}/staged/out.txt"
        store.upload(str(src), uri)
        dest = tmp_path / "back.txt"
        store.download(uri, str(dest))
        assert dest.read_text() == "payload"


class TestProfileCli:
    def test_profile_subcommand_buckets_a_saved_model(self, tmp_path, capsys):
        """`cli profile` — trace a saved model's jitted train step and
        bucket device time via the HLO-mapped analysis (works on CPU too:
        the xplane trace has a CPU plane... the TPU-plane filter means the
        report may be empty there, so only the plumbing is asserted)."""
        import json as _json
        import numpy as np
        from deeplearning4j_tpu import cli
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.util.model_serializer import write_model

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=3))
                .build())
        net = MultiLayerNetwork(conf).init()
        mp = str(tmp_path / "m.zip")
        write_model(net, mp)
        rng = np.random.default_rng(0)
        dp = str(tmp_path / "d.npz")
        np.savez(dp, features=rng.normal(size=(64, 6)).astype(np.float32),
                 labels=np.eye(3, dtype=np.float32)[
                     rng.integers(0, 3, 64)])
        out = str(tmp_path / "report.json")
        try:
            rc = cli.main(["profile", "--modelPath", mp, "--dataPath", dp,
                           "--batchSize", "16",
                           "--logDir", str(tmp_path / "prof"),
                           "--out", out])
        except RuntimeError as e:
            # CPU backends may produce no TPU plane — plumbing still ran
            assert "XLA Ops" in str(e) or "xplane" in str(e)
            return
        assert rc == 0
        report = _json.loads(open(out).read())
        assert "device_ms_per_step" in report and "buckets" in report
