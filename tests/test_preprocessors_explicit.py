"""Explicit InputPreProcessor family: utility specs, composition,
ListBuilder.input_pre_processor override + serde.

Reference: nn/conf/preprocessor/*.java (ZeroMeanPrePreProcessor,
UnitVarianceProcessor, ZeroMeanAndUnitVariancePreProcessor,
BinomialSamplingPreProcessor, ComposableInputPreProcessor,
NeuralNetConfiguration.ListBuilder.inputPreProcessor).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import preprocessors as pp
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers.core import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class TestUtilitySpecs:
    # zero_mean/unit_variance/standardize use per-FEATURE statistics over
    # the batch axis (DL4J subiRowVector(mean(0)) semantics)
    def test_zero_mean(self):
        x = jnp.asarray([[0.0, 2.0], [2.0, 4.0]])
        out = np.asarray(pp.apply("zero_mean", x))
        np.testing.assert_allclose(out, [[-1, -1], [1, 1]], atol=1e-7)

    def test_unit_variance_and_zero_guard(self):
        x = jnp.asarray([[1.0, 5.0], [3.0, 5.0]])
        out = np.asarray(pp.apply("unit_variance", x))
        assert abs(out[:, 0].std() - 1.0) < 1e-6
        np.testing.assert_allclose(out[:, 1], [5.0, 5.0])  # std=0 column unchanged

    def test_standardize(self):
        x = jnp.asarray([[1.0, 10.0], [2.0, 20.0], [3.0, 30.0], [4.0, 40.0]])
        out = np.asarray(pp.apply("standardize", x))
        np.testing.assert_allclose(out.mean(axis=0), [0, 0], atol=1e-6)
        np.testing.assert_allclose(out.std(axis=0), [1, 1], atol=1e-6)

    def test_binomial_sampling_deterministic(self):
        x = jnp.full((4, 100), 0.5)
        a = np.asarray(pp.apply("binomial_sampling:7", x))
        b = np.asarray(pp.apply("binomial_sampling:7", x))
        np.testing.assert_array_equal(a, b)  # same seed -> same draw
        assert set(np.unique(a)) <= {0.0, 1.0}
        assert 0.2 < a.mean() < 0.8
        # p=0 and p=1 are certain
        zeros = np.asarray(pp.apply("binomial_sampling", jnp.zeros((3, 3))))
        ones = np.asarray(pp.apply("binomial_sampling", jnp.ones((3, 3))))
        assert zeros.sum() == 0 and ones.sum() == 9

    def test_composition_spec(self):
        x = jnp.asarray([[2.0, 40.0], [4.0, 80.0], [6.0, 120.0]])
        out = np.asarray(pp.apply("zero_mean|unit_variance", x))
        np.testing.assert_allclose(out.mean(axis=0), [0, 0], atol=1e-6)
        np.testing.assert_allclose(out.std(axis=0), [1, 1], atol=1e-6)
        # output_type passes through identity-shaped specs
        it = InputType.feed_forward(2)
        assert pp.output_type("zero_mean|unit_variance", it) == it

    def test_composed_reshape_chain(self):
        x = jnp.ones((2, 4, 4, 3))
        out = pp.apply("cnn_to_ff|standardize", x)
        assert out.shape == (2, 48)
        it = pp.output_type("cnn_to_ff|standardize",
                            InputType.convolutional(4, 4, 3))
        assert it.kind == "ff" and it.size == 48

    def test_standardize_gradient_finite_on_constant_column(self):
        import jax
        x = jnp.asarray([[1.0, 5.0], [2.0, 5.0], [3.0, 5.0]])
        for spec in ("standardize", "unit_variance"):
            g = jax.grad(lambda v: pp.apply(spec, v).sum())(x)
            assert bool(jnp.isfinite(g).all()), spec

    def test_unknown_spec_raises(self):
        with pytest.raises(ValueError):
            pp.apply("warp_drive", jnp.ones((1, 2)))
        with pytest.raises(ValueError):
            pp.output_type("warp_drive", InputType.feed_forward(2))


class TestExplicitOverride:
    def _conf(self):
        return (NeuralNetConfiguration.builder().seed(3).updater("sgd").list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2))
                .input_pre_processor(0, "cnn_to_ff")
                .set_input_type(InputType.convolutional(4, 4, 3))
                .build())

    def test_override_sets_n_in_and_runs(self):
        conf = self._conf()
        assert conf.layers[0].n_in == 48
        net = MultiLayerNetwork(conf)
        net.init()
        out = net.output(np.ones((2, 4, 4, 3), np.float32))
        assert np.asarray(out).shape == (2, 2)

    def test_serde_round_trip_preserves_override(self):
        conf = self._conf()
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert conf2.input_pre_processors == {0: "cnn_to_ff"}
        assert conf2.layers[0].n_in == 48
        net = MultiLayerNetwork(conf2)
        net.init()
        assert np.asarray(net.output(np.ones((1, 4, 4, 3), np.float32))).shape == (1, 2)

    def test_normalizing_preprocessor_changes_activations(self):
        base = (NeuralNetConfiguration.builder().seed(3).updater("sgd").list()
                .layer(DenseLayer(n_in=3, n_out=4, activation="identity"))
                .layer(OutputLayer(n_in=4, n_out=2))
                .build())
        with_pre = (NeuralNetConfiguration.builder().seed(3).updater("sgd").list()
                    .layer(DenseLayer(n_in=3, n_out=4, activation="identity"))
                    .layer(OutputLayer(n_in=4, n_out=2))
                    .input_pre_processor(0, "standardize")
                    .build())
        x = np.asarray([[10.0, 20.0, 30.0], [40.0, 60.0, 80.0]], np.float32)
        n1 = MultiLayerNetwork(base); n1.init()
        n2 = MultiLayerNetwork(with_pre); n2.init()
        o1, o2 = np.asarray(n1.output(x)), np.asarray(n2.output(x))
        assert not np.allclose(o1, o2)
        # column-standardized input fed to the base net == preprocessed net
        xs = (x - x.mean(axis=0)) / x.std(axis=0)
        np.testing.assert_allclose(np.asarray(n1.output(xs)), o2, rtol=1e-5)
