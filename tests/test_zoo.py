"""Zoo model construction + forward/fit smoke tests.

Mirrors the reference's ``deeplearning4j-zoo/src/test/.../TestInstantiation.java``
(build every zoo model, forward a batch, fit a batch) at CPU-friendly sizes.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (
    AlexNet, Darknet19, FaceNetNN4Small2, GoogLeNet, InceptionResNetV1,
    LeNet, ModelSelector, ResNet50, SimpleCNN, TextGenerationLSTM, TinyYOLO,
    VGG16, VGG19, YOLO2,
)


def _nhwc(shape_chw, batch=2):
    c, h, w = shape_chw
    return np.random.RandomState(0).rand(batch, h, w, c).astype(np.float32)


def _onehot(n, k, rng=0):
    r = np.random.RandomState(rng)
    y = np.zeros((n, k), np.float32)
    y[np.arange(n), r.randint(0, k, n)] = 1
    return y


def _fit_and_forward(model, n_labels, batch=2):
    net = model.init()
    x = _nhwc(model.input_shape, batch)
    y = _onehot(batch, n_labels)
    out = net.output(x)
    out = out[0] if isinstance(out, list) else out
    assert out.shape == (batch, n_labels)
    assert np.allclose(np.asarray(out).sum(axis=-1), 1.0, atol=1e-4)
    net.fit(x, y, epochs=1)
    return net


class TestZooInstantiation:
    def test_lenet(self):
        _fit_and_forward(LeNet(num_labels=10, input_shape=(1, 28, 28)), 10)

    def test_simplecnn(self):
        _fit_and_forward(SimpleCNN(num_labels=5, input_shape=(3, 48, 48)), 5)

    def test_alexnet(self):
        _fit_and_forward(AlexNet(num_labels=7, input_shape=(3, 112, 112)), 7)

    def test_vgg16_small(self):
        _fit_and_forward(VGG16(num_labels=4, input_shape=(3, 64, 64)), 4)

    def test_vgg19_builds(self):
        conf = VGG19(num_labels=4, input_shape=(3, 64, 64)).conf()
        assert conf.num_params() > 0

    def test_darknet19(self):
        _fit_and_forward(Darknet19(num_labels=6, input_shape=(3, 64, 64)), 6)

    def test_resnet50(self):
        net = ResNet50(num_labels=4, input_shape=(3, 64, 64)).init()
        x = _nhwc((3, 64, 64))
        out = net.output(x)
        out = out[0] if isinstance(out, list) else out
        assert out.shape == (2, 4)
        net.fit(x, _onehot(2, 4), epochs=1)

    def test_googlenet(self):
        net = GoogLeNet(num_labels=4, input_shape=(3, 64, 64)).init()
        out = net.output(_nhwc((3, 64, 64)))
        out = out[0] if isinstance(out, list) else out
        assert out.shape == (2, 4)

    def test_inception_resnet_v1_builds(self):
        conf = InceptionResNetV1(num_labels=8, input_shape=(3, 96, 96)).conf()
        assert conf.num_params() > 1_000_000

    def test_facenet(self):
        net = FaceNetNN4Small2(num_labels=4, input_shape=(3, 64, 64)).init()
        x = _nhwc((3, 64, 64))
        out = net.output(x)
        out = out[0] if isinstance(out, list) else out
        assert out.shape == (2, 4)
        net.fit(x, _onehot(2, 4), epochs=1)

    def test_tiny_yolo(self):
        m = TinyYOLO(num_labels=3, input_shape=(3, 64, 64))
        net = m.init()
        x = _nhwc((3, 64, 64))
        out = net.output(x)
        out = out[0] if isinstance(out, list) else out
        # 64/32 = 2x2 grid, 5 anchors * (5+3) channels
        assert out.shape[1:3] == (2, 2)

    def test_yolo2_builds(self):
        conf = YOLO2(num_labels=3, input_shape=(3, 64, 64)).conf()
        assert conf.num_params() > 1_000_000

    def test_text_generation_lstm(self):
        m = TextGenerationLSTM(num_labels=12, max_length=10)
        net = m.init()
        x = np.random.RandomState(0).rand(2, 10, 12).astype(np.float32)
        y = np.zeros((2, 10, 12), np.float32)
        y[..., 0] = 1
        out = net.output(x)
        assert out.shape == (2, 10, 12)
        net.fit(x, y, epochs=1)

    def test_model_selector(self):
        names = ModelSelector.available()
        # the reference's 13 architectures (ZooModel.java inventory) ...
        reference_13 = {
            "alexnet", "darknet19", "facenetnn4small2", "googlenet",
            "inceptionresnetv1", "lenet", "resnet50", "simplecnn",
            "textgenerationlstm", "tinyyolo", "vgg16", "vgg19", "yolo2"}
        assert reference_13 <= set(names)
        # ... plus the attention-era additions with no reference counterpart
        assert set(names) - reference_13 == {"transformerencoder",
                                             "transformerlm",
                                             "visiontransformer"}
        m = ModelSelector.select("lenet", num_labels=10)
        assert isinstance(m, LeNet)
        with pytest.raises(KeyError):
            ModelSelector.select("nope")

    def test_meta_data(self):
        md = ResNet50(num_labels=1000).meta_data()
        assert md.input_shape == ((3, 224, 224),)
        assert not md.use_mds


class TestTransformerEncoder:
    def test_small_encoder_trains(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.zoo.models import TransformerEncoder

        m = TransformerEncoder(num_labels=2, n_layers=2, d_model=16,
                               n_heads=2, d_ff=32, vocab_size=50,
                               max_length=12, seed=7)
        net = ComputationGraph(m.conf()).init()
        rng = np.random.default_rng(0)
        # learnable toy task: class = does token 7 appear in the sequence
        x = rng.integers(0, 50, size=(96, 12)).astype(np.float32)
        cls = (x == 7).any(axis=1).astype(int)
        y = np.eye(2, dtype=np.float32)[cls]
        from deeplearning4j_tpu.datasets.dataset import DataSet
        s0 = net.score(DataSet(x, y))
        for _ in range(60):
            net.fit(x, y)
        assert net.score_ < s0

    def test_vit_patchifies_and_learns(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.zoo.models import VisionTransformer

        m = VisionTransformer(num_labels=2, image_size=16, patch_size=4,
                              n_layers=2, d_model=32, n_heads=4, d_ff=64,
                              seed=7)
        assert m.num_patches == 16
        net = ComputationGraph(m.conf()).init()
        rng = np.random.default_rng(0)
        # learnable toy task: class = bright top-left patch
        x = rng.normal(0, 0.3, size=(64, 16, 16, 3)).astype(np.float32)
        cls = rng.integers(0, 2, 64)
        x[cls == 1, :4, :4, :] += 2.0
        y = np.eye(2, dtype=np.float32)[cls]
        from deeplearning4j_tpu.datasets.dataset import DataSet
        s0 = net.score(DataSet(x, y))
        for _ in range(40):
            net.fit(x, y)
        assert net.score_ < s0
        pred = np.asarray(net.output_single(x)).argmax(1)
        assert (pred == cls).mean() > 0.9

    def test_vit_rejects_indivisible_patch(self):
        from deeplearning4j_tpu.zoo.models import VisionTransformer
        with pytest.raises(ValueError):
            VisionTransformer(image_size=30, patch_size=4)

    def test_selector_has_transformer(self):
        from deeplearning4j_tpu.zoo.zoo_model import ModelSelector
        assert "transformerencoder" in ModelSelector.available()

    def test_encoder_variable_length_masking(self):
        # padded batch + mask must equal the unpadded prefix batch
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.zoo.models import TransformerEncoder

        m = TransformerEncoder(num_labels=2, n_layers=2, d_model=16,
                               n_heads=2, d_ff=32, vocab_size=50,
                               max_length=12, seed=7)
        net = ComputationGraph(m.conf()).init()
        rng = np.random.default_rng(0)
        x_short = rng.integers(1, 50, size=(3, 8)).astype(np.float32)
        x_pad = np.zeros((3, 12), np.float32)
        x_pad[:, :8] = x_short
        mask = np.zeros((3, 12), np.float32)
        mask[:, :8] = 1.0
        out_short = np.asarray(net.output(x_short))
        out_pad = np.asarray(net.output(x_pad, masks=[mask]))
        np.testing.assert_allclose(out_pad, out_short, atol=1e-5)


class TestInitPretrained:
    """ZooModel.java:51-93 — cache lookup, Adler32 verification, full
    restore through the real checkpoint readers (own zip AND reference
    DL4J ModelSerializer zip)."""

    def _stage(self, tmp_path, monkeypatch, src, name):
        import shutil
        zoo_dir = tmp_path / "zoo"
        zoo_dir.mkdir(exist_ok=True)
        monkeypatch.setenv("DL4J_TPU_ZOO_DIR", str(zoo_dir))
        dst = zoo_dir / name
        shutil.copyfile(src, dst)
        return str(dst)

    def test_dl4j_zip_restores_through_zoo_path(self, tmp_path, monkeypatch):
        import os
        from deeplearning4j_tpu.zoo.zoo_model import PretrainedType
        from deeplearning4j_tpu.zoo.models import LeNet
        fix = os.path.join(os.path.dirname(__file__), "fixtures",
                           "dl4j_checkpoint_convnet.zip")
        self._stage(tmp_path, monkeypatch, fix, "lenet_mnist.zip")
        net = LeNet(num_labels=3).init_pretrained(PretrainedType.MNIST)
        exp = np.load(os.path.join(os.path.dirname(__file__), "fixtures",
                                   "dl4j_checkpoint_convnet_expected.npz"))
        out = np.asarray(net.output(exp["x"]))
        np.testing.assert_allclose(out, exp["out"], rtol=1e-5, atol=1e-6)

    def test_checksum_pass_and_mismatch(self, tmp_path, monkeypatch):
        import os
        import zlib
        from deeplearning4j_tpu.zoo.zoo_model import PretrainedType
        from deeplearning4j_tpu.zoo.models import LeNet
        fix = os.path.join(os.path.dirname(__file__), "fixtures",
                           "dl4j_checkpoint_convnet.zip")
        staged = self._stage(tmp_path, monkeypatch, fix, "lenet_mnist.zip")
        with open(staged, "rb") as fh:
            good = zlib.adler32(fh.read())
        net = LeNet(num_labels=3).init_pretrained(
            PretrainedType.MNIST, expected_checksum=good)
        assert net.params is not None
        with pytest.raises(ValueError, match="failed checksum"):
            LeNet(num_labels=3).init_pretrained(
                PretrainedType.MNIST, expected_checksum=good + 1)
        assert os.path.exists(staged)  # user files are never deleted
        # registered class-level checksum is honored too
        monkeypatch.setattr(LeNet, "PRETRAINED_CHECKSUMS",
                            {PretrainedType.MNIST: good}, raising=False)
        assert LeNet(num_labels=3).init_pretrained(
            PretrainedType.MNIST).params is not None

    def test_own_format_zip_loads(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.util.model_serializer import write_model
        from deeplearning4j_tpu.zoo.zoo_model import PretrainedType
        from deeplearning4j_tpu.zoo.models import SimpleCNN
        m = SimpleCNN(num_labels=4, input_shape=(3, 32, 32)).init()
        src = tmp_path / "own.zip"
        write_model(m, str(src))
        self._stage(tmp_path, monkeypatch, str(src), "simplecnn_cifar10.zip")
        net = SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
            .init_pretrained(PretrainedType.CIFAR10)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(net.output(x)),
                                   np.asarray(m.output(x)), rtol=1e-5)

    def test_missing_raises(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.zoo.zoo_model import PretrainedType
        from deeplearning4j_tpu.zoo.models import LeNet
        monkeypatch.setenv("DL4J_TPU_ZOO_DIR", str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError, match="No pretrained weights"):
            LeNet().init_pretrained(PretrainedType.VGGFACE)


class TestPretrainedTransport:
    """ZooModel.java:51-81 — the FULL transport round trip: registered URL
    → fetch → Adler32 verify → cache → restore; corrupt downloads deleted
    so a retry re-fetches; cache hits skip the transport entirely.
    file:// URLs drive the identical urllib path as http(s)."""

    def _serve(self, tmp_path, monkeypatch):
        """Stage a weight blob at a file:// 'origin' + point the cache at
        an empty dir. Returns (model_cls, origin_path, checksum, cache_dir,
        reference_net)."""
        import os
        import zlib
        from deeplearning4j_tpu.util.model_serializer import write_model
        from deeplearning4j_tpu.zoo.models import SimpleCNN
        origin = tmp_path / "origin"
        origin.mkdir()
        m = SimpleCNN(num_labels=4, input_shape=(3, 32, 32)).init()
        blob = origin / "weights.zip"
        write_model(m, str(blob))
        with open(blob, "rb") as fh:
            good = zlib.adler32(fh.read())
        cache = tmp_path / "cache"
        monkeypatch.setenv("DL4J_TPU_ZOO_DIR", str(cache))
        return blob, good, cache, m

    def test_fetch_checksum_cache_restore(self, tmp_path, monkeypatch):
        import os
        from deeplearning4j_tpu.zoo.zoo_model import PretrainedType
        from deeplearning4j_tpu.zoo.models import SimpleCNN
        blob, good, cache, ref = self._serve(tmp_path, monkeypatch)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_URLS",
            {PretrainedType.CIFAR10: blob.as_uri()}, raising=False)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_CHECKSUMS",
            {PretrainedType.CIFAR10: good}, raising=False)
        net = SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
            .init_pretrained(PretrainedType.CIFAR10)
        # the artifact landed in the cache slot (and no .part residue)
        cached = cache / "simplecnn_cifar10.zip"
        assert cached.exists()
        assert not (cache / "simplecnn_cifar10.zip.part").exists()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(net.output(x)),
                                   np.asarray(ref.output(x)), rtol=1e-5)
        # cache HIT: origin removed, second init must not touch transport
        os.remove(blob)
        net2 = SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
            .init_pretrained(PretrainedType.CIFAR10)
        np.testing.assert_allclose(np.asarray(net2.output(x)),
                                   np.asarray(ref.output(x)), rtol=1e-5)

    def test_corrupt_download_deleted_then_refetch_succeeds(
            self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.zoo.zoo_model import PretrainedType
        from deeplearning4j_tpu.zoo.models import SimpleCNN
        blob, good, cache, _ = self._serve(tmp_path, monkeypatch)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_URLS",
            {PretrainedType.CIFAR10: blob.as_uri()}, raising=False)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_CHECKSUMS",
            {PretrainedType.CIFAR10: good + 1}, raising=False)
        with pytest.raises(ValueError, match="corrupt download was deleted"):
            SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
                .init_pretrained(PretrainedType.CIFAR10)
        # the reference deletes bad downloads (ZooModel.java:75-81): the
        # cache slot must be empty so the next attempt re-fetches
        assert not (cache / "simplecnn_cifar10.zip").exists()
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_CHECKSUMS",
            {PretrainedType.CIFAR10: good}, raising=False)
        net = SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
            .init_pretrained(PretrainedType.CIFAR10)
        assert net.params is not None

    def test_sha256_verified_when_registered(self, tmp_path, monkeypatch):
        """ADVICE r4: Adler32 over plain http is corruption detection only;
        a registered SHA-256 adds tamper-evident verification with the
        same download-deletion semantics."""
        import hashlib
        from deeplearning4j_tpu.zoo.zoo_model import PretrainedType
        from deeplearning4j_tpu.zoo.models import SimpleCNN
        blob, good, cache, ref = self._serve(tmp_path, monkeypatch)
        with open(blob, "rb") as fh:
            digest = hashlib.sha256(fh.read()).hexdigest()
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_URLS",
            {PretrainedType.CIFAR10: blob.as_uri()}, raising=False)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_CHECKSUMS",
            {PretrainedType.CIFAR10: good}, raising=False)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_SHA256",
            {PretrainedType.CIFAR10: digest.upper()},  # case-insensitive
            raising=False)
        net = SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
            .init_pretrained(PretrainedType.CIFAR10)
        assert net.params is not None

        # wrong digest: the forged blob passes Adler32 registration (an
        # attacker can match Adler32) but fails SHA-256 — download deleted
        import shutil
        shutil.rmtree(cache)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_SHA256",
            {PretrainedType.CIFAR10: "0" * 64}, raising=False)
        with pytest.raises(ValueError, match="SHA-256"):
            SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
                .init_pretrained(PretrainedType.CIFAR10)
        assert not (cache / "simplecnn_cifar10.zip").exists()

    def test_fetched_cache_reverified_user_files_trusted(
            self, tmp_path, monkeypatch):
        """A fetched artifact re-verifies against the registry checksum on
        every load (corruption in the cache is caught and evicted); a
        user-placed file is their own weights — registry checksums don't
        apply, only an explicit expected_checksum does."""
        from deeplearning4j_tpu.zoo.zoo_model import PretrainedType
        from deeplearning4j_tpu.zoo.models import SimpleCNN
        blob, good, cache, _ = self._serve(tmp_path, monkeypatch)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_URLS",
            {PretrainedType.CIFAR10: blob.as_uri()}, raising=False)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_CHECKSUMS",
            {PretrainedType.CIFAR10: good}, raising=False)
        SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
            .init_pretrained(PretrainedType.CIFAR10)
        slot = cache / "simplecnn_cifar10.zip"
        marker = cache / "simplecnn_cifar10.zip.src"
        assert marker.exists()
        # corrupt the fetched cache: the next load must catch it, but never
        # delete a file it didn't just download (the slot could equally be
        # the user's own replacement)
        slot.write_bytes(slot.read_bytes() + b"bitrot")
        with pytest.raises(ValueError, match="delete the file"):
            SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
                .init_pretrained(PretrainedType.CIFAR10)
        assert slot.exists()
        slot.unlink()
        marker.unlink()
        # user-placed file in the slot (their own fine-tune, a DIFFERENT
        # byte stream than the registry artifact): registry checksum does
        # NOT apply — it loads
        import zlib
        from deeplearning4j_tpu.util.model_serializer import write_model
        own = SimpleCNN(num_labels=4, input_shape=(3, 32, 32), seed=777).init()
        write_model(own, str(slot))
        with open(slot, "rb") as fh:
            assert zlib.adler32(fh.read()) != good
        net = SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
            .init_pretrained(PretrainedType.CIFAR10)
        assert net.params is not None

    def test_interrupted_fetch_leaves_no_artifact(self, tmp_path, monkeypatch):
        """A transport failure mid-stream must not leave a half-written
        file posing as a finished artifact in the cache slot."""
        from deeplearning4j_tpu.zoo.zoo_model import PretrainedType, ZooModel
        from deeplearning4j_tpu.zoo.models import SimpleCNN
        blob, good, cache, _ = self._serve(tmp_path, monkeypatch)
        monkeypatch.setattr(
            SimpleCNN, "PRETRAINED_URLS",
            {PretrainedType.CIFAR10: blob.as_uri()}, raising=False)

        import shutil
        def explode(src, dst):
            dst.write(b"partial")
            raise OSError("link dropped")
        monkeypatch.setattr(shutil, "copyfileobj", explode)
        with pytest.raises(OSError, match="link dropped"):
            SimpleCNN(num_labels=4, input_shape=(3, 32, 32)) \
                .init_pretrained(PretrainedType.CIFAR10)
        assert not (cache / "simplecnn_cifar10.zip").exists()
        assert not (cache / "simplecnn_cifar10.zip.part").exists()


class TestLabels:
    """zoo/util label helpers (Labels SPI, decodePredictions,
    VOC/COCO/ImageNet tables)."""

    def test_voc_and_coco_tables(self):
        from deeplearning4j_tpu.zoo.labels import COCOLabels, VOCLabels
        voc, coco = VOCLabels(), COCOLabels()
        assert len(voc) == 20 and len(coco) == 80
        assert voc.get_label(14) == "person"
        assert coco.get_label(0) == "person"
        assert coco.get_label(79) == "toothbrush"

    def test_decode_predictions_top5(self):
        from deeplearning4j_tpu.zoo.labels import VOCLabels
        rng = np.random.default_rng(0)
        probs = rng.dirichlet(np.ones(20), size=3)
        probs[1, 7] = 5.0  # cat dominates example 1
        probs = probs / probs.sum(1, keepdims=True)
        decoded = VOCLabels().decode_predictions(probs, top=5)
        assert len(decoded) == 3 and len(decoded[0]) == 5
        assert decoded[1][0].label == "cat"
        assert decoded[1][0].probability > 0.5
        # descending probability within each example
        ps = [c.probability for c in decoded[0]]
        assert ps == sorted(ps, reverse=True)

    def test_class_count_mismatch_raises(self):
        from deeplearning4j_tpu.zoo.labels import VOCLabels
        with pytest.raises(ValueError, match="label"):
            VOCLabels().decode_predictions(np.ones((2, 80)) / 80)

    def test_imagenet_loads_keras_index_format(self, tmp_path, monkeypatch):
        import json
        from deeplearning4j_tpu.zoo.labels import ImageNetLabels
        idx = {str(i): [f"n{i:08d}", f"class_{i}"] for i in range(1000)}
        idx["0"] = ["n01440764", "tench"]
        p = tmp_path / "imagenet_class_index.json"
        p.write_text(json.dumps(idx))
        labels = ImageNetLabels(str(p))
        assert len(labels) == 1000
        assert labels.get_label(0) == "tench"
        # env-dir resolution
        monkeypatch.setenv("DL4J_TPU_ZOO_DIR", str(tmp_path))
        assert ImageNetLabels().get_label(0) == "tench"
        monkeypatch.setenv("DL4J_TPU_ZOO_DIR", str(tmp_path / "none"))
        with pytest.raises(FileNotFoundError, match="label table"):
            ImageNetLabels()


def test_darknet19_resolution_specific_cache_slots(monkeypatch, tmp_path):
    """224 and 448 Darknet19 weights are different artifacts (different
    URLs/checksums) — they must occupy different cache slots."""
    from deeplearning4j_tpu.zoo.models import Darknet19
    monkeypatch.setenv("DL4J_TPU_ZOO_DIR", str(tmp_path))
    p224 = Darknet19(input_shape=(3, 224, 224))._cache_path("imagenet")
    p448 = Darknet19(input_shape=(3, 448, 448))._cache_path("imagenet")
    assert p224 != p448


def test_fetch_failure_leaves_no_orphan_src_marker(monkeypatch, tmp_path):
    """A crash mid-fetch must not leave a .src marker without an artifact
    in a way that later misattributes a user-placed file to the fetcher."""
    from deeplearning4j_tpu.zoo.zoo_model import ZooModel
    dest = tmp_path / "slot.zip"
    import shutil

    def explode(src, dst):
        raise OSError("mid-stream failure")
    monkeypatch.setattr(shutil, "copyfileobj", explode)
    blob = tmp_path / "origin.zip"
    blob.write_bytes(b"payload")
    with pytest.raises(OSError):
        ZooModel._fetch(blob.as_uri(), str(dest))
    assert not dest.exists()
    assert not (tmp_path / "slot.zip.part").exists()
    assert not (tmp_path / "slot.zip.src").exists()
