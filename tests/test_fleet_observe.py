"""Fleet observability (ISSUE 15): cross-process trace stitching,
metrics federation, and the incident flight recorder.

Rounds 7-8's observe/ stack was strictly single-process; rounds 10-13
made the interesting failures multi-process. These tests prove the
operator plane now spans the JOB:

- workers stream crash-durable span files + Prometheus snapshot files
  next to their heartbeats; the supervisor opens a per-generation
  ``elastic_job`` span whose context ships to workers via
  ``DL4J_TPU_ELASTIC_TRACEPARENT`` so everything parents into one job
  trace;
- ``FleetRegistry`` merges worker snapshots through
  ``parse_prometheus_text``, re-labels ``{slot,host,generation}`` under
  a cardinality bound, and feeds the union to ``AlertManager`` and a
  supervisor ``/metrics`` port;
- ``merge_chrome_traces`` aligns per-process monotonic clocks via the
  span files' epoch anchors and emits ONE Perfetto timeline (worker
  rows, supervisor decisions as instant events, DCN flow arrows);
- every recovery decision writes a bounded ``incident_*`` bundle that
  ``tools/validate_incident.py`` lints.

The acceptance proof runs a REAL 2-host x 2-worker subprocess job with
an injected ``kill_host``: one merged validated trace with the victim's
last ``train_iteration``, DCN arrows, and the shrink decision; a
``{slot,host}``-labeled /metrics union an alert rule fires on; and a
validated incident bundle naming the victim, decision and last steps.
"""

import json
import os
import sys
import urllib.request

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

from validate_incident import validate_bundle  # noqa: E402
from validate_trace import validate_events, validate_file  # noqa: E402

from deeplearning4j_tpu.observe import (  # noqa: E402
    FleetMetricsServer,
    FleetRegistry,
    MetricsFileExporter,
    MetricsRegistry,
    SpanFileWriter,
    ThresholdRule,
    TraceRecorder,
    Tracer,
    disable_tracing,
    enable_tracing,
    merge_chrome_traces,
    parse_prometheus_text,
    read_span_file,
    text_timeline,
)
from deeplearning4j_tpu.observe.incident import IncidentRecorder  # noqa: E402
from deeplearning4j_tpu.parallel import elastic  # noqa: E402
from deeplearning4j_tpu.parallel.elastic import (  # noqa: E402
    BackoffPolicy,
    ElasticJobSupervisor,
    WorkerSpec,
)
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource  # noqa: E402
from deeplearning4j_tpu.util import faultinject  # noqa: E402

from test_elastic import FakeWorld, GenTicker  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_observability_state():
    faultinject.set_plan(None)
    faultinject.set_host(None)
    disable_tracing()
    yield
    faultinject.set_plan(None)
    faultinject.set_host(None)
    disable_tracing()


def make_supervisor(tmp_path, num_workers, **kw):
    clock = ManualTimeSource(start_ms=1_000)
    world = FakeWorld(clock)
    reg = MetricsRegistry()
    ports = iter(range(43000, 44000))
    sup = ElasticJobSupervisor(
        WorkerSpec(argv=["worker"], env={}), num_workers,
        ckpt_dir=str(tmp_path / "ckpt"), clock=clock,
        sleep_fn=world.sleep, launcher=world, metrics=reg,
        port_fn=lambda: next(ports), poll_interval_s=1.0, **kw)
    return sup, world, reg


# ---------------------------------------------------------------------------
# worker-side federation endpoint
# ---------------------------------------------------------------------------

class TestMetricsFileExporter:
    def test_export_round_trips_through_parse(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("steps_total", "steps", ("model",)).inc(
            7, model="elastic")
        path = str(tmp_path / "metrics.prom")
        exporter = MetricsFileExporter(reg, path)
        assert exporter.export()
        with open(path, encoding="utf-8") as fh:
            sample = parse_prometheus_text(fh.read())
        assert sample["steps_total"][(("model", "elastic"),)] == 7
        assert exporter.exports == 1 and exporter.errors == 0

    def test_unwritable_path_is_counted_not_raised(self, tmp_path):
        exporter = MetricsFileExporter(
            MetricsRegistry(), str(tmp_path / "no_dir" / "m.prom"))
        assert not exporter.export()
        assert exporter.errors == 1


# ---------------------------------------------------------------------------
# supervisor-side federation: merge, relabel, bound, alert hookup
# ---------------------------------------------------------------------------

def _write_snapshot(path, text):
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(text)


class TestFleetRegistry:
    def test_merges_and_relabels_worker_snapshots(self, tmp_path):
        local = MetricsRegistry()
        local.gauge("elastic_world_size", "w").set(2)
        fleet = FleetRegistry(local=local)
        for slot in (0, 1):
            _write_snapshot(
                tmp_path / f"m{slot}.prom",
                'training_steps_total{model="elastic"} %d\n' % (10 + slot))
            fleet.set_source(slot, str(tmp_path / f"m{slot}.prom"),
                             {"slot": slot, "host": slot // 2,
                              "generation": 1})
        sample = parse_prometheus_text(fleet.exposition())
        assert sample["elastic_world_size"][()] == 2  # local series kept
        key0 = (("generation", "1"), ("host", "0"), ("model", "elastic"),
                ("slot", "0"))
        key1 = (("generation", "1"), ("host", "0"), ("model", "elastic"),
                ("slot", "1"))
        assert sample["training_steps_total"][key0] == 10
        assert sample["training_steps_total"][key1] == 11

    def test_federation_labels_override_worker_labels(self, tmp_path):
        fleet = FleetRegistry()
        _write_snapshot(tmp_path / "m.prom",
                        'x_total{slot="evil"} 1\n')
        fleet.set_source(0, str(tmp_path / "m.prom"),
                         {"slot": 0, "generation": 3})
        sample = parse_prometheus_text(fleet.exposition())
        assert sample["x_total"][(("generation", "3"), ("slot", "0"))] == 1

    def test_cardinality_bound_drops_and_counts(self, tmp_path):
        fleet = FleetRegistry(max_series=2)
        _write_snapshot(tmp_path / "m.prom",
                        "a_total 1\nb_total 2\nc_total 3\n")
        fleet.set_source(0, str(tmp_path / "m.prom"), {"slot": 0})
        assert len(fleet.federated_lines()) == 2
        sample = parse_prometheus_text(fleet.local.exposition())
        assert sample["fleet_federation_dropped_series_total"][()] == 1

    def test_missing_source_is_a_boot_window_not_an_error(self, tmp_path):
        """A registered-but-not-yet-written snapshot is normal during
        worker boot (the supervisor pre-unlinks it at launch) — it must
        NOT inflate the scrape-error counter a rule might watch."""
        fleet = FleetRegistry()
        fleet.set_source(0, str(tmp_path / "gone.prom"), {"slot": 0})
        assert fleet.federated_lines() == []
        sample = parse_prometheus_text(fleet.exposition())
        errs = sample.get("fleet_federation_scrape_errors_total", {})
        assert errs.get((), 0) == 0  # never incremented

    def test_corrupt_source_counts_scrape_error(self, tmp_path):
        fleet = FleetRegistry()
        _write_snapshot(tmp_path / "bad.prom", 'x{y="unclosed 1\n')
        fleet.set_source(0, str(tmp_path / "bad.prom"), {"slot": 0})
        assert fleet.federated_lines() == []
        sample = parse_prometheus_text(fleet.exposition())
        assert sample["fleet_federation_scrape_errors_total"][()] == 1

    def test_removed_source_goes_absent(self, tmp_path):
        fleet = FleetRegistry()
        _write_snapshot(tmp_path / "m.prom", "a_total 1\n")
        fleet.set_source(0, str(tmp_path / "m.prom"), {"slot": 0})
        assert "a_total" in parse_prometheus_text(fleet.exposition())
        fleet.remove_source(0)
        assert "a_total" not in parse_prometheus_text(fleet.exposition())

    def test_alert_manager_fires_on_federated_series(self, tmp_path):
        from deeplearning4j_tpu.observe import AlertManager, CallbackSink
        fleet = FleetRegistry()
        _write_snapshot(
            tmp_path / "m.prom",
            'training_steps_total{model="elastic"} 30\n')
        fleet.set_source(2, str(tmp_path / "m.prom"),
                         {"slot": 2, "host": 1, "generation": 1})
        seen = []
        mgr = AlertManager(
            fleet,
            [ThresholdRule("fleet-steps", "training_steps_total", ">", 0,
                           labels={"slot": "2", "host": "1"})],
            [CallbackSink(seen.append)],
            time_source=ManualTimeSource(start_ms=1_000))
        mgr.evaluate_once()
        assert mgr.firing() == ["fleet-steps"]
        assert seen and seen[0].state == "firing"

    def test_http_server_serves_alerts_when_attached(self, tmp_path):
        from deeplearning4j_tpu.observe import AlertManager
        fleet = FleetRegistry()
        mgr = AlertManager(
            fleet, [ThresholdRule("r", "x_total", ">", 0)], [],
            time_source=ManualTimeSource(start_ms=1_000))
        srv = FleetMetricsServer(fleet, alerts=mgr)
        port = srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/alerts", timeout=10) as r:
                body = json.loads(r.read())
            assert body["rules"][0]["name"] == "r"
        finally:
            srv.stop()

    def test_http_server_serves_the_union(self, tmp_path):
        fleet = FleetRegistry()
        _write_snapshot(tmp_path / "m.prom", "a_total 4\n")
        fleet.set_source(0, str(tmp_path / "m.prom"),
                         {"slot": 0, "generation": 1})
        srv = FleetMetricsServer(fleet)
        port = srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                text = r.read().decode()
            sample = parse_prometheus_text(text)
            assert sample["a_total"][
                (("generation", "1"), ("slot", "0"))] == 4
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                assert json.loads(r.read())["status"] == "ok"
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# crash-durable span streaming + clock-aligned merge
# ---------------------------------------------------------------------------

class TestSpanFileStreaming:
    def test_writer_streams_spans_and_reader_parses(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        writer = SpanFileWriter(path, label="slot 0 gen 1",
                                extra_meta={"slot": 0})
        tracer = Tracer(writer)
        with tracer.span("outer", attrs={"step": 1, "loss": float("nan")}):
            with tracer.span("inner"):
                pass
        writer.close()
        parsed = read_span_file(path)
        assert parsed["label"] == "slot 0 gen 1"
        assert parsed["anchor"] is not None
        names = [s["name"] for s in parsed["spans"]]
        assert names == ["inner", "outer"]  # completion order
        outer = parsed["spans"][1]
        assert outer["attrs"]["loss"] == "nan"  # strict-JSON sanitized
        inner = parsed["spans"][0]
        assert inner["parent"] == outer["span"]
        assert inner["trace"] == outer["trace"]

    def test_torn_tail_line_is_skipped(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        writer = SpanFileWriter(path, label="w")
        tracer = Tracer(writer)
        with tracer.span("a"):
            pass
        writer.close()
        with open(path, "a", encoding="utf-8") as fh:
            fh.write('{"kind": "span", "name": "torn"')  # no newline
        parsed = read_span_file(path)
        assert [s["name"] for s in parsed["spans"]] == ["a"]

    def test_writer_truncates_a_stale_stream(self, tmp_path):
        """One stream = one process = one anchor: a re-run supervisor
        reuses per-generation filenames, and a stale process's spans
        under a fresh anchor would mis-align the whole merged trace."""
        path = str(tmp_path / "spans.jsonl")
        w1 = SpanFileWriter(path, label="run 1")
        t1 = Tracer(w1)
        with t1.span("old_run_span"):
            pass
        w1.close()
        w2 = SpanFileWriter(path, label="run 2")
        t2 = Tracer(w2)
        with t2.span("new_run_span"):
            pass
        w2.close()
        parsed = read_span_file(path)
        assert parsed["label"] == "run 2"
        assert [s["name"] for s in parsed["spans"]] == ["new_run_span"]

    def test_reader_keeps_first_anchor_on_multi_meta_files(self, tmp_path):
        path = tmp_path / "spans.jsonl"
        path.write_text(
            _meta_line("first", 10, 1_000)
            + _span_line("s1", "a" * 16, 20, 30)
            + _meta_line("second", 999, 9_999)
            + _span_line("s2", "b" * 16, 40, 50))
        parsed = read_span_file(str(path))
        assert parsed["label"] == "first"
        assert parsed["anchor"] == (10, 1_000)

    def test_links_are_serialized(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        writer = SpanFileWriter(path, label="w")
        tracer = Tracer(writer)
        with tracer.span("src") as src:
            src_ctx = src.context
        with tracer.span("dst") as dst:
            dst.add_link(src_ctx)
        writer.close()
        parsed = read_span_file(path)
        dst_rec = [s for s in parsed["spans"] if s["name"] == "dst"][0]
        assert dst_rec["links"] == [{"trace": src_ctx.trace_id,
                                     "span": src_ctx.span_id}]


def _span_line(name, span_id, start_ns, end_ns, *, parent=None, cat="app",
               tid=1, links=(), trace="ab" * 16):
    rec = {"kind": "span", "name": name, "cat": cat, "trace": trace,
           "span": span_id, "parent": parent, "start_ns": start_ns,
           "end_ns": end_ns, "tid": tid, "tname": f"t{tid}"}
    if links:
        rec["links"] = [{"trace": trace, "span": s} for s in links]
    return json.dumps(rec) + "\n"


def _meta_line(label, anchor_perf, anchor_epoch):
    return json.dumps({"kind": "meta", "label": label, "pid": 1,
                       "anchor_perf_ns": anchor_perf,
                       "anchor_epoch_us": anchor_epoch}) + "\n"


class TestMergeChromeTraces:
    def test_aligns_clocks_across_processes(self, tmp_path):
        # process A: anchor epoch 1_000_000us, span at +1ms of its clock
        a = tmp_path / "a.jsonl"
        a.write_text(
            _meta_line("worker A", 0, 1_000_000)
            + _span_line("a_span", "a" * 16, 1_000_000, 2_000_000))
        # process B: a clock whose perf counter is WAY offset, anchored
        # 5ms later in wall time; span at +0 of its clock
        b = tmp_path / "b.jsonl"
        b.write_text(
            _meta_line("worker B", 77_000_000, 1_005_000)
            + _span_line("b_span", "b" * 16, 77_000_000, 78_000_000))
        obj = merge_chrome_traces([str(a), str(b)])
        assert validate_events(obj) == []
        xs = {e["name"]: e for e in obj["traceEvents"] if e["ph"] == "X"}
        # A's span starts 1ms after its anchor = wall 1_001_000us = base
        assert xs["a_span"]["ts"] == pytest.approx(0.0)
        # B's span starts at wall 1_005_000us = 4ms after A's
        assert xs["b_span"]["ts"] == pytest.approx(4000.0)
        labels = {e["args"]["name"] for e in obj["traceEvents"]
                  if e["ph"] == "M" and e["name"] == "process_name"}
        assert labels == {"worker A", "worker B"}

    def test_cross_process_flow_arrows(self, tmp_path):
        a = tmp_path / "a.jsonl"
        a.write_text(
            _meta_line("A", 0, 0)
            + _span_line("dcn_send", "c" * 16, 100_000, 200_000, cat="dcn"))
        b = tmp_path / "b.jsonl"
        b.write_text(
            _meta_line("B", 0, 0)
            + _span_line("dcn_recv", "d" * 16, 300_000, 400_000, cat="dcn",
                         links=["c" * 16]))
        obj = merge_chrome_traces([str(a), str(b)])
        assert validate_events(obj) == []
        flows = [e for e in obj["traceEvents"] if e.get("cat") == "flow"]
        assert {e["ph"] for e in flows} == {"s", "f"}
        start = [e for e in flows if e["ph"] == "s"][0]
        end = [e for e in flows if e["ph"] == "f"][0]
        assert start["pid"] != end["pid"]  # the arrow crosses processes
        assert start["id"] == end["id"]

    def test_decision_spans_become_instant_events(self, tmp_path):
        a = tmp_path / "a.jsonl"
        a.write_text(
            _meta_line("supervisor", 0, 0)
            + _span_line("elastic_shrink", "e" * 16, 100, 100,
                         cat="decision"))
        obj = merge_chrome_traces([str(a)])
        assert validate_events(obj) == []
        inst = [e for e in obj["traceEvents"] if e["ph"] == "i"]
        assert len(inst) == 1 and inst[0]["name"] == "elastic_shrink"

    def test_live_recorder_source_and_write(self, tmp_path):
        recorder = TraceRecorder()
        tracer = Tracer(recorder)
        with tracer.span("live_span"):
            pass
        out = str(tmp_path / "merged.json")
        obj = merge_chrome_traces(
            [{"label": "supervisor", "spans": recorder.spans()}], out=out)
        assert validate_file(out) == []
        assert any(e["ph"] == "X" and e["name"] == "live_span"
                   for e in obj["traceEvents"])

    def test_empty_sources_produce_valid_empty_trace(self, tmp_path):
        out = str(tmp_path / "empty.json")
        obj = merge_chrome_traces([], out=out)
        assert obj["traceEvents"] == []
        assert validate_file(out) == []


class TestTextTimelineLinks:
    def test_links_are_rendered(self):
        recorder = TraceRecorder()
        tracer = Tracer(recorder)
        with tracer.span("batch_execute") as sp:
            req_ctx = sp.context
        with tracer.span("inference_request") as sp:
            sp.add_link(req_ctx)
        text = text_timeline(recorder.spans())
        assert "[<-batch_execute]" in text

    def test_unresolvable_link_shows_id_prefix(self):
        from deeplearning4j_tpu.observe import SpanContext
        recorder = TraceRecorder()
        tracer = Tracer(recorder)
        with tracer.span("s") as sp:
            sp.add_link(SpanContext("f" * 32, "deadbeef00112233"))
        assert "<-deadbeef" in text_timeline(recorder.spans())


# ---------------------------------------------------------------------------
# incident flight recorder + validator
# ---------------------------------------------------------------------------

def _manifest_kwargs(**over):
    kw = dict(
        job_id="job", generation=1, ts_ms=123456, decision="shrink",
        reason="signal on slot 2", backoff_s=0.0,
        ladder=[{"rung": "restart", "taken": False, "detail": "budget 0/0"},
                {"rung": "shrink", "taken": True, "detail": "ok"}],
        victim={"slot": 2, "host": 1, "death_reason": "signal"},
        dead_slots=[2, 3], world_before=[0, 1, 2, 3], world_after=[0, 1],
        workers=[{"slot": s, "host": s // 2, "last_step": 10 + s,
                  "live": True, "death_reason": None, "exit_code": None}
                 for s in range(4)],
        checkpoint={"restore_step": 1, "eligible_steps": [1]})
    kw.update(over)
    return kw


class TestIncidentRecorder:
    def test_full_bundle_validates(self, tmp_path):
        span_path = str(tmp_path / "spans.slot2.jsonl")
        writer = SpanFileWriter(span_path, label="slot 2 gen 1")
        tracer = Tracer(writer)
        for i in range(8):
            with tracer.span("train_iteration", attrs={"iteration": i}):
                pass
        writer.close()
        rec = IncidentRecorder(str(tmp_path / "incidents"), max_spans=5,
                               max_log_lines=3, max_log_bytes=10)
        bundle = rec.record(
            metrics_text="a_total 1\n", span_files=[span_path],
            live_spans=("supervisor", writer.spans()),
            log_tails={2: "x" * 100}, **_manifest_kwargs())
        assert os.path.basename(bundle) == "incident_001_001"
        assert validate_bundle(bundle) == []
        with open(os.path.join(bundle, "incident.json"),
                  encoding="utf-8") as fh:
            m = json.load(fh)
        assert m["decision"]["action"] == "shrink"
        assert m["victim"]["slot"] == 2 and m["victim"]["host"] == 1
        assert [w["last_step"] for w in m["workers"]] == [10, 11, 12, 13]
        assert any(r["rung"] == "shrink" and r["taken"]
                   for r in m["decision"]["ladder"])
        # bounds actually applied
        tail = read_span_file(os.path.join(bundle, "spans",
                                           "spans.slot2.jsonl"))
        assert len(tail["spans"]) == 5  # last-N of the 8 recorded
        assert tail["spans"][-1]["attrs"]["iteration"] == 7
        assert os.path.getsize(
            os.path.join(bundle, "logs", "slot2.log")) == 10
        # the bundle's span dir is itself merge-loadable
        obj = merge_chrome_traces(sorted(
            os.path.join(bundle, "spans", n)
            for n in os.listdir(os.path.join(bundle, "spans"))))
        assert validate_events(obj) == []

    def test_fault_plan_echo(self, tmp_path):
        plan = str(tmp_path / "plan.json")
        with open(plan, "w", encoding="utf-8") as fh:
            json.dump({"faults": [{"type": "kill", "worker": 2,
                                   "step": 5}]}, fh)
        rec = IncidentRecorder(str(tmp_path / "incidents"))
        bundle = rec.record(fault_plan_env=plan, **_manifest_kwargs())
        with open(os.path.join(bundle, "incident.json"),
                  encoding="utf-8") as fh:
            m = json.load(fh)
        assert m["fault_plan"]["env"] == plan
        assert "kill" in m["fault_plan"]["content"]
        assert validate_bundle(bundle) == []

    def test_validator_rejects_bad_manifests(self, tmp_path):
        rec = IncidentRecorder(str(tmp_path / "incidents"))
        bundle = rec.record(**_manifest_kwargs())
        path = os.path.join(bundle, "incident.json")
        with open(path, encoding="utf-8") as fh:
            m = json.load(fh)
        m["decision"]["action"] = "explode"
        m["workers"][0].pop("last_step")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(m, fh)
        problems = validate_bundle(bundle)
        assert any("decision.action" in p for p in problems)
        assert any("last_step" in p for p in problems)

    def test_validator_rejects_bound_violations(self, tmp_path):
        rec = IncidentRecorder(str(tmp_path / "incidents"),
                               max_log_bytes=4)
        bundle = rec.record(log_tails={0: "ok"}, **_manifest_kwargs())
        # grow a tail past the declared bound behind the recorder's back
        with open(os.path.join(bundle, "logs", "slot0.log"), "ab") as fh:
            fh.write(b"overflowing bytes")
        problems = validate_bundle(bundle)
        assert any("max_log_bytes" in p for p in problems)

    def test_missing_bundle_is_reported(self, tmp_path):
        problems = validate_bundle(str(tmp_path / "nope"))
        assert problems and "unreadable manifest" in problems[0]

    def test_seq_seeds_past_existing_bundles(self, tmp_path):
        """A re-run supervisor restarts generation numbering; the seq
        must not collide with a previous run's bundle (that would mix
        the old run's spans/logs into the new incident's directory)."""
        rec1 = IncidentRecorder(str(tmp_path / "incidents"))
        b1 = rec1.record(**_manifest_kwargs())
        assert os.path.basename(b1) == "incident_001_001"
        rec2 = IncidentRecorder(str(tmp_path / "incidents"))
        b2 = rec2.record(**_manifest_kwargs())
        assert os.path.basename(b2) == "incident_001_002"
        assert validate_bundle(b1) == [] and validate_bundle(b2) == []

    def test_incident_span_files_bounded_to_victim_generation(
            self, tmp_path):
        """A long job accumulates one span stream per generation per
        worker; each bundle must copy only the dying generation's."""
        sup, world, _ = make_supervisor(
            tmp_path, 2, min_workers=1,
            backoff=BackoffPolicy(max_restarts=0))
        enable_tracing(Tracer(TraceRecorder()), jax_hook=False)

        def write_streams():
            # simulated worker streams, written AFTER run start (the
            # supervisor clears stale .jsonl at _run entry); the gen-7
            # file plays a stray stream the gen-1 incident must skip
            for gen, slot in ((1, 0), (1, 1), (7, 0)):
                w = SpanFileWriter(
                    os.path.join(sup.trace_dir,
                                 f"spans.gen{gen:03d}.slot{slot}.jsonl"),
                    label=f"slot {slot} gen {gen}")
                tr = Tracer(w)
                with tr.span("x"):
                    pass
                w.close()
        ticker = GenTicker()

        def script(w):
            gen, tick = ticker(w)
            if tick == 1:
                if gen == 1:
                    write_streams()
                for slot in list(w.current):
                    w.beat(slot)
            elif tick == 2 and gen == 1:
                w.exit(0, -9)
            elif tick == 2:
                for slot in list(w.current):
                    w.exit(slot, 0)
        world.script = script
        sup.run()
        bundle = sup.incidents.bundles[0]
        names = sorted(os.listdir(os.path.join(bundle, "spans")))
        assert "spans.gen007.slot0.jsonl" not in names
        assert "spans.gen001.slot0.jsonl" in names
        assert "spans.gen001.slot1.jsonl" in names


# ---------------------------------------------------------------------------
# supervisor integration (manual clock, fake processes — no sleeps)
# ---------------------------------------------------------------------------

class TestSupervisorFleetIntegration:
    def test_traceparent_and_trace_dir_ride_the_env(self, tmp_path):
        sup, world, _ = make_supervisor(tmp_path, 1)
        recorder = TraceRecorder()
        enable_tracing(Tracer(recorder), jax_hook=False)
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                w.beat(0)
            else:
                w.exit(0, 0)
        world.script = script
        sup.run()
        env = world.current[0][0]
        tp = env[elastic.ENV_TRACEPARENT]
        assert env[elastic.ENV_TRACE_DIR] == sup.trace_dir
        job_spans = [s for s in recorder.spans()
                     if s.name == "elastic_job"]
        assert len(job_spans) == 1
        assert tp == job_spans[0].context.traceparent()
        assert job_spans[0].attrs["outcome"] == "completed"

    def test_no_tracer_means_no_trace_env(self, tmp_path):
        sup, world, _ = make_supervisor(tmp_path, 1)
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                w.beat(0)
            else:
                w.exit(0, 0)
        world.script = script
        sup.run()
        env = world.current[0][0]
        assert elastic.ENV_TRACEPARENT not in env
        assert elastic.ENV_TRACE_DIR not in env
        assert elastic.ENV_METRICS_FILE not in env

    def test_fleet_env_metrics_server_and_midrun_scrape(self, tmp_path):
        fetched = {}
        sup, world, reg = make_supervisor(
            tmp_path, 2, num_hosts=2, min_hosts=1, min_workers=1,
            fleet=None, metrics_port=0,
            backoff=BackoffPolicy(max_restarts=0))
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    env, _ = w.current[slot]
                    with open(env[elastic.ENV_METRICS_FILE], "w",
                              encoding="utf-8") as fh:
                        fh.write('training_steps_total{model="elastic"}'
                                 f" {10 + slot}\n")
                    w.beat(slot)
            elif tick == 2:
                url = sup.metrics_server.url() + "/metrics"
                with urllib.request.urlopen(url, timeout=10) as r:
                    fetched["text"] = r.read().decode()
                for slot in list(w.current):
                    w.exit(slot, 0)
        world.script = script
        sup.run()
        assert sup.metrics_server is None  # stopped at exit
        sample = parse_prometheus_text(fetched["text"])
        key = (("generation", "1"), ("host", "1"), ("model", "elastic"),
               ("slot", "1"))
        assert sample["training_steps_total"][key] == 11
        assert sample["elastic_world_size"][()] == 2  # supervisor series

    def test_shrink_writes_validated_incident_bundle(self, tmp_path):
        sup, world, reg = make_supervisor(
            tmp_path, 3, min_workers=2,
            backoff=BackoffPolicy(max_restarts=0))
        recorder = TraceRecorder()
        enable_tracing(Tracer(recorder), jax_hook=False)
        ticker = GenTicker()

        def script(w):
            gen, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    env, proc = w.current[slot]
                    if proc.rc is None:
                        w._beats += 1
                        with open(env[elastic.ENV_HEARTBEAT], "w",
                                  encoding="utf-8") as fh:
                            fh.write(f"{gen}:{4 + slot}:{w._beats}")
            elif tick == 2 and gen == 1:
                w.exit(1, -9)
            elif tick == 2:
                for slot in list(w.current):
                    w.exit(slot, 0)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        assert len(sup.incidents.bundles) == 1
        bundle = sup.incidents.bundles[0]
        assert validate_bundle(bundle) == []
        with open(os.path.join(bundle, "incident.json"),
                  encoding="utf-8") as fh:
            m = json.load(fh)
        assert m["decision"]["action"] == "shrink"
        assert m["victim"] == {"slot": 1, "host": None,
                               "death_reason": "signal"}
        assert m["world"] == {"before": [0, 1, 2], "after": [0, 2]}
        # the heartbeat-reported last step of every worker is recorded
        assert {w["slot"]: w["last_step"] for w in m["workers"]} == \
            {0: 4, 1: 5, 2: 6}
        rungs = [(r["rung"], r["taken"]) for r in m["decision"]["ladder"]]
        assert ("restart", False) in rungs and ("shrink", True) in rungs
        # the supervisor's own spans landed in the bundle, decision incl.
        sup_spans = read_span_file(
            os.path.join(bundle, "spans", "supervisor.jsonl"))
        assert any(s["name"] == "elastic_shrink"
                   and s["cat"] == "decision" for s in sup_spans["spans"])
        # ...and the decision span parents into the generation's job trace
        job = [s for s in recorder.spans() if s.name == "elastic_job"][0]
        decision = [s for s in recorder.spans()
                    if s.name == "elastic_shrink"][0]
        assert decision.trace_id == job.trace_id
        assert decision.parent_id == job.span_id

    def test_run_clears_stale_trace_streams(self, tmp_path):
        """A previous run on the same ckpt_dir reuses generation
        numbering; its span files must not contaminate this run's merge
        or its incident bundles."""
        sup, world, _ = make_supervisor(tmp_path, 1)
        os.makedirs(sup.trace_dir, exist_ok=True)
        stale = os.path.join(sup.trace_dir, "spans.gen001.slot0.jsonl")
        w = SpanFileWriter(stale, label="previous run")
        tr = Tracer(w)
        with tr.span("stale_span"):
            pass
        w.close()
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                w.beat(0)
            else:
                w.exit(0, 0)
        world.script = script
        sup.run()
        assert not os.path.exists(stale)
        assert sup.write_fleet_trace(
            str(tmp_path / "merged.json")) == 0  # nothing stale merged

    def test_incidents_disabled_is_a_noop(self, tmp_path):
        sup, world, _ = make_supervisor(
            tmp_path, 2, min_workers=1, incidents=False,
            backoff=BackoffPolicy(max_restarts=0))
        ticker = GenTicker()

        def script(w):
            gen, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    w.beat(slot)
            elif tick == 2 and gen == 1:
                w.exit(0, -9)
            elif tick == 2:
                for slot in list(w.current):
                    w.exit(slot, 0)
        world.script = script
        sup.run()
        assert sup.incidents is None
        assert not os.path.isdir(os.path.join(sup.ckpt_dir, "incidents"))


class TestTailLogHardening:
    def _sup(self, tmp_path):
        sup, _, _ = make_supervisor(tmp_path, 1)
        return sup

    def test_tail_caps_the_read(self, tmp_path):
        sup = self._sup(tmp_path)
        log_dir = os.path.join(sup.ckpt_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, "gen001_slot0.log")
        with open(path, "wb") as fh:
            fh.write(b"a" * (sup.TAIL_LOG_CAP + 500))
        out = sup.tail_log(0, 1, n_bytes=10 * sup.TAIL_LOG_CAP)
        assert len(out) == sup.TAIL_LOG_CAP  # ring-buffer style cap

    def test_tail_of_small_file_returns_everything(self, tmp_path):
        sup = self._sup(tmp_path)
        log_dir = os.path.join(sup.ckpt_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        with open(os.path.join(log_dir, "gen001_slot0.log"), "w") as fh:
            fh.write("short log")
        assert sup.tail_log(0, 1) == "short log"

    def test_truncated_file_never_raises(self, tmp_path):
        sup = self._sup(tmp_path)
        log_dir = os.path.join(sup.ckpt_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, "gen001_slot0.log")
        with open(path, "wb") as fh:
            fh.write(b"x" * 9000)
        # the worker rotates its log to empty between reads
        with open(path, "wb"):
            pass
        assert sup.tail_log(0, 1) == ""
        assert sup.tail_log(0, 1, n_bytes=-5) == ""  # degenerate request
        assert sup.tail_log(9, 9) == ""  # missing incarnation


# ---------------------------------------------------------------------------
# DCN spans + flow links (satellite of the trace tentpole)
# ---------------------------------------------------------------------------

class _FrameQueue:
    def __init__(self):
        self.frames = []

    def publish(self, frame):
        self.frames.append(frame)

    def poll(self, timeout=0.0):
        return self.frames.pop(0) if self.frames else None


class TestDcnSpans:
    def _pair(self):
        from deeplearning4j_tpu.parallel.dcn import CrossSliceGradientBridge
        q = _FrameQueue()
        a = CrossSliceGradientBridge(q, _FrameQueue(), threshold=1e-3,
                                     slice_id="A", host=0)
        b = CrossSliceGradientBridge(_FrameQueue(), q, threshold=1e-3,
                                     slice_id="B", host=1)
        return a, b, q

    def test_send_and_recv_spans_with_flow_link(self):
        recorder = TraceRecorder()
        enable_tracing(Tracer(recorder), jax_hook=False)
        a, b, q = self._pair()
        a.publish_update([{"w": np.zeros(16, np.float32)}])
        assert a.publish_update([{"w": np.ones(16, np.float32)}]) > 0
        # the sender's span context rides the frame header
        frame = q.frames[0]
        import struct as _struct
        hlen = _struct.unpack(">I", frame[:4])[0]
        meta = json.loads(frame[4:4 + hlen].decode())
        assert "tp" in meta
        _, applied = b.poll_and_apply([{"w": np.zeros(16, np.float32)}])
        assert applied == 1
        sends = [s for s in recorder.spans() if s.name == "dcn_send"]
        recvs = [s for s in recorder.spans() if s.name == "dcn_recv"]
        assert len(sends) == 1 and len(recvs) == 1
        assert recvs[0].links[0].span_id == sends[0].span_id
        assert recvs[0].attrs["from"] == "A"
        # flow arrow survives the Chrome export
        from deeplearning4j_tpu.observe import to_chrome_trace
        events = to_chrome_trace(recorder.spans())["traceEvents"]
        assert any(e.get("cat") == "flow" and e["ph"] == "s"
                   for e in events)

    def test_no_tracer_no_header_no_spans(self):
        a, b, q = self._pair()
        a.publish_update([{"w": np.zeros(16, np.float32)}])
        assert a.publish_update([{"w": np.ones(16, np.float32)}]) > 0
        import struct as _struct
        frame = q.frames[0]
        hlen = _struct.unpack(">I", frame[:4])[0]
        meta = json.loads(frame[4:4 + hlen].decode())
        assert "tp" not in meta
        _, applied = b.poll_and_apply([{"w": np.zeros(16, np.float32)}])
        assert applied == 1  # semantics unchanged while tracing is off

    def test_malformed_frame_still_dropped(self):
        recorder = TraceRecorder()
        enable_tracing(Tracer(recorder), jax_hook=False)
        a, b, q = self._pair()
        a.publish_update([{"w": np.zeros(16, np.float32)}])
        assert a.publish_update([{"w": np.ones(16, np.float32)}]) > 0
        q.frames[0] = q.frames[0][:-8]  # truncate mid-payload
        _, applied = b.poll_and_apply([{"w": np.zeros(16, np.float32)}])
        assert applied == 0


# ---------------------------------------------------------------------------
# pipeline journal trace correlation (satellite)
# ---------------------------------------------------------------------------

class TestPipelineTraceCorrelation:
    def test_journal_records_carry_active_trace_id(self, tmp_path):
        from deeplearning4j_tpu.pipeline.state import PipelineJournal
        recorder = TraceRecorder()
        tracer = Tracer(recorder)
        enable_tracing(tracer, jax_hook=False)
        j = PipelineJournal(str(tmp_path))
        token = j.acquire()
        with tracer.span("pipeline_run") as sp:
            j.append(token, {"event": "note", "message": "in-span"})
            want = sp.trace_id
        j.append(token, {"event": "note", "message": "outside"})
        recs = j._raw_records()
        assert recs[0]["trace_id"] == want
        assert recs[0]["span_id"]
        assert "trace_id" not in recs[1]  # no open span: no stamp

    def test_explicit_tracer_correlates_without_global_activation(
            self, tmp_path):
        """A ContinuousPipeline built with tracer= (never enable_tracing)
        must still stamp journal records — the span ids live on the
        shared contextvar, not the global tracer."""
        from deeplearning4j_tpu.pipeline.state import PipelineJournal
        tracer = Tracer(TraceRecorder())  # NOT globally enabled
        j = PipelineJournal(str(tmp_path))
        token = j.acquire()
        with tracer.span("pipeline_run") as sp:
            j.append(token, {"event": "note"})
            want = sp.trace_id
        assert j._raw_records()[0]["trace_id"] == want

    def test_no_tracer_appends_unchanged(self, tmp_path):
        from deeplearning4j_tpu.pipeline.state import PipelineJournal
        j = PipelineJournal(str(tmp_path))
        token = j.acquire()
        j.append(token, {"event": "note"})
        assert "trace_id" not in j._raw_records()[0]

    def test_run_cycle_opens_pipeline_run_span(self):
        from deeplearning4j_tpu.pipeline.runner import ContinuousPipeline
        recorder = TraceRecorder()
        tracer = Tracer(recorder)
        p = ContinuousPipeline.__new__(ContinuousPipeline)
        p.tracer = tracer
        p.name = "m"
        p._run_cycle_inner = lambda: {"run": 3, "outcome": "PROMOTE"}
        summary = ContinuousPipeline.run_cycle(p)
        assert summary["outcome"] == "PROMOTE"
        spans = [s for s in recorder.spans() if s.name == "pipeline_run"]
        assert len(spans) == 1
        assert spans[0].attrs["run"] == 3
        assert spans[0].attrs["outcome"] == "PROMOTE"

    def test_run_cycle_without_tracer_skips_spans(self):
        from deeplearning4j_tpu.pipeline.runner import ContinuousPipeline
        p = ContinuousPipeline.__new__(ContinuousPipeline)
        p.tracer = None
        p._run_cycle_inner = lambda: {"run": 1, "outcome": "ROLLBACK"}
        assert ContinuousPipeline.run_cycle(p)["outcome"] == "ROLLBACK"


# ---------------------------------------------------------------------------
# CI acceptance proofs on real subprocess CPU workers
# ---------------------------------------------------------------------------

def _sub_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


SAMPLES, FEATURES, CLASSES = 240, 6, 3
BATCH = 24
EPOCHS = 3


def _make_job_inputs(tmp_path):
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.util import model_serializer
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=CLASSES))
            .set_input_type(InputType.feed_forward(FEATURES)).build())
    net = MultiLayerNetwork(conf).init()
    model_path = str(tmp_path / "model.zip")
    model_serializer.write_model(net, model_path)
    rng = np.random.default_rng(0)
    yc = rng.integers(0, CLASSES, SAMPLES)
    x = rng.normal(size=(SAMPLES, FEATURES)).astype(np.float32)
    x[np.arange(SAMPLES), yc] += 2.5
    y = np.eye(CLASSES, dtype=np.float32)[yc]
    data_path = str(tmp_path / "data.npz")
    np.savez(data_path, features=x, labels=y)
    return model_path, data_path


def _debug(sup, result):
    out = []
    for g in result.generations:
        for slot in g.world:
            out.append(f"--- gen {g.generation} slot {slot} ---\n"
                       + sup.tail_log(slot, g.generation, 2000))
    return "\n".join(out)


def test_cli_metrics_port_requires_elastic():
    from deeplearning4j_tpu import cli
    with pytest.raises(SystemExit):
        cli.parallel_wrapper_main([
            "--modelPath", "m", "--dataPath", "d",
            "--modelOutputPath", "o", "--metrics-port", "0"])


@pytest.mark.multiprocess
def test_cli_elastic_supports_trace_and_metrics_port(tmp_path, monkeypatch,
                                                     capsys):
    """``train --elastic --trace`` (previously rejected) now writes ONE
    merged fleet trace; ``--metrics-port`` serves the union during the
    run."""
    from deeplearning4j_tpu import cli
    model_path, data_path = _make_job_inputs(tmp_path)
    monkeypatch.setenv("PYTHONPATH",
                       REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    out_trace = str(tmp_path / "fleet.json")
    cli.parallel_wrapper_main([
        "--modelPath", model_path, "--dataPath", data_path,
        "--modelOutputPath", str(tmp_path / "out.zip"),
        "--batchSize", str(BATCH), "--epochs", "1",
        "--elastic", "1", "--ckpt-dir", str(tmp_path / "ckpt"),
        "--trace", out_trace, "--metrics-port", "0"])
    assert os.path.exists(str(tmp_path / "out.zip"))
    assert validate_file(out_trace) == []
    with open(out_trace, encoding="utf-8") as fh:
        events = json.load(fh)["traceEvents"]
    assert any(e["ph"] == "X" and e["name"] == "elastic_job"
               for e in events)
    assert any(e["ph"] == "X" and e["name"] == "train_iteration"
               for e in events)
    assert "merged fleet trace" in capsys.readouterr().out


@pytest.mark.multiprocess
def test_traceparent_roundtrip_through_real_subprocess_worker(tmp_path):
    """Satellite: env → ``run_elastic_worker`` → the worker's root span
    is parented to the SUPERVISOR's per-generation elastic_job span, and
    the merged trace re-validates through tools/validate_trace.py."""
    model_path, data_path = _make_job_inputs(tmp_path)
    spec = WorkerSpec(
        argv=[sys.executable, "-m",
              "deeplearning4j_tpu.parallel.elastic_worker",
              "--modelPath", model_path, "--dataPath", data_path,
              "--out", str(tmp_path / "final.zip"),
              "--batchSize", str(BATCH), "--epochs", "1"],
        env=_sub_env())
    recorder = TraceRecorder()
    enable_tracing(Tracer(recorder), jax_hook=False)
    sup = ElasticJobSupervisor(
        spec, 1, ckpt_dir=str(tmp_path / "ckpt"),
        metrics=MetricsRegistry(), poll_interval_s=0.2,
        job_deadline_s=300)
    result = sup.run()
    assert result.status == "completed", _debug(sup, result)

    job = [s for s in recorder.spans() if s.name == "elastic_job"][0]
    files = [os.path.join(sup.trace_dir, n)
             for n in sorted(os.listdir(sup.trace_dir))
             if n.endswith(".jsonl")]
    assert len(files) == 1
    parsed = read_span_file(files[0])
    roots = [s for s in parsed["spans"] if s["name"] == "elastic_worker"]
    assert len(roots) == 1
    assert roots[0]["trace"] == job.trace_id
    assert roots[0]["parent"] == job.span_id
    # train_iteration spans nest under the worker root in the SAME trace
    # (the listener anchors its window at the first iteration, so the
    # very first step has no span — 9 of 10 here)
    iters = [s for s in parsed["spans"] if s["name"] == "train_iteration"]
    assert len(iters) >= 9
    assert max(s["attrs"]["iteration"] for s in iters) == 10
    assert all(s["trace"] == job.trace_id for s in iters)
    assert all(s["parent"] == roots[0]["span"] for s in iters)

    out = str(tmp_path / "merged.json")
    n = sup.write_fleet_trace(out)
    assert n > 0
    assert validate_file(out) == []


@pytest.mark.multiprocess
@pytest.mark.multihost
def test_fleet_observability_acceptance_kill_host(tmp_path):
    """ISSUE 15 acceptance: a 2-host x 2-worker job with an injected
    ``kill_host`` produces (a) ONE merged validated Chrome trace showing
    the victim's last train_iteration, DCN flow arrows and the shrink
    decision; (b) a /metrics union with {slot,host}-labeled worker
    series an alert rule fires on; (c) a validated incident bundle
    naming the victim, the decision and each worker's last step."""
    model_path, data_path = _make_job_inputs(tmp_path)
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w", encoding="utf-8") as fh:
        json.dump({"faults": [{"type": "kill_host", "host": 1,
                               "step": 25, "signal": "KILL"}]}, fh)
    dcn_dir = str(tmp_path / "dcn")
    spec = WorkerSpec(
        argv=[sys.executable, os.path.join(HERE, "fleet_worker.py"),
              "--modelPath", model_path, "--dataPath", data_path,
              "--out", str(tmp_path / "final.zip"),
              "--batchSize", str(BATCH), "--epochs", str(EPOCHS),
              "--dcn-dir", dcn_dir, "--peers", "0,1,2,3"],
        env=_sub_env({"DL4J_TPU_FAULT_PLAN": plan_path}))
    recorder = TraceRecorder()
    enable_tracing(Tracer(recorder), jax_hook=False)
    reg = MetricsRegistry()
    sup = ElasticJobSupervisor(
        spec, 4, num_hosts=2, min_hosts=1, min_workers=2,
        ckpt_dir=str(tmp_path / "ckpt"),
        backoff=BackoffPolicy(max_restarts=0),
        metrics=reg, fleet=FleetRegistry(local=reg),
        poll_interval_s=0.2, job_deadline_s=540)
    result = sup.run()

    assert result.status == "completed", _debug(sup, result)
    g1, g2 = result.generations
    assert g1.decision == "shrink", _debug(sup, result)
    assert g1.primary_host == 1
    assert g2.world == [0, 1]

    # ---- (a) ONE merged Chrome trace, validated, with everything on it
    out = str(tmp_path / "fleet_trace.json")
    n_events = sup.write_fleet_trace(out)
    assert n_events > 0
    assert validate_file(out) == [], validate_file(out)[:10]
    with open(out, encoding="utf-8") as fh:
        events = json.load(fh)["traceEvents"]
    labels = {e["pid"]: e["args"]["name"] for e in events
              if e["ph"] == "M" and e["name"] == "process_name"}
    victim_pids = {pid for pid, lab in labels.items()
                   if lab in ("slot 2 gen 1", "slot 3 gen 1")}
    assert victim_pids, labels
    assert "supervisor" in labels.values()
    # the victim's last train_iteration spans are on the timeline
    victim_iters = [e for e in events if e["ph"] == "X"
                    and e["name"] == "train_iteration"
                    and e["pid"] in victim_pids]
    assert victim_iters, "victim training spans missing from the merge"
    assert max(e["args"]["iteration"] for e in victim_iters) >= 20
    # DCN exchange rendered: send + recv spans and at least one arrow
    assert any(e["ph"] == "X" and e["name"] == "dcn_send" for e in events)
    assert any(e["ph"] == "X" and e["name"] == "dcn_recv" for e in events)
    flows = [e for e in events if e.get("cat") == "flow"]
    assert any(e["ph"] == "s" for e in flows)
    assert any(e["ph"] == "f" for e in flows)
    # the supervisor's shrink decision is an instant event on the SAME
    # timeline
    decisions = [e for e in events if e["ph"] == "i"
                 and e["name"] == "elastic_shrink"]
    assert len(decisions) == 1
    assert decisions[0]["args"]["decision"] == "shrink"
    # worker spans joined the supervisor's job trace (generation 1)
    job_traces = {s.trace_id for s in recorder.spans()
                  if s.name == "elastic_job"}
    assert any(e["ph"] == "X" and e["name"] == "train_iteration"
               and e["pid"] in victim_pids
               and e["args"]["trace_id"] in job_traces for e in events)

    # ---- (b) /metrics union with {slot,host}-labeled worker series
    srv = FleetMetricsServer(sup.fleet)
    port = srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
    finally:
        srv.stop()
    sample = parse_prometheus_text(text)
    steps = sample["training_steps_total"]
    slots_seen = {dict(k).get("slot") for k in steps}
    hosts_seen = {dict(k).get("host") for k in steps}
    assert slots_seen == {"0", "1"}  # the surviving world
    assert hosts_seen == {"0"}
    assert all(dict(k).get("generation") == "2" for k in steps)
    assert sample["elastic_world_size"][()] == 2  # supervisor series too
    from deeplearning4j_tpu.observe import AlertManager, CallbackSink
    seen = []
    mgr = AlertManager(
        sup.fleet,
        [ThresholdRule("fleet-steps", "training_steps_total", ">", 0,
                       labels={"host": "0"})],
        [CallbackSink(seen.append)],
        time_source=ManualTimeSource(start_ms=1_000))
    mgr.evaluate_once()
    assert mgr.firing() == ["fleet-steps"]

    # ---- (c) a validated incident bundle naming victim/decision/steps
    assert len(sup.incidents.bundles) == 1
    bundle = sup.incidents.bundles[0]
    assert validate_bundle(bundle) == [], validate_bundle(bundle)
    with open(os.path.join(bundle, "incident.json"),
              encoding="utf-8") as fh:
        m = json.load(fh)
    assert m["decision"]["action"] == "shrink"
    assert m["victim"]["host"] == 1
    assert m["victim"]["slot"] in (2, 3)
    assert sorted(m["dead_slots"]) == [2, 3]
    assert m["world"] == {"before": [0, 1, 2, 3], "after": [0, 1]}
    steps_by_slot = {w["slot"]: w["last_step"] for w in m["workers"]}
    assert set(steps_by_slot) == {0, 1, 2, 3}
    assert all(s is not None and s >= 1 for s in steps_by_slot.values())
    # gen 1 started fresh; the recovered world resumes from a committed
    # step — both recorded
    assert m["checkpoint"]["restore_step"] is None
    assert m["checkpoint"]["next_restore_step"] in (1, 2)
    assert m["checkpoint"]["next_restore_step"] == g2.restore_step
    assert m["fault_plan"]["env"] == plan_path
    assert "kill_host" in m["fault_plan"]["content"]
    # the bundle carries the victims' span tails + log tails + metrics
    span_names = sorted(os.listdir(os.path.join(bundle, "spans")))
    assert any("slot2" in n for n in span_names)
    assert os.path.exists(os.path.join(bundle, "metrics.prom"))
    for slot in (2, 3):
        assert os.path.exists(
            os.path.join(bundle, "logs", f"slot{slot}.log"))
