"""Zero-stall training input pipeline tests: fit() auto-prefetch
(AsyncDataSetIterator + device-put stage), the transfer/host-wait
observability, and the donated-buffer audit of the fused train step.

Models the reference's async-ETL contract (MultiLayerNetwork.java:1262-1267
wraps fit iterators in AsyncDataSetIterator unless the source carries
asyncSupported() == false) plus this framework's observe conventions.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import (DataSet, ListDataSetIterator,
                                                 batch_nbytes)
from deeplearning4j_tpu.datasets.iterators import (AsyncDataSetIterator,
                                                   AsyncShieldDataSetIterator,
                                                   DefaultCallback,
                                                   device_put_batch,
                                                   wrap_for_prefetch)
from deeplearning4j_tpu.nn import helpers
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_registry():
    helpers.clear_all_helpers()
    yield
    helpers.clear_all_helpers()


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=24, activation="relu"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(12)).build())
    return MultiLayerNetwork(conf).init()


def _dataset(rng, b=64):
    x = rng.normal(size=(b, 12)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=b)]
    return DataSet(x, y)


class TestWrapForPrefetch:
    def test_plain_iterator_wrapped_and_batches_preserved(self, rng):
        it = ListDataSetIterator(_dataset(rng), batch_size=16)
        base = [np.asarray(ds.features) for ds in it]
        wrapped = wrap_for_prefetch(it, 2)
        assert isinstance(wrapped, AsyncDataSetIterator)
        got = list(wrapped)
        assert len(got) == len(base)
        for ref, ds in zip(base, got):
            # the device-put stage ran in the producer thread
            assert isinstance(ds.features, jax.Array)
            np.testing.assert_array_equal(np.asarray(ds.features), ref)

    def test_depth_none_defaults_on_zero_disables(self, rng):
        it = ListDataSetIterator(_dataset(rng), batch_size=16)
        assert isinstance(wrap_for_prefetch(it, None), AsyncDataSetIterator)
        assert wrap_for_prefetch(it, 0) is it

    def test_async_shield_never_wrapped(self, rng):
        shield = AsyncShieldDataSetIterator(
            ListDataSetIterator(_dataset(rng), batch_size=16))
        assert wrap_for_prefetch(shield, 2) is shield

    def test_existing_async_iterator_kept(self, rng):
        it = AsyncDataSetIterator(
            ListDataSetIterator(_dataset(rng), batch_size=16), queue_size=4)
        assert wrap_for_prefetch(it, 2) is it

    def test_single_batch_list_not_wrapped(self, rng):
        src = [_dataset(rng, b=8)]
        assert wrap_for_prefetch(src, 2) is src
        multi = [_dataset(rng, b=8), _dataset(rng, b=8)]
        assert isinstance(wrap_for_prefetch(multi, 2), AsyncDataSetIterator)

    def test_device_put_batch_moves_masks_too(self, rng):
        b, t = 4, 6
        ds = DataSet(rng.normal(size=(b, t, 3)).astype(np.float32),
                     rng.normal(size=(b, t, 2)).astype(np.float32),
                     np.ones((b, t), np.float32), np.ones((b, t), np.float32))
        out = device_put_batch(ds)
        assert out is ds
        for a in (ds.features, ds.labels, ds.features_mask, ds.labels_mask):
            assert isinstance(a, jax.Array)


class TestDefaultCallbackMasks:
    def test_masks_device_put_alongside_features(self, rng):
        """Regression: DefaultCallback used to ship features/labels but DROP
        the masks, so masked RNN batches re-transferred their masks on the
        training thread every step."""
        b, t = 4, 6
        ds = DataSet(rng.normal(size=(b, t, 3)).astype(np.float32),
                     rng.normal(size=(b, t, 2)).astype(np.float32),
                     np.ones((b, t), np.float32), np.ones((b, t), np.float32))
        DefaultCallback().call(ds)
        for a in (ds.features, ds.labels, ds.features_mask, ds.labels_mask):
            assert isinstance(a, jax.Array)


class TestFitPrefetch:
    def test_mln_fit_with_prefetch_trains_and_counts_transfer(self, rng):
        net = _net()
        data = _dataset(rng)
        it = ListDataSetIterator(data, batch_size=16)
        expected = sum(batch_nbytes(ds) for ds in it)
        before = net.transfer_bytes
        net.fit(it, epochs=2, prefetch_depth=2)
        assert net.iteration == 8  # 4 batches x 2 epochs
        assert net.transfer_bytes - before == 2 * expected

    def test_mln_fit_prefetch_matches_plain_path(self, rng):
        """Prefetch is a scheduling change, not a numeric one: same data,
        same steps, bit-identical parameters either way."""
        data = _dataset(rng)
        a, b = _net(seed=9), _net(seed=9)
        a.fit(ListDataSetIterator(data, batch_size=16), epochs=1,
              prefetch_depth=0)
        b.fit(ListDataSetIterator(data, batch_size=16), epochs=1,
              prefetch_depth=2)
        for la, lb in zip(a.params, b.params):
            for k in la:
                np.testing.assert_array_equal(np.asarray(la[k]),
                                              np.asarray(lb[k]))

    def test_graph_fit_with_prefetch(self, rng):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=12, n_out=16,
                                           activation="relu"), "in")
                .add_layer("out", OutputLayer(n_in=16, n_out=3), "d")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf).init()
        it = ListDataSetIterator(_dataset(rng), batch_size=16)
        g.fit(it, epochs=1, prefetch_depth=2)
        assert g.iteration == 4
        assert g.transfer_bytes > 0

    def test_parallel_wrapper_prefetch_passthrough(self, rng):
        from deeplearning4j_tpu.parallel import ParallelWrapper
        net = _net(seed=4)
        it = ListDataSetIterator(_dataset(rng), batch_size=16)
        ParallelWrapper(net).fit(it, epochs=1, prefetch_depth=1)
        assert net.iteration == 4

    def test_host_wait_span_and_transfer_metric_exported(self, rng):
        from deeplearning4j_tpu.observe import (Tracer, disable_tracing,
                                                enable_tracing)
        from deeplearning4j_tpu.observe.listener import TraceListener
        from deeplearning4j_tpu.observe.metrics import MetricsRegistry

        net = _net(seed=3)
        it = ListDataSetIterator(_dataset(rng), batch_size=16)
        metrics = MetricsRegistry()
        tracer = enable_tracing(Tracer(metrics=metrics))
        net.listeners.append(TraceListener(tracer, metrics, model_name="m"))
        try:
            net.fit(it, epochs=1, prefetch_depth=2)
        finally:
            disable_tracing()
        waits = [s for s in tracer.recorder.spans() if s.name == "host_wait"]
        # one wait per batch plus the end-of-iterator probe
        assert len(waits) == 5
        counter = metrics.get("training_transfer_bytes_total")
        assert counter is not None
        assert counter.value(model="m") == net.transfer_bytes


def _train_step_args(net, ds):
    return (net.params, net.states, net.updater_states,
            jnp.float32(0.0), jnp.float32(0.0),
            jnp.asarray(np.asarray(ds.features)),
            jnp.asarray(np.asarray(ds.labels)),
            None, None, jax.random.PRNGKey(0), None)


class TestDonationAudit:
    """HLO audit: the train step must KEEP donating its param/updater-state
    buffers with the fused updater registered (in-place RMW is the point),
    and the inference path must donate nothing (serving reuses inputs)."""

    def test_train_step_keeps_donation_with_fused_updater(self, rng):
        from deeplearning4j_tpu.nn.pallas_kernels import PallasUpdaterHelper
        net = _net(seed=6)
        ds = _dataset(rng, b=16)
        helpers.set_helper("updater", PallasUpdaterHelper())
        fn = net._get_train_step(False)
        hlo = fn.lower(*_train_step_args(net, ds)).compile().as_text()
        assert "input_output_alias" in hlo

    def test_train_step_donates_on_stock_path_too(self, rng):
        net = _net(seed=6)
        ds = _dataset(rng, b=16)
        fn = net._get_train_step(False)
        hlo = fn.lower(*_train_step_args(net, ds)).compile().as_text()
        assert "input_output_alias" in hlo

    def test_predict_donates_nothing(self, rng):
        net = _net(seed=6)
        x = jnp.asarray(rng.normal(size=(16, 12)).astype(np.float32))
        fn = net._output_fn()
        hlo = fn.lower(net.params, net.states, x, None).compile().as_text()
        assert "input_output_alias" not in hlo


@pytest.mark.smoke
class TestBenchTrainPipelineCheck:
    """The committed BENCH_TRAIN series must keep passing its own --check
    (same pattern as bench_serving --check in the smoke tier)."""

    COMMITTED = os.path.join(REPO, "BENCH_TRAIN_r01.json")

    def test_committed_record_schema(self):
        with open(self.COMMITTED, encoding="utf-8") as fh:
            rec = json.load(fh)
        assert rec["metric"] == "train_pipeline"
        assert rec["series"] == "BENCH_TRAIN_r01"
        pre = rec["prefetch"]
        assert pre["on"]["wall_ms_per_step"] < pre["off"]["wall_ms_per_step"]
        assert pre["on"]["steady_state_compiles"] == 0
        assert pre["off"]["steady_state_compiles"] == 0
        fu = rec["fused_updater"]
        assert fu["max_abs_param_diff"] <= 2e-5
        assert fu["pallas_calls_in_train_step"] == fu["fusable_tensors"] > 0

    def test_check_passes(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--train-pipeline", "--check", self.COMMITTED],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=560)
        assert proc.returncode == 0, proc.stderr + proc.stdout
        assert "train-pipeline check OK" in proc.stdout
