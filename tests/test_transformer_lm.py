"""TransformerLM (causal decoder) — causality, KV-cache decoding, training,
generation.

Reference seam: the zoo's text-generation model
(``deeplearning4j-zoo/.../zoo/model/TextGenerationLSTM.java``) and stateful
inference (``MultiLayerNetwork.rnnTimeStep:2800``); the attention-era decoder
has no reference counterpart (the snapshot predates attention, SURVEY.md §5).
The KV-cache path must match the full quadratic forward exactly — the same
"same-math equivalence" bar the reference applies to its cuDNN helpers
(``deeplearning4j-cuda/src/test/.../ValidateCudnnLSTM.java``).
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.zoo.models import TransformerLM, generate, lm_labels

VOCAB = 11


def tiny_lm(**kw):
    args = dict(vocab_size=VOCAB, max_length=16, n_layers=2, d_model=32,
                n_heads=4, d_ff=64, seed=7)
    args.update(kw)
    net = ComputationGraph(TransformerLM(**args).conf())
    net.init()
    return net


def cycle_batch(rng, n, t, step=3):
    """Sequences following a fixed successor rule: x[t+1] = (x[t]+step) % V —
    a next-token task a 2-layer decoder learns quickly."""
    start = rng.integers(0, VOCAB, size=(n, 1))
    seq = (start + step * np.arange(t)[None, :]) % VOCAB
    return seq.astype(np.float32)


class TestCausality:
    def test_future_tokens_do_not_change_past_outputs(self):
        net = tiny_lm()
        ids = np.array([[1, 2, 3, 4, 5, 6, 7, 8]], np.float32)
        full = np.asarray(net.output(ids))
        ids2 = ids.copy()
        ids2[0, -1] = 9
        full2 = np.asarray(net.output(ids2))
        np.testing.assert_allclose(full[:, :-1], full2[:, :-1], atol=1e-6)
        assert np.abs(full[:, -1] - full2[:, -1]).max() > 1e-6

    def test_padding_mask_matches_short_batch(self):
        net = tiny_lm()
        rng = np.random.default_rng(0)
        short = rng.integers(0, VOCAB, size=(3, 5)).astype(np.float32)
        pad = np.zeros((3, 8), np.float32)
        pad[:, :5] = short
        mask = np.zeros((3, 8), np.float32)
        mask[:, :5] = 1.0
        out_short = np.asarray(net.output(short))
        out_pad = np.asarray(net.output(pad, masks=[mask]))
        np.testing.assert_allclose(out_pad[:, :5], out_short, atol=1e-5)


class TestKVCache:
    def test_single_token_steps_equal_full_forward(self):
        net = tiny_lm()
        ids = np.array([[1, 2, 3, 4, 5, 6, 7, 8],
                        [8, 7, 6, 5, 4, 3, 2, 1]], np.float32)
        full = np.asarray(net.output(ids))
        net.rnn_clear_previous_state()
        steps = [np.asarray(net.rnn_time_step(ids[:, t:t + 1, None]))[:, 0]
                 for t in range(ids.shape[1])]
        np.testing.assert_allclose(np.stack(steps, 1), full, atol=1e-5)

    def test_prompt_chunk_then_single_steps(self):
        net = tiny_lm()
        ids = np.array([[3, 1, 4, 1, 5, 9, 2, 6]], np.float32)
        full = np.asarray(net.output(ids))
        net.rnn_clear_previous_state()
        chunk = np.asarray(net.rnn_time_step(ids[:, :5, None]))
        np.testing.assert_allclose(chunk, full[:, :5], atol=1e-5)
        for t in range(5, 8):
            o = np.asarray(net.rnn_time_step(ids[:, t:t + 1, None]))
            np.testing.assert_allclose(o[:, 0], full[:, t], atol=1e-5)

    def test_clear_state_resets_positions(self):
        net = tiny_lm()
        ids = np.array([[1, 2, 3]], np.float32)
        net.rnn_clear_previous_state()
        a = np.asarray(net.rnn_time_step(ids[:, :, None]))
        net.rnn_clear_previous_state()
        b = np.asarray(net.rnn_time_step(ids[:, :, None]))
        np.testing.assert_allclose(a, b, atol=1e-6)


class TestTraining:
    def test_learns_successor_rule_and_generates_it(self):
        net = tiny_lm(seed=3)
        rng = np.random.default_rng(0)
        x = cycle_batch(rng, 64, 16)
        y = lm_labels(x, VOCAB)
        lmask = np.ones(x.shape[:2], np.float32)
        lmask[:, -1] = 0.0  # last step has no next token
        ds = DataSet(x, y, labels_mask=lmask)
        s0 = net.score(ds)
        for _ in range(150):
            net.fit(ds)
        assert net.score_ < s0 * 0.2, (s0, net.score_)
        # greedy generation continues the +3 cycle
        prompt = cycle_batch(np.random.default_rng(1), 2, 6)
        gen = generate(net, prompt, 6)
        want = (prompt[:, -1:] + 3 * np.arange(1, 7)[None, :]) % VOCAB
        assert (gen == want).mean() > 0.9, (gen, want)

    def test_lm_labels_shift(self):
        ids = np.array([[0, 1, 2, 3]])
        lab = lm_labels(ids, 5)
        assert lab.shape == (1, 4, 5)
        assert lab[0, 0, 1] == 1.0 and lab[0, 2, 3] == 1.0
        assert lab[0, 3, 3] == 1.0  # final step repeats last id


class TestGuards:
    def test_kv_cache_overflow_raises(self):
        net = tiny_lm()  # max_length 16
        net.rnn_clear_previous_state()
        ids = np.ones((1, 10, 1), np.float32)
        net.rnn_time_step(ids)
        with np.testing.assert_raises(ValueError):
            net.rnn_time_step(ids)  # 10 + 10 > 16

    def test_generate_capacity_check(self):
        net = tiny_lm()
        with np.testing.assert_raises(ValueError):
            generate(net, np.ones((1, 10)), 10)  # needs 19 > 16 slots
        # exactly at capacity is fine: 10 + 7 - 1 == 16
        generate(net, np.ones((1, 10)), 7)

    def test_num_labels_is_vocab_size(self):
        from deeplearning4j_tpu.zoo.zoo_model import ModelSelector
        m = ModelSelector.select("transformerlm", num_labels=40)
        assert m.vocab_size == 40 and m.num_labels == 40

    def test_causal_helper_flag_respected(self):
        # a causal=True seq-parallel helper must refuse non-causal requests
        # and take causal ones (and vice versa) — outputs never change
        import jax
        from deeplearning4j_tpu.parallel.ring import (
            SequenceParallelAttentionHelper)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("sp",))
        h = SequenceParallelAttentionHelper(mesh, axis_name="sp", causal=True)
        q_shape = (2, 4, 8, 16)
        assert h.supports(None, q_shape, None, False, causal=True)
        assert not h.supports(None, q_shape, None, False, causal=False)
        h2 = SequenceParallelAttentionHelper(mesh, axis_name="sp")
        assert h2.supports(None, q_shape, None, False)
        assert not h2.supports(None, q_shape, None, False, causal=True)


class TestGenerate:
    def test_temperature_sampling_in_vocab(self):
        net = tiny_lm()
        gen = generate(net, np.array([[1, 2, 3]]), 4, temperature=1.0, seed=5)
        assert gen.shape == (1, 4)
        assert ((gen >= 0) & (gen < VOCAB)).all()

    def test_device_loop_matches_host_greedy(self):
        # the single-dispatch lax.scan decode must equal the host loop
        # token for token under greedy sampling
        from deeplearning4j_tpu.zoo.models import generate_on_device
        net = tiny_lm()
        prompt = np.array([[1, 2, 3, 4], [5, 6, 7, 8]])
        host = generate(net, prompt, 8)
        dev = generate_on_device(net, prompt, 8)
        assert (host == dev).all()

    def test_device_loop_sampling_and_edges(self):
        from deeplearning4j_tpu.zoo.models import generate_on_device
        net = tiny_lm()
        prompt = np.array([[1, 2, 3]])
        s = generate_on_device(net, prompt, 5, temperature=1.0, seed=3)
        assert s.shape == (1, 5) and ((s >= 0) & (s < VOCAB)).all()
        assert generate_on_device(net, prompt, 0).shape == (1, 0)
        with np.testing.assert_raises(ValueError):
            generate_on_device(net, np.ones((1, 10)), 10)  # > capacity

    def test_selector_has_transformer_lm(self):
        from deeplearning4j_tpu.zoo.zoo_model import ModelSelector
        assert "transformerlm" in ModelSelector.available()


class TestTBPTTCapacity:
    def test_tbptt_overflow_rejected_before_jit(self):
        # jitted TBPTT steps cannot raise on KV-cache overflow; the host
        # loop must reject overlong sequences upfront
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import (
            CausalSelfAttentionLayer, RnnOutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(CausalSelfAttentionLayer(n_in=8, n_out=8, n_heads=2,
                                                max_cache=8))
                .layer(RnnOutputLayer(n_out=4, loss="mcxent",
                                      activation="softmax"))
                .backprop_type("tbptt").t_bptt_length(4)
                .set_input_type(InputType.recurrent(8, 16))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(2, 16, 8)).astype(np.float32)
        y = np.zeros((2, 16, 4), np.float32)
        y[..., 0] = 1
        with np.testing.assert_raises(ValueError):
            net.fit(x, y)

    def test_device_loop_temperature_not_cached_across_values(self):
        # each temperature must compile its own sampler (the value is baked
        # into the closure, so it must be part of the cache key)
        from deeplearning4j_tpu.zoo.models import generate_on_device
        net = tiny_lm()
        prompt = np.array([[1, 2, 3]])
        generate_on_device(net, prompt, 4, temperature=0.5, seed=1)
        generate_on_device(net, prompt, 4, temperature=2.0, seed=1)
        keys = [k for k in net._jit_cache if k and k[0] == "generate"]
        assert len(set(keys)) == 2


class TestBeamSearch:
    """Device-side beam search: beams ride the batch axis, carries are
    re-indexed per step; one compiled dispatch for the whole search."""

    def _trained(self):
        net = tiny_lm(seed=3)
        rng = np.random.default_rng(0)
        x = cycle_batch(rng, 64, 16)
        y = lm_labels(x, VOCAB)
        lmask = np.ones(x.shape[:2], np.float32)
        lmask[:, -1] = 0.0
        ds = DataSet(x, y, labels_mask=lmask)
        for _ in range(150):
            net.fit(ds)
        return net

    def test_beam_one_equals_greedy(self):
        from deeplearning4j_tpu.zoo.models import (beam_search,
                                                   generate_on_device)
        net = tiny_lm()
        prompt = np.array([[1, 2, 3, 4], [5, 6, 7, 8]])
        greedy = generate_on_device(net, prompt, 6)
        toks, scores = beam_search(net, prompt, 6, beam_size=1)
        assert (toks == greedy).all()
        assert scores.shape == (2,) and np.isfinite(scores).all()

    def test_beam_finds_the_learned_sequence(self):
        from deeplearning4j_tpu.zoo.models import beam_search
        net = self._trained()
        prompt = cycle_batch(np.random.default_rng(1), 2, 6)
        toks, scores = beam_search(net, prompt, 6, beam_size=4)
        want = (prompt[:, -1:] + 3 * np.arange(1, 7)[None, :]) % VOCAB
        assert (toks == want).all(), (toks, want)
        # wider beam can only match or improve the greedy path's score
        t1, s1 = beam_search(net, prompt, 6, beam_size=1)
        assert (scores >= s1 - 1e-5).all()

    def test_eos_freezes_finished_beams(self):
        from deeplearning4j_tpu.zoo.models import beam_search
        net = self._trained()
        prompt = cycle_batch(np.random.default_rng(1), 1, 6)
        want = (prompt[:, -1:] + 3 * np.arange(1, 7)[None, :]) % VOCAB
        eos = int(want[0, 1])                 # hit at step 1
        toks, _ = beam_search(net, prompt, 6, beam_size=3, eos_id=eos)
        assert toks[0, 1] == eos
        assert (toks[0, 2:] == eos).all()     # frozen: eos repeats at 0 cost

    def test_capacity_and_empty(self):
        from deeplearning4j_tpu.zoo.models import beam_search
        net = tiny_lm()
        toks, scores = beam_search(net, np.array([[1, 2]]), 0)
        assert toks.shape == (1, 0)
        with np.testing.assert_raises(ValueError):
            beam_search(net, np.ones((1, 10)), 10)
        with np.testing.assert_raises_regex(ValueError, "length_penalty"):
            beam_search(net, np.array([[1, 2]]), 3, length_penalty=-0.5)

    def test_length_penalty_normalizes_scores(self):
        # with no EOS every beam has full length L, so alpha=1.0 must
        # return exactly rawscore/L for the same winning beam
        from deeplearning4j_tpu.zoo.models import beam_search
        net = self._trained()
        prompt = cycle_batch(np.random.default_rng(1), 2, 6)
        toks_raw, s_raw = beam_search(net, prompt, 6, beam_size=3)
        toks_n, s_n = beam_search(net, prompt, 6, beam_size=3,
                                  length_penalty=1.0)
        assert (toks_raw == toks_n).all()
        np.testing.assert_allclose(s_n, s_raw / 6.0, rtol=1e-5)

    def test_length_penalty_counts_tokens_to_eos(self):
        # an early-EOS beam's frozen raw sum is divided by its true short
        # length, not the full horizon
        from deeplearning4j_tpu.zoo.models import beam_search
        net = self._trained()
        prompt = cycle_batch(np.random.default_rng(1), 1, 6)
        want = (prompt[:, -1:] + 3 * np.arange(1, 7)[None, :]) % VOCAB
        eos = int(want[0, 1])                 # hit at step 1 → length 2
        toks, s_n = beam_search(net, prompt, 6, beam_size=1, eos_id=eos,
                                length_penalty=1.0)
        _, s_raw = beam_search(net, prompt, 6, beam_size=1, eos_id=eos)
        assert toks[0, 1] == eos
        np.testing.assert_allclose(s_n, s_raw / 2.0, rtol=1e-5)

    def test_graph_only_paths_reject_mln_clearly(self):
        from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.zoo.models import (beam_search,
                                                   generate_on_device)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(0).list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=4, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        with np.testing.assert_raises_regex(TypeError, "ComputationGraph"):
            generate_on_device(net, np.array([[1, 2]]), 3)
        with np.testing.assert_raises_regex(TypeError, "ComputationGraph"):
            beam_search(net, np.array([[1, 2]]), 3)


class TestTopKTopP:
    def test_top_k_one_is_greedy(self):
        from deeplearning4j_tpu.zoo.models import generate_on_device
        net = tiny_lm()
        prompt = np.array([[1, 2, 3]])
        greedy = generate_on_device(net, prompt, 6)
        k1 = generate_on_device(net, prompt, 6, temperature=1.0, top_k=1,
                                seed=9)
        assert (k1 == greedy).all()

    def test_top_k_restricts_support(self):
        # with top_k=2, every sampled token must be one of the 2 most
        # likely continuations of its actual prefix — verify step by step
        # against the stateful model
        from deeplearning4j_tpu.zoo.models import generate_on_device
        net = tiny_lm()
        prompt = np.array([[1, 2, 3]])
        toks = generate_on_device(net, prompt, 5, temperature=1.0, top_k=2,
                                  seed=4)[0]
        net.rnn_clear_previous_state()
        probs = np.asarray(net.rnn_time_step(prompt[:, :, None].astype(np.float32)))
        for t in range(5):
            top2 = np.argsort(probs[0, -1])[-2:]
            assert toks[t] in top2, (t, toks[t], top2)
            probs = np.asarray(net.rnn_time_step(
                np.array([[toks[t]]])[:, :, None].astype(np.float32)))

    def test_top_p_tiny_nucleus_is_greedy(self):
        from deeplearning4j_tpu.zoo.models import generate_on_device
        net = tiny_lm()
        prompt = np.array([[4, 5, 6]])
        greedy = generate_on_device(net, prompt, 6)
        p_small = generate_on_device(net, prompt, 6, temperature=1.0,
                                     top_p=1e-6, seed=11)
        assert (p_small == greedy).all()  # nucleus always keeps >= 1 token

    def test_top_p_samples_in_vocab(self):
        from deeplearning4j_tpu.zoo.models import generate_on_device
        net = tiny_lm()
        s = generate_on_device(net, np.array([[1, 2]]), 5, temperature=1.2,
                               top_p=0.9, top_k=5, seed=3)
        assert ((s >= 0) & (s < VOCAB)).all()
