"""Pod-scale fault tolerance: host failure domains, async sharded
checkpoints, partition-tolerant recovery (ISSUE 13).

The round-10 elastic supervisor treated every worker as its own failure
domain, checkpointed synchronously through rank 0, and only knew
localhost. These tests prove the pod-scale extension:

- **host failure domains**: workers grouped into host groups (CI
  simulates hosts as process groups on localhost); ANY worker death
  marks its whole host the victim, budgets charge the host, shrink
  removes the host — per-host slice shapes stay valid down to
  ``min_hosts``. Coordinator bind/advertise is configurable
  (``WorkerSpec`` / ``DL4J_TPU_ELASTIC_{BIND,ADVERTISE}_HOST``) instead
  of hardcoded loopback.
- **async sharded checkpointing** as the recovery substrate: every rank
  snapshots its shard on the training thread and a bounded background
  pipeline writes it, with the generation-fencing commit protocol
  extended — the stamp lands only after ALL ranks' finalize landed, a
  crash at any phase leaves a torn (never-restorable) step, and a slow
  filesystem backpressures instead of accumulating (``slow_save``).
- **partition tolerance**: the step-progress watchdog distinguishes a
  partition (heartbeats alive, no step progress anywhere) from a slow
  worker, and resolves it as death of the least-progressed side.

The CI acceptance proofs run REAL subprocess CPU workers: a 2-host x
2-workers-per-host job whose fault plan SIGKILLs one whole host
mid-step shrinks to the surviving host with final params EQUAL to a
clean resume from the same checkpoint step — and a DCN partition fault
resolves the same way.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

from validate_fault_plan import validate_file, validate_plan  # noqa: E402

from deeplearning4j_tpu.observe import (  # noqa: E402
    MetricsRegistry,
    parse_prometheus_text,
)
from deeplearning4j_tpu.parallel import elastic  # noqa: E402
from deeplearning4j_tpu.parallel.elastic import (  # noqa: E402
    AsyncCheckpointSession,
    BackoffPolicy,
    ElasticJobFailed,
    ElasticJobSupervisor,
    ElasticWorkerContext,
    GenerationLedger,
    WorkerSpec,
    read_step_stamps,
)
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource  # noqa: E402
from deeplearning4j_tpu.util import faultinject  # noqa: E402

from test_elastic import FakeWorld, GenTicker, _tiny_net  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leaked_fault_state():
    """Every test starts and ends with fault injection + host identity
    inactive."""
    faultinject.set_plan(None)
    faultinject.set_host(None)
    yield
    faultinject.set_plan(None)
    faultinject.set_host(None)


def make_supervisor(tmp_path, num_workers, **kw):
    clock = ManualTimeSource(start_ms=1_000)
    world = FakeWorld(clock)
    reg = MetricsRegistry()
    ports = iter(range(42000, 43000))
    sup = ElasticJobSupervisor(
        WorkerSpec(argv=["worker"], env={}), num_workers,
        ckpt_dir=str(tmp_path / "ckpt"), clock=clock,
        sleep_fn=world.sleep, launcher=world, metrics=reg,
        port_fn=lambda: next(ports), poll_interval_s=1.0, **kw)
    return sup, world, reg


def beat_step(world, slot, step, generation=1):
    """Heartbeat with an explicit step payload (the format the
    supervisor's progress watchdog parses)."""
    env, proc = world.current[slot]
    if proc.rc is not None:
        return
    world._beats += 1
    with open(env[elastic.ENV_HEARTBEAT], "w", encoding="utf-8") as fh:
        fh.write(f"{generation}:{step}:{world._beats}")


# ---------------------------------------------------------------------------
# host failure domains: the decision ladder operates on whole hosts
# ---------------------------------------------------------------------------

class TestHostFailureDomains:
    def test_worker_death_marks_whole_host_victim_and_shrinks(
            self, tmp_path):
        """One worker of host 1 dies → the WHOLE host is the victim;
        shrink removes both of its slots, the surviving host keeps its
        full slice shape."""
        sup, world, reg = make_supervisor(
            tmp_path, 4, num_hosts=2, min_hosts=1, min_workers=2,
            backoff=BackoffPolicy(max_restarts=0))
        ticker = GenTicker()

        def script(w):
            gen, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    w.beat(slot)
            elif tick == 2 and gen == 1:
                w.exit(2, -9)  # one worker of host 1 dies
            elif tick == 2:
                for slot in list(w.current):
                    w.exit(slot, 0)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        g1, g2 = result.generations
        assert g1.decision == "shrink"
        assert g1.primary_slot == 2
        assert g1.primary_host == 1
        assert g2.world == [0, 1]       # host 0 intact, host 1 removed
        envs = {s: world.current[s][0] for s in (0, 1)}
        assert envs[0][elastic.ENV_HOST] == "0"
        assert envs[1][elastic.ENV_HOST] == "0"
        assert envs[0][elastic.ENV_NUM_HOSTS] == "2"
        series = parse_prometheus_text(reg.exposition())
        assert series["elastic_hosts"][()] == 1
        assert series["elastic_world_size"][()] == 2

    def test_host_budget_charged_once_per_host_fault(self, tmp_path):
        """Two workers of the same host dying in different rounds charge
        the HOST's budget — max_restarts=1 gives one restart for the
        host, then shrink; per-slot charging would have burned the
        budget twice as fast or cascaded."""
        sup, world, reg = make_supervisor(
            tmp_path, 4, num_hosts=2, min_hosts=1, min_workers=2,
            backoff=BackoffPolicy(max_restarts=1, base_s=1.0, jitter=0.0))
        ticker = GenTicker()

        def script(w):
            gen, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    w.beat(slot)
            elif tick == 2 and gen == 1:
                w.exit(2, 1)       # host 1, first fault: restart
            elif tick == 2 and gen == 2:
                w.exit(3, 1)       # host 1 again: budget spent → shrink
            elif tick == 2:
                for slot in list(w.current):
                    w.exit(slot, 0)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        assert [g.decision for g in result.generations] == \
            ["restart", "shrink", None]
        assert [g.primary_host for g in result.generations] == [1, 1, None]
        assert result.generations[1].world == [0, 1, 2, 3]  # restart kept 4
        assert result.generations[2].world == [0, 1]

    def test_min_hosts_floor_fails_loudly(self, tmp_path):
        sup, world, reg = make_supervisor(
            tmp_path, 4, num_hosts=2, min_hosts=2, min_workers=2,
            backoff=BackoffPolicy(max_restarts=0))
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    w.beat(slot)
            elif tick == 2:
                w.exit(0, 1)
        world.script = script
        with pytest.raises(ElasticJobFailed) as ei:
            sup.run()
        assert "min_hosts" in str(ei.value)
        assert "host 0" in str(ei.value)

    def test_constructor_validates_host_grouping(self, tmp_path):
        with pytest.raises(ValueError, match="divide"):
            ElasticJobSupervisor(WorkerSpec(argv=["w"]), 4, num_hosts=3,
                                 ckpt_dir=str(tmp_path))
        with pytest.raises(ValueError, match="min_hosts"):
            ElasticJobSupervisor(WorkerSpec(argv=["w"]), 4, num_hosts=2,
                                 min_hosts=3, ckpt_dir=str(tmp_path))

    def test_host_of_assignment_is_stable_block_mapping(self, tmp_path):
        sup, _, _ = make_supervisor(tmp_path, 6, num_hosts=3)
        assert [sup.host_of(s) for s in range(6)] == [0, 0, 1, 1, 2, 2]
        sup2, _, _ = make_supervisor(tmp_path, 2)
        assert sup2.host_of(1) is None  # no grouping: per-slot domains


# ---------------------------------------------------------------------------
# coordinator bind/advertise (satellite: no more hardcoded 127.0.0.1)
# ---------------------------------------------------------------------------

class TestCoordinatorAddressing:
    def test_defaults_keep_loopback(self, monkeypatch):
        monkeypatch.delenv(elastic.ENV_BIND_HOST, raising=False)
        monkeypatch.delenv(elastic.ENV_ADVERTISE_HOST, raising=False)
        spec = WorkerSpec(argv=["w"])
        assert spec.resolved_bind_host() == "127.0.0.1"
        assert spec.resolved_advertise_host() == "127.0.0.1"

    def test_env_and_spec_override(self, monkeypatch):
        monkeypatch.setenv(elastic.ENV_BIND_HOST, "10.1.2.3")
        spec = WorkerSpec(argv=["w"])
        assert spec.resolved_bind_host() == "10.1.2.3"
        assert spec.resolved_advertise_host() == "10.1.2.3"  # follows bind
        monkeypatch.setenv(elastic.ENV_ADVERTISE_HOST, "pod-a.local")
        assert spec.resolved_advertise_host() == "pod-a.local"
        explicit = WorkerSpec(argv=["w"], bind_host="0.0.0.0",
                              advertise_host="tpu-host-7")
        assert explicit.resolved_bind_host() == "0.0.0.0"
        assert explicit.resolved_advertise_host() == "tpu-host-7"

    def test_wildcard_bind_never_advertised(self, monkeypatch):
        monkeypatch.delenv(elastic.ENV_ADVERTISE_HOST, raising=False)
        spec = WorkerSpec(argv=["w"], bind_host="0.0.0.0")
        assert spec.resolved_advertise_host() != "0.0.0.0"

    def test_ipv6_literals_are_bracketed(self):
        assert elastic._join_host_port("fd00::1", 4711) == "[fd00::1]:4711"
        assert elastic._join_host_port("[fd00::1]", 4711) \
            == "[fd00::1]:4711"
        assert elastic._join_host_port("10.0.0.1", 4711) == "10.0.0.1:4711"
        ctx = ElasticWorkerContext(
            coordinator="[fd00::1]:4711", num_processes=2, process_id=0,
            slot=0, generation=1, token="t", ckpt_dir="/tmp/x",
            heartbeat_path="/tmp/x/hb", restore_step=None,
            bind_host="::")
        from deeplearning4j_tpu.parallel import master as master_mod
        calls = []
        orig = master_mod.init_distributed
        master_mod.init_distributed = lambda **kw: calls.append(kw)
        try:
            ctx.init_distributed()
        finally:
            master_mod.init_distributed = orig
        assert calls[-1]["coordinator_bind_address"] == "[::]:4711"

    def test_bind_host_reaches_process_zero_coordinator(self, monkeypatch):
        """The bind/advertise split must reach jax: process 0 LISTENS on
        the bind interface while peers dial the advertised address."""
        from deeplearning4j_tpu.parallel import master as master_mod
        calls = []
        monkeypatch.setattr(
            master_mod, "init_distributed",
            lambda **kw: calls.append(kw))
        ctx = ElasticWorkerContext(
            coordinator="pod-a.local:4711", num_processes=2, process_id=0,
            slot=0, generation=1, token="t", ckpt_dir="/tmp/x",
            heartbeat_path="/tmp/x/hb", restore_step=None,
            bind_host="0.0.0.0")
        ctx.init_distributed()
        assert calls[-1]["coordinator_bind_address"] == "0.0.0.0:4711"
        assert calls[-1]["coordinator_address"] == "pod-a.local:4711"
        # non-zero ranks never bind the coordinator
        ctx.process_id = 1
        ctx.init_distributed()
        assert calls[-1]["coordinator_bind_address"] is None

    def test_supervisor_exports_bind_host_env_when_not_loopback(
            self, tmp_path):
        clock = ManualTimeSource(start_ms=1_000)
        world = FakeWorld(clock)
        sup = ElasticJobSupervisor(
            WorkerSpec(argv=["w"], env={}, bind_host="0.0.0.0",
                       advertise_host="pod-a.local"),
            1, ckpt_dir=str(tmp_path / "ckpt"), clock=clock,
            sleep_fn=world.sleep, launcher=world,
            metrics=MetricsRegistry(), port_fn=lambda: 4711,
            poll_interval_s=1.0)
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                w.beat(0)
            else:
                w.exit(0, 0)
        world.script = script
        sup.run()
        env = world.current[0][0]
        assert env[elastic.ENV_BIND_HOST] == "0.0.0.0"
        assert env[elastic.ENV_COORDINATOR] == "pod-a.local:4711"

    def test_supervisor_advertises_configured_host(self, tmp_path):
        clock = ManualTimeSource(start_ms=1_000)
        world = FakeWorld(clock)
        sup = ElasticJobSupervisor(
            WorkerSpec(argv=["w"], env={}, advertise_host="10.9.9.9"),
            1, ckpt_dir=str(tmp_path / "ckpt"), clock=clock,
            sleep_fn=world.sleep, launcher=world,
            metrics=MetricsRegistry(), port_fn=lambda: 45678,
            poll_interval_s=1.0)
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                w.beat(0)
            elif tick == 2:
                w.exit(0, 0)
        world.script = script
        sup.run()
        env = world.current[0][0]
        assert env[elastic.ENV_COORDINATOR] == "10.9.9.9:45678"


# ---------------------------------------------------------------------------
# partition watchdog: liveness without progress → kill the minority side
# ---------------------------------------------------------------------------

class TestPartitionWatchdog:
    def test_partition_resolved_as_death_of_lagging_host(self, tmp_path):
        """All four workers keep heartbeating, but host 1 froze at step 4
        while host 0 reached 5 (then blocked on the cross-host
        collective): the watchdog kills host 1, the ladder shrinks it
        away, the job completes on host 0."""
        sup, world, reg = make_supervisor(
            tmp_path, 4, num_hosts=2, min_hosts=1, min_workers=2,
            progress_timeout_s=5.0,
            backoff=BackoffPolicy(max_restarts=0))
        ticker = GenTicker()

        def script(w):
            gen, tick = ticker(w)
            if gen == 1:
                for slot in (0, 1):
                    beat_step(w, slot, 5 if tick >= 2 else 4)
                for slot in (2, 3):
                    beat_step(w, slot, 4)  # frozen: alive, no progress
            else:
                if tick == 1:
                    for slot in list(w.current):
                        beat_step(w, slot, 6, generation=2)
                elif tick == 2:
                    for slot in list(w.current):
                        w.exit(slot, 0)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        g1, g2 = result.generations
        assert g1.decision == "shrink"
        assert g1.primary_host == 1
        assert sorted(g1.dead_slots) == [2, 3]
        assert g2.world == [0, 1]
        # the partitioned procs were killed by the supervisor
        for slot in (2, 3):
            assert world.generations[0][slot][1].kill_calls >= 1
        series = parse_prometheus_text(reg.exposition())
        assert series["elastic_partitions_total"][()] == 1
        assert series["elastic_worker_deaths_total"][
            (("reason", "partition"),)] == 2

    def test_slow_but_progressing_worker_is_not_a_partition(self, tmp_path):
        """As long as steps complete anywhere within the window, the
        watchdog stays quiet — a slow worker is not a partition."""
        sup, world, reg = make_supervisor(
            tmp_path, 2, progress_timeout_s=5.0,
            backoff=BackoffPolicy(max_restarts=0))
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick >= 12:
                for slot in list(w.current):
                    w.exit(slot, 0)
            else:
                # step advances every OTHER tick: slow, but progressing
                for slot in list(w.current):
                    beat_step(w, slot, tick // 2)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        assert result.restarts_total == 0
        series = parse_prometheus_text(reg.exposition())
        assert ("elastic_partitions_total" not in series
                or series["elastic_partitions_total"][()] == 0)

    def test_global_startup_stall_is_not_a_partition(self, tmp_path):
        """A first-step compile stalls EVERY worker before any step has
        completed — the watchdog must stay quiet (startup/heartbeat
        timeouts own that window), else it would kill a healthy host and
        loop on recompiles."""
        sup, world, reg = make_supervisor(
            tmp_path, 4, num_hosts=2, min_hosts=1, min_workers=2,
            progress_timeout_s=3.0)
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick >= 10:  # "compile" finished: run to completion
                for slot in list(w.current):
                    w.exit(slot, 0)
            else:
                for slot in list(w.current):
                    beat_step(w, slot, 0)  # alive, step 0, never advances
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        assert result.restarts_total == 0
        series = parse_prometheus_text(reg.exposition())
        assert ("elastic_partitions_total" not in series
                or series["elastic_partitions_total"][()] == 0)

    def test_declared_save_holds_the_watchdog(self, tmp_path):
        """A worker whose heartbeat declares an in-progress checkpoint
        (``:save`` payload) refreshes its progress clock — a long save
        stall (slow filesystem, backpressured async window) must not be
        resolved as a partition."""
        sup, world, reg = make_supervisor(
            tmp_path, 2, progress_timeout_s=4.0,
            backoff=BackoffPolicy(max_restarts=0))
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    beat_step(w, slot, 1)
            elif tick == 2:
                for slot in list(w.current):
                    beat_step(w, slot, 2)  # real progress happened once
            elif tick >= 14:
                for slot in list(w.current):
                    w.exit(slot, 0)
            else:
                # slot 0 is saving (declares it); slot 1 blocked on the
                # collective behind it — 10+ ticks with no step progress
                env, proc = w.current[0]
                if proc.rc is None:
                    w._beats += 1
                    with open(env[elastic.ENV_HEARTBEAT], "w",
                              encoding="utf-8") as fh:
                        fh.write(f"1:2:{w._beats}:save")
                beat_step(w, 1, 2)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        assert result.restarts_total == 0  # watchdog held fire
        series = parse_prometheus_text(reg.exposition())
        assert ("elastic_partitions_total" not in series
                or series["elastic_partitions_total"][()] == 0)

    def test_legacy_heartbeats_never_trip_the_watchdog(self, tmp_path):
        """Workers that never report a parseable step (legacy format)
        leave progress tracking inactive even when the watchdog is
        armed."""
        sup, world, reg = make_supervisor(
            tmp_path, 2, progress_timeout_s=3.0)
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick >= 10:
                for slot in list(w.current):
                    w.exit(slot, 0)
            else:
                for slot in list(w.current):
                    w.beat(slot)  # "beatN": no step payload
        world.script = script
        assert sup.run().status == "completed"

    def test_progress_beat_env_armed_with_watchdog(self, tmp_path):
        sup, world, _ = make_supervisor(tmp_path, 1,
                                        progress_timeout_s=8.0)
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                w.beat(0)
            else:
                w.exit(0, 0)
        world.script = script
        sup.run()
        env = world.current[0][0]
        assert float(env[elastic.ENV_PROGRESS_BEAT]) == pytest.approx(1.0)
        sup2, world2, _ = make_supervisor(tmp_path, 1)
        ticker2 = GenTicker()

        def script2(w):
            _, tick = ticker2(w)
            if tick == 1:
                w.beat(0)
            else:
                w.exit(0, 0)
        world2.script = script2
        sup2.run()
        assert elastic.ENV_PROGRESS_BEAT not in world2.current[0][0]


# ---------------------------------------------------------------------------
# host-scoped fault plan schema + hooks
# ---------------------------------------------------------------------------

class TestHostFaultPlan:
    def test_parse_host_faults(self):
        plan = faultinject.FaultPlan.parse({"faults": [
            {"type": "kill_host", "host": 1, "step": 10},
            {"type": "partition", "host": 0, "step": 20, "duration_s": 5},
            {"type": "slow_save", "worker": 0, "step": 2,
             "duration_s": 1.0},
            {"type": "kill", "worker": 1, "step": 3, "phase": "pre_stamp"},
        ]})
        assert plan.faults[0].host == 1
        assert plan.faults[3].phase == "pre_stamp"
        assert plan.lint() == []

    @pytest.mark.parametrize("bad,msg", [
        ({"faults": [{"type": "kill_host", "step": 1}]}, "host group"),
        ({"faults": [{"type": "partition", "host": "*", "step": 1}]},
         "host group"),
        ({"faults": [{"type": "partition", "host": -1, "step": 1}]},
         "host group"),
        ({"faults": [{"type": "kill", "host": 1, "step": 1}]},
         "only valid on"),
        ({"faults": [{"type": "kill", "worker": 0, "step": 1,
                      "phase": "nope"}]}, "save phase"),
        ({"faults": [{"type": "partition", "host": 0, "step": 1,
                      "phase": "pre_write"}]}, "phase"),
    ])
    def test_schema_errors(self, bad, msg):
        with pytest.raises(ValueError, match=msg):
            faultinject.FaultPlan.parse(bad)

    def test_lint_host_shadowing(self):
        plan = faultinject.FaultPlan.parse({"faults": [
            {"type": "kill_host", "host": 1, "step": 5},
            {"type": "partition", "host": 1, "step": 9},
        ]})
        assert any("can never fire" in p for p in plan.lint())
        clean = faultinject.FaultPlan.parse({"faults": [
            {"type": "kill_host", "host": 1, "step": 5},
            {"type": "partition", "host": 0, "step": 9},
        ]})
        assert clean.lint() == []

    def test_kill_host_fires_for_any_worker_of_the_host(self, monkeypatch):
        killed = []
        monkeypatch.setattr(faultinject, "_kill",
                            lambda pid, sig: killed.append(sig))
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "kill_host", "host": 1, "step": 10}]}))
        faultinject.set_host(0)
        faultinject.on_step(0, 10)
        assert killed == []
        faultinject.set_host(1)
        faultinject.on_step(2, 9)
        assert killed == []
        faultinject.on_step(2, 10)
        assert killed == [9]
        # explicit host argument wins over the process-local identity
        killed.clear()
        faultinject.set_host(None)
        faultinject.on_step(3, 10, host=1)
        assert killed == [9]

    def test_partition_blocks_step_on_the_cut_host(self, monkeypatch):
        slept = []
        monkeypatch.setattr(faultinject, "_sleep", slept.append)
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "partition", "host": 1, "step": 7,
             "duration_s": 11.0}]}))
        faultinject.set_host(1)
        faultinject.on_step(2, 6)
        assert slept == []
        faultinject.on_step(2, 7)
        assert slept == [11.0]
        faultinject.on_step(2, 8)  # sticky from the configured step on
        assert slept == [11.0, 11.0]
        faultinject.set_host(0)
        faultinject.on_step(0, 7)  # the other side of the cut trains on
        assert slept == [11.0, 11.0]

    def test_phase_kill_does_not_fire_on_step(self, monkeypatch):
        killed = []
        monkeypatch.setattr(faultinject, "_kill",
                            lambda pid, sig: killed.append(sig))
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "kill", "worker": 0, "step": 2,
             "phase": "mid_shard"}]}))
        faultinject.on_step(0, 2)
        assert killed == []
        faultinject.on_save_phase(0, 2, "pre_write")
        assert killed == []
        faultinject.on_save_phase(0, 2, "mid_shard")
        assert killed == [9]

    def test_phase_kill_does_not_shadow_plain_kill(self, monkeypatch):
        """A phase-scoped kill listed BEFORE a plain kill for the same
        (worker, step) must not swallow the plain one in on_step."""
        killed = []
        monkeypatch.setattr(faultinject, "_kill",
                            lambda pid, sig: killed.append(sig))
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "kill", "worker": 1, "step": 5, "phase": "pre_write"},
            {"type": "kill", "worker": "*", "step": 5},
        ]}))
        faultinject.on_step(1, 5)
        assert killed == [9]  # the plain step-5 kill fired

    def test_slow_save_defaults_to_pre_write_phase(self, monkeypatch):
        slept = []
        monkeypatch.setattr(faultinject, "_sleep", slept.append)
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "slow_save", "worker": 0, "step": 3,
             "duration_s": 2.5}]}))
        faultinject.on_save_phase(0, 3, "mid_shard")
        assert slept == []
        faultinject.on_save_phase(0, 3, "pre_write")
        assert slept == [2.5]

    def test_slow_save_host_scoped(self, monkeypatch):
        """A host field stalls the saver thread of every worker on that
        host — and ONLY them (the default worker '*' must not leak)."""
        slept = []
        monkeypatch.setattr(faultinject, "_sleep", slept.append)
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "slow_save", "host": 1, "step": 2,
             "duration_s": 4.0}]}))
        faultinject.set_host(0)
        faultinject.on_save_phase(0, 2, "pre_write")
        assert slept == []
        faultinject.set_host(1)
        faultinject.on_save_phase(3, 2, "pre_write")
        assert slept == [4.0]

    def test_validator_host_bounds_and_grouping(self, tmp_path):
        spec = {"faults": [{"type": "kill_host", "host": 5, "step": 1}]}
        problems = validate_plan(spec, num_workers=4, num_hosts=2)
        assert any("host 5" in p and "2 host groups" in p
                   for p in problems)
        # host-scoped plan against a job with no host grouping
        problems = validate_plan(spec, num_workers=4)
        assert any("no host grouping" in p for p in problems)
        assert validate_plan(spec, num_workers=4, num_hosts=8) == []

    @pytest.mark.smoke
    def test_shipped_pod_plan_is_clean(self):
        path = os.path.join(REPO, "examples", "pod_fault_plan.json")
        assert validate_file(path) == []
        assert validate_file(path, num_workers=4, num_hosts=2) == []


# ---------------------------------------------------------------------------
# DCN partition: frames never cross the cut, in either direction
# ---------------------------------------------------------------------------

class _FrameQueue:
    def __init__(self):
        self.frames = []

    def publish(self, frame):
        self.frames.append(frame)

    def poll(self, timeout=0.0):
        return self.frames.pop(0) if self.frames else None


class TestDcnPartition:
    def _bridge_pair(self, host_a=0, host_b=1):
        from deeplearning4j_tpu.parallel.dcn import CrossSliceGradientBridge
        a_out, b_out = _FrameQueue(), _FrameQueue()
        a = CrossSliceGradientBridge(a_out, b_out, threshold=1e-3,
                                     slice_id="A", host=host_a)
        b = CrossSliceGradientBridge(b_out, a_out, threshold=1e-3,
                                     slice_id="B", host=host_b)
        return a, b, a_out

    def test_partitioned_traffic_blocked_both_directions(self):
        """The cut is enforced at each receiver (destination-aware):
        frames published by EITHER side after the partition never apply
        across the boundary."""
        a, b, a_out = self._bridge_pair()
        a.publish_update([{"w": np.zeros(16, np.float32)}])  # baseline
        b.publish_update([{"w": np.zeros(16, np.float32)}])
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "partition", "host": 0, "step": 0}]}))
        a.publish_update([{"w": np.ones(16, np.float32)}])
        b.publish_update([{"w": np.ones(16, np.float32)}])
        _, applied_b = b.poll_and_apply([{"w": np.zeros(16, np.float32)}])
        _, applied_a = a.poll_and_apply([{"w": np.zeros(16, np.float32)}])
        assert applied_b == 0 and applied_a == 0

    def test_inflight_frame_from_cut_peer_dropped_at_receiver(self):
        a, b, a_out = self._bridge_pair()
        a.publish_update([{"w": np.zeros(16, np.float32)}])
        assert a.publish_update([{"w": np.ones(16, np.float32)}]) > 0
        assert len(a_out.frames) == 1  # in flight BEFORE the partition
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "partition", "host": 0, "step": 0}]}))
        params_b = [{"w": np.zeros(16, np.float32)}]
        params_b, applied = b.poll_and_apply(params_b)
        assert applied == 0  # receiver honored the cut
        np.testing.assert_allclose(np.asarray(params_b[0]["w"]), 0.0)

    def test_same_host_traffic_unaffected(self):
        a, b, a_out = self._bridge_pair(host_a=1, host_b=1)
        a.publish_update([{"w": np.zeros(16, np.float32)}])
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "partition", "host": 0, "step": 0}]}))
        assert a.publish_update([{"w": np.ones(16, np.float32)}]) > 0
        params_b, applied = b.poll_and_apply(
            [{"w": np.zeros(16, np.float32)}])
        assert applied == 1  # the cut separates host 0; 1↔1 flows


# ---------------------------------------------------------------------------
# async sharded checkpointing: overlap, backpressure, commit protocol
# ---------------------------------------------------------------------------

def _worker_ctx(d, token="t1", generation=1, num_processes=1,
                process_id=0, slot=0):
    return ElasticWorkerContext(
        coordinator="", num_processes=num_processes,
        process_id=process_id, slot=slot, generation=generation,
        token=token, ckpt_dir=str(d),
        heartbeat_path=os.path.join(str(d), "hb"), restore_step=None)


class TestAsyncCheckpointSession:
    def test_async_save_commits_stamp_and_restores(self, tmp_path):
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager)
        net, x, y = _tiny_net()
        d = tmp_path / "ckpt"
        ledger = GenerationLedger(str(d))
        ledger.open_generation(1, "t1", [0])
        ctx = _worker_ctx(d)
        with OrbaxCheckpointManager(str(d)) as mgr:
            net.fit(x, y)
            session = AsyncCheckpointSession(ctx, manager=mgr)
            session.submit(1, net)
            assert session.close(timeout=60)
            assert session.errors == []
            assert session.committed == [1]
        stamps = read_step_stamps(str(d))
        assert [s["step"] for s in stamps] == [1]
        assert stamps[0]["token"] == "t1"
        assert ledger.eligible("t1", 1)
        with OrbaxCheckpointManager(str(d)) as mgr2:
            restored = mgr2.restore(1)
            assert restored.iteration == net.iteration

    def test_snapshot_decouples_save_from_training(self, tmp_path):
        """The checkpoint must contain the params AT SUBMIT TIME even
        though training keeps mutating the model while the save is
        stalled in the background — the whole point of the snapshot."""
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager)
        net, x, y = _tiny_net()
        d = tmp_path / "ckpt"
        ctx = _worker_ctx(d)
        net.fit(x, y)
        want = [{k: np.asarray(v).copy() for k, v in layer.items()}
                for layer in net.params]
        gate = threading.Event()
        orig_sleep = faultinject._sleep
        faultinject._sleep = lambda s: gate.wait(30)
        try:
            faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
                {"type": "slow_save", "worker": 0, "step": 1,
                 "duration_s": 30}]}))
            with OrbaxCheckpointManager(str(d)) as mgr:
                session = AsyncCheckpointSession(ctx, manager=mgr)
                t0 = time.perf_counter()
                session.submit(1, net)
                submit_wall = time.perf_counter() - t0
                # the save is STILL in flight after submit returned: the
                # heartbeat must keep declaring it (the supervisor's
                # partition watchdog holds fire for the whole window,
                # including a slow final flush)
                assert ctx._saving == 1
                ctx.heartbeat(5)
                with open(ctx.heartbeat_path, encoding="utf-8") as fh:
                    assert fh.read().endswith(":save")
                for _ in range(3):
                    net.fit(x, y)  # training overlaps the stalled save
                gate.set()
                assert session.close(timeout=60)
                assert session.errors == []
                assert ctx._saving == 0  # released when the item landed
        finally:
            faultinject._sleep = orig_sleep
            gate.set()
        assert submit_wall < 5.0  # submit returned, save ran behind
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager as Mgr)
        with Mgr(str(d)) as mgr2:
            restored = mgr2.restore(1)
        for layer_w, layer_r in zip(want, restored.params):
            for k in layer_w:
                np.testing.assert_array_equal(
                    layer_w[k], np.asarray(layer_r[k]),
                    err_msg=f"param {k} drifted past the snapshot")

    def test_bounded_in_flight_backpressures(self, tmp_path):
        """With max_in_flight=1 and the filesystem stalled, the SECOND
        submit blocks until the first completes — a slow disk slows
        training down instead of accumulating unbounded snapshots."""
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager)
        net, x, y = _tiny_net()
        d = tmp_path / "ckpt"
        ctx = _worker_ctx(d)
        net.fit(x, y)
        release = threading.Event()
        started = threading.Event()
        orig_sleep = faultinject._sleep

        def gated(_s):
            started.set()
            release.wait(30)
        faultinject._sleep = gated
        try:
            faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
                {"type": "slow_save", "worker": 0, "step": 1,
                 "duration_s": 30}]}))
            with OrbaxCheckpointManager(str(d)) as mgr:
                session = AsyncCheckpointSession(ctx, manager=mgr,
                                                 max_in_flight=1)
                session.submit(1, net)
                assert started.wait(10)

                unblocked = threading.Event()

                def second():
                    session.submit(2, net)
                    unblocked.set()
                t = threading.Thread(target=second, daemon=True)
                t.start()
                assert not unblocked.wait(0.5)  # window full: blocked
                release.set()
                assert unblocked.wait(30)       # drained: admitted
                t.join(timeout=30)
                assert session.close(timeout=60)
                assert session.submit_stall_s > 0.2  # stall was measured
                assert sorted(session.committed) == [1, 2]
        finally:
            faultinject._sleep = orig_sleep
            release.set()

    def test_all_rank_shards_gate_the_stamp(self, tmp_path):
        """Rank 0 must NOT stamp until every rank's shard landed: with a
        peer shard missing the commit times out and the step stays
        torn."""
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager)
        from deeplearning4j_tpu.parallel.master import SharedTrainingMaster

        class _OneShardMaster:
            """Quacks like the master for the session: rank-0 shard
            only; rank 1 never writes (killed mid-save)."""

            def state_snapshot(self):
                return {"threshold": np.float64(1e-3),
                        "steps_done": np.int64(1),
                        "shake_restore": np.float64(-1.0)}

            write_state_snapshot = staticmethod(
                SharedTrainingMaster.write_state_snapshot)

        net, x, y = _tiny_net()
        d = tmp_path / "ckpt"
        ctx = _worker_ctx(d, num_processes=2)
        net.fit(x, y)
        with OrbaxCheckpointManager(str(d)) as mgr:
            session = AsyncCheckpointSession(
                ctx, manager=mgr, master=_OneShardMaster(),
                peer_wait_s=0.3)
            session.submit(1, net)
            assert session.close(timeout=60)
            assert len(session.errors) == 1
            assert "never appeared" in session.errors[0]
            assert session.committed == []
        assert read_step_stamps(str(d)) == []  # torn: unstamped
        # rank 0's own shard DID land (atomic) — only the stamp is held
        assert os.path.exists(ctx.master_state_path(1, rank=0))


# ---------------------------------------------------------------------------
# the torn-async-save matrix: kill at every commit phase x restart
# ---------------------------------------------------------------------------

class _SimulatedKill(BaseException):
    """Raised in place of SIGKILL inside the saver thread: everything
    after the kill point must behave as if the process vanished."""


@pytest.mark.parametrize("phase", ["pre_write", "mid_shard", "pre_stamp"])
def test_torn_async_save_matrix(tmp_path, phase, monkeypatch):
    """Kill (via fault plan) at pre-write / mid-shard /
    post-finalize-pre-stamp, then restart: the latest fence-eligible
    step always restores, the torn step never does."""
    from deeplearning4j_tpu.util.orbax_checkpoint import (
        OrbaxCheckpointManager)

    def raise_kill(pid, sig):
        raise _SimulatedKill(f"SIGKILL({sig}) at {phase}")
    monkeypatch.setattr(faultinject, "_kill", raise_kill)

    net, x, y = _tiny_net()
    d = str(tmp_path / "ckpt")
    ledger = GenerationLedger(d)
    ledger.open_generation(1, "t1", [0])
    ctx = _worker_ctx(d, token="t1")

    # step 1 commits cleanly; the kill lands during step 2's save
    faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
        {"type": "kill", "worker": 0, "step": 2, "phase": phase}]}))
    with OrbaxCheckpointManager(d) as mgr:
        net.fit(x, y)
        session = AsyncCheckpointSession(ctx, manager=mgr)
        session.submit(1, net)
        net.fit(x, y)
        session.submit(2, net)
        assert session.close(timeout=120)
        assert session.committed == [1]
        assert len(session.errors) == 1 and "SIGKILL" in session.errors[0]

    # the torn step never committed...
    assert [s["step"] for s in read_step_stamps(d)] == [1]
    # ...and a RESTART (new supervisor over the same dir) fences the old
    # lineage against exactly the stamps on disk
    ledger2 = GenerationLedger(d)
    eligible = sorted({s["step"] for s in read_step_stamps(d)
                       if ledger2.eligible(s["token"], s["step"])})
    assert eligible == [1]
    if phase == "pre_stamp":
        # the orbax bytes for step 2 are fully finalized on disk — and
        # still unrestorable, because no stamp means no eligibility
        assert os.path.isdir(os.path.join(d, "2"))
    with OrbaxCheckpointManager(d) as mgr2:
        restored = mgr2.restore(eligible[-1], fallback=True,
                                fallback_steps=eligible)
        assert mgr2.restored_step == 1

    # the restarted generation re-trains step 2 and commits it under its
    # OWN token — overwrite_existing clears any torn finalized leftover
    faultinject.set_plan(None)
    ledger2.open_generation(2, "t2", [0])
    ctx2 = _worker_ctx(d, token="t2", generation=2)
    with OrbaxCheckpointManager(d) as mgr3:
        restored.fit(x, y)
        session2 = AsyncCheckpointSession(ctx2, manager=mgr3)
        session2.submit(2, restored)
        assert session2.close(timeout=120)
        assert session2.errors == []
        assert session2.committed == [2]
    eligible2 = sorted({s["step"] for s in read_step_stamps(d)
                        if ledger2.eligible(s["token"], s["step"])})
    assert eligible2 == [1, 2]
    with OrbaxCheckpointManager(d) as mgr4:
        again = mgr4.restore(2, fallback=True, fallback_steps=eligible2)
        assert mgr4.restored_step == 2
        assert again.iteration == restored.iteration


def test_sync_save_fires_same_phase_hooks(tmp_path, monkeypatch):
    """Phase-scoped faults must behave identically under --save-mode
    sync: a pre_stamp kill during a SYNC save leaves the orbax bytes
    finalized but the step unstamped — torn, never restorable."""
    from deeplearning4j_tpu.util.orbax_checkpoint import (
        OrbaxCheckpointManager)

    def raise_kill(pid, sig):
        raise _SimulatedKill(f"SIGKILL({sig})")
    monkeypatch.setattr(faultinject, "_kill", raise_kill)

    net, x, y = _tiny_net()
    d = str(tmp_path / "ckpt")
    ctx = _worker_ctx(d)
    faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
        {"type": "kill", "worker": 0, "step": 1, "phase": "pre_stamp"}]}))
    with OrbaxCheckpointManager(d) as mgr:
        net.fit(x, y)
        with pytest.raises(_SimulatedKill):
            ctx.save_checkpoint(1, net, manager=mgr)
    assert os.path.isdir(os.path.join(d, "1"))  # orbax bytes finalized
    assert read_step_stamps(d) == []            # but never committed
    # heartbeats written inside a blocking save declare it
    ctx2 = _worker_ctx(d)
    ctx2._saving = 1
    ctx2.heartbeat(7)
    with open(ctx2.heartbeat_path, encoding="utf-8") as fh:
        assert fh.read() == "1:7:1:save"


# ---------------------------------------------------------------------------
# preemption: SIGTERM flushes the in-flight async save under a grace bound
# ---------------------------------------------------------------------------

class TestPreemptionAsyncFlush:
    def test_sigterm_flushes_in_flight_save_and_snapshots(
            self, tmp_path):
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager)
        from deeplearning4j_tpu.util.preemption import PreemptionHandler
        net, x, y = _tiny_net()
        d = tmp_path / "ckpt"
        ctx = _worker_ctx(d)
        net.fit(x, y)
        with OrbaxCheckpointManager(str(d)) as mgr:
            session = AsyncCheckpointSession(ctx, manager=mgr)
            session.submit(1, net)
            handler = PreemptionHandler(
                net, str(tmp_path / "preempt.zip"),
                async_saver=session, flush_grace_s=60.0)
            handler._handle(15, None)  # SIGTERM path, no real signal
            # the in-flight async step committed within the grace window
            assert session.committed == [1]
            assert not handler.flush_timed_out.is_set()
            assert handler.saved.is_set()
            assert os.path.exists(str(tmp_path / "preempt.zip"))
            session.close(timeout=30)

    def test_flush_grace_deadline_is_bounded(self, tmp_path):
        from deeplearning4j_tpu.util.preemption import PreemptionHandler

        class _NeverLands:
            def flush(self, timeout=None):
                time.sleep(min(timeout or 0.0, 0.2))
                return False

        net, _, _ = _tiny_net()
        handler = PreemptionHandler(
            net, str(tmp_path / "preempt.zip"),
            async_saver=_NeverLands(), flush_grace_s=0.2)
        t0 = time.perf_counter()
        handler._handle(15, None)
        elapsed = time.perf_counter() - t0
        assert elapsed < 10.0  # bounded: did not wait for the save
        assert handler.flush_timed_out.is_set()
        # the handler still wrote ITS OWN snapshot after giving up
        assert handler.saved.is_set()

    def test_no_async_saver_is_a_noop(self, tmp_path):
        from deeplearning4j_tpu.util.preemption import PreemptionHandler
        net, _, _ = _tiny_net()
        handler = PreemptionHandler(net, str(tmp_path / "p.zip"))
        assert handler.flush_async() is True
        assert not handler.flush_timed_out.is_set()


# ---------------------------------------------------------------------------
# CI acceptance proofs on real subprocess CPU workers
# ---------------------------------------------------------------------------

def _sub_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


SAMPLES, FEATURES, CLASSES = 240, 6, 3
BATCH = 24          # divisible by 4 AND 2: survives the host shrink
EPOCHS = 3          # 10 iterations/epoch


def _make_job_inputs(tmp_path):
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.util import model_serializer
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=CLASSES))
            .set_input_type(InputType.feed_forward(FEATURES)).build())
    net = MultiLayerNetwork(conf).init()
    model_path = str(tmp_path / "model.zip")
    model_serializer.write_model(net, model_path)
    rng = np.random.default_rng(0)
    yc = rng.integers(0, CLASSES, SAMPLES)
    x = rng.normal(size=(SAMPLES, FEATURES)).astype(np.float32)
    x[np.arange(SAMPLES), yc] += 2.5
    y = np.eye(CLASSES, dtype=np.float32)[yc]
    data_path = str(tmp_path / "data.npz")
    np.savez(data_path, features=x, labels=y)
    return model_path, data_path, x, y


def _pod_spec(tmp_path, model_path, data_path, out_path, plan_path,
              save_mode):
    return WorkerSpec(
        argv=[sys.executable, "-m",
              "deeplearning4j_tpu.parallel.elastic_worker",
              "--modelPath", model_path, "--dataPath", data_path,
              "--out", out_path, "--batchSize", str(BATCH),
              "--epochs", str(EPOCHS), "--threshold", "1e-3",
              "--save-mode", save_mode],
        env=_sub_env({"DL4J_TPU_FAULT_PLAN": plan_path}))


def _debug(sup, result):
    out = []
    for g in result.generations:
        for slot in g.world:
            out.append(f"--- gen {g.generation} slot {slot} ---\n"
                       + sup.tail_log(slot, g.generation, 2000))
    return "\n".join(out)


def _assert_matches_clean_resume(sup, result, out_path, x, y):
    """Final params of the shrunk elastic job EQUAL a clean 2-worker
    resume from the same checkpoint step (<=2e-5)."""
    import jax

    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    from deeplearning4j_tpu.parallel import (DistributedMultiLayerNetwork,
                                             SharedTrainingMaster)
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.util import model_serializer
    from deeplearning4j_tpu.util.orbax_checkpoint import (
        OrbaxCheckpointManager)

    restore_step = result.generations[-1].restore_step
    with OrbaxCheckpointManager(sup.ckpt_dir, active_processes={0},
                                barrier_sync_key_prefix="cmp") as mgr:
        net_b = mgr.restore(restore_step)
    assert int(net_b.epoch) == restore_step
    mesh2 = make_mesh({"data": 2}, devices=jax.devices()[:2])
    master = SharedTrainingMaster(batch_size_per_worker=BATCH,
                                  threshold=1e-3, mesh=mesh2)
    front = DistributedMultiLayerNetwork(net_b, master)
    for _ in range(int(net_b.epoch), EPOCHS):
        front.fit(ListDataSetIterator(DataSet(x, y), BATCH), epochs=1)

    elastic_net = model_serializer.restore_model(out_path)
    assert int(elastic_net.epoch) == EPOCHS
    for i, (a, b) in enumerate(zip(elastic_net.params, net_b.params)):
        for k in a:
            np.testing.assert_allclose(
                np.asarray(a[k]), np.asarray(b[k]), rtol=2e-5, atol=2e-6,
                err_msg=f"layer {i} param {k}: pod recovery diverged "
                        "from the clean 2-worker resume")


@pytest.mark.multiprocess
@pytest.mark.multihost
def test_kill_host_shrinks_to_surviving_host_and_matches(tmp_path):
    """ISSUE 13 acceptance: a 2-host x 2-workers-per-host job whose
    fault plan SIGKILLs the whole of host 1 mid-step (async saves
    overlapping training) shrinks to the surviving host [0, 1] and
    completes; final params EQUAL a clean 2-worker resume from the same
    (async-committed) checkpoint step."""
    model_path, data_path, x, y = _make_job_inputs(tmp_path)
    out_path = str(tmp_path / "final.zip")
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w", encoding="utf-8") as fh:
        json.dump({"faults": [{"type": "kill_host", "host": 1,
                               "step": 25, "signal": "KILL"}]}, fh)
    assert validate_file(plan_path, num_workers=4, num_hosts=2) == []

    spec = _pod_spec(tmp_path, model_path, data_path, out_path, plan_path,
                     save_mode="async")
    reg = MetricsRegistry()
    sup = ElasticJobSupervisor(
        spec, 4, num_hosts=2, min_hosts=1, min_workers=2,
        ckpt_dir=str(tmp_path / "ckpt"),
        backoff=BackoffPolicy(max_restarts=0),
        metrics=reg, poll_interval_s=0.2,
        job_deadline_s=540)  # hard bound: the job can never hang CI
    result = sup.run()

    assert result.status == "completed", _debug(sup, result)
    assert len(result.generations) == 2, _debug(sup, result)
    g1, g2 = result.generations
    assert g1.decision == "shrink"
    assert g1.primary_host == 1
    assert g1.primary_slot in (2, 3)
    assert g2.world == [0, 1]
    # the shrunk generation resumed from an ASYNC-committed step (step 1
    # certainly landed 15 iterations before the kill; step 2's save may
    # still have been in flight when the host died — both are valid
    # fence-eligible restore points, and the comparator resumes from
    # whichever the supervisor chose)
    assert g2.restore_step in (1, 2), _debug(sup, result)
    series = parse_prometheus_text(reg.exposition())
    assert series["elastic_restarts_total"][(("decision", "shrink"),)] == 1
    assert series["elastic_world_size"][()] == 2
    assert series["elastic_hosts"][()] == 1
    _assert_matches_clean_resume(sup, result, out_path, x, y)


@pytest.mark.multiprocess
@pytest.mark.multihost
def test_partition_resolves_to_surviving_host_and_matches(tmp_path):
    """ISSUE 13 acceptance: a DCN partition (host 1 cut off mid-step:
    training blocks on the dead collective while background heartbeats
    stay alive) is detected by the step-progress watchdog, resolved as
    death of the lagging side, and the job shrinks to host 0 with final
    params EQUAL to the clean 2-worker resume."""
    model_path, data_path, x, y = _make_job_inputs(tmp_path)
    out_path = str(tmp_path / "final.zip")
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w", encoding="utf-8") as fh:
        json.dump({"faults": [{"type": "partition", "host": 1,
                               "step": 14, "duration_s": 3600}]}, fh)
    assert validate_file(plan_path, num_workers=4, num_hosts=2) == []

    spec = _pod_spec(tmp_path, model_path, data_path, out_path, plan_path,
                     save_mode="sync")
    reg = MetricsRegistry()
    sup = ElasticJobSupervisor(
        spec, 4, num_hosts=2, min_hosts=1, min_workers=2,
        ckpt_dir=str(tmp_path / "ckpt"),
        backoff=BackoffPolicy(max_restarts=0),
        progress_timeout_s=10.0,  # < gloo's collective timeout
        metrics=reg, poll_interval_s=0.2,
        job_deadline_s=540)
    result = sup.run()

    assert result.status == "completed", _debug(sup, result)
    assert len(result.generations) == 2, _debug(sup, result)
    g1, g2 = result.generations
    assert g1.decision == "shrink", _debug(sup, result)
    assert g1.primary_host == 1, _debug(sup, result)
    assert sorted(g1.dead_slots) == [2, 3]
    assert g2.world == [0, 1]
    assert g2.restore_step == 1, _debug(sup, result)
    series = parse_prometheus_text(reg.exposition())
    assert series["elastic_partitions_total"][()] == 1
    assert series["elastic_worker_deaths_total"][
        (("reason", "partition"),)] == 2
    assert series["elastic_hosts"][()] == 1
    _assert_matches_clean_resume(sup, result, out_path, x, y)
