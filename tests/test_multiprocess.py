"""2-process jax.distributed training equality.

The reference locks distributed semantics with
``TestCompareParameterAveragingSparkVsSingleMachine.java`` (SURVEY §4.5):
the distributed result must equal single-machine training. Here the
distributed side is TWO real OS processes joined through ``init_distributed``
(the JAX coordination service), each owning one CPU device, running
``SharedTrainingMaster`` over a 2-device global mesh with Gloo collectives —
the cross-process path the virtual 8-device mesh cannot exercise. The
baseline is the same training run in THIS process on a 2-device slice of the
virtual mesh: identical math ⇒ identical parameters.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(out, env, worker):
    """One launch attempt on a fresh port; returns (ok, outputs)."""
    coordinator = f"127.0.0.1:{_free_port()}"
    procs = [subprocess.Popen(
        [sys.executable, worker, coordinator, str(pid), out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(stdout)
    ok = all(p.returncode == 0 for p in procs)
    return ok, procs, outputs


def test_two_process_shared_training_matches_single_process(tmp_path):
    # bounded by the workers' communicate(timeout=420) inside _run_workers
    out = str(tmp_path / "dist_params.npz")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(HERE, "distributed_worker.py")
    # the free-port probe races with other processes grabbing ephemeral
    # ports — retry on a fresh port rather than flake
    for attempt in range(3):
        ok, procs, outputs = _run_workers(out, env, worker)
        if ok:
            break
    for pid, (p, stdout) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{stdout[-4000:]}"
    assert "WORKER0_DONE" in outputs[0]
    dist = np.load(out)

    # ---- single-process baseline: identical run on a 2-device mesh -------
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel import (
        DistributedMultiLayerNetwork,
        SharedTrainingMaster,
    )
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    import jax

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.05)).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    yc = rng.integers(0, 3, 256)
    x = rng.normal(size=(256, 6)).astype(np.float32)
    x[np.arange(256), yc] += 2.5
    y = np.eye(3, dtype=np.float32)[yc]
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    master = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                  mesh=mesh)
    DistributedMultiLayerNetwork(net, master).fit(
        ListDataSetIterator(DataSet(x, y), 32), epochs=3)

    for i, layer in enumerate(net.params):
        for k, v in layer.items():
            np.testing.assert_allclose(
                dist[f"{i}:{k}"], np.asarray(v), rtol=2e-5, atol=2e-6,
                err_msg=f"layer {i} param {k} diverged between 2-process "
                        "and single-process training")
    assert np.isfinite(dist["score"])
