"""2-process jax.distributed training equality.

The reference locks distributed semantics with
``TestCompareParameterAveragingSparkVsSingleMachine.java`` (SURVEY §4.5):
the distributed result must equal single-machine training. Here the
distributed side is TWO real OS processes joined through ``init_distributed``
(the JAX coordination service), each owning one CPU device, running
``SharedTrainingMaster`` over a 2-device global mesh with Gloo collectives —
the cross-process path the virtual 8-device mesh cannot exercise. The
baseline is the same training run in THIS process on a 2-device slice of the
virtual mesh: identical math ⇒ identical parameters.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(out, env, worker):
    """One launch attempt on a fresh port; returns (ok, outputs)."""
    coordinator = f"127.0.0.1:{_free_port()}"
    procs = [subprocess.Popen(
        [sys.executable, worker, coordinator, str(pid), out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(stdout)
    ok = all(p.returncode == 0 for p in procs)
    return ok, procs, outputs


def test_two_process_shared_training_matches_single_process(tmp_path):
    # bounded by the workers' communicate(timeout=420) inside _run_workers
    out = str(tmp_path / "dist_params.npz")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(HERE, "distributed_worker.py")
    # the free-port probe races with other processes grabbing ephemeral
    # ports — retry on a fresh port rather than flake
    for attempt in range(3):
        ok, procs, outputs = _run_workers(out, env, worker)
        if ok:
            break
    for pid, (p, stdout) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{stdout[-4000:]}"
    assert "WORKER0_DONE" in outputs[0]
    dist = np.load(out)

    # ---- single-process baseline: identical run on a 2-device mesh -------
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel import (
        DistributedMultiLayerNetwork,
        SharedTrainingMaster,
    )
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    import jax

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.05)).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    yc = rng.integers(0, 3, 256)
    x = rng.normal(size=(256, 6)).astype(np.float32)
    x[np.arange(256), yc] += 2.5
    y = np.eye(3, dtype=np.float32)[yc]
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    master = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                  mesh=mesh)
    DistributedMultiLayerNetwork(net, master).fit(
        ListDataSetIterator(DataSet(x, y), 32), epochs=3)

    for i, layer in enumerate(net.params):
        for k, v in layer.items():
            np.testing.assert_allclose(
                dist[f"{i}:{k}"], np.asarray(v), rtol=2e-5, atol=2e-6,
                err_msg=f"layer {i} param {k} diverged between 2-process "
                        "and single-process training")
    assert np.isfinite(dist["score"])


def _launch(worker, args, env):
    return [subprocess.Popen(
        [sys.executable, worker, *args(pid)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]


def _run_to_completion(worker, args_of, env, attempts=3):
    """Launch the 2-process job on a fresh coordinator port and wait for
    clean exit; retry on a new port if it fails (the _free_port probe races
    other processes for ephemeral ports — same retry _run_workers has)."""
    for attempt in range(attempts):
        coord = f"127.0.0.1:{_free_port()}"
        procs = _launch(worker, lambda pid: args_of(coord, pid), env)
        outputs = []
        for p in procs:
            stdout, _ = p.communicate(timeout=420)
            outputs.append(stdout)
        if all(p.returncode == 0 for p in procs):
            return outputs
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
    return outputs


def test_worker_failure_recovery_from_preemption_checkpoint(tmp_path):
    """Kill the 2-process job mid-training (SIGKILL, no grace — a real
    preemption), restart it, resume from the orbax rotation checkpoint:
    final params must EQUAL an uninterrupted run. Puts the framework
    strictly ahead of the reference's fixed-membership design
    (SharedTrainingWrapper.java:131-156, where a lost worker ends the job)."""
    import signal
    import time

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(HERE, "failover_worker.py")
    workdir = str(tmp_path)
    full_out = str(tmp_path / "full.npz")
    resume_out = str(tmp_path / "resume.npz")

    # ---- run A: uninterrupted 6 epochs --------------------------------
    _run_to_completion(
        worker, lambda coord, pid: [coord, str(pid), full_out, "full",
                                    workdir], env)

    # ---- run B: killed after the epoch-3 checkpoint -------------------
    marker = os.path.join(workdir, "epoch3_saved")
    for attempt in range(3):
        coord = f"127.0.0.1:{_free_port()}"
        procs = _launch(
            worker,
            lambda pid: [coord, str(pid), resume_out, "victim", workdir],
            env)
        deadline = time.time() + 420
        died_early = False
        while not os.path.exists(marker):
            assert time.time() < deadline, "checkpoint marker never appeared"
            if any(p.poll() is not None for p in procs):
                died_early = True  # port race or startup flake: retry
                break
            time.sleep(0.5)
        if not died_early:
            break
        for p in procs:
            p.kill()
            p.communicate(timeout=60)
    assert os.path.exists(marker), "workers kept dying before the kill point"
    # preemption without grace: SIGKILL one worker; the peer loses its
    # collective partner and cannot finish — kill the whole job, like a
    # slice preemption taking every host down
    procs[1].send_signal(signal.SIGKILL)
    time.sleep(2.0)
    for p in procs:
        p.kill()
        p.communicate(timeout=60)

    # ---- run C: restart, resume from the checkpoint -------------------
    _run_to_completion(
        worker, lambda coord, pid: [coord, str(pid), resume_out, "resume",
                                    workdir], env)

    full = np.load(full_out)
    resumed = np.load(resume_out)
    assert set(full.files) == set(resumed.files)
    for k in full.files:
        np.testing.assert_allclose(
            resumed[k], full[k], rtol=2e-5, atol=2e-6,
            err_msg=f"{k} diverged between uninterrupted and resumed runs")
