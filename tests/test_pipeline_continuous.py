"""Continuous-training pipeline (``deeplearning4j_tpu/pipeline/``).

Covers the subsystem bottom-up:

- journal fencing (GenerationLedger pattern: stale tokens un-committable,
  zombie appends ineligible on replay, torn lines skipped);
- state-machine legality (stage order, single-terminal-decision rule,
  resume points);
- the registry's canary data plane (deterministic weighted routing,
  warm-gating, shadow sampling/divergence accounting, describe payloads);
- the route satellite (result count, join(timeout) raising);
- gate / trainer / canary-controller units;
- the E2E acceptance proof: promote path, regression rollback path
  (gate AND alert-driven), and the crash-resume matrix — the pipeline is
  killed (fault injector) at the enter and commit of EVERY stage, then
  restarted, and must converge to the same terminal state with exactly
  one terminal commit in the journal (single-promote semantics);
- the CLI: in-process-only flags rejected; a real subprocess run
  SIGKILLed mid-CANARY by a ``DL4J_TPU_FAULT_PLAN`` resumes on restart.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observe.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource
from deeplearning4j_tpu.pipeline import (AlreadyDecided, CanaryController,
                                         ContinuousPipeline,
                                         ContinuousTrainer, EvalGate,
                                         IllegalTransition, PipelineConfig,
                                         PipelineJournal,
                                         PipelineStateMachine, StalePipelineError,
                                         StreamBuffer, StreamStuck)
from deeplearning4j_tpu.serving import ModelRegistry
from deeplearning4j_tpu.streaming import Route
from deeplearning4j_tpu.util import faultinject

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# journal + fencing
# ---------------------------------------------------------------------------

class TestJournalFencing:
    def test_append_replay_round_trip(self, tmp_path):
        j = PipelineJournal(str(tmp_path))
        t = j.acquire()
        j.append(t, {"event": "run", "run": 1})
        j.append(t, {"event": "enter", "run": 1, "stage": "TRAIN"})
        recs = j.records()
        assert [r["event"] for r in recs] == ["run", "enter"]
        assert [r["seq"] for r in recs] == [1, 2]
        assert all(r["token"] == t for r in recs)

    def test_stale_token_refused(self, tmp_path):
        j1 = PipelineJournal(str(tmp_path))
        t1 = j1.acquire()
        j1.append(t1, {"event": "run", "run": 1})
        j2 = PipelineJournal(str(tmp_path))
        j2.acquire()
        with pytest.raises(StalePipelineError):
            j1.append(t1, {"event": "enter", "run": 1, "stage": "TRAIN"})

    def test_zombie_append_ineligible_on_replay(self, tmp_path):
        """A write that slips past the owner re-read race (simulated by
        appending the line directly) parses but is NOT part of recovered
        state: its seq is outside its fenced token's snapshot."""
        j1 = PipelineJournal(str(tmp_path))
        t1 = j1.acquire()
        j1.append(t1, {"event": "run", "run": 1})
        j2 = PipelineJournal(str(tmp_path))
        t2 = j2.acquire()  # fences t1 with known_seqs=[1]
        with open(j1.journal_path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps({"event": "commit", "run": 1,
                                 "stage": "PROMOTE", "seq": 2,
                                 "token": t1}) + "\n")
        assert len(j1._raw_records()) == 2      # the bytes exist
        recs = j2.records()
        assert len(recs) == 1                   # the state does not
        assert recs[0]["event"] == "run"
        j2.append(t2, {"event": "enter", "run": 1, "stage": "TRAIN"})
        assert [r["event"] for r in j2.records()] == ["run", "enter"]

    def test_torn_final_line_skipped_and_repaired(self, tmp_path):
        j = PipelineJournal(str(tmp_path))
        t = j.acquire()
        j.append(t, {"event": "run", "run": 1})
        with open(j.journal_path, "a", encoding="utf-8") as fh:
            fh.write('{"event": "enter", "run": 1, "seq": 2, "tok')
        assert [r["event"] for r in j.records()] == ["run"]
        # a restart must REPAIR the torn tail: its first append starts a
        # fresh line instead of concatenating into the torn JSON (which
        # would silently drop the new record from every future replay)
        j2 = PipelineJournal(str(tmp_path))
        t2 = j2.acquire()
        j2.append(t2, {"event": "note", "run": 1})
        assert [r["event"] for r in j2.records()] == ["run", "note"]
        j3 = PipelineJournal(str(tmp_path))
        j3.acquire()
        assert [r["event"] for r in j3.records()] == ["run", "note"]


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------

class TestStateMachine:
    def test_happy_path_promote(self, tmp_path):
        sm = PipelineStateMachine(str(tmp_path))
        assert sm.resume_point() is None
        run = sm.begin_run()
        sm.enter("TRAIN")
        sm.commit("TRAIN", candidate_version=2)
        sm.enter("EVAL", candidate_version=2)
        sm.commit("EVAL", passed=True)
        sm.enter("CANARY", candidate_version=2)
        sm.note("canary ramp", fraction=0.25)
        sm.commit("CANARY", decision="promote")
        sm.enter("PROMOTE", candidate_version=2)
        sm.commit("PROMOTE", version=2)
        assert sm.decided(run) == "PROMOTE"
        assert sm.state().stage == "IDLE"
        assert sm.begin_run() == run + 1

    def test_illegal_transitions(self, tmp_path):
        sm = PipelineStateMachine(str(tmp_path))
        with pytest.raises(IllegalTransition):
            sm.enter("TRAIN")           # no open run
        sm.begin_run()
        with pytest.raises(IllegalTransition):
            sm.enter("EVAL")            # TRAIN comes first
        with pytest.raises(IllegalTransition):
            sm.commit("TRAIN")          # never entered
        sm.enter("TRAIN")
        with pytest.raises(IllegalTransition):
            sm.begin_run()              # run still open
        sm.commit("TRAIN")
        with pytest.raises(IllegalTransition):
            sm.enter("PROMOTE")         # EVAL must gate first

    def test_single_terminal_decision(self, tmp_path):
        sm = PipelineStateMachine(str(tmp_path))
        sm.begin_run()
        sm.enter("TRAIN")
        sm.commit("TRAIN")
        sm.enter("EVAL")
        sm.commit("EVAL", passed=True)
        sm.enter("CANARY")
        sm.commit("CANARY", decision="promote")
        sm.enter("PROMOTE")
        sm.commit("PROMOTE")
        with pytest.raises((AlreadyDecided, IllegalTransition)):
            sm.enter("ROLLBACK")
        with pytest.raises(IllegalTransition):
            sm.commit("PROMOTE")

    def test_resume_point_and_fencing(self, tmp_path):
        a = PipelineStateMachine(str(tmp_path))
        a.begin_run()
        a.enter("TRAIN")
        a.commit("TRAIN", candidate_version=5)
        a.enter("EVAL", candidate_version=5)
        # crash here; a new process takes over
        b = PipelineStateMachine(str(tmp_path))
        rp = b.resume_point()
        assert (rp.run, rp.stage, rp.committed) == (1, "EVAL", False)
        assert rp.data == {"candidate_version": 5}
        # the old incarnation is now a zombie: un-committable
        with pytest.raises(StalePipelineError):
            a.commit("EVAL", passed=True)
        b.commit("EVAL", passed=False)
        b.enter("ROLLBACK")
        b.commit("ROLLBACK", reason="gate failed")
        assert b.decided(1) == "ROLLBACK"

    def test_notes_do_not_affect_replay(self, tmp_path):
        a = PipelineStateMachine(str(tmp_path))
        a.begin_run()
        a.enter("TRAIN")
        a.note("operator looked at it", mood="fine")
        b = PipelineStateMachine(str(tmp_path))
        assert b.resume_point().stage == "TRAIN"
        assert not b.resume_point().committed


# ---------------------------------------------------------------------------
# registry canary data plane (duck-typed stub models: no device work)
# ---------------------------------------------------------------------------

class _Stub:
    """Duck-typed model returning a constant; registers as warmup-skipped
    (no input spec), which counts as warm for the traffic gate."""

    def __init__(self, value):
        self.value = float(value)

    def output(self, x):
        x = np.asarray(x)
        return np.full((x.shape[0], 1), self.value, np.float32)


def _stub_registry(metrics=None):
    reg = ModelRegistry(metrics=metrics, wait_ms=0.5, max_batch_size=8)
    reg.register("m", model=_Stub(1.0))
    reg.register("m", model=_Stub(2.0), activate=False)
    return reg


class TestWeightedRouting:
    def test_deterministic_split_exact_counts(self):
        reg = _stub_registry()
        try:
            reg.set_traffic_split("m", {2: 0.25})
            served = [reg.predict_versioned("m", np.ones((1, 4)))[1]
                      for _ in range(8)]
            assert served.count(2) == 2, served
            assert served.count(1) == 6, served
        finally:
            reg.shutdown()

    def test_split_validation(self):
        reg = _stub_registry()
        try:
            with pytest.raises(Exception):
                reg.set_traffic_split("m", {9: 0.5})     # unknown version
            with pytest.raises(ValueError):
                reg.set_traffic_split("m", {1: 0.5})     # live version
            with pytest.raises(ValueError):
                reg.set_traffic_split("m", {2: 1.5})     # fraction > 1
        finally:
            reg.shutdown()

    def test_cold_version_refused_a_fraction(self):
        reg = _stub_registry()
        try:
            served = reg.get("m")
            served.warmup_state[2] = {"status": "warming", "buckets": [8],
                                      "warm": [], "seconds": 0,
                                      "reason": None}
            with pytest.raises(ValueError, match="not warmed"):
                reg.set_traffic_split("m", {2: 0.5})
            served.warmup_state[2]["status"] = "error"
            with pytest.raises(ValueError, match="not warmed"):
                reg.set_traffic_split("m", {2: 0.5})
        finally:
            reg.shutdown()

    def test_describe_gauge_and_clear(self):
        metrics = MetricsRegistry()
        reg = _stub_registry(metrics)
        try:
            reg.set_traffic_split("m", {2: 0.25})
            d = reg.get("m").describe()
            assert d["traffic"] == [{"version": 2, "fraction": 0.25}]
            assert ('serving_canary_fraction{model="m",version="2"} 0.25'
                    in metrics.exposition())
            reg.clear_traffic_split("m")
            assert "traffic" not in reg.get("m").describe()
            assert ('serving_canary_fraction{model="m",version="2"} 0'
                    in metrics.exposition())
        finally:
            reg.shutdown()

    def test_activate_clears_split(self):
        reg = _stub_registry()
        try:
            reg.set_traffic_split("m", {2: 0.5})
            reg.activate("m", 2)
            assert reg.get_traffic_split("m") == {}
            assert reg.get("m").current_version == 2
        finally:
            reg.shutdown()

    def test_pinned_version_bypasses_split(self):
        reg = _stub_registry()
        try:
            reg.set_traffic_split("m", {2: 0.99})
            out, v = reg.predict_versioned("m", np.ones((1, 4)), version=1)
            assert v == 1 and float(out[0, 0]) == 1.0
        finally:
            reg.shutdown()


class TestShadowMode:
    def test_sampling_stride_and_counts(self):
        metrics = MetricsRegistry()
        reg = _stub_registry(metrics)
        try:
            reg.set_shadow("m", 2, sample=0.5, divergence_threshold=10.0)
            for _ in range(8):
                reg.predict("m", np.ones((1, 4)))
            assert reg.drain_shadow()
            state = reg.shadow_state("m")
            assert state["requests"] == 4      # every 2nd request sampled
            assert state["divergences"] == 0   # |2-1| < 10
            assert ('shadow_requests_total{model="m"} 4'
                    in metrics.exposition())
        finally:
            reg.shutdown()

    def test_divergence_counted_and_logged(self):
        metrics = MetricsRegistry()
        reg = _stub_registry(metrics)
        try:
            reg.set_shadow("m", 2, sample=1.0, divergence_threshold=0.5)
            for _ in range(3):
                reg.predict("m", np.ones((2, 4)))
            assert reg.drain_shadow()
            state = reg.shadow_state("m")
            assert state["requests"] == 3
            assert state["divergences"] == 3   # |2-1| = 1 > 0.5
            log = reg.shadow_log("m")
            assert len(log) == 3 and log[0]["diff"] == pytest.approx(1.0)
            assert ('shadow_divergence_total{model="m"} 3'
                    in metrics.exposition())
            d = reg.get("m").describe()["shadow"]
            assert d["version"] == 2 and d["divergences"] == 3
        finally:
            reg.shutdown()

    def test_bounded_divergence_log(self):
        reg = _stub_registry()
        try:
            reg.set_shadow("m", 2, sample=1.0, divergence_threshold=0.0,
                           max_log=5)
            for _ in range(12):
                reg.predict("m", np.ones((1, 4)))
            assert reg.drain_shadow()
            assert len(reg.shadow_log("m")) == 5
            assert reg.shadow_state("m")["divergences"] == 12
        finally:
            reg.shutdown()

    def test_crashing_candidate_is_maximally_divergent(self):
        reg = ModelRegistry(wait_ms=0.5)
        try:
            reg.register("m", model=_Stub(1.0))

            class Boom:
                def output(self, x):
                    raise RuntimeError("shadow model exploded")

            reg.register("m", model=Boom(), activate=False)
            reg.set_shadow("m", 2, sample=1.0)
            reg.predict("m", np.ones((1, 4)))
            assert reg.drain_shadow()
            state = reg.shadow_state("m")
            assert state["divergences"] == 1
            assert "error" in reg.shadow_log("m")[0]
        finally:
            reg.shutdown()

    def test_off_response_path_never_blocks_predict(self):
        reg = _stub_registry()
        try:
            reg.set_shadow("m", 2, sample=1.0, max_queue=2)
            t0 = time.perf_counter()
            for _ in range(20):
                reg.predict("m", np.ones((1, 4)))
            assert time.perf_counter() - t0 < 5.0
            reg.drain_shadow()
            state = reg.shadow_state("m")
            assert state["requests"] + state["dropped"] == 20
        finally:
            reg.shutdown()

    def test_shadow_validation(self):
        reg = _stub_registry()
        try:
            with pytest.raises(ValueError):
                reg.set_shadow("m", 1)           # live version
            with pytest.raises(Exception):
                reg.set_shadow("m", 9)           # unknown version
            with pytest.raises(ValueError):
                reg.set_shadow("m", 2, sample=0.0)
        finally:
            reg.shutdown()


class TestCanaryOverHTTP:
    def test_v1_models_reports_traffic_and_shadow(self):
        """Operators must see a canary in flight from the serving API:
        the /v1/models payload carries the live split + shadow counters."""
        from urllib.request import urlopen
        from deeplearning4j_tpu.serving import ModelServer

        reg = _stub_registry()
        server = ModelServer(reg)
        try:
            server.start()
            reg.set_traffic_split("m", {2: 0.25})
            reg.set_shadow("m", 2, sample=1.0, divergence_threshold=0.5)
            for _ in range(4):
                reg.predict("m", np.ones((1, 4)))
            assert reg.drain_shadow()
            body = json.load(urlopen(f"{server.url}/v1/models", timeout=5))
            m = body["models"][0]
            assert m["traffic"] == [{"version": 2, "fraction": 0.25}]
            assert m["shadow"]["version"] == 2
            assert m["shadow"]["requests"] == 3   # 1 of 4 went to v2
            assert m["shadow"]["divergences"] == 3
            one = json.load(urlopen(f"{server.url}/v1/models/m", timeout=5))
            assert one["traffic"] and one["shadow"]
        finally:
            server.stop(drain=False, shutdown_registry=True)


# ---------------------------------------------------------------------------
# route satellite
# ---------------------------------------------------------------------------

class TestRouteResultAndJoin:
    def test_background_result_count(self):
        out = []
        r = Route().from_source(range(5)).to_list(out).start()
        assert r.join(timeout=5) == 5
        assert r.result == 5 and out == list(range(5))

    def test_join_timeout_raises(self):
        release = []

        def slow(x):
            while not release:
                time.sleep(0.01)
            return x

        r = (Route().from_source(range(3)).transform(slow)
             .to_list([]).start())
        with pytest.raises(TimeoutError):
            r.join(timeout=0.1)
        release.append(True)
        assert r.join(timeout=5) == 3

    def test_error_route_returns_none(self):
        r = (Route().from_source([1, 0]).transform(lambda x: 1 // x)
             .to_list([]).start())
        assert r.join(timeout=5) is None
        assert r.error is not None and r.result is None


# ---------------------------------------------------------------------------
# gate / trainer / canary units
# ---------------------------------------------------------------------------

class _ScoreModel:
    """Duck-typed model with a fixed eval loss (gate unit tests)."""

    def __init__(self, loss):
        self._loss = float(loss)

    def score(self, ds):
        return self._loss


class TestEvalGate:
    def test_loss_margins(self):
        ds = DataSet(np.zeros((4, 2), np.float32),
                     np.zeros((4, 1), np.float32))
        gate = EvalGate(ds, metric="loss", rel_margin=0.1, abs_margin=0.0)
        assert gate.evaluate(_ScoreModel(1.05), _ScoreModel(1.0)).passed
        assert not gate.evaluate(_ScoreModel(1.2), _ScoreModel(1.0)).passed
        strict = EvalGate(ds, metric="loss")
        assert not strict.evaluate(_ScoreModel(1.0001),
                                   _ScoreModel(1.0)).passed
        r = strict.evaluate(_ScoreModel(0.9), _ScoreModel(1.0))
        assert r.passed and r.metric == "loss"
        assert r.to_dict()["baseline"] == 1.0

    def test_validation(self):
        ds = DataSet(np.zeros((2, 2), np.float32),
                     np.zeros((2, 1), np.float32))
        with pytest.raises(ValueError):
            EvalGate(ds, metric="vibes")
        with pytest.raises(ValueError):
            EvalGate(ds, rel_margin=-1)

    def test_journaled_baseline_reused(self):
        ds = DataSet(np.zeros((2, 2), np.float32),
                     np.zeros((2, 1), np.float32))
        gate = EvalGate(ds, metric="loss")
        r = gate.evaluate(_ScoreModel(0.5), None, baseline_value=1.0)
        assert r.passed and r.baseline == 1.0


class TestStreamBufferAndTrainer:
    def test_buffer_put_take_close(self):
        buf = StreamBuffer(capacity=4)
        buf.put(1)
        buf.put(2)
        assert buf.take(1) == [1]
        assert buf.take(5, timeout_s=0.05) == [2]
        assert buf.take(1, timeout_s=0.05) == []
        buf.close()
        with pytest.raises(RuntimeError):
            buf.put(3)

    def test_trainer_mini_epochs_and_watchdog_attached(self):
        from deeplearning4j_tpu.nn.conf import (InputType,
                                                NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.observe.health import TrainingWatchdog
        from deeplearning4j_tpu.observe.listener import TraceListener

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_out=4, activation="relu"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        buf = StreamBuffer()
        rng = np.random.default_rng(0)
        for _ in range(4):
            x = rng.normal(size=(8, 4)).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
            buf.put(DataSet(x, y))
        trainer = ContinuousTrainer(net, buf, batch_size=8,
                                    batches_per_mini_epoch=2,
                                    take_timeout_s=0.2,
                                    watchdog={"action": "log"})
        kinds = {type(l) for l in trainer.listeners}
        assert TraceListener in kinds and TrainingWatchdog in kinds
        stats = trainer.train_mini_epoch()
        assert stats["examples"] == 16 and stats["batches"] == 2
        stats = trainer.train_mini_epoch()
        assert trainer.examples_seen == 32
        with pytest.raises(StreamStuck):
            trainer.train_mini_epoch()

    def test_trainer_rebatches_tuples_and_singles(self):
        from deeplearning4j_tpu.pipeline.trainer import _to_datasets
        x8 = np.ones((8, 3), np.float32)
        y8 = np.ones((8, 2), np.float32)
        single = (np.ones(3, np.float32), np.ones(2, np.float32))
        out = _to_datasets([DataSet(x8, y8), (x8, y8), single], 5)
        assert sum(np.asarray(d.features).shape[0] for d in out) == 17
        assert np.asarray(out[0].features).shape == (5, 3)


class _FakeCanaryRegistry:
    """Duck-typed registry recording the controller's calls."""

    def __init__(self):
        self.calls = []
        self.shadow = None
        self.divergences = 0

    def set_traffic_split(self, name, fractions):
        self.calls.append(("split", dict(fractions)))

    def clear_traffic_split(self, name):
        self.calls.append(("clear_split",))

    def set_shadow(self, name, version, **kw):
        self.shadow = {"version": version, **kw}
        self.calls.append(("shadow", version))

    def clear_shadow(self, name):
        self.calls.append(("clear_shadow",))
        self.shadow = None

    def shadow_state(self, name):
        if self.shadow is None:
            return None
        return {"version": self.shadow["version"], "requests": 10,
                "divergences": self.divergences, "dropped": 0,
                "sample": 1.0}

    def drain_shadow(self, timeout_s=5.0):
        return True


class _FakeAlerts:
    def __init__(self):
        self.rules = []

    def firing(self):
        return list(self.rules)


class TestCanaryController:
    SCHEDULE = [{"fraction": 0.1, "hold_s": 10},
                {"fraction": 0.5, "hold_s": 10}]

    def test_ramp_to_promote(self):
        reg, clock = _FakeCanaryRegistry(), ManualTimeSource(0)
        c = CanaryController(reg, "m", 2, schedule=self.SCHEDULE,
                             time_source=clock, shadow_sample=0.5)
        c.start()
        assert ("shadow", 2) in reg.calls
        assert ("split", {2: 0.1}) in reg.calls
        assert c.tick() is None            # hold not elapsed
        clock.advance(seconds=11)
        assert c.tick() is None            # ramped to step 2
        assert ("split", {2: 0.5}) in reg.calls
        clock.advance(seconds=11)
        assert c.tick() == "promote"
        assert c.shadow_final["requests"] == 10
        assert ("clear_split",) in reg.calls
        assert ("clear_shadow",) in reg.calls
        assert c.tick() == "promote"       # decision is sticky

    def test_alert_firing_rolls_back(self):
        reg, clock = _FakeCanaryRegistry(), ManualTimeSource(0)
        alerts = _FakeAlerts()
        c = CanaryController(reg, "m", 2, schedule=self.SCHEDULE,
                             time_source=clock, alerts=alerts,
                             abort_on_alerts=["predict_slo_burn"])
        c.start()
        alerts.rules = ["unrelated_rule"]
        clock.advance(seconds=11)
        assert c.tick() is None            # unwatched rule: keep ramping
        alerts.rules = ["predict_slo_burn"]
        assert c.tick() == "rollback"
        assert "predict_slo_burn" in c.reason

    def test_divergence_budget_rolls_back(self):
        reg, clock = _FakeCanaryRegistry(), ManualTimeSource(0)
        c = CanaryController(reg, "m", 2, schedule=self.SCHEDULE,
                             time_source=clock, shadow_sample=1.0,
                             max_divergences=3)
        c.start()
        reg.divergences = 5
        assert c.tick() == "rollback"
        assert "divergences" in c.reason

    def test_report_alarm_rolls_back(self):
        reg, clock = _FakeCanaryRegistry(), ManualTimeSource(0)
        c = CanaryController(reg, "m", 2, schedule=self.SCHEDULE,
                             time_source=clock)
        c.start()
        c.report_alarm("watchdog: loss divergence")
        assert c.tick() == "rollback"
        assert "watchdog" in c.reason

    def test_schedule_validation(self):
        with pytest.raises(ValueError):
            CanaryController(_FakeCanaryRegistry(), "m", 2, schedule=[])
        with pytest.raises(ValueError):
            CanaryController(_FakeCanaryRegistry(), "m", 2, schedule=[
                {"fraction": 0.5, "hold_s": 1},
                {"fraction": 0.2, "hold_s": 1}])  # must increase


# ---------------------------------------------------------------------------
# config schema
# ---------------------------------------------------------------------------

class TestPipelineConfig:
    def test_defaults_and_overrides(self):
        cfg = PipelineConfig.parse({"name": "m",
                                    "train": {"mini_epochs": 7}})
        assert cfg.name == "m"
        assert cfg.train["mini_epochs"] == 7
        assert cfg.train["batch_size"] == 32      # default retained

    def test_schema_errors_name_the_field(self):
        for spec, needle in (
                ({"nope": 1}, "nope"),
                ({"train": {"batch_size": 0}}, "train.batch_size"),
                ({"gate": {"metric": "vibes"}}, "gate.metric"),
                ({"canary": {"schedule": []}}, "canary.schedule"),
                ({"canary": {"shadow_sample": 2}}, "shadow_sample"),
                ({"cycles": 0}, "cycles"),
                ({"train": {"watchdog": "explode"}}, "watchdog")):
            with pytest.raises(ValueError, match=needle.replace(".", r"\.")):
                PipelineConfig.parse(spec)

    def test_lint_contradictions(self):
        cfg = PipelineConfig.parse(
            {"canary": {"shadow_sample": 0, "max_divergences": 3}})
        assert any("shadow_sample" in p for p in cfg.lint())
        assert not PipelineConfig.parse({}).lint()

    def test_shipped_example_config_valid(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        from validate_pipeline_config import validate_file
        assert validate_file(
            os.path.join(REPO, "examples", "pipeline_config.json")) == []


# ---------------------------------------------------------------------------
# E2E: promote, rollback, crash-resume matrix
# ---------------------------------------------------------------------------

_W = np.array(np.random.default_rng(3).normal(size=(6, 2)), np.float32)


def _mesh_data(rng, n, invert=False):
    x = rng.normal(size=(n, 6)).astype(np.float32)
    labels = (x @ _W).argmax(1)
    if invert:
        labels = 1 - labels
    return x, np.eye(2, dtype=np.float32)[labels]


def _small_net(seed=1):
    from deeplearning4j_tpu.nn.conf import (InputType,
                                            NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


_E2E_CONFIG = {
    "name": "m",
    "train": {"batch_size": 16, "batches_per_mini_epoch": 2,
              "mini_epochs": 2, "take_timeout_s": 0.3,
              "watchdog": "raise"},
    "gate": {"metric": "loss", "rel_margin": 0.02, "abs_margin": 0.0},
    "canary": {"schedule": [{"fraction": 0.25, "hold_s": 10},
                            {"fraction": 0.5, "hold_s": 10}],
               "shadow_sample": 0.5, "divergence_threshold": 10.0,
               "max_divergences": None, "abort_on_alerts": None,
               "poll_s": 0.01}}


def _build_pipeline(state_dir, registry, clock, *, invert=False,
                    alerts=None, config=None, metrics=None):
    rng = np.random.default_rng(42)
    buf = StreamBuffer()
    for _ in range(6):
        buf.put(DataSet(*_mesh_data(rng, 16, invert=invert)))
    eval_set = DataSet(*_mesh_data(np.random.default_rng(43), 64))

    def wait(poll_s):
        for i in range(4):
            registry.predict("m", eval_set.features[2 * i:2 * i + 2])
        clock.advance(seconds=6)

    return ContinuousPipeline(
        registry, "m", str(state_dir),
        config=PipelineConfig.parse(config or _E2E_CONFIG),
        buffer=buf, eval_set=eval_set, time_source=clock,
        metrics=metrics, alerts=alerts,
        sample_input=eval_set.features[:1], canary_wait=wait)


@pytest.fixture
def serving_registry():
    rng = np.random.default_rng(5)
    net = _small_net()
    net.fit(DataSet(*_mesh_data(rng, 128)), epochs=3)
    reg = ModelRegistry(wait_ms=0.5, buckets=[2, 16])
    reg.register("m", model=net,
                 sample_input=np.zeros((1, 6), np.float32))
    yield reg
    reg.shutdown()


class TestPipelineEndToEnd:
    def test_promote_path(self, tmp_path, serving_registry):
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock)
        summary = pipe.run_cycle()
        assert summary["outcome"] == "PROMOTE", summary
        assert serving_registry.get("m").current_version == 2
        canary = [r for r in pipe.sm.stage_history(1)
                  if r.get("stage") == "CANARY"
                  and r.get("event") == "commit"][0]["data"]
        assert canary["decision"] == "promote"
        assert canary["shadow"]["requests"] > 0   # shadow diffs recorded
        # the candidate checkpoint was persisted for cross-process resume
        assert canary["candidate_version"] == 2

    def test_regression_rolls_back_via_gate(self, tmp_path,
                                            serving_registry):
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock,
                               invert=True)
        summary = pipe.run_cycle()
        assert summary["outcome"] == "ROLLBACK", summary
        assert serving_registry.get("m").current_version == 1  # unchanged
        ev = [r for r in pipe.sm.stage_history(1)
              if r.get("stage") == "EVAL"
              and r.get("event") == "commit"][0]["data"]
        assert ev["passed"] is False

    def test_canary_rolls_back_on_firing_alert(self, tmp_path,
                                               serving_registry):
        clock = ManualTimeSource(0)
        alerts = _FakeAlerts()
        cfg = json.loads(json.dumps(_E2E_CONFIG))
        cfg["gate"] = {"metric": "loss", "rel_margin": 1.0,
                       "abs_margin": 1.0}  # gate passes; canary decides
        cfg["canary"]["abort_on_alerts"] = ["slo_burn"]
        pipe = _build_pipeline(tmp_path, serving_registry, clock,
                               alerts=alerts, config=cfg)
        orig_wait = pipe.canary_wait
        ticks = []

        def wait_then_fire(poll_s):
            orig_wait(poll_s)
            ticks.append(1)
            if len(ticks) == 1:
                alerts.rules = ["slo_burn"]  # SLO burns mid-ramp

        pipe.canary_wait = wait_then_fire
        summary = pipe.run_cycle()
        assert summary["outcome"] == "ROLLBACK", summary
        assert serving_registry.get("m").current_version == 1
        assert "slo_burn" in summary["detail"]["reason"]
        # no traffic plumbing survives the rollback
        assert serving_registry.get_traffic_split("m") == {}
        assert serving_registry.shadow_state("m") is None


# ---------------------------------------------------------------------------
# crash-resume matrix: kill at every stage boundary, restart, converge
# ---------------------------------------------------------------------------

class _Killed(BaseException):
    """Stand-in for SIGKILL: raised by the patched fault-injector kill so
    the 'process death' unwinds the pipeline mid-transition without
    tearing down the test process."""


@pytest.fixture
def fault_kill(monkeypatch):
    """Arm a kill at journal seq N for the 'pipeline' fault slot."""

    def arm(seq):
        plan = faultinject.FaultPlan.parse(
            {"faults": [{"type": "kill", "worker": "pipeline",
                         "step": int(seq)}]})
        faultinject.set_plan(plan)

    def killer(pid, signum):
        faultinject.set_plan(None)  # one shot
        raise _Killed(f"fault-injected kill (pid {pid}, sig {signum})")

    monkeypatch.setattr(faultinject, "_kill", killer)
    yield arm
    faultinject.set_plan(None)


def _reference_seq_map(tmp_path_factory):
    """One clean run to learn which journal seq each stage boundary
    lands on (deterministic: same config, same data)."""
    rng = np.random.default_rng(5)
    net = _small_net()
    net.fit(DataSet(*_mesh_data(rng, 128)), epochs=3)
    reg = ModelRegistry(wait_ms=0.5, buckets=[2, 16])
    reg.register("m", model=net,
                 sample_input=np.zeros((1, 6), np.float32))
    state = tmp_path_factory.mktemp("ref")
    pipe = _build_pipeline(state, reg, ManualTimeSource(0))
    assert pipe.run_cycle()["outcome"] == "PROMOTE"
    seq_map = {}
    for r in pipe.sm.journal.records():
        if r["event"] in ("enter", "commit"):
            seq_map[(r["stage"], r["event"])] = r["seq"]
    reg.shutdown()
    return seq_map


_KILL_POINTS = [("TRAIN", "enter"), ("TRAIN", "commit"),
                ("EVAL", "enter"), ("EVAL", "commit"),
                ("CANARY", "enter"), ("CANARY", "commit"),
                ("PROMOTE", "enter"), ("PROMOTE", "commit")]


@pytest.fixture(scope="module")
def seq_map(tmp_path_factory):
    return _reference_seq_map(tmp_path_factory)


class TestCrashResumeMatrix:
    @pytest.mark.parametrize("stage,event", _KILL_POINTS,
                             ids=[f"{s}-{e}" for s, e in _KILL_POINTS])
    def test_kill_restart_converges_to_single_promote(
            self, tmp_path, serving_registry, fault_kill, seq_map,
            stage, event):
        """Kill the pipeline exactly when the (stage, event) record lands
        in the journal; a fresh pipeline over the same journal + registry
        must converge to the SAME terminal state as an unkilled run —
        exactly one PROMOTE commit, zero ROLLBACKs, candidate live."""
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock)
        fault_kill(seq_map[(stage, event)])
        with pytest.raises(_Killed):
            pipe.run_cycle()

        # restart: new machine over the same journal; the dead process's
        # token is fenced, its in-flight transition un-committable
        resumed = _build_pipeline(tmp_path, serving_registry, clock)
        rp = resumed.sm.resume_point()
        if rp is not None:
            assert rp.run == 1
            summary = resumed.run_cycle()
        else:
            # the terminal commit itself landed before the kill — the run
            # is already decided; a new cycle would start run 2
            summary = {"outcome": resumed.sm.decided(1)}
        assert summary["outcome"] == "PROMOTE", (stage, event, summary)

        terminals = [r for r in resumed.sm.journal.records()
                     if r.get("event") == "commit"
                     and r.get("stage") in ("PROMOTE", "ROLLBACK")]
        assert [(r["run"], r["stage"]) for r in terminals] == \
            [(1, "PROMOTE")], terminals
        served = serving_registry.get("m")
        promoted = [r for r in resumed.sm.journal.records()
                    if (r.get("stage"), r.get("event")) ==
                    ("PROMOTE", "commit")][0]["data"]["version"]
        assert served.current_version == promoted
        # the zombie cannot decide the run a second time
        with pytest.raises((StalePipelineError, AlreadyDecided,
                            IllegalTransition)):
            pipe.sm.commit("PROMOTE", version=99)

    def test_kill_at_begin_run_continues_same_run(
            self, tmp_path, serving_registry, fault_kill):
        """A crash right after begin_run must not abandon run 1
        undecided: the restart CONTINUES run 1 (one terminal per run)."""
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock)
        fault_kill(1)  # the 'run' journal record itself
        with pytest.raises(_Killed):
            pipe.run_cycle()
        resumed = _build_pipeline(tmp_path, serving_registry, clock)
        assert resumed.sm.open_empty_run()
        summary = resumed.run_cycle()
        assert summary["run"] == 1 and summary["outcome"] == "PROMOTE"
        runs = [r["run"] for r in resumed.sm.journal.records()
                if r.get("event") == "run"]
        assert runs == [1]

    def test_kill_mid_canary_rollback_run_stays_rollback(
            self, tmp_path, serving_registry, fault_kill, seq_map):
        """The degraded-candidate run killed mid-flight still converges
        to exactly one ROLLBACK (never a promote) after restart."""
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock,
                               invert=True)
        fault_kill(seq_map[("EVAL", "commit")])
        with pytest.raises(_Killed):
            pipe.run_cycle()
        resumed = _build_pipeline(tmp_path, serving_registry, clock,
                                  invert=True)
        summary = resumed.run_cycle()
        assert summary["outcome"] == "ROLLBACK", summary
        terminals = [r for r in resumed.sm.journal.records()
                     if r.get("event") == "commit"
                     and r.get("stage") in ("PROMOTE", "ROLLBACK")]
        assert [(r["run"], r["stage"]) for r in terminals] == \
            [(1, "ROLLBACK")], terminals
        assert serving_registry.get("m").current_version == 1


# ---------------------------------------------------------------------------
# review hardening: cross-process promote restore, warm-wait, retention,
# sync-path deadlines
# ---------------------------------------------------------------------------

class TestReviewHardening:
    def test_restore_promoted_across_processes(self, tmp_path,
                                               serving_registry):
        """A restarted process registers the ORIGINAL baseline; the
        journal's committed PROMOTE must be re-applied or the pipeline
        silently serves (and exports) pre-promotion weights."""
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock)
        assert pipe.run_cycle()["outcome"] == "PROMOTE"
        promoted = serving_registry.get("m")
        promoted_model = promoted.versions[promoted.current_version].model
        probe = np.zeros((2, 6), np.float32)
        want = np.asarray(promoted_model.output(probe))

        # "restart": a fresh registry holding only the stale baseline
        rng = np.random.default_rng(5)
        baseline = _small_net()
        baseline.fit(DataSet(*_mesh_data(rng, 128)), epochs=3)
        fresh = ModelRegistry(wait_ms=0.5, buckets=[2, 16])
        fresh.register("m", model=baseline,
                       sample_input=np.zeros((1, 6), np.float32))
        try:
            resumed = _build_pipeline(tmp_path, fresh, clock)
            v = resumed.restore_promoted()
            assert v is not None
            served = fresh.get("m")
            assert served.current_version == v
            got = np.asarray(served.versions[v].model.output(probe))
            np.testing.assert_allclose(got, want, rtol=1e-5)
        finally:
            fresh.shutdown()

    def test_restore_promoted_noop_without_promote(self, tmp_path,
                                                   serving_registry):
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock,
                               invert=True)
        assert pipe.run_cycle()["outcome"] == "ROLLBACK"
        resumed = _build_pipeline(tmp_path, serving_registry, clock)
        assert resumed.restore_promoted() is None

    def test_rollback_retires_candidate_and_checkpoint(
            self, tmp_path, serving_registry):
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock,
                               invert=True)
        assert pipe.run_cycle()["outcome"] == "ROLLBACK"
        served = serving_registry.get("m")
        assert sorted(served.versions) == [1]      # candidate retired
        assert 2 not in served.warmup_state
        assert not os.path.exists(
            os.path.join(str(tmp_path), "candidate_run0001.zip"))

    def test_promote_prunes_older_candidate_zips(self, tmp_path,
                                                 serving_registry):
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock,
                               invert=True)
        assert pipe.run_cycle()["outcome"] == "ROLLBACK"
        pipe2 = _build_pipeline(tmp_path, serving_registry, clock)
        assert pipe2.run_cycle()["outcome"] == "PROMOTE"
        zips = [n for n in os.listdir(str(tmp_path))
                if n.startswith("candidate_run") and n.endswith(".zip")]
        assert zips == ["candidate_run0002.zip"]   # promoted run only

    def test_canary_waits_out_async_warmup_error_via_rewarm(
            self, tmp_path, serving_registry):
        """A FAILED candidate warmup gets one rewarm() instead of
        crash-looping on the warm-gated traffic split."""
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock)
        real = serving_registry.warmup_state
        flips = []

        def flaky(name, version=None):
            if version == 2 and not flips:
                flips.append(1)
                return {"status": "error", "reason": "transient OOM"}
            return real(name, version)

        serving_registry.warmup_state = flaky
        try:
            summary = pipe.run_cycle()
        finally:
            del serving_registry.warmup_state
        assert summary["outcome"] == "PROMOTE", summary
        assert flips  # the error path was actually exercised

    def test_canary_rolls_back_when_candidate_never_warms(
            self, tmp_path, serving_registry):
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock)
        pipe.warm_timeout_s = 0.2
        serving_registry.warmup_state = \
            lambda name, version=None: {"status": "warming"}
        try:
            summary = pipe.run_cycle()
        finally:
            del serving_registry.warmup_state
        assert summary["outcome"] == "ROLLBACK", summary
        assert "warm" in summary["detail"]["reason"]
        assert serving_registry.get("m").current_version == 1

    def test_lost_candidate_resolves_to_rollback_not_crash_loop(
            self, tmp_path, serving_registry, fault_kill, seq_map):
        """A resumed run whose candidate is unrecoverable (fresh
        registry, checkpoint deleted) must DECIDE — a journaled ROLLBACK
        — instead of raising on every restart forever."""
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock)
        fault_kill(seq_map[("EVAL", "enter")])
        with pytest.raises(_Killed):
            pipe.run_cycle()
        for n in os.listdir(str(tmp_path)):      # lose the checkpoint
            if n.endswith(".zip"):
                os.unlink(os.path.join(str(tmp_path), n))
        rng = np.random.default_rng(5)
        baseline = _small_net()
        baseline.fit(DataSet(*_mesh_data(rng, 128)), epochs=3)
        fresh = ModelRegistry(wait_ms=0.5, buckets=[2, 16])
        fresh.register("m", model=baseline,
                       sample_input=np.zeros((1, 6), np.float32))
        try:
            resumed = _build_pipeline(tmp_path, fresh, clock)
            summary = resumed.run_cycle()
            assert summary["outcome"] == "ROLLBACK", summary
            assert "candidate lost" in summary["detail"]["reason"]
            terminals = [r for r in resumed.sm.journal.records()
                         if r.get("event") == "commit"
                         and r.get("stage") in ("PROMOTE", "ROLLBACK")]
            assert [(r["run"], r["stage"]) for r in terminals] == \
                [(1, "ROLLBACK")], terminals
            # and the journal is at IDLE: the next cycle is a fresh run
            assert resumed.sm.resume_point() is None
        finally:
            fresh.shutdown()

    def test_unregister_validation_and_cleanup(self):
        reg = _stub_registry()
        try:
            with pytest.raises(ValueError):
                reg.unregister("m", 1)             # live version refused
            reg.set_traffic_split("m", {2: 0.25})
            reg.set_shadow("m", 2, sample=1.0)
            reg.unregister("m", 2)
            assert reg.get_traffic_split("m") == {}
            assert reg.shadow_state("m") is None
            assert sorted(reg.get("m").versions) == [1]
            with pytest.raises(Exception):
                reg.predict_versioned("m", np.ones((1, 4)), version=2)
        finally:
            reg.shutdown()

    def test_versions_never_reused_after_unregister(self):
        """Journals and per-version metric series must never conflate
        two candidates under one number."""
        reg = _stub_registry()
        try:
            reg.unregister("m", 2)
            v = reg.register("m", model=_Stub(3.0), activate=False)
            assert v == 3
        finally:
            reg.shutdown()

    def test_failed_stream_rolls_back_instead_of_promoting(
            self, tmp_path, serving_registry):
        """A route that DIED (error set) is not a drained one — the
        partially-trained candidate must not reach the gate."""
        clock = ManualTimeSource(0)
        pipe = _build_pipeline(tmp_path, serving_registry, clock)
        boom = RuntimeError("kafka gone")
        bad_route = Route().from_source([1]).to_list([])
        bad_route.error = boom
        pipe.route = bad_route
        # one mini-epoch of data arrives, then the stream 'fails'
        pipe.buffer = StreamBuffer()
        rng = np.random.default_rng(42)
        pipe.buffer.put(DataSet(*_mesh_data(rng, 32)))
        pipe.config.train["take_timeout_s"] = 0.1
        summary = pipe.run_cycle()
        assert summary["outcome"] == "ROLLBACK", summary
        assert "stream failed" in summary["detail"]["reason"]

    def test_sync_routed_path_honors_deadline(self):
        from deeplearning4j_tpu.parallel.inference import (
            InferenceDeadlineExceeded)

        class Slow(_Stub):
            def output(self, x):
                time.sleep(0.05)
                return super().output(x)

        reg = ModelRegistry(wait_ms=0.5)
        try:
            reg.register("m", model=_Stub(1.0))
            reg.register("m", model=Slow(2.0), activate=False)
            with pytest.raises(InferenceDeadlineExceeded):
                reg.predict_versioned("m", np.ones((1, 4)), version=2,
                                      deadline_s=0.001)
            out, v = reg.predict_versioned("m", np.ones((1, 4)),
                                           version=2, deadline_s=5.0)
            assert v == 2 and float(out[0, 0]) == 2.0
        finally:
            reg.shutdown()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestPipelineCLI:
    def test_rejects_in_process_only_flags(self, tmp_path, capsys):
        from deeplearning4j_tpu import cli
        with pytest.raises(SystemExit) as ei:
            cli.pipeline_main([
                "--modelPath", "m.zip", "--dataPath", "d.npz",
                "--config", "c.json", "--state-dir", str(tmp_path),
                "--trace", "out.json", "--watchdog", "raise"])
        assert ei.value.code == 2
        err = capsys.readouterr().err
        assert "--trace" in err and "--watchdog" in err
        assert "train.watchdog" in err

    def test_rejects_bad_eval_fraction(self, tmp_path, capsys):
        from deeplearning4j_tpu import cli
        with pytest.raises(SystemExit):
            cli.pipeline_main([
                "--modelPath", "m.zip", "--dataPath", "d.npz",
                "--config", "c.json", "--state-dir", str(tmp_path),
                "--eval-fraction", "1.5"])

    @pytest.mark.multiprocess
    def test_subprocess_kill_mid_canary_resumes(self, tmp_path):
        """The acceptance proof, with a REAL process and a REAL SIGKILL:
        a fault plan kills the pipeline CLI mid-CANARY (journal seq 8 =
        the first ramp note); re-running the same command resumes from
        the journal and converges — exactly one PROMOTE, never two."""
        from deeplearning4j_tpu.util import model_serializer

        rng = np.random.default_rng(5)
        net = _small_net()
        net.fit(DataSet(*_mesh_data(rng, 128)), epochs=3)
        model_path = str(tmp_path / "model.zip")
        model_serializer.write_model(net, model_path)
        x, y = _mesh_data(rng, 160)
        data_path = str(tmp_path / "data.npz")
        np.savez(data_path, features=x, labels=y)
        config = dict(_E2E_CONFIG,
                      canary=dict(_E2E_CONFIG["canary"],
                                  schedule=[{"fraction": 0.25,
                                             "hold_s": 0.2},
                                            {"fraction": 0.5,
                                             "hold_s": 0.2}],
                                  poll_s=0.05))
        config_path = str(tmp_path / "pipeline.json")
        with open(config_path, "w") as fh:
            json.dump(config, fh)
        state_dir = str(tmp_path / "state")
        plan = json.dumps({"faults": [{"type": "kill",
                                       "worker": "pipeline", "step": 8}]})
        argv = [sys.executable, "-m", "deeplearning4j_tpu.cli", "pipeline",
                "--modelPath", model_path, "--dataPath", data_path,
                "--config", config_path, "--state-dir", state_dir,
                "--cycles", "1"]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8",
                   PYTHONPATH=os.pathsep.join(
                       p for p in (REPO,
                                   os.environ.get("PYTHONPATH", "")) if p))

        first = subprocess.run(
            argv, env=dict(env, DL4J_TPU_FAULT_PLAN=plan),
            timeout=300, capture_output=True, text=True)
        assert first.returncode == -9, (first.returncode, first.stdout,
                                        first.stderr)
        journal = os.path.join(state_dir, "pipeline_journal.jsonl")
        mid = [json.loads(l) for l in open(journal) if l.endswith("\n")]
        assert any(r.get("stage") == "CANARY" and r.get("event") == "enter"
                   for r in mid)
        assert not any(r.get("stage") in ("PROMOTE", "ROLLBACK")
                       and r.get("event") == "commit" for r in mid)

        second = subprocess.run(argv, env=env, timeout=300,
                                capture_output=True, text=True)
        assert second.returncode == 0, (second.stdout[-2000:],
                                        second.stderr[-2000:])
        assert "run 1: PROMOTE" in second.stdout, second.stdout
        final = [json.loads(l) for l in open(journal) if l.endswith("\n")]
        terminals = [(r["run"], r["stage"]) for r in final
                     if r.get("event") == "commit"
                     and r.get("stage") in ("PROMOTE", "ROLLBACK")]
        assert terminals == [(1, "PROMOTE")], terminals

        # multi-cycle: each cycle gets its OWN stream pass — a greedy
        # first cycle must not starve later ones into aborted rollbacks
        state2 = str(tmp_path / "state2")
        third = subprocess.run(
            [a if a != state_dir else state2 for a in argv[:-2]]
            + ["--cycles", "2"],
            env=env, timeout=300, capture_output=True, text=True)
        assert third.returncode == 0, (third.stdout[-2000:],
                                       third.stderr[-2000:])
        j2 = os.path.join(state2, "pipeline_journal.jsonl")
        recs = [json.loads(l) for l in open(j2) if l.endswith("\n")]
        trains = [r for r in recs if (r.get("stage"), r.get("event"))
                  == ("TRAIN", "commit")]
        assert len(trains) == 2
        assert all("aborted" not in r.get("data", {}) for r in trains), \
            [r.get("data") for r in trains]
