"""SameDiff standalone graph-builder tests.

Capability parity with ND4J's SameDiff/SDVariable API (the tensor-level
graph builder the reference's SameDiff layers are written against —
``nn/conf/layers/samediff/``): variable algebra, execution, autodiff vs
finite differences, training, save/load.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.nn.updaters import Adam, Sgd

RNG = np.random.default_rng(7)


class TestAlgebraAndExec:
    def test_operator_algebra_matches_numpy(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(None, 3))
        w = sd.var("w", value=RNG.normal(size=(3, 4)))
        b = sd.var("b", value=RNG.normal(size=(4,)))
        y = (x @ w + b) * 2.0 - 1.0
        y = y / 3.0
        out = sd.nn.tanh(y, name="out")
        xv = RNG.normal(size=(5, 3)).astype(np.float32)
        got = sd.output({"x": xv}, "out")["out"]
        wv = np.asarray(sd.variables_map["w"])
        bv = np.asarray(sd.variables_map["b"])
        want = np.tanh(((xv @ wv + bv) * 2.0 - 1.0) / 3.0)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_reductions_and_math(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(4, 5))
        sd.math.exp(x.sum(dims=1), name="se")
        x.mean(dims=0, keepdims=True, name="m")
        x.std(dims=1, bias_corrected=True, name="s")
        sd.math.clip_by_value(x, -0.5, 0.5, name="c")
        xv = RNG.normal(size=(4, 5)).astype(np.float32)
        outs = sd.output({"x": xv}, "se", "m", "s", "c")
        np.testing.assert_allclose(outs["se"], np.exp(xv.sum(1)), rtol=1e-4)
        np.testing.assert_allclose(outs["m"], xv.mean(0, keepdims=True), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(outs["s"], xv.std(1, ddof=1), rtol=1e-4)
        np.testing.assert_allclose(outs["c"], np.clip(xv, -0.5, 0.5), rtol=1e-6)

    def test_structure_ops(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(2, 6))
        x.reshape(3, 4, name="r")
        x.T(name="t")
        x[0:1, 2:5].rename("sl")
        xv = np.arange(12, dtype=np.float32).reshape(2, 6)
        outs = sd.output({"x": xv}, "r", "t", "sl")
        np.testing.assert_array_equal(outs["r"], xv.reshape(3, 4))
        np.testing.assert_array_equal(outs["t"], xv.T)
        np.testing.assert_array_equal(outs["sl"], xv[0:1, 2:5])

    def test_scalar_promotion_and_maximum(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(3,))
        sd.math.maximum(x, 0.0, name="relu_like")
        xv = np.array([-1.0, 0.5, 2.0], np.float32)
        out = sd.output({"x": xv}, "relu_like")["relu_like"]
        np.testing.assert_array_equal(out, np.maximum(xv, 0.0))

    def test_shape_inference(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(8, 3))
        w = sd.var("w", shape=(3, 5))
        y = x.mmul(w, name="y")
        assert y.shape == (8, 5)

    def test_eval_shortcut_and_repeat_no_recompile(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(2, 2))
        y = sd.math.sqrt(sd.math.abs(x) + 1.0, name="y")
        xv = RNG.normal(size=(2, 2)).astype(np.float32)
        a = y.eval({"x": xv})
        b = y.eval({"x": xv})
        np.testing.assert_array_equal(a, b)
        assert len(sd._jit_cache) == 1  # second eval reused the compiled fn


class TestAutodiff:
    def test_gradients_match_finite_differences(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(4, 3))
        y = sd.place_holder("y", shape=(4, 2))
        w = sd.var("w", value=RNG.normal(size=(3, 2)) * 0.5)
        b = sd.var("b", value=np.zeros(2))
        pred = sd.nn.tanh(x @ w + b, name="pred")
        sd.loss.mean_squared_error(y, pred, name="loss")
        sd.set_loss_variables("loss")

        xv = RNG.normal(size=(4, 3))
        yv = RNG.normal(size=(4, 2))
        grads = sd.calculate_gradients({"x": xv, "y": yv}, "w", "b")

        # finite differences on the same loss
        def loss_at(wv, bv):
            p = np.tanh(xv @ wv + bv)
            return np.mean((p - yv) ** 2)

        wv = np.asarray(sd.variables_map["w"], np.float64)
        bv = np.asarray(sd.variables_map["b"], np.float64)
        eps = 1e-5
        for (name, val, grad) in (("w", wv, grads["w"]), ("b", bv, grads["b"])):
            flat = val.ravel()
            for i in range(flat.size):
                d = np.zeros_like(flat)
                d[i] = eps
                dv = (d.reshape(val.shape))
                num = (loss_at(wv + dv, bv) - loss_at(wv - dv, bv)) / (2 * eps) \
                    if name == "w" else \
                    (loss_at(wv, bv + dv) - loss_at(wv, bv - dv)) / (2 * eps)
                assert abs(num - grad.ravel()[i]) < 1e-3, (name, i)

    def test_var_gradient_accessor(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(2, 2))
        w = sd.var("w", value=np.eye(2))
        sd.loss.mse(x, x @ w, name="l")
        sd.set_loss_variables("l")
        sd.calculate_gradients({"x": np.ones((2, 2), np.float32)})
        g = w.gradient()
        assert g.shape == (2, 2)

    def test_softmax_ce_loss_grad_direction(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(8, 4))
        y = sd.place_holder("y", shape=(8, 3))
        w = sd.var("w", value=np.zeros((4, 3)))
        logits = x @ w
        logits.rename("logits")
        sd.loss.softmax_cross_entropy(y, logits, name="loss")
        sd.set_loss_variables("loss")
        xv = RNG.normal(size=(8, 4)).astype(np.float32)
        yv = np.eye(3, dtype=np.float32)[RNG.integers(0, 3, 8)]
        g = sd.calculate_gradients({"x": xv, "y": yv}, "w")["w"]
        # analytic: x^T (softmax(logits) - y) / n with w=0 → softmax = 1/3
        want = xv.T @ (np.full_like(yv, 1 / 3) - yv) / 8
        np.testing.assert_allclose(g, want, rtol=1e-4, atol=1e-5)


class TestTraining:
    def test_fit_linear_regression(self):
        true_w = np.array([[2.0], [-3.0], [0.5]], np.float32)
        xv = RNG.normal(size=(256, 3)).astype(np.float32)
        yv = xv @ true_w + 0.01 * RNG.normal(size=(256, 1)).astype(np.float32)

        sd = SameDiff.create()
        x = sd.place_holder("input", shape=(None, 3))
        y = sd.place_holder("label", shape=(None, 1))
        w = sd.var("w", value=np.zeros((3, 1)))
        b = sd.var("b", value=np.zeros(1))
        pred = (x @ w + b)
        pred.rename("pred")
        sd.loss.mean_squared_error(y, pred, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(TrainingConfig(
            updater=Adam(0.05),
            data_set_feature_mapping=["input"],
            data_set_label_mapping=["label"]))
        from deeplearning4j_tpu.datasets.dataset import DataSet
        final = sd.fit(DataSet(xv, yv), epochs=200)
        assert final < 1e-2
        np.testing.assert_allclose(np.asarray(sd.variables_map["w"]), true_w,
                                   atol=0.1)

    def test_fit_with_l2(self):
        sd = SameDiff.create()
        x = sd.place_holder("input", shape=(None, 2))
        y = sd.place_holder("label", shape=(None, 1))
        w = sd.var("w", value=np.ones((2, 1)))
        sd.loss.mse(y, x @ w, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(TrainingConfig(
            updater=Sgd(0.1), l2=1.0,
            data_set_feature_mapping=["input"],
            data_set_label_mapping=["label"]))
        from deeplearning4j_tpu.datasets.dataset import DataSet
        xv = np.zeros((8, 2), np.float32)
        yv = np.zeros((8, 1), np.float32)
        sd.fit(DataSet(xv, yv), epochs=20)
        # pure-l2 pull toward zero
        assert np.abs(np.asarray(sd.variables_map["w"])).max() < 0.5


class TestSerde:
    def test_save_load_roundtrip(self, tmp_path):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(None, 3))
        w = sd.var("w", value=RNG.normal(size=(3, 2)))
        sd.nn.softmax(x @ w, name="out")
        xv = RNG.normal(size=(4, 3)).astype(np.float32)
        want = sd.output({"x": xv}, "out")["out"]

        p = str(tmp_path / "graph.npz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        got = sd2.output({"x": xv}, "out")["out"]
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_loaded_graph_trains(self, tmp_path):
        sd = SameDiff.create()
        x = sd.place_holder("input", shape=(None, 2))
        y = sd.place_holder("label", shape=(None, 1))
        w = sd.var("w", value=np.zeros((2, 1)))
        sd.loss.mse(y, x @ w, name="loss")
        sd.set_loss_variables("loss")
        p = str(tmp_path / "g.npz")
        sd.save(p)

        sd2 = SameDiff.load(p)
        sd2.set_training_config(TrainingConfig(
            updater=Sgd(0.5),
            data_set_feature_mapping=["input"],
            data_set_label_mapping=["label"]))
        from deeplearning4j_tpu.datasets.dataset import DataSet
        xv = RNG.normal(size=(64, 2)).astype(np.float32)
        yv = (xv @ np.array([[1.0], [2.0]], np.float32))
        l0 = sd2.fit(DataSet(xv, yv), epochs=1)
        l1 = sd2.fit(DataSet(xv, yv), epochs=30)
        assert l1 < l0


class TestConvOps:
    def test_conv2d_and_pool(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(2, 8, 8, 1))
        k = sd.var("k", value=RNG.normal(size=(3, 3, 1, 4)) * 0.1)
        c = sd.nn.conv2d(x, k, stride=(1, 1), padding="SAME", name="c")
        sd.nn.max_pooling2d(c, size=(2, 2), stride=(2, 2), name="p")
        xv = RNG.normal(size=(2, 8, 8, 1)).astype(np.float32)
        outs = sd.output({"x": xv}, "c", "p")
        assert outs["c"].shape == (2, 8, 8, 4)
        assert outs["p"].shape == (2, 4, 4, 4)
        # pooling really is max over 2x2 windows
        assert np.allclose(outs["p"][0, 0, 0],
                           outs["c"][0, :2, :2].max(axis=(0, 1)))


class TestErrors:
    def test_unknown_op_raises(self):
        sd = SameDiff.create()
        with pytest.raises(AttributeError):
            sd.math.frobulate
    def test_duplicate_name_raises(self):
        sd = SameDiff.create()
        sd.place_holder("x", shape=(1,))
        with pytest.raises(ValueError):
            sd.place_holder("x", shape=(1,))

    def test_grad_without_loss_raises(self):
        sd = SameDiff.create()
        sd.place_holder("x", shape=(1,))
        with pytest.raises(ValueError):
            sd.calculate_gradients({"x": np.ones(1)})

    def test_cross_graph_mixing_raises(self):
        sd1, sd2 = SameDiff.create(), SameDiff.create()
        a = sd1.place_holder("a", shape=(1,))
        b = sd2.place_holder("b", shape=(1,))
        with pytest.raises(ValueError):
            _ = a + b


class TestEvaluate:
    def test_evaluate_accuracy(self):
        sd = SameDiff.create()
        x = sd.place_holder("input", shape=(None, 4))
        y = sd.place_holder("label", shape=(None, 3))
        w = sd.var("w", value=RNG.normal(size=(4, 3)))
        sd.nn.softmax(x @ w, name="probs")
        sd.loss.softmax_cross_entropy(y, x @ w, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(TrainingConfig(
            updater=Adam(0.1),
            data_set_feature_mapping=["input"],
            data_set_label_mapping=["label"]))
        cls = RNG.integers(0, 3, 256)
        xv = RNG.normal(size=(256, 4)).astype(np.float32)
        xv[np.arange(256), cls] += 3.0
        yv = np.eye(3, dtype=np.float32)[cls]
        from deeplearning4j_tpu.datasets.dataset import DataSet
        ds = DataSet(xv, yv)
        sd.fit(ds, epochs=50)
        ev = sd.evaluate(ds, "probs")
        assert ev.accuracy() > 0.9


class TestControlFlow:
    """if_cond / while_loop (ND4J SameDiff control flow) lowered to
    lax.cond / lax.while_loop — one compiled graph, trip count on device."""

    def test_if_cond_takes_each_branch(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(3,))
        w = sd.var("w", value=np.array([2.0, 2.0, 2.0], np.float32))
        out = sd.if_cond(sd.math.gt(x.sum(), 0.0),
                         lambda s: x * w, lambda s: x - w, name="branch")
        pos = sd.output({"x": np.array([1., 2., 3.], np.float32)}, "branch")
        neg = sd.output({"x": np.array([-1., -2., -3.], np.float32)}, "branch")
        np.testing.assert_allclose(pos["branch"], [2., 4., 6.])
        np.testing.assert_allclose(neg["branch"], [-3., -4., -5.])
        assert out.shape == (3,)

    def test_if_cond_gradient_flows_through_taken_branch(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(3,))
        w = sd.var("w", value=np.array([2.0, 2.0, 2.0], np.float32))
        sd.if_cond(sd.math.gt(x.sum(), 0.0),
                   lambda s: x * w, lambda s: x - w, name="branch")
        sd.set_loss_variables("branch")
        xv = np.array([1., 2., 3.], np.float32)
        g = sd.calculate_gradients({"x": xv}, "w")
        np.testing.assert_allclose(g["w"], xv)      # d(sum(x*w))/dw = x
        g = sd.calculate_gradients({"x": -xv}, "w")
        np.testing.assert_allclose(g["w"], [-1., -1., -1.])  # d(sum(x-w))/dw

    def test_while_loop_dynamic_trip_count(self):
        sd = SameDiff.create()
        n = sd.place_holder("n", shape=())
        i0 = sd.constant("i0", np.float32(1.0))
        a0 = sd.constant("a0", np.float32(0.0))
        fin = sd.while_loop([i0, a0],
                            lambda s, i, a: s.math.lte(i, n),
                            lambda s, i, a: [i + 1.0, a + i])
        # same compiled graph, trip count decided on device
        assert sd.output({"n": np.float32(10)}, fin[1].name)[fin[1].name] == 55
        assert sd.output({"n": np.float32(4)}, fin[1].name)[fin[1].name] == 10
        assert sd.output({"n": np.float32(0)}, fin[1].name)[fin[1].name] == 0

    def test_while_loop_closes_over_outer_variable(self):
        sd = SameDiff.create()
        r = sd.var("rate", value=np.float32(2.0))
        x0 = sd.constant("x0", np.float32(1.0))
        lim = sd.constant("lim", np.float32(100.0))
        fin = sd.while_loop([x0],
                            lambda s, x: s.math.lt(x, lim),
                            lambda s, x: [x * r])
        assert sd.output({}, fin[0].name)[fin[0].name] == 128.0

    def test_control_flow_serde_round_trip(self, tmp_path):
        sd = SameDiff.create()
        n = sd.place_holder("n", shape=())
        i0 = sd.constant("i0", np.float32(1.0))
        a0 = sd.constant("a0", np.float32(0.0))
        fin = sd.while_loop([i0, a0],
                            lambda s, i, a: s.math.lte(i, n),
                            lambda s, i, a: [i + 1.0, a + i], name="loop")
        sd.save(str(tmp_path / "cf"))
        sd2 = SameDiff.load(str(tmp_path / "cf"))
        got = sd2.output({"n": np.float32(10)}, fin[1].name)[fin[1].name]
        assert got == 55

    def test_nested_control_flow_rejected(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=())
        c = sd.constant("c", np.float32(1.0))
        with pytest.raises(NotImplementedError):
            sd.if_cond(sd.math.gt(x, 0.0),
                       lambda s: s.if_cond(s.math.gt(c, 0.0),
                                           lambda s2: c, lambda s2: c + 1),
                       lambda s: c)

    def test_no_variables_inside_bodies(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=())
        c = sd.constant("c", np.float32(1.0))
        with pytest.raises(ValueError):
            sd.if_cond(sd.math.gt(x, 0.0),
                       lambda s: s.var("w2", value=np.float32(1.0)),
                       lambda s: c)

    def test_if_cond_passthrough_branch(self):
        # a branch may return a captured outer node directly
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=())
        c = sd.constant("c", np.float32(7.0))
        sd.if_cond(sd.math.gt(x, 0.0), lambda s: x * 2.0, lambda s: c,
                   name="o")
        assert sd.output({"x": np.float32(3.0)}, "o")["o"] == 6.0
        assert sd.output({"x": np.float32(-3.0)}, "o")["o"] == 7.0

    def test_while_loop_passthrough_body(self):
        sd = SameDiff.create()
        lim = sd.constant("lim", np.float32(5.0))
        i0 = sd.constant("i0", np.float32(0.0))
        fin = sd.while_loop([i0],
                            lambda s, i: s.math.lt(i, lim),
                            lambda s, i: [i + 1.0])
        assert sd.output({}, fin[0].name)[fin[0].name] == 5.0

    def test_rename_passthrough_capture_updates_control_attrs(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=())
        c = sd.constant("c", np.float32(7.0))
        sd.if_cond(sd.math.gt(x, 0.0), lambda s: x * 2.0, lambda s: c,
                   name="o")
        sd.rename("c", "c2")
        assert sd.output({"x": np.float32(-1.0)}, "o")["o"] == 7.0


class TestBoundedWhileLoopGradients:
    """while_loop(max_iterations=K) lowers to lax.scan with an active-flag
    mask: identical forward results for trip counts <= K, and reverse-mode
    differentiable — the round-2 verdict's SameDiff autodiff gap."""

    def test_forward_equals_dynamic_lowering(self):
        for n in (0, 4, 10):
            sd = SameDiff.create()
            nv = sd.place_holder("n", shape=())
            i0 = sd.constant("i0", np.float32(1.0))
            a0 = sd.constant("a0", np.float32(0.0))
            fin = sd.while_loop([i0, a0],
                                lambda s, i, a: s.math.lte(i, nv),
                                lambda s, i, a: [i + 1.0, a + i],
                                max_iterations=16)
            got = sd.output({"n": np.float32(n)}, fin[1].name)[fin[1].name]
            assert got == n * (n + 1) / 2

    def test_gradient_matches_finite_differences(self):
        # x -> x * r^k with k = dynamic trip count (r=1.5, until x >= 10)
        def build(r_val):
            sd = SameDiff.create()
            x = sd.place_holder("x", shape=())
            r = sd.var("r", value=np.float32(r_val))
            x0 = sd.constant("limstart", np.float32(1.0))
            fin = sd.while_loop(
                [x.mul(x0)],  # seed carry from the placeholder
                lambda s, v: s.math.lt(v, 10.0),
                lambda s, v: [v * r], max_iterations=12, name="loop")
            sd.set_loss_variables(fin[0].name)
            return sd
        xv = np.float32(1.0)
        sd = build(1.5)
        g = sd.calculate_gradients({"x": xv}, "r")["r"]
        # central differences over r
        eps = 1e-3
        lo = build(1.5 - eps).output({"x": xv}, "loop_out0")["loop_out0"]
        hi = build(1.5 + eps).output({"x": xv}, "loop_out0")["loop_out0"]
        num = (hi - lo) / (2 * eps)
        np.testing.assert_allclose(g, num, rtol=5e-3)

    def test_trains_through_loop(self):
        # learn r so that 1 * r^4 == 16 (fixed 4-iteration loop)
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(4,))
        y = sd.place_holder("y", shape=(4,))
        r = sd.var("r", value=np.float32(1.5))
        i0 = sd.constant("c_i0", np.float32(0.0))
        fin = sd.while_loop([i0, x.mul(sd.constant("one", np.float32(1.0)))],
                            lambda s, i, v: s.math.lt(i, 4.0),
                            lambda s, i, v: [i + 1.0, v * r],
                            max_iterations=8, name="powloop")
        loss = sd.math.square(fin[1] - y).mean(name="loss")
        sd.set_loss_variables("loss")
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu.nn.updaters import Adam
        sd.set_training_config(TrainingConfig(
            updater=Adam(0.05), data_set_feature_mapping=["x"],
            data_set_label_mapping=["y"]))
        from deeplearning4j_tpu.datasets.dataset import DataSet
        xv = np.ones(4, np.float32)
        yv = np.full(4, 16.0, np.float32)
        for _ in range(200):
            sd.fit(DataSet(xv, yv))
        assert abs(float(sd.variables_map["r"]) - 2.0) < 0.05

    def test_serde_keeps_max_iterations(self, tmp_path):
        sd = SameDiff.create()
        n = sd.place_holder("n", shape=())
        i0 = sd.constant("i0", np.float32(1.0))
        a0 = sd.constant("a0", np.float32(0.0))
        fin = sd.while_loop([i0, a0],
                            lambda s, i, a: s.math.lte(i, n),
                            lambda s, i, a: [i + 1.0, a + i],
                            max_iterations=16, name="loop")
        sd.save(str(tmp_path / "bounded"))
        sd2 = SameDiff.load(str(tmp_path / "bounded"))
        assert sd2._nodes["loop"].attrs["max_iterations"] == 16
        got = sd2.output({"n": np.float32(10)}, fin[1].name)[fin[1].name]
        assert got == 55

    def test_exceeding_bound_truncates(self):
        sd = SameDiff.create()
        n = sd.place_holder("n", shape=())
        i0 = sd.constant("i0", np.float32(1.0))
        a0 = sd.constant("a0", np.float32(0.0))
        fin = sd.while_loop([i0, a0],
                            lambda s, i, a: s.math.lte(i, n),
                            lambda s, i, a: [i + 1.0, a + i],
                            max_iterations=3)
        # true trip count 10 > K=3: the scan stops at K iterations
        got = sd.output({"n": np.float32(10)}, fin[1].name)[fin[1].name]
        assert got == 1 + 2 + 3


class TestExtendedMathOps:
    def test_cumulative_and_sort(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(4,))
        sd.math.cumsum(x, name="cs")
        sd.math.cumprod(x, name="cp")
        sd.math.sort(x, descending=True, name="srt")
        xv = np.array([3.0, 1.0, 2.0, 4.0], np.float32)
        out = sd.output({"x": xv}, "cs", "cp", "srt")
        np.testing.assert_allclose(out["cs"], np.cumsum(xv))
        np.testing.assert_allclose(out["cp"], np.cumprod(xv))
        np.testing.assert_allclose(out["srt"], [4, 3, 2, 1])

    def test_trig_family_and_checks(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(3,))
        sd.math.atan(x, name="at")
        sd.math.sinh(x, name="sh")
        sd.math.isnan(x, name="nn")
        xv = np.array([0.0, 0.5, np.nan], np.float32)
        out = sd.output({"x": xv}, "at", "sh", "nn")
        np.testing.assert_allclose(out["at"][:2], np.arctan(xv[:2]),
                                   rtol=1e-6)
        np.testing.assert_allclose(out["nn"], [0.0, 0.0, 1.0])

    def test_l2_normalize_and_logsumexp_gradients_flow(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(2, 3))
        w = sd.var("w", value=np.ones((2, 3), np.float32))
        h = sd.math.l2_normalize(x.mul(w), name="l2n")
        sd.math.logsumexp(h, name="lse")
        sd.set_loss_variables("lse")
        g = sd.calculate_gradients(
            {"x": np.arange(6, dtype=np.float32).reshape(2, 3) + 1}, "w")
        assert np.isfinite(g["w"]).all()

    def test_diag_trace_mod(self):
        sd = SameDiff.create()
        m = sd.place_holder("m", shape=(3, 3))
        sd.math.trace(m, name="tr")
        sd.math.mod(m, sd.constant("two", np.float32(2.0)), name="md")
        mv = np.arange(9, dtype=np.float32).reshape(3, 3)
        out = sd.output({"m": mv}, "tr", "md")
        assert out["tr"] == np.trace(mv)
        np.testing.assert_allclose(out["md"], mv % 2)


def test_top_k_values_and_indices():
    sd = SameDiff.create()
    x = sd.place_holder("x", shape=(2, 5))
    vals, idx = sd.top_k(x, 2)
    xv = np.array([[1.0, 5.0, 3.0, 2.0, 4.0],
                   [9.0, 0.0, 8.0, 7.0, 1.0]], np.float32)
    out = sd.output({"x": xv}, vals.name, idx.name)
    np.testing.assert_allclose(out[vals.name], [[5, 4], [9, 8]])
    np.testing.assert_allclose(out[idx.name], [[1, 4], [0, 2]])


class TestScatterGatherSegment:
    """ND4J scatter/gather(ND)/segment op families (the round-4 op-parity
    audit additions — see KNOWN_GAPS.md for the full audit table)."""

    def test_scatter_family_numeric(self):
        ref0 = np.arange(12, dtype=np.float32).reshape(4, 3)
        idx = np.array([0, 2], np.int32)
        upd = np.full((2, 3), 10.0, np.float32)
        cases = {
            "scatter_update": lambda r: (r.__setitem__(idx, upd), r)[1],
            "scatter_add": lambda r: (r.__setitem__(idx, r[idx] + upd), r)[1],
            "scatter_sub": lambda r: (r.__setitem__(idx, r[idx] - upd), r)[1],
            "scatter_mul": lambda r: (r.__setitem__(idx, r[idx] * upd), r)[1],
            "scatter_div": lambda r: (r.__setitem__(idx, r[idx] / upd), r)[1],
            "scatter_max": lambda r: (r.__setitem__(idx, np.maximum(r[idx], upd)), r)[1],
            "scatter_min": lambda r: (r.__setitem__(idx, np.minimum(r[idx], upd)), r)[1],
        }
        for op, expect in cases.items():
            sd = SameDiff.create()
            r = sd.place_holder("r", shape=(4, 3))
            i = sd.constant("i", idx)
            u = sd.constant("u", upd)
            getattr(sd.math, op)(r, i, u, name="out")
            got = sd.output({"r": ref0.copy()}, "out")["out"]
            np.testing.assert_allclose(got, expect(ref0.copy()), err_msg=op)

    def test_scatter_add_accumulates_duplicates(self):
        """ND4J ScatterAdd accumulates every update for a repeated index."""
        sd = SameDiff.create()
        r = sd.place_holder("r", shape=(3,))
        i = sd.constant("i", np.array([1, 1, 1], np.int32))
        u = sd.constant("u", np.ones(3, np.float32))
        sd.math.scatter_add(r, i, u, name="out")
        got = sd.output({"r": np.zeros(3, np.float32)}, "out")["out"]
        np.testing.assert_allclose(got, [0.0, 3.0, 0.0])

    def test_gather_and_gather_nd(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(3, 4))
        i = sd.constant("i", np.array([2, 0], np.int32))
        sd.math.gather(x, i, 0, name="g")
        nd_idx = sd.constant("ndi", np.array([[0, 1], [2, 3]], np.int32))
        sd.math.gather_nd(x, nd_idx, name="gnd")
        xv = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = sd.output({"x": xv}, "g", "gnd")
        np.testing.assert_allclose(out["g"], xv[[2, 0]])
        np.testing.assert_allclose(out["gnd"], [xv[0, 1], xv[2, 3]])

    def test_segment_reductions(self):
        data = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0], [7.0, 8.0]],
                        np.float32)
        ids = np.array([0, 0, 2, 2], np.int32)
        sd = SameDiff.create()
        d = sd.place_holder("d", shape=(4, 2))
        i = sd.constant("i", ids)
        sd.math.segment_sum(d, i, 3, name="s")
        sd.math.segment_mean(d, i, 3, name="m")
        sd.math.segment_max(d, i, 3, name="mx")
        out = sd.output({"d": data}, "s", "m", "mx")
        np.testing.assert_allclose(out["s"], [[4, 6], [0, 0], [12, 14]])
        np.testing.assert_allclose(out["m"], [[2, 3], [0, 0], [6, 7]])
        # empty segment of a max reduction is the dtype's lowest value
        np.testing.assert_allclose(out["mx"][0], [3, 4])
        np.testing.assert_allclose(out["mx"][2], [7, 8])

    def test_scatter_add_is_differentiable(self):
        """Gradients flow through scatter into the updates variable (the
        embedding-style update pattern)."""
        sd = SameDiff.create()
        base = sd.constant("base", np.zeros((4, 2), np.float32))
        upd = sd.var("upd", value=np.ones((2, 2), np.float32))
        i = sd.constant("i", np.array([1, 3], np.int32))
        s = sd.math.scatter_add(base, i, upd, name="s")
        (s * s).sum(name="loss")
        sd.set_loss_variables("loss")
        grads = sd.calculate_gradients({}, "upd")
        np.testing.assert_allclose(grads["upd"], 2.0 * np.ones((2, 2)))


class TestExtendedConvOps:
    def test_conv1d(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(2, 8, 3))
        k = sd.var("k", value=RNG.normal(size=(3, 3, 5)) * 0.1)
        sd.nn.conv1d(x, k, stride=1, padding="SAME", name="c")
        xv = RNG.normal(size=(2, 8, 3)).astype(np.float32)
        out = sd.output({"x": xv}, "c")["c"]
        assert out.shape == (2, 8, 5)
        # middle position == the explicit dot product over the window
        kv = np.asarray(sd.variables_map["k"])
        expect = sum(xv[0, 4 + dt] @ kv[dt + 1] for dt in (-1, 0, 1))
        np.testing.assert_allclose(out[0, 4], expect, rtol=1e-4)

    def test_depthwise_conv2d(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(1, 6, 6, 2))
        k = sd.var("k", value=RNG.normal(size=(3, 3, 2, 2)) * 0.1)
        sd.nn.depthwise_conv2d(x, k, stride=(1, 1), padding="VALID", name="c")
        xv = RNG.normal(size=(1, 6, 6, 2)).astype(np.float32)
        out = sd.output({"x": xv}, "c")["c"]
        assert out.shape == (1, 4, 4, 4)
        # channel 0 outputs depend ONLY on input channel 0 (multiplier 2:
        # out channels [0,1] come from in channel 0)
        kv = np.asarray(sd.variables_map["k"])
        expect = sum(xv[0, 1 + di, 1 + dj, 0] * kv[di, dj, 0, 0]
                     for di in (0, 1, 2) for dj in (0, 1, 2))
        np.testing.assert_allclose(out[0, 1, 1, 0], expect, rtol=1e-4)

    def test_deconv2d_upsamples(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(1, 4, 4, 3))
        k = sd.var("k", value=RNG.normal(size=(2, 2, 3, 5)) * 0.1)
        sd.nn.deconv2d(x, k, stride=(2, 2), padding="SAME", name="c")
        xv = RNG.normal(size=(1, 4, 4, 3)).astype(np.float32)
        out = sd.output({"x": xv}, "c")["c"]
        assert out.shape == (1, 8, 8, 5)
        # k=2/s=2 SAME: non-overlapping 2x2 blocks — each input pixel
        # stamps the kernel UNFLIPPED, out[2i+a,2j+b] = x[i,j]@w[a,b]
        # (gradient-of-conv semantics == DL4J DeConv2D; conv_transpose
        # without the flip would stamp w[1-a,1-b] instead)
        kv = np.asarray(sd.variables_map["k"])
        for a in (0, 1):
            for b in (0, 1):
                np.testing.assert_allclose(
                    out[0, 2 + a, 4 + b], xv[0, 1, 2] @ kv[a, b],
                    rtol=1e-4)

    def test_space_depth_round_trip(self):
        sd = SameDiff.create()
        x = sd.place_holder("x", shape=(2, 4, 4, 3))
        s = sd.nn.space_to_depth(x, 2, name="s2d")
        sd.nn.depth_to_space(s, 2, name="d2s")
        xv = RNG.normal(size=(2, 4, 4, 3)).astype(np.float32)
        out = sd.output({"x": xv}, "s2d", "d2s")
        assert out["s2d"].shape == (2, 2, 2, 12)
        np.testing.assert_allclose(out["d2s"], xv)  # exact inverse


def test_segment_ops_require_num_segments_loudly():
    sd = SameDiff.create()
    d = sd.place_holder("d", shape=(4, 2))
    i = sd.constant("i", np.array([0, 0, 1, 1], np.int32))
    import pytest
    with pytest.raises(ValueError, match="num_segments"):
        sd.math.segment_sum(d, i, name="s")
        sd.output({"d": np.zeros((4, 2), np.float32)}, "s")


def test_plain_array_indices_bind_as_inputs_not_attrs():
    """The natural ND4J spelling — plain list/ndarray indices with a
    positional axis/num_segments scalar — must bind arrays to tensor
    inputs and only SCALARS to declared attrs; an explicit kwarg attr is
    never overwritten positionally."""
    sd = SameDiff.create()
    x = sd.place_holder("x", shape=(3, 4))
    sd.math.gather(x, np.array([2, 0]), 0, name="g")
    sd.math.segment_sum(x, np.array([0, 0, 1], np.int32), 2, name="s")
    g2 = sd.math.gather(x, [0, 2], axis=1, name="g2")
    assert g2 is not None
    xv = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = sd.output({"x": xv}, "g", "s", "g2")
    np.testing.assert_allclose(out["g"], xv[[2, 0]])
    np.testing.assert_allclose(out["s"], [xv[0] + xv[1], xv[2]])
    np.testing.assert_allclose(out["g2"], xv[:, [0, 2]])


def test_scalar_gather_index_binds_as_input():
    """gather(x, 2, 0) — scalar index, positional axis — must treat 2 as
    the indices INPUT and 0 as the axis attr (the op's required tensor
    inputs are satisfied before scalars start filling attrs)."""
    sd = SameDiff.create()
    x = sd.place_holder("x", shape=(3, 4))
    sd.math.gather(x, 2, 0, name="g")
    xv = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = sd.output({"x": xv}, "g")["g"]
    np.testing.assert_allclose(out, xv[2])
