"""Generate DL4J ModelSerializer checkpoint fixtures with real
``coefficients.bin`` / ``updaterState.bin`` payloads.

The flattened layouts here are written INDEPENDENTLY of the importer
(hand-coded per layer family, mirroring DL4J's ParamInitializer order and
WeightInitUtil 'f' weight order / conv 'c' order) so the reader in
``modelimport/dl4j.py`` is genuinely inverted against them, not round-tripped
through its own logic.

Run from the repo root:  python tests/fixtures/make_nd4j_checkpoint_fixtures.py
"""

import io
import json
import os
import zipfile

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def nd4j_bytes(arr: np.ndarray) -> bytes:
    from deeplearning4j_tpu.modelimport.nd4j_binary import nd4j_array_to_bytes
    return nd4j_array_to_bytes(np.asarray(arr, np.float32).reshape(1, -1), "c")


def conv_net_fixture():
    """Conv(3x3,1→4) → BN(4) → Dense(100→10) → Output(10→3), Adam."""
    rng = np.random.default_rng(1234)
    conf = {
        "backprop": True,
        "backpropType": "Standard",
        "confs": [
            {"seed": 7, "layer": {"convolution": {
                "layerName": "c0",
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationReLU"},
                "kernelSize": [3, 3], "stride": [1, 1], "padding": [0, 0],
                "convolutionMode": "Truncate", "nin": 1, "nout": 4,
                "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 0.01},
            }}},
            {"layer": {"batchNormalization": {
                "layerName": "bn", "eps": 1e-5, "decay": 0.9, "nin": 4,
                "nout": 4,
                "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 0.01},
            }}},
            {"layer": {"dense": {
                "layerName": "d0",
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationTanH"},
                "nin": 144, "nout": 10,
                "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 0.01},
            }}},
            {"layer": {"output": {
                "layerName": "out",
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                "nin": 10, "nout": 3,
                "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 0.01},
            }}},
        ],
        "inputPreProcessors": {"2": {"cnnToFeedForward": {
            "inputHeight": 6, "inputWidth": 6, "numChannels": 4}}},
    }
    # per-layer params in OUR layouts
    conv_W = rng.normal(0, 0.3, (3, 3, 1, 4)).astype(np.float32)   # HWIO
    conv_b = rng.normal(0, 0.1, (4,)).astype(np.float32)
    bn_gamma = rng.uniform(0.5, 1.5, (4,)).astype(np.float32)
    bn_beta = rng.normal(0, 0.1, (4,)).astype(np.float32)
    bn_mean = rng.normal(0, 0.2, (4,)).astype(np.float32)
    bn_var = rng.uniform(0.5, 1.5, (4,)).astype(np.float32)
    d_W = rng.normal(0, 0.1, (144, 10)).astype(np.float32)
    d_b = rng.normal(0, 0.1, (10,)).astype(np.float32)
    o_W = rng.normal(0, 0.2, (10, 3)).astype(np.float32)
    o_b = rng.normal(0, 0.1, (3,)).astype(np.float32)

    # DL4J flattened layout, hand-coded:
    #   conv:  W as [nOut, nIn, kH, kW] 'c'  (our HWIO → OIHW transpose)
    #   dense: W as [nIn, nOut] 'f'; biases & BN vectors flat
    flat = np.concatenate([
        np.transpose(conv_W, (3, 2, 0, 1)).flatten(order="C"), conv_b,
        bn_gamma, bn_beta, bn_mean, bn_var,
        d_W.flatten(order="F"), d_b,
        o_W.flatten(order="F"), o_b,
    ]).astype(np.float32)

    # Adam updater state. DL4J groups contiguous same-updater params into
    # UpdaterBlocks; BN global mean/var carry a stateless pseudo-updater, so
    # the blocks here are A = [conv W,b + bn gamma,beta] and B = [dense +
    # output], each stored as [M(block), V(block)] — hand-coded layout,
    # independent of the reader.
    n_a = conv_W.size + conv_b.size + bn_gamma.size + bn_beta.size
    n_trainable = n_a + d_W.size + d_b.size + o_W.size + o_b.size
    m = np.arange(n_trainable, dtype=np.float32) * 1e-3
    v = np.arange(n_trainable, dtype=np.float32) * 1e-4 + 1e-6
    upd = np.concatenate([m[:n_a], v[:n_a], m[n_a:], v[n_a:]])

    zpath = os.path.join(HERE, "dl4j_checkpoint_convnet.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("configuration.json", json.dumps(conf))
        z.writestr("coefficients.bin", nd4j_bytes(flat))
        z.writestr("updaterState.bin", nd4j_bytes(upd))

    # recorded activations from the restored net (regression lock)
    from deeplearning4j_tpu.modelimport.dl4j import restore_multi_layer_network
    net = restore_multi_layer_network(zpath)
    x = rng.normal(0, 1, (2, 8, 8, 1)).astype(np.float32)
    out = np.asarray(net.output(x))
    np.savez(os.path.join(HERE, "dl4j_checkpoint_convnet_expected.npz"),
             x=x, out=out,
             conv_W=conv_W, conv_b=conv_b, bn_gamma=bn_gamma, bn_beta=bn_beta,
             bn_mean=bn_mean, bn_var=bn_var, d_W=d_W, d_b=d_b, o_W=o_W,
             o_b=o_b, m=m, v=v)
    print("wrote", zpath, "out[0]:", out[0])


def lstm_fixture():
    """GravesLSTM(5→6, peepholes) → RnnOutput(6→2), Nesterovs."""
    rng = np.random.default_rng(99)
    conf = {
        "backpropType": "TruncatedBPTT",
        "tbpttFwdLength": 8, "tbpttBackLength": 8,
        "confs": [
            {"layer": {"gravesLSTM": {
                "layerName": "l0",
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationTanH"},
                "nin": 5, "nout": 6, "forgetGateBiasInit": 1.0,
                "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Nesterovs",
                             "learningRate": 0.1, "momentum": 0.9},
            }}},
            {"layer": {"rnnoutput": {
                "layerName": "out",
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                "nin": 6, "nout": 2,
                "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Nesterovs",
                             "learningRate": 0.1, "momentum": 0.9},
            }}},
        ],
    }
    h = 6
    W = rng.normal(0, 0.2, (5, 4 * h)).astype(np.float32)
    RW = rng.normal(0, 0.2, (h, 4 * h + 3)).astype(np.float32)  # peepholes
    b = rng.normal(0, 0.05, (4 * h,)).astype(np.float32)
    oW = rng.normal(0, 0.3, (h, 2)).astype(np.float32)
    ob = rng.normal(0, 0.1, (2,)).astype(np.float32)
    flat = np.concatenate([
        W.flatten(order="F"), RW.flatten(order="F"), b,
        oW.flatten(order="F"), ob,
    ]).astype(np.float32)
    upd = np.arange(flat.size, dtype=np.float32) * 1e-3  # Nesterovs: [V(all)]

    zpath = os.path.join(HERE, "dl4j_checkpoint_lstm.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("configuration.json", json.dumps(conf))
        z.writestr("coefficients.bin", nd4j_bytes(flat))
        z.writestr("updaterState.bin", nd4j_bytes(upd))

    from deeplearning4j_tpu.modelimport.dl4j import restore_multi_layer_network
    net = restore_multi_layer_network(zpath)
    x = rng.normal(0, 1, (2, 7, 5)).astype(np.float32)
    out = np.asarray(net.output(x))
    np.savez(os.path.join(HERE, "dl4j_checkpoint_lstm_expected.npz"),
             x=x, out=out, W=W, RW=RW, b=b, oW=oW, ob=ob, upd=upd)
    print("wrote", zpath, "out[0,0]:", out[0, 0])


def graph_fixture():
    """ComputationGraph zip: two parallel dense branches + elementwise add +
    output, plus a GravesBidirectionalLSTM head on a second input-free chain
    is overkill — keep the branchy-but-chain-serialized shape DL4J's topo
    sort shares with ours."""
    rng = np.random.default_rng(7)
    dense = lambda nin, nout, name: {"dense": {
        "layerName": name, "nin": nin, "nout": nout,
        "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationTanH"},
        "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Nesterovs",
                     "learningRate": 0.1, "momentum": 0.9}}}
    conf = {
        "networkInputs": ["in"],
        "networkOutputs": ["out"],
        "vertices": {
            "a": {"LayerVertex": {"layerConf": {"layer": dense(4, 6, "a")}}},
            "b": {"LayerVertex": {"layerConf": {"layer": dense(4, 6, "b")}}},
            "ew": {"ElementWiseVertex": {"op": "Add"}},
            "out": {"LayerVertex": {"layerConf": {"layer": {"output": {
                "layerName": "out", "nin": 6, "nout": 2,
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Nesterovs",
                             "learningRate": 0.1, "momentum": 0.9}}}}}},
        },
        "vertexInputs": {"a": ["in"], "b": ["in"], "ew": ["a", "b"],
                         "out": ["ew"]},
    }
    aW = rng.normal(0, 0.3, (4, 6)).astype(np.float32)
    ab = rng.normal(0, 0.1, (6,)).astype(np.float32)
    bW = rng.normal(0, 0.3, (4, 6)).astype(np.float32)
    bb = rng.normal(0, 0.1, (6,)).astype(np.float32)
    oW = rng.normal(0, 0.3, (6, 2)).astype(np.float32)
    ob = rng.normal(0, 0.1, (2,)).astype(np.float32)
    # flattened in topological layer order a, b, out; dense W 'f'
    flat = np.concatenate([aW.flatten("F"), ab, bW.flatten("F"), bb,
                           oW.flatten("F"), ob]).astype(np.float32)
    upd = np.arange(flat.size, dtype=np.float32) * 1e-3  # Nesterovs [V(all)]

    zpath = os.path.join(HERE, "dl4j_checkpoint_graph.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("configuration.json", json.dumps(conf))
        z.writestr("coefficients.bin", nd4j_bytes(flat))
        z.writestr("updaterState.bin", nd4j_bytes(upd))

    from deeplearning4j_tpu.modelimport.dl4j import restore_computation_graph
    net = restore_computation_graph(zpath)
    x = rng.normal(0, 1, (3, 4)).astype(np.float32)
    out = np.asarray(net.output(x))
    np.savez(os.path.join(HERE, "dl4j_checkpoint_graph_expected.npz"),
             x=x, out=out, aW=aW, ab=ab, bW=bW, bb=bb, oW=oW, ob=ob, upd=upd)
    print("wrote", zpath, "out[0]:", out[0])


def branchy_graph_fixture():
    """Adversarial parallel-branch graph: three same-shaped dense branches
    whose INSERTION order (z, m, a) disagrees with name order, merged by
    concat. DL4J's topologicalSortOrder processes them by vertex INDEX
    (insertion order), so the flattened coefficients follow z, m, a — a
    lexicographic tie-break would swap the branch weights silently. The
    expected output is computed by a MANUAL numpy forward pass, independent
    of the importer."""
    rng = np.random.default_rng(21)
    dense = lambda nin, nout, name: {"dense": {
        "layerName": name, "nin": nin, "nout": nout,
        "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationTanH"},
        "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Adam",
                     "learningRate": 0.01, "beta1": 0.9, "beta2": 0.999}}}
    conf = {
        "networkInputs": ["in"],
        "networkOutputs": ["out"],
        "vertices": {
            "stem": {"LayerVertex": {"layerConf": {"layer": dense(4, 5, "stem")}}},
            "z_branch": {"LayerVertex": {"layerConf": {"layer": dense(5, 3, "z_branch")}}},
            "m_branch": {"LayerVertex": {"layerConf": {"layer": dense(5, 3, "m_branch")}}},
            "a_branch": {"LayerVertex": {"layerConf": {"layer": dense(5, 3, "a_branch")}}},
            "merge": {"MergeVertex": {}},
            "out": {"LayerVertex": {"layerConf": {"layer": {"output": {
                "layerName": "out", "nin": 9, "nout": 2,
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 0.01, "beta1": 0.9,
                             "beta2": 0.999}}}}}},
        },
        "vertexInputs": {"stem": ["in"], "z_branch": ["stem"],
                         "m_branch": ["stem"], "a_branch": ["stem"],
                         "merge": ["z_branch", "m_branch", "a_branch"],
                         "out": ["merge"]},
    }
    P = {}
    for name, (nin, nout) in [("stem", (4, 5)), ("z", (5, 3)), ("m", (5, 3)),
                              ("a", (5, 3)), ("o", (9, 2))]:
        P[name + "W"] = rng.normal(0, 0.4, (nin, nout)).astype(np.float32)
        P[name + "b"] = rng.normal(0, 0.2, (nout,)).astype(np.float32)
    # DL4J topologicalSortOrder: FIFO Kahn over vertex indices (insertion
    # order) -> layer order stem, z_branch, m_branch, a_branch, out
    flat = np.concatenate([
        P["stemW"].flatten("F"), P["stemb"],
        P["zW"].flatten("F"), P["zb"],
        P["mW"].flatten("F"), P["mb"],
        P["aW"].flatten("F"), P["ab"],
        P["oW"].flatten("F"), P["ob"]]).astype(np.float32)
    # Adam state [M(all), V(all)] over the same layout
    upd = np.arange(2 * flat.size, dtype=np.float32) * 1e-3

    zpath = os.path.join(HERE, "dl4j_checkpoint_branchy_graph.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("configuration.json", json.dumps(conf))
        z.writestr("coefficients.bin", nd4j_bytes(flat))
        z.writestr("updaterState.bin", nd4j_bytes(upd))

    # independent manual forward
    x = rng.normal(0, 1, (3, 4)).astype(np.float32)
    h = np.tanh(x @ P["stemW"] + P["stemb"])
    zb = np.tanh(h @ P["zW"] + P["zb"])
    mb = np.tanh(h @ P["mW"] + P["mb"])
    ab = np.tanh(h @ P["aW"] + P["ab"])
    merged = np.concatenate([zb, mb, ab], axis=1)
    logits = merged @ P["oW"] + P["ob"]
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    out = e / e.sum(axis=1, keepdims=True)
    np.savez(os.path.join(HERE, "dl4j_checkpoint_branchy_graph_expected.npz"),
             x=x, out=out, upd=upd, **P)
    print("wrote", zpath)


if __name__ == "__main__":
    import jax
    jax.config.update("jax_platforms", "cpu")
    conv_net_fixture()
    lstm_fixture()
    graph_fixture()
    branchy_graph_fixture()
