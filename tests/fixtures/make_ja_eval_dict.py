"""Generate the CJK accuracy fixture: a few-hundred-entry MeCab-format
mini-dictionary (tests/fixtures/ja_eval_dict/) plus a tagged evaluation
corpus (tests/fixtures/ja_tagged_corpus.tsv, ``sentence<TAB>tok|tok|...``).

The dictionary is hand-designed in ipadic's shape: context-id classes for
noun / case-particle / binding-particle / adnominal / verb-renyou /
verb-basic / auxiliary / adjective / adverb / punctuation, per-word costs,
and a full connection matrix in MeCab's ``matrix.def`` layout. Sentences are
built compositionally from the vocabulary so the gold segmentation is the
construction itself — including adversarial strings where greedy
longest-match derails (すもも…, longest-entry traps).

Run from the repo root:  PYTHONPATH=. python tests/fixtures/make_ja_eval_dict.py
"""

import os

HERE = os.path.dirname(os.path.abspath(__file__))
OUT_DIR = os.path.join(HERE, "ja_eval_dict")
CORPUS = os.path.join(HERE, "ja_tagged_corpus.tsv")

# context-id classes (0 = BOS/EOS, MeCab convention)
NOUN, CASE, BIND, ADNOM, VREN, VBAS, AUX, ADJ, ADV, PUNCT = range(1, 11)

NOUNS = """私 犬 猫 鳥 魚 山 川 海 空 雨 雪 風 花 木 森 水 朝 昼 夜 人
子供 先生 学生 友達 家 学校 会社 駅 道 町 村 国 世界 言葉 本 紙 手紙 机
椅子 窓 部屋 庭 車 電車 自転車 飛行機 船 音楽 歌 絵 写真 映画 電話 新聞
雑誌 料理 今日 明日 昨日 今 東京 京都 日本 名前 天気 問題 質問 答え 意味
話 仕事 旅行 買い物 散歩 勉強 運動 練習 試験 宿題 休み 時間 お金 店 服
靴 帽子 傘 鞄 箱 石 橋 池 月 星 太陽 地図 公園 病院 銀行 図書館 鶏
すもも もも うち もの 春 夏 秋 冬 雲 光 声 音 味 色 形 夢 心 力 目 耳
口 手 足 頭 顔 体 肉 野菜 果物 茶 米 酒 塩 砂糖 卵 牛乳 医者 警察
兄 姉 弟 妹 父 母 祖父 祖母 家族 犬小屋""".split()

CASE_PARTICLES = "が を に で と へ から まで より や".split()
BIND_PARTICLES = "は も".split()

# (renyou stem, basic form) pairs
VERBS = [("食べ", "食べる"), ("見", "見る"), ("行き", "行く"),
         ("来", "来る"), ("し", "する"), ("読み", "読む"), ("書き", "書く"),
         ("話し", "話す"), ("聞き", "聞く"), ("買い", "買う"),
         ("歩き", "歩く"), ("走り", "走る"), ("泳ぎ", "泳ぐ"),
         ("飲み", "飲む"), ("作り", "作る"), ("使い", "使う"),
         ("待ち", "待つ"), ("立ち", "立つ"), ("座り", "座る"),
         ("寝", "寝る"), ("起き", "起きる"), ("働き", "働く"),
         ("遊び", "遊ぶ"), ("学び", "学ぶ"), ("教え", "教える"),
         ("帰り", "帰る"), ("入り", "入る"), ("出", "出る"),
         ("思い", "思う"), ("言い", "言う"), ("泣き", "泣く"),
         ("笑い", "笑う"), ("歌い", "歌う"), ("撮り", "撮る"),
         ("売り", "売る"), ("開け", "開ける"), ("閉め", "閉める"),
         ("届き", "届く"), ("住み", "住む"), ("降り", "降る")]
AUXES = "ます ました ません た ない です でした たい".split()
ADJS = """高い 安い 大きい 小さい 新しい 古い 良い 悪い 早い 遅い 暑い
寒い 白い 黒い 赤い 青い 楽しい 美しい 強い 弱い 長い 短い 重い 軽い
広い 狭い 近い 遠い 甘い 辛い""".split()
ADVS = "とても すぐ もう まだ よく 少し たくさん いつも 時々 今朝".split()
PUNCTS = "。 、".split()


def entries():
    out = []
    for w in NOUNS:
        out.append((w, NOUN, NOUN, 3000 + 500 * max(0, 2 - len(w)),
                    "名詞,一般,*,*,*,*," + w))
    for w in CASE_PARTICLES:
        out.append((w, CASE, CASE, 800, "助詞,格助詞,*,*,*,*," + w))
    for w in BIND_PARTICLES:
        out.append((w, BIND, BIND, 900, "助詞,係助詞,*,*,*,*," + w))
    out.append(("の", ADNOM, ADNOM, 700, "助詞,連体化,*,*,*,*,の"))
    for ren, basic in VERBS:
        out.append((ren, VREN, VREN, 3200,
                    f"動詞,自立,*,*,一段,連用形,{basic}"))
        out.append((basic, VBAS, VBAS, 3400,
                    f"動詞,自立,*,*,一段,基本形,{basic}"))
    for w in AUXES:
        out.append((w, AUX, AUX, 1200, "助動詞,*,*,*,*,基本形," + w))
    for w in ADJS:
        out.append((w, ADJ, ADJ, 3300, "形容詞,自立,*,*,*,基本形," + w))
    for w in ADVS:
        out.append((w, ADV, ADV, 3100, "副詞,一般,*,*,*,*," + w))
    for w in PUNCTS:
        out.append((w, PUNCT, PUNCT, 100, "記号,句点,*,*,*,*," + w))
    # adversarial longest-match traps: long entries whose COSTS must lose
    # to the compositional segmentation (the 食べた-noun pattern)
    out.append(("食べた", NOUN, NOUN, 9000, "名詞,一般,*,*,*,*,食べた"))
    out.append(("ものの", NOUN, NOUN, 9500, "名詞,一般,*,*,*,*,ものの"))
    out.append(("日本語", NOUN, NOUN, 2800, "名詞,一般,*,*,*,*,日本語"))
    out.append(("今日は", NOUN, NOUN, 9800, "名詞,一般,*,*,*,*,今日は"))
    return out


def matrix():
    """connection(prev.right_id, next.left_id) — MeCab matrix.def layout
    (rows ``right left cost``). Negative = preferred transition."""
    n = 11
    default = 2000
    m = {(r, l): default for r in range(n) for l in range(n)}

    def set_(r, l, c):
        m[(r, l)] = c

    BOSEOS = 0
    for l in (NOUN, ADV, ADJ, VREN, VBAS):
        set_(BOSEOS, l, 0)          # sentences start with content words
    set_(BOSEOS, CASE, 6000)
    set_(BOSEOS, BIND, 6000)
    set_(BOSEOS, ADNOM, 6000)
    set_(BOSEOS, AUX, 6000)
    # noun → particles cheap, noun→noun pricey (compounds are explicit
    # dictionary entries, not free concatenation)
    set_(NOUN, CASE, -800)
    set_(NOUN, BIND, -800)
    set_(NOUN, ADNOM, -600)
    set_(NOUN, PUNCT, -200)
    set_(NOUN, NOUN, 2600)
    set_(NOUN, AUX, -300)           # 学生です
    set_(NOUN, BOSEOS, 400)
    # case particle → content
    for l in (NOUN, VREN, VBAS, ADJ, ADV):
        set_(CASE, l, -500)
    set_(CASE, BIND, 400)           # には, では: particle chains allowed
    set_(CASE, PUNCT, 3000)
    # binding particle → content
    for l in (NOUN, VREN, VBAS, ADJ, ADV):
        set_(BIND, l, -500)
    set_(BIND, PUNCT, 3000)
    # の → noun
    set_(ADNOM, NOUN, -900)
    set_(ADNOM, CASE, 4000)
    set_(ADNOM, BIND, 4000)
    set_(ADNOM, ADNOM, 4000)
    # verb renyou → aux strongly
    set_(VREN, AUX, -1200)
    set_(VREN, PUNCT, 2500)
    set_(VREN, BOSEOS, 2500)
    # verb basic → punct / EOS / noun (relative clause)
    set_(VBAS, PUNCT, -400)
    set_(VBAS, BOSEOS, -200)
    set_(VBAS, NOUN, 600)
    # aux → aux (ませ+ん not modeled; ました is one entry), punct, EOS
    set_(AUX, PUNCT, -500)
    set_(AUX, BOSEOS, -300)
    set_(AUX, AUX, 800)
    set_(AUX, NOUN, 1500)
    # adjective → noun (高い山), punct, EOS, aux (高いです)
    set_(ADJ, NOUN, -400)
    set_(ADJ, PUNCT, -200)
    set_(ADJ, BOSEOS, -100)
    set_(ADJ, AUX, -200)
    # adverb → verb/adj
    for l in (VREN, VBAS, ADJ):
        set_(ADV, l, -400)
    set_(ADV, NOUN, 800)
    # punct → start-ish
    for l in (NOUN, ADV, ADJ, VREN, VBAS):
        set_(PUNCT, l, 0)
    set_(PUNCT, BOSEOS, -500)
    return n, m


# -- corpus ------------------------------------------------------------------
def sentences():
    """(gold token list) per sentence; surface = ''.join(tokens)."""
    S = []

    def s(*toks):
        S.append(list(toks))

    # everyday SOV sentences
    s("私", "は", "本", "を", "読み", "ます", "。")
    s("犬", "が", "庭", "で", "遊び", "ます", "。")
    s("先生", "は", "学生", "に", "言葉", "を", "教え", "ます", "。")
    s("子供", "は", "牛乳", "を", "飲み", "ました", "。")
    s("友達", "と", "映画", "を", "見", "ます", "。")
    s("母", "は", "料理", "を", "作り", "ました", "。")
    s("鳥", "が", "空", "へ", "飛行機", "より", "早い", "。")
    s("私", "は", "東京", "へ", "行き", "ます", "。")
    s("学生", "は", "図書館", "で", "勉強", "を", "し", "ます", "。")
    s("父", "は", "新聞", "を", "読み", "ません", "。")
    s("姉", "は", "歌", "を", "歌い", "ました", "。")
    s("弟", "は", "川", "で", "泳ぎ", "たい", "。")
    s("祖母", "は", "手紙", "を", "書き", "ます", "。")
    s("警察", "は", "町", "を", "歩き", "ます", "。")
    s("医者", "は", "病院", "で", "働き", "ます", "。")
    s("雨", "が", "降り", "ます", "。")
    s("雪", "が", "降り", "ました", "。")
    s("私", "は", "駅", "から", "家", "まで", "歩き", "ました", "。")
    # genitive chains
    s("日本", "の", "山", "は", "高い", "。")
    s("京都", "の", "寒い", "冬", "の", "朝", "。")
    s("先生", "の", "話", "は", "長い", "。")
    s("友達", "の", "犬", "の", "名前", "。")
    s("世界", "の", "海", "は", "広い", "。")
    s("子供", "の", "声", "が", "聞き", "たい", "。")
    # adjectives / adverbs
    s("今日", "の", "天気", "は", "良い", "です", "。")
    s("とても", "大きい", "家", "です", "。")
    s("すぐ", "帰り", "ます", "。")
    s("まだ", "宿題", "を", "し", "ません", "。")
    s("いつも", "朝", "は", "早い", "。")
    s("時々", "海", "へ", "行き", "ます", "。")
    s("新しい", "服", "を", "買い", "ました", "。")
    s("古い", "橋", "を", "使い", "ません", "。")
    s("甘い", "果物", "が", "良い", "。")
    # particle chains には / では
    s("庭", "に", "は", "鶏", "が", "遊び", "ます", "。")
    s("森", "で", "は", "鳥", "が", "歌い", "ます", "。")
    # adversarial: the classic, plus longest-entry traps
    s("すもも", "も", "もも", "も", "もも", "の", "うち", "。")
    s("私", "は", "すもも", "を", "食べ", "た", "。")
    s("もの", "の", "意味", "を", "聞き", "ます", "。")
    s("今日", "は", "休み", "です", "。")          # vs 今日は entry
    s("魚", "を", "食べ", "た", "犬", "。")        # vs 食べた noun
    s("日本語", "を", "学び", "ます", "。")
    s("うち", "の", "猫", "は", "黒い", "。")
    s("もも", "の", "花", "が", "美しい", "。")
    # longer compositions
    s("私", "の", "兄", "は", "会社", "で", "働き", "ます", "。")
    s("昨日", "は", "雨", "でした", "。")
    s("明日", "の", "朝", "、", "公園", "を", "走り", "ます", "。")
    s("夏", "の", "夜", "は", "暑い", "です", "。")
    s("冬", "の", "山", "は", "白い", "。")
    s("店", "で", "靴", "と", "帽子", "を", "買い", "ました", "。")
    s("銀行", "の", "近い", "店", "は", "安い", "。")
    s("池", "の", "魚", "は", "小さい", "。")
    s("光", "が", "窓", "から", "入り", "ます", "。")
    s("音楽", "を", "聞き", "たい", "。")
    s("写真", "を", "撮り", "ました", "。")
    s("夢", "の", "話", "を", "し", "ました", "。")
    return [x for x in S if x]


def main():
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(os.path.join(OUT_DIR, "entries.csv"), "w",
              encoding="utf-8") as f:
        for surface, lid, rid, cost, feats in entries():
            f.write(f"{surface},{lid},{rid},{cost},{feats}\n")
    n, m = matrix()
    with open(os.path.join(OUT_DIR, "matrix.def"), "w",
              encoding="utf-8") as f:
        f.write(f"{n} {n}\n")
        for (r, l), c in sorted(m.items()):
            f.write(f"{r} {l} {c}\n")
    with open(CORPUS, "w", encoding="utf-8") as f:
        for toks in sentences():
            f.write("".join(toks) + "\t" + "|".join(toks) + "\n")
    print(f"wrote {OUT_DIR} ({len(entries())} entries) and {CORPUS} "
          f"({len(sentences())} sentences)")


if __name__ == "__main__":
    main()
