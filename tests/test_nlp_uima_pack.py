"""UIMA-pack depth: trees, sentiment, stemming, POS filtering.

Reference: deeplearning4j-nlp-uima — text/corpora/treeparser/*.java,
text/corpora/sentiwordnet/SWN3.java,
tokenization/tokenizer/preprocessor/StemmingPreprocessor.java,
tokenization/tokenizer/PosUimaTokenizer.java.
"""

import pytest

from deeplearning4j_tpu.nlp.trees import (
    BinarizeTreeTransformer,
    CollapseUnaries,
    HeadWordFinder,
    Tree,
    TreeVectorizer,
)
from deeplearning4j_tpu.nlp.sentiment import SWN3
from deeplearning4j_tpu.nlp.stemming import (
    CustomStemmingPreprocessor,
    EmbeddedStemmingPreprocessor,
    PorterStemmer,
    PosTokenizerFactory,
    StemmingPreprocessor,
    heuristic_pos_tagger,
)

PTB = "(S (NP (DT the) (NN cat)) (VP (VBZ sits) (PP (IN on) (NP (DT the) (NN mat)))))"


class TestTree:
    def test_penn_round_trip(self):
        t = Tree.from_penn(PTB)
        assert t.label == "S"
        assert t.yield_words() == ["the", "cat", "sits", "on", "the", "mat"]
        assert t.tags() == ["DT", "NN", "VBZ", "IN", "DT", "NN"]
        assert Tree.from_penn(t.to_penn()).to_penn() == t.to_penn()

    def test_structure_predicates(self):
        t = Tree.from_penn(PTB)
        np = t.children[0]
        assert not np.is_leaf() and not np.is_pre_terminal()
        dt = np.children[0]
        assert dt.is_pre_terminal()
        assert dt.children[0].is_leaf()
        assert t.depth() == 5  # S > VP > PP > NP > NN > leaf
        assert t.first_child() is np

    def test_ptb_empty_wrapper(self):
        # real .mrg files wrap every sentence in an empty-label node
        t = Tree.from_penn("( (S (NP (NN dog)) (VP (VBZ barks))) )")
        assert t.label == "S"
        assert t.yield_words() == ["dog", "barks"]

    def test_collapse_does_not_mutate_source(self):
        t1 = Tree.from_penn("(S (X (NP (DT the) (NN cat))))")
        np_node = t1.children[0].children[0]
        CollapseUnaries().transform(t1)
        # source tree's structure and parent pointers untouched
        assert np_node.parent is t1.children[0]
        assert t1.to_penn() == "(S (X (NP (DT the) (NN cat))))"

    def test_unbalanced_raises(self):
        with pytest.raises(ValueError):
            Tree.from_penn("(S (NP")
        with pytest.raises(ValueError):
            Tree.from_penn("(S a)) extra")


class TestTransformers:
    def test_binarize_right(self):
        t = Tree.from_penn("(X (A a) (B b) (C c) (D d))")
        b = BinarizeTreeTransformer().transform(t)
        # every internal node now has <= 2 children
        def check(node):
            assert len(node.children) <= 2
            for c in node.children:
                check(c)
        check(b)
        assert b.yield_words() == ["a", "b", "c", "d"]  # yield preserved
        assert b.children[0].label == "A"
        assert b.children[1].label.startswith("X@")  # intermediate label
        # binarized trees stay parseable by the module's own serde
        assert Tree.from_penn(b.to_penn()).to_penn() == b.to_penn()

    def test_binarize_left(self):
        t = Tree.from_penn("(X (A a) (B b) (C c))")
        b = BinarizeTreeTransformer(factor="left").transform(t)
        assert b.yield_words() == ["a", "b", "c"]
        assert b.children[1].label == "C"

    def test_collapse_unaries(self):
        t = Tree.from_penn("(S (X (Y (NP (DT the) (NN cat)))))")
        c = CollapseUnaries().transform(t)
        # chain S->X->Y->NP collapses; top label kept, children are NP's
        assert c.label == "S"
        assert [ch.label for ch in c.children] == ["DT", "NN"]
        assert c.yield_words() == ["the", "cat"]

    def test_vectorizer_with_labels(self):
        tv = TreeVectorizer()
        trees = tv.get_trees_with_labels([PTB], "pos", ["neg", "pos"])
        assert len(trees) == 1

        def all_labeled(node):
            assert node.gold_label == 1
            for c in node.children:
                all_labeled(c)
        all_labeled(trees[0])
        with pytest.raises(ValueError):
            tv.get_trees_with_labels([PTB], "missing", ["neg", "pos"])


class TestHeadWordFinder:
    def test_head_rules(self):
        t = Tree.from_penn(PTB)
        hf = HeadWordFinder()
        # S -> VP (head1), VP -> VBZ (head1) -> 'sits'
        head = hf.find_head(t)
        assert head.value == "sits"
        np = t.children[0]
        assert hf.find_head(np).value == "cat"  # NP NN rule


class TestSWN3:
    def test_builtin_lexicon_scoring(self):
        swn = SWN3()
        assert swn.extract("good") > 0
        assert swn.extract("terrible") < 0
        assert swn.score("a good movie") > 0
        # negation flips the sentence score — case-insensitively
        assert swn.score("not a good movie") < 0
        assert swn.score("Not a good movie") < 0
        assert swn.class_for_score(0.8) == "strong_positive"
        assert swn.class_for_score(-0.8) == "strong_negative"
        assert swn.class_for_score(0.0) == "neutral"

    def test_expanded_lexicon_semantics(self):
        """r5 lexicon expansion regression locks: degree-adverb 'pretty'
        and politeness 'please' carry no polarity, hardly/barely negate
        through the negation mechanism, and single-POS effective scores
        respect the strong/plain/weak convention."""
        swn = SWN3()
        assert swn.extract("pretty") == 0.0
        assert swn.extract("please") == 0.0
        assert swn.classify("pretty bad") in ("negative", "weak_negative")
        assert swn.score("hardly a good movie") < 0
        assert swn.score("barely acceptable") < 0
        # no NEW word outranks the strongest single-POS entries via
        # POS summation (love/hate keep their historical v+n pairs)
        for w in ("praise", "delight", "waste", "damage", "anger"):
            assert abs(swn.extract(w)) <= 0.875, w
        # breadth: common review vocabulary scores sensibly
        assert swn.classify(
            "an outstanding and memorable masterpiece") == "strong_positive"
        assert swn.classify(
            "a dreadful waste of time , confusing and dull"
        ) == "strong_negative"

    def test_load_swn_format(self, tmp_path):
        p = tmp_path / "swn.txt"
        p.write_text(
            "# comment line\n"
            "a\t001\t0.75\t0\tgood#1 unspoiled#2\tgloss text\n"
            "a\t002\t0\t0.625\tbad#1\tgloss\n"
            "v\t003\t0.5\t0\tgood#1\tgloss\n")
        swn = SWN3(str(p))
        assert swn.extract("good") == pytest.approx(0.75 + 0.5)
        assert swn.extract("unspoiled") == pytest.approx(0.75)  # rank-weighted single sense
        assert swn.extract("bad") == pytest.approx(-0.625)
        assert swn.classify("bad bad bad") in ("strong_negative", "negative")


class TestStemming:
    def test_porter_classic_cases(self):
        st = PorterStemmer()
        for word, stem in [
            ("caresses", "caress"), ("ponies", "poni"), ("cats", "cat"),
            ("feed", "feed"), ("agreed", "agre"), ("plastered", "plaster"),
            ("motoring", "motor"), ("sing", "sing"), ("conflated", "conflat"),
            ("happy", "happi"), ("relational", "relat"),
            ("conditional", "condit"), ("rational", "ration"),
            ("digitizer", "digit"), ("operator", "oper"),
            ("feudalism", "feudal"), ("decisiveness", "decis"),
            ("hopefulness", "hope"), ("formaliti", "formal"),
            ("triplicate", "triplic"), ("formative", "form"),
            ("formalize", "formal"), ("electrical", "electr"),
            ("hopeful", "hope"), ("goodness", "good"),
            ("revival", "reviv"), ("allowance", "allow"),
            ("inference", "infer"), ("airliner", "airlin"),
            ("adoption", "adopt"), ("activate", "activ"),
            ("probate", "probat"), ("controll", "control"),
            ("roll", "roll"),
        ]:
            assert st.stem(word) == stem, word

    def test_stemming_preprocessor_cleans_and_stems(self):
        pre = StemmingPreprocessor()
        # CommonPreprocessor strips punctuation/lowercases, then stems
        assert pre.pre_process("Motoring,") == "motor"

    def test_embedded_and_custom(self):
        class Upper:
            def pre_process(self, t):
                return t.lower()
        emb = EmbeddedStemmingPreprocessor(Upper())
        assert emb.pre_process("MOTORING") == "motor"

        class FakeStemmer:
            def stem(self, t):
                return t[:3]
        cus = CustomStemmingPreprocessor(FakeStemmer())
        assert cus.pre_process("abcdef") == "abc"


class TestPosTokenizer:
    def test_heuristic_tagger(self):
        tags = heuristic_pos_tagger(["the", "cat", "is", "running", "quickly"])
        assert tags == ["DT", "NN", "VBZ", "VBG", "RB"]

    def test_pos_filter_none_substitution(self):
        tf = PosTokenizerFactory(allowed_pos_tags={"NN", "NNS"})
        tokens = tf.create("the cat is running").get_tokens()
        assert tokens == ["NONE", "cat", "NONE", "NONE"]

    def test_preprocessor_skips_sentinel(self):
        tf = PosTokenizerFactory(allowed_pos_tags={"NN", "VBG"})
        tf.set_token_pre_processor(StemmingPreprocessor())
        tokens = tf.create("the cat is running").get_tokens()
        # valid tokens stemmed; sentinel NONE untouched (not 'none')
        assert tokens == ["NONE", "cat", "NONE", "run"]

    def test_pos_filter_strip(self):
        tf = PosTokenizerFactory(allowed_pos_tags={"NN"}, strip_nones=True)
        assert tf.create("the cat sat <TAG>").get_tokens() == ["cat", "sat"]

    def test_custom_tagger(self):
        tf = PosTokenizerFactory(allowed_pos_tags={"KEEP"},
                                 tagger=lambda ts: ["KEEP" if t == "x" else "DROP"
                                                    for t in ts])
        assert tf.create("x y x").get_tokens() == ["x", "NONE", "x"]


class TestPosTaggerMeasuredAccuracy:
    """The measured number for the bundled suffix-heuristic tagger (the
    pluggable default where the reference loads an OpenNLP MAXENT model):
    token accuracy over a hand-tagged 238-token PTB fixture. The residual
    errors are open-class JJ/NN ambiguity a lexicon-free heuristic cannot
    resolve — documented in KNOWN_GAPS.md; a real tagger plugs in via
    PosTokenizerFactory(tagger=...)."""

    def test_accuracy_floor(self):
        import os
        from deeplearning4j_tpu.nlp.stemming import heuristic_pos_tagger
        corpus = os.path.join(os.path.dirname(__file__), "fixtures",
                              "en_pos_corpus.tsv")
        total = correct = coarse_ok = 0
        with open(corpus, encoding="utf-8") as f:
            for line in f:
                pairs = [p.rsplit("/", 1) for p in line.split()]
                toks = [p[0] for p in pairs]
                gold = [p[1] for p in pairs]
                pred = heuristic_pos_tagger(toks)
                for g, p in zip(gold, pred):
                    total += 1
                    correct += g == p
                    gc = g[:2] if g[0] in "NV" else g
                    pc = p[:2] if p and p[0] in "NV" else p
                    coarse_ok += gc == pc
        assert total == 238
        # measured 2026-07 (r5, with the two Brill-style context rules):
        # 0.845 exact / 0.870 coarse — up from 0.832/0.861; residual =
        # open-class JJ/NN ambiguity a lexicon would resolve
        assert correct / total > 0.83
        assert coarse_ok / total > 0.85

    def test_context_rules(self):
        """r5 Brill-style transformations: aux + -ed → VBN participle,
        to/modal + bare form → VB infinitive — and -ly adverbs keep the
        RB rule even after a modal."""
        from deeplearning4j_tpu.nlp.stemming import heuristic_pos_tagger
        tags = heuristic_pos_tagger(["they", "have", "walked", "home"])
        assert tags[2] == "VBN"
        tags = heuristic_pos_tagger(["she", "walked", "home"])
        assert tags[1] == "VBD"  # no auxiliary → simple past stays
        tags = heuristic_pos_tagger(["to", "buy", "milk"])
        assert tags[1] == "VB"
        tags = heuristic_pos_tagger(["must", "leave", "now"])
        assert tags[1] == "VB"
        tags = heuristic_pos_tagger(["will", "probably", "win"])
        assert tags[1] == "RB"  # -ly exclusion

    def test_closed_classes_exact(self):
        """Punctuation, possessive pronouns, modals, number words are
        FINITE classes — they must tag exactly."""
        from deeplearning4j_tpu.nlp.stemming import heuristic_pos_tagger
        toks = ["My", "brother", "must", "buy", "three", "eggs", "."]
        tags = heuristic_pos_tagger(toks)
        assert tags[0] == "PRP$" and tags[2] == "MD"
        assert tags[4] == "CD" and tags[6] == "."

    def test_capitalization_overrides_closed_classes(self):
        """Acronyms and mid-sentence capitalized closed-class homographs
        are proper nouns ("US" the country, "May" the month); sentence-
        initial closed words and the pronoun "I" keep their tags."""
        from deeplearning4j_tpu.nlp.stemming import heuristic_pos_tagger as t
        assert t(["The", "US", "economy"]) == ["DT", "NNP", "NN"]
        assert t(["In", "May", "we", "met"])[1] == "NNP"
        assert t(["May", "I", "help"])[:2] == ["MD", "PRP"]
        assert t(["It", "costs", ".5", "dollars"])[2] == "CD"
