"""Config-serde fuzz: random layer stacks must survive JSON/YAML round
trips with identical outputs.

The reference locks its config format with per-release regression tests
(RegressionTest050..080); this sweep goes further — a seeded generator
builds random MultiLayerConfigurations across the layer/regularizer/
preprocessor space, and for each one asserts that from_json(to_json)
builds a network whose outputs match the original exactly (same init
seed). Catches any layer field missing from to_dict/from_dict.
"""

import random

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers.conv import ConvolutionLayer
from deeplearning4j_tpu.nn.layers.core import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    ElementWiseMultiplicationLayer,
    PReLULayer,
)
from deeplearning4j_tpu.nn.layers.norm import BatchNormalizationLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.layers.pooling import SubsamplingLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

ACTIVATIONS = ["relu", "tanh", "sigmoid", "elu", "swish", "gelu",
               "leakyrelu", "softsign"]
UPDATERS = ["sgd", "adam", "rmsprop", "nesterovs", "adagrad", "amsgrad"]


def random_dense_conf(rng: random.Random) -> MultiLayerConfiguration:
    b = (NeuralNetConfiguration.builder()
         .seed(rng.randint(0, 10_000))
         .updater(rng.choice(UPDATERS))
         .weight_init(rng.choice(["xavier", "relu", "lecun_normal"]))
         .l2(rng.choice([0.0, 1e-4]))
         .list())
    width = rng.choice([4, 8, 12])
    n_hidden = rng.randint(1, 4)
    b.layer(DenseLayer(n_in=5, n_out=width,
                       activation=rng.choice(ACTIVATIONS),
                       dropout=rng.choice([None, 0.9])))
    for _ in range(n_hidden - 1):
        kind = rng.randrange(4)
        if kind == 0:
            b.layer(DenseLayer(n_in=width, n_out=width,
                               activation=rng.choice(ACTIVATIONS)))
        elif kind == 1:
            b.layer(ActivationLayer(activation=rng.choice(ACTIVATIONS)))
        elif kind == 2:
            b.layer(ElementWiseMultiplicationLayer(n_in=width, n_out=width))
        else:
            b.layer(PReLULayer(input_shape=(width,)))
    b.layer(OutputLayer(n_in=width, n_out=3))
    if rng.random() < 0.3:
        b.input_pre_processor(0, rng.choice(["zero_mean", "standardize"]))
    return b.build()


def random_conv_conf(rng: random.Random) -> MultiLayerConfiguration:
    b = (NeuralNetConfiguration.builder()
         .seed(rng.randint(0, 10_000))
         .updater(rng.choice(UPDATERS))
         .list())
    channels = rng.choice([4, 8])
    b.layer(ConvolutionLayer(n_out=channels, kernel_size=(3, 3),
                             convolution_mode="same",
                             activation=rng.choice(ACTIVATIONS)))
    if rng.random() < 0.5:
        b.layer(BatchNormalizationLayer())
    if rng.random() < 0.5:
        b.layer(SubsamplingLayer())
    if rng.random() < 0.3:
        b.layer(DropoutLayer(dropout=0.8))
    b.layer(DenseLayer(n_out=8, activation="relu"))
    b.layer(OutputLayer(n_out=3))
    b.set_input_type(InputType.convolutional(8, 8, 2))
    return b.build()


def assert_round_trip_identical(conf: MultiLayerConfiguration, x: np.ndarray,
                                seed_idx: int, fmt: str) -> None:
    if fmt == "json":
        restored = MultiLayerConfiguration.from_json(conf.to_json())
    else:
        restored = MultiLayerConfiguration.from_yaml(conf.to_yaml())
    a = MultiLayerNetwork(conf)
    a.init(seed=42)
    b = MultiLayerNetwork(restored)
    b.init(seed=42)
    np.testing.assert_allclose(
        np.asarray(a.output(x)), np.asarray(b.output(x)), rtol=1e-6,
        err_msg=f"case {seed_idx} ({fmt}): round-tripped config diverges\n"
                f"{conf.to_json()}")
    # training one step keeps them identical too (updaters serialized)
    y = np.eye(3, dtype=np.float32)[np.arange(len(x)) % 3]
    a.fit(x, y)
    b.fit(x, y)
    np.testing.assert_allclose(
        np.asarray(a.output(x)), np.asarray(b.output(x)), rtol=1e-5,
        err_msg=f"case {seed_idx} ({fmt}): diverged after one train step")


class TestConfigFuzz:
    @pytest.mark.parametrize("case", range(12))
    def test_dense_stacks_round_trip(self, case):
        rng = random.Random(1000 + case)
        conf = random_dense_conf(rng)
        x = np.random.RandomState(case).randn(6, 5).astype(np.float32)
        fmt = "yaml" if case % 3 == 0 else "json"
        assert_round_trip_identical(conf, x, case, fmt)

    @pytest.mark.parametrize("case", range(8))
    def test_conv_stacks_round_trip(self, case):
        rng = random.Random(2000 + case)
        conf = random_conv_conf(rng)
        x = np.random.RandomState(case).randn(4, 8, 8, 2).astype(np.float32)
        fmt = "yaml" if case % 3 == 0 else "json"
        assert_round_trip_identical(conf, x, case, fmt)


def random_graph_conf(rng: random.Random):
    """Random DAG: dense chain with skip connections through merge or
    elementwise vertices."""
    from deeplearning4j_tpu.nn.vertices import ElementWiseVertex, MergeVertex

    width = rng.choice([4, 8])
    g = (NeuralNetConfiguration.builder()
         .seed(rng.randint(0, 10_000))
         .updater(rng.choice(UPDATERS))
         .graph_builder()
         .add_inputs("in"))
    g.add_layer("d0", DenseLayer(n_in=5, n_out=width,
                                 activation=rng.choice(ACTIVATIONS)), "in")
    prev = "d0"
    for i in range(1, rng.randint(2, 4)):
        g.add_layer(f"d{i}", DenseLayer(n_in=width, n_out=width,
                                        activation=rng.choice(ACTIVATIONS)),
                    prev)
        if rng.random() < 0.5:
            # skip connection: combine with the previous activation
            kind = rng.randrange(2)
            if kind == 0:
                g.add_vertex(f"skip{i}", ElementWiseVertex(op="add"),
                             prev, f"d{i}")
                prev = f"skip{i}"
            else:
                g.add_vertex(f"skip{i}", MergeVertex(), prev, f"d{i}")
                g.add_layer(f"proj{i}", DenseLayer(n_in=2 * width,
                                                   n_out=width,
                                                   activation="identity"),
                            f"skip{i}")
                prev = f"proj{i}"
        else:
            prev = f"d{i}"
    g.add_layer("out", OutputLayer(n_in=width, n_out=3), prev)
    g.set_outputs("out")
    return g.build()


class TestGraphConfigFuzz:
    @pytest.mark.parametrize("case", range(10))
    def test_random_dags_round_trip(self, case):
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        rng = random.Random(3000 + case)
        conf = random_graph_conf(rng)
        restored = ComputationGraphConfiguration.from_json(conf.to_json())
        a = ComputationGraph(conf)
        a.init(seed=42)
        b = ComputationGraph(restored)
        b.init(seed=42)
        x = np.random.RandomState(case).randn(6, 5).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(a.output_single(x)), np.asarray(b.output_single(x)),
            rtol=1e-6,
            err_msg=f"graph case {case}: round-trip diverges\n{conf.to_json()}")
        y = np.eye(3, dtype=np.float32)[np.arange(6) % 3]
        a.fit(x, y)
        b.fit(x, y)
        np.testing.assert_allclose(
            np.asarray(a.output_single(x)), np.asarray(b.output_single(x)),
            rtol=1e-5,
            err_msg=f"graph case {case}: diverged after one train step")


class TestGradientFuzz:
    """Randomized composite gradient checks (GradientCheckUtil backbone,
    fuzzed): tiny random stacks must pass f64 central differences."""

    @pytest.mark.parametrize("case", range(5))
    def test_random_dense_stack_gradients(self, case):
        from deeplearning4j_tpu.util.gradient_check import check_model_gradients

        rng = random.Random(4000 + case)
        b = (NeuralNetConfiguration.builder()
             .seed(rng.randint(0, 10_000))
             .updater("sgd")
             .activation(rng.choice(["tanh", "sigmoid", "softsign"]))
             .l2(rng.choice([0.0, 1e-3]))
             .list())
        width = 3
        b.layer(DenseLayer(n_in=3, n_out=width))
        if rng.random() < 0.5:
            b.layer(ElementWiseMultiplicationLayer(n_in=width, n_out=width))
        if rng.random() < 0.5:
            b.layer(PReLULayer(input_shape=(width,)))
        b.layer(OutputLayer(n_in=width, n_out=2,
                            loss=rng.choice(["mcxent",
                                             "negativeloglikelihood"])))
        net = MultiLayerNetwork(b.build())
        net.init(seed=7)
        x = np.random.RandomState(case).randn(4, 3)
        y = np.eye(2)[np.random.RandomState(case + 1).randint(0, 2, 4)]
        assert check_model_gradients(net, x, y, subset=40, seed=case)

    @pytest.mark.parametrize("case", range(3))
    def test_random_conv_stack_gradients(self, case):
        from deeplearning4j_tpu.util.gradient_check import check_model_gradients

        rng = random.Random(5000 + case)
        b = (NeuralNetConfiguration.builder()
             .seed(rng.randint(0, 10_000))
             .updater("sgd").activation("tanh").list())
        b.layer(ConvolutionLayer(n_out=2, kernel_size=(2, 2),
                                 convolution_mode="same"))
        if rng.random() < 0.5:
            b.layer(SubsamplingLayer())
        b.layer(DenseLayer(n_out=4))
        b.layer(OutputLayer(n_out=2))
        b.set_input_type(InputType.convolutional(4, 4, 2))
        net = MultiLayerNetwork(b.build())
        net.init(seed=7)
        x = np.random.RandomState(case).randn(3, 4, 4, 2)
        y = np.eye(2)[np.random.RandomState(case + 1).randint(0, 2, 3)]
        assert check_model_gradients(net, x, y, subset=40, seed=case)
