"""Distributed-training tests on the 8-virtual-device CPU mesh.

Mirrors the reference's strategy of validating distributed semantics without
a cluster (`BaseSparkTest.java:89` local[N] mode) and its equivalence test
`TestCompareParameterAveragingSparkVsSingleMachine.java`: the distributed
result must match single-machine SGD.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.parallel import (
    EncodingHandler,
    ParallelInference,
    ParallelWrapper,
    make_mesh,
    threshold_decode,
    threshold_encode,
)


def small_net(seed=7, lr=0.1, updater="sgd"):
    from deeplearning4j_tpu.nn.updaters import Sgd, Adam
    u = Sgd(lr) if updater == "sgd" else Adam(lr)
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(u)
            .list()
            .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def make_data(rng, n=64):
    x = rng.normal(size=(n, 12)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=n)]
    return x, y


class TestMesh:
    def test_make_mesh_infer(self):
        m = make_mesh({"data": -1})
        assert m.shape["data"] == len(jax.devices())

    def test_make_mesh_2d(self):
        m = make_mesh({"data": 4, "model": 2})
        assert m.shape["data"] == 4 and m.shape["model"] == 2


class TestSharedGradients:
    def test_matches_single_machine(self, rng):
        """Sharded-batch step == unsharded step (same global batch)."""
        x, y = make_data(rng)
        ref = small_net()
        dist = small_net()
        ref.fit(x, y)
        pw = ParallelWrapper(dist, make_mesh({"data": 8}), mode="shared_gradients")
        pw.fit(x, y)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-5, atol=1e-6)

    def test_multiple_steps_adam(self, rng):
        x, y = make_data(rng)
        ref = small_net(updater="adam")
        dist = small_net(updater="adam")
        data = [DataSet(x[i * 16:(i + 1) * 16], y[i * 16:(i + 1) * 16])
                for i in range(4)]
        ref.fit(data, epochs=2)
        ParallelWrapper(dist, make_mesh({"data": 4}),
                        mode="shared_gradients").fit(data, epochs=2)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-4, atol=1e-5)


class TestAveraging:
    def test_freq1_sgd_equals_single_machine(self, rng):
        """averaging_frequency=1 + SGD: pmean of per-worker updates ==
        full-batch update (the TestCompareParameterAveragingSparkVsSingleMachine
        invariant)."""
        x, y = make_data(rng, n=64)
        ref = small_net()
        dist = small_net()
        ref.fit(x, y)
        pw = ParallelWrapper(dist, make_mesh({"data": 8}), mode="averaging",
                             averaging_frequency=1)
        pw.fit(x, y)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-5, atol=1e-6)

    def test_freq4_runs_and_learns(self, rng):
        x, y = make_data(rng, n=256)
        net = small_net()
        data = [DataSet(x[i * 32:(i + 1) * 32], y[i * 32:(i + 1) * 32])
                for i in range(8)]
        s0 = None
        pw = ParallelWrapper(net, make_mesh({"data": 4}), mode="averaging",
                             averaging_frequency=4)
        for _ in range(6):
            pw.fit(data)
            if s0 is None:
                s0 = net.score_
        assert net.iteration == 48
        assert net.score_ < s0


class TestRaggedBatches:
    def test_tail_batch_not_divisible(self, rng):
        """Dataset size not divisible by workers: tail batch must still train
        (unsharded fallback), matching single-machine results."""
        x, y = make_data(rng, n=100)  # batches of 16 → tail of 4 on 8 workers
        ref = small_net()
        dist = small_net()
        data = [DataSet(x[s:s + 16], y[s:s + 16]) for s in range(0, 100, 16)]
        ref.fit(data)
        ParallelWrapper(dist, make_mesh({"data": 8}),
                        mode="shared_gradients").fit(data)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-4, atol=1e-5)

    def test_averaging_rejects_tp(self):
        net = small_net()
        with pytest.raises(ValueError, match="tensor parallelism"):
            ParallelWrapper(net, make_mesh({"data": 4, "model": 2}),
                            mode="averaging", tp_axis="model")


class TestTensorParallel:
    def test_tp_sharded_step(self, rng):
        """Dense weights sharded over a 'model' axis still produce the same
        training result as replicated execution."""
        x, y = make_data(rng)
        ref = small_net()
        dist = small_net()
        ref.fit(x, y)
        mesh = make_mesh({"data": 2, "model": 4})
        pw = ParallelWrapper(dist, mesh, mode="shared_gradients", tp_axis="model")
        pw.fit(x, y)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-4, atol=1e-5)


class TestMegatronSpecs:
    """The designed (round-5) paired column→row TP rule."""

    def _ffn_net(self):
        conf = (NeuralNetConfiguration.builder().seed(3)
                .list()
                .layer(DenseLayer(n_in=32, n_out=128, activation="relu"))
                .layer(DenseLayer(n_in=128, n_out=32,
                                  activation="identity"))
                .layer(OutputLayer(n_in=32, n_out=8, activation="softmax",
                                   loss="negativeloglikelihood"))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_pairing_on_mln_ffn(self):
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel.sharding import tp_param_specs

        net = self._ffn_net()
        specs = tp_param_specs(net, "model")
        # Dense0 column: W [32,128] sharded on OUT, b sharded
        assert specs[0]["W"] == P(None, "model")
        assert specs[0]["b"] == P("model")
        # Dense1 row: W [128,32] sharded on IN, b replicated
        assert specs[1]["W"] == P("model", None)
        assert specs[1]["b"] == P()
        # OutputLayer cannot START a pair → replicated
        assert specs[2]["W"] == P()

    def test_dense_to_output_pairs_as_row_end(self):
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel.sharding import tp_param_specs

        net = small_net()  # Dense(12→16) → OutputLayer(16→4)
        specs = tp_param_specs(net, "model")
        assert specs[0]["W"] == P(None, "model")
        assert specs[1]["W"] == P("model", None)

    def test_attention_specs_on_graph(self):
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.parallel.sharding import tp_param_specs
        from deeplearning4j_tpu.zoo.models import TransformerEncoder

        net = ComputationGraph(TransformerEncoder(
            num_labels=2, vocab_size=32, max_length=8, n_layers=1,
            d_model=16, n_heads=2, d_ff=32).conf()).init()
        specs = tp_param_specs(net, "model")
        att = specs["block0-att"]
        assert att["Wqkv"] == P(None, "model")
        assert att["bqkv"] == P("model")
        assert att["Wo"] == P("model", None)
        assert att["bo"] == P()
        # FFN pair inside the block
        assert specs["block0-ff1"]["W"] == P(None, "model")
        assert specs["block0-ff2"]["W"] == P("model", None)
        # LayerNorm replicated
        assert all(s == P() for s in specs["block0-ln1"].values())

    def test_residual_tap_breaks_pair(self):
        """A dense whose activation is ALSO tapped by an elementwise vertex
        must not become column-parallel: the tap edge would force the
        all-gather the pairing exists to avoid."""
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.vertices import ElementWiseVertex
        from deeplearning4j_tpu.parallel.sharding import tp_param_specs

        g = (NeuralNetConfiguration.builder().seed(0).graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.feed_forward(16)))
        g.add_layer("d1", DenseLayer(n_in=16, n_out=16, activation="relu"),
                    "in")
        g.add_layer("d2", DenseLayer(n_in=16, n_out=16,
                                     activation="identity"), "d1")
        g.add_vertex("res", ElementWiseVertex(op="add"), "d1", "d2")
        g.add_layer("out", OutputLayer(n_in=16, n_out=4,
                                       activation="softmax",
                                       loss="negativeloglikelihood"), "res")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        specs = tp_param_specs(net, "model")
        assert specs["d1"]["W"] == P()  # tap disqualifies the pair
        assert specs["d2"]["W"] == P()

    @staticmethod
    def _count_collectives(txt):
        import re
        return len(re.findall(
            r"\b(all-reduce|all-gather|collective-permute|all-to-all|"
            r"reduce-scatter)\b", txt))

    def test_megatron_specs_fewer_collectives(self):
        """Quantifies VERDICT r4 Weak #3: the old every-layer output-dim
        rule forces resharding between consecutive layers; the paired rule
        compiles to strictly fewer collectives on the same FFN stack."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deeplearning4j_tpu.parallel.sharding import tp_param_specs

        mesh = make_mesh({"data": 2, "model": 4})
        x = np.random.default_rng(0).normal(size=(8, 32)).astype(np.float32)
        xs = jax.device_put(jnp.asarray(x),
                            NamedSharding(mesh, P("data", None)))

        def compile_with(specs_fn):
            net = self._ffn_net()
            specs = specs_fn(net)
            placed = []
            for pd, sd in zip(net.params, specs):
                placed.append({
                    n: jax.device_put(v, NamedSharding(mesh, sd[n]))
                    for n, v in pd.items()})

            def forward(params, xin):
                h, _, _ = net._forward_all(params, net.states, xin,
                                           train=False, rng=None, mask=None)
                return h

            return jax.jit(forward).lower(placed, xs).compile().as_text()

        def legacy_specs(net):
            # the replaced round-1 rule, kept here only as the comparator
            out = []
            for p in net.params:
                d = {}
                for n, v in p.items():
                    if v.ndim >= 2 and v.shape[-1] > 1:
                        d[n] = P(*([None] * (v.ndim - 1)), "model")
                    elif v.ndim == 1 and v.shape[0] > 1:
                        d[n] = P("model")
                    else:
                        d[n] = P()
                out.append(d)
            return out

        legacy = self._count_collectives(compile_with(legacy_specs))
        megatron = self._count_collectives(compile_with(
            lambda net: tp_param_specs(net, "model", mesh)))
        assert megatron < legacy, (megatron, legacy)

    def test_attention_collectives(self):
        """Head-major Wqkv: the TP-sharded encoder block compiles with NO
        activation all-gathers — the [3,H,Dh] fused layout measured 5 of
        them on this mesh because the qkv reshape could not propagate the
        column sharding (tp does not divide 3)."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.parallel.sharding import shard_model
        from deeplearning4j_tpu.zoo.models import TransformerEncoder

        mesh = make_mesh({"data": 2, "model": 4})
        net = ComputationGraph(TransformerEncoder(
            num_labels=4, vocab_size=32, max_length=8, n_layers=1,
            d_model=32, n_heads=4, d_ff=64, seed=2).conf()).init()
        shard_model(net, mesh, tp_axis="model")
        x = jax.device_put(
            jnp.zeros((8, 8)),
            NamedSharding(mesh, P("data", None)))

        def forward(params, xin):
            acts, _, _, _ = net._forward_all(params, net.states,
                                             {"tokens": xin}, train=False,
                                             rng=None)
            return acts

        txt = jax.jit(forward).lower(net.params, x).compile().as_text()
        import re
        gathers = re.findall(r"\ball-gather\b", txt)
        assert not gathers, f"{len(gathers)} all-gathers in TP attention"

    def test_tp_transformer_graph_matches_replicated(self, rng):
        """Head-sharded attention + paired FFN on a real TransformerEncoder
        graph: outputs and a training step match replicated execution."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.parallel.sharding import shard_model
        from deeplearning4j_tpu.zoo.models import TransformerEncoder

        def build():
            return ComputationGraph(TransformerEncoder(
                num_labels=4, vocab_size=32, max_length=8, n_layers=1,
                d_model=16, n_heads=2, d_ff=32, seed=11).conf()).init()

        ref, dist = build(), build()
        mesh = make_mesh({"data": 2, "model": 4})
        shard_model(dist, mesh, tp_axis="model")

        x = rng.integers(0, 32, size=(8, 8)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=8)]
        np.testing.assert_allclose(np.asarray(dist.output_single(x)),
                                   np.asarray(ref.output_single(x)),
                                   rtol=2e-4, atol=1e-5)
        ref.fit(x, y)
        dist.fit(x, y)
        np.testing.assert_allclose(np.asarray(dist.output_single(x)),
                                   np.asarray(ref.output_single(x)),
                                   rtol=2e-4, atol=1e-5)


class TestCompression:
    def test_encode_decode_roundtrip(self):
        r = jnp.asarray([0.0, 0.5, -0.2, 0.01, -0.9, 0.0, 0.3, -0.001])
        msg, new_r = threshold_encode(r, 0.25, capacity=8)
        assert int(msg.count) == 3  # 0.5, -0.9, 0.3 exceed the 0.25 threshold
        dense = threshold_decode(msg, 8)
        expect = np.array([0, 0.25, 0, 0, -0.25, 0, 0.25, 0], np.float32)
        np.testing.assert_allclose(np.asarray(dense), expect)
        # residual = original - sent
        np.testing.assert_allclose(np.asarray(new_r), np.asarray(r) - expect,
                                   atol=1e-7)

    def test_capacity_drop(self):
        r = jnp.ones(100) * 5.0
        msg, _ = threshold_encode(r, 1.0, capacity=10)
        assert int(msg.count) == 10
        dense = threshold_decode(msg, 100)
        assert float(jnp.sum(jnp.abs(dense))) == pytest.approx(10.0)

    def test_handler_residual_accumulates(self):
        h = EncodingHandler(threshold=1.0, capacity=4)
        g = jnp.full((8,), 0.6)
        msg1 = h.encode(g)          # residual 0.6 < 1.0 → nothing sent
        assert int(msg1.count) == 0
        msg2 = h.encode(g)          # residual 1.2 ≥ 1.0 → sent (capped at 4)
        assert int(msg2.count) == 4


class TestParallelInference:
    def test_batched_output_matches_direct(self, rng):
        net = small_net()
        x, _ = make_data(rng, n=8)
        pi = ParallelInference(net, mode="batched", max_batch_size=16)
        try:
            got = pi.output(x)
            want = np.asarray(net.output(x))
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        finally:
            pi.shutdown()

    def test_concurrent_requests(self, rng):
        import threading
        net = small_net()
        pi = ParallelInference(net, mode="batched", max_batch_size=64,
                               mesh=make_mesh({"data": 4}))
        xs = [rng.normal(size=(4, 12)).astype(np.float32) for _ in range(8)]
        results = [None] * 8

        def call(i):
            results[i] = pi.output(xs[i])

        threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            for i in range(8):
                want = np.asarray(net.output(xs[i]))
                np.testing.assert_allclose(results[i], want, rtol=1e-4, atol=1e-5)
        finally:
            pi.shutdown()


class TestParallelInferenceModes:
    def test_inplace_mode_concurrent(self, rng):
        import threading
        net = small_net()
        pi = ParallelInference(net, mode="inplace")
        xs = [rng.normal(size=(4, 12)).astype(np.float32) for _ in range(6)]
        results = [None] * 6

        def call(i):
            results[i] = pi.output(xs[i])

        threads = [threading.Thread(target=call, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, x in enumerate(xs):
            np.testing.assert_allclose(results[i], np.asarray(net.output(x)),
                                       rtol=1e-5, atol=1e-6)

    def test_update_model_swaps_serving(self, rng):
        net_a = small_net(seed=1)
        net_b = small_net(seed=2)
        x, _ = make_data(rng, n=4)
        pi = ParallelInference(net_a, mode="batched", max_batch_size=8)
        try:
            got_a = pi.output(x)
            np.testing.assert_allclose(got_a, np.asarray(net_a.output(x)),
                                       rtol=1e-5, atol=1e-6)
            pi.update_model(net_b)
            got_b = pi.output(x)
            np.testing.assert_allclose(got_b, np.asarray(net_b.output(x)),
                                       rtol=1e-5, atol=1e-6)
            assert not np.allclose(got_a, got_b)
        finally:
            pi.shutdown()


class TestParallelInferenceRobustness:
    """The serving tier's containment contract (round-6 fixes): a dispatcher
    crash must never strand waiters, deadlines must keep expired work off
    the device, and degenerate requests are rejected client-side."""

    def test_dispatcher_crash_fails_waiters_and_future_requests(self, rng):
        import threading
        from deeplearning4j_tpu.parallel.inference import DispatcherCrashed
        net = small_net()
        pi = ParallelInference(net, mode="batched", max_batch_size=4)
        try:
            def boom(batch, n):
                raise RuntimeError("kaboom")

            pi._dispatch = boom
            errors = []

            def call():
                try:
                    pi.output(rng.normal(size=(2, 12)).astype(np.float32))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            threads = [threading.Thread(target=call) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)          # pre-fix these hung forever
            assert len(errors) == 4
            assert all(isinstance(e, DispatcherCrashed) for e in errors)
            assert not pi.healthy
            assert isinstance(pi.dispatcher_error, RuntimeError)
            with pytest.raises(DispatcherCrashed):   # fast-fail from now on
                pi.output(np.zeros((1, 12), np.float32))
        finally:
            pi.shutdown()

    def test_deadline_expired_request_never_dispatched(self):
        import threading

        class Gate:
            def __init__(self):
                self.gate = threading.Event()
                self.entered = threading.Event()
                self.calls = 0

            def output(self, x):
                self.calls += 1
                self.entered.set()
                assert self.gate.wait(10.0)
                return np.zeros((np.asarray(x).shape[0], 2), np.float32)

        from deeplearning4j_tpu.parallel.inference import (
            InferenceDeadlineExceeded)
        gate = Gate()
        pi = ParallelInference(gate, mode="batched", max_batch_size=4)
        try:
            got = {}
            t = threading.Thread(
                target=lambda: got.setdefault(
                    "a", pi.output(np.zeros((1, 3), np.float32))))
            t.start()
            assert gate.entered.wait(5.0)    # dispatcher stuck in batch 1
            with pytest.raises(InferenceDeadlineExceeded):
                pi.output(np.zeros((1, 3), np.float32), deadline_s=0.05)
            gate.gate.set()
            t.join(timeout=10)
            assert got["a"].shape == (1, 2)
            # the expired request was skipped; a fresh one forms batch 2
            assert pi.output(np.zeros((1, 3), np.float32)).shape == (1, 2)
            assert gate.calls == 2
        finally:
            gate.gate.set()
            pi.shutdown()

    def test_zero_dim_request_rejected_client_side(self):
        net = small_net()
        pi = ParallelInference(net, mode="batched")
        try:
            with pytest.raises(ValueError, match="at least 1-d"):
                pi.output(np.float32(3.0))
            # the dispatcher survived — normal requests still serve
            assert pi.healthy
            assert pi.output(np.zeros((1, 12), np.float32)).shape == (1, 4)
        finally:
            pi.shutdown()


def conv_bn_net(seed=3, lr=0.05):
    """Small VGG-style conv block WITH BatchNorm — BN's batch statistics
    under data parallelism are the classic silent-divergence trap
    (BASELINE.json configs[4] coverage)."""
    from deeplearning4j_tpu.nn.conf import InputType
    from deeplearning4j_tpu.nn.layers import (BatchNormalizationLayer,
                                              ConvolutionLayer,
                                              SubsamplingLayer)
    from deeplearning4j_tpu.nn.updaters import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr))
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    convolution_mode="same",
                                    activation="identity"))
            .layer(BatchNormalizationLayer())
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    convolution_mode="same",
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="negativeloglikelihood"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    return MultiLayerNetwork(conf).init()


def make_image_data(rng, n=64):
    x = rng.normal(size=(n, 8, 8, 1)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=n)]
    return x, y


class TestConvBnDataParallel:
    """Conv+BN under both ParallelWrapper modes vs single-device training
    (TestCompareParameterAveragingSparkVsSingleMachine pattern, extended to
    BN running statistics)."""

    def _assert_nets_equal(self, a, b, rtol=1e-4, atol=1e-5):
        for pa, pb in zip(a.params, b.params):
            for n in pa:
                np.testing.assert_allclose(np.asarray(pa[n]),
                                           np.asarray(pb[n]),
                                           rtol=rtol, atol=atol, err_msg=n)
        for sa, sb in zip(a.states, b.states):
            for n in sa:
                np.testing.assert_allclose(np.asarray(sa[n]),
                                           np.asarray(sb[n]),
                                           rtol=rtol, atol=atol, err_msg=n)

    def test_shared_gradients_exact_including_bn_stats(self, rng):
        """GSPMD sharding preserves GLOBAL-batch semantics: BN normalizes
        over the full batch even though it is split across 8 devices, so
        every parameter AND running statistic matches single-device."""
        x, y = make_image_data(rng)
        ref = conv_bn_net()
        dist = conv_bn_net()
        for i in range(3):
            ref.fit(x, y)
        pw = ParallelWrapper(dist, make_mesh({"data": 8}),
                             mode="shared_gradients")
        for i in range(3):
            pw.fit(x, y)
        self._assert_nets_equal(ref, dist)
        # the BN layer really tracked stats (not zeros/ones inits)
        bn_mean = np.asarray(dist.states[1]["mean"])
        assert np.abs(bn_mean).max() > 1e-4

    def test_averaging_matches_manual_per_worker_simulation(self, rng):
        """Averaging mode == its specified semantics, simulated by hand:
        each of the 8 workers runs k local steps on its own shard from the
        same replicated start, then params/states/updater states are
        averaged. BN running stats per worker come from LOCAL batch stats
        (the reference's semantics too), so the average differs from
        single-device global-batch stats — the simulation is the correct
        oracle, not the single-device run."""
        x, y = make_image_data(rng, n=64)
        k, workers = 2, 8
        local = 64 // workers  # per-worker batch per step after stacking k
        # wrapper run
        dist = conv_bn_net()
        # materialize COPIES: the wrapper's jitted step donates (deletes)
        # the original buffers
        copy = lambda tree: jax.tree_util.tree_map(np.array, tree)
        init_params = copy(dist.params)
        init_states = copy(dist.states)
        init_upd = copy(dist.updater_states)
        pw = ParallelWrapper(dist, make_mesh({"data": 8}), mode="averaging",
                             averaging_frequency=k)
        data = [DataSet(x[i * 32:(i + 1) * 32], y[i * 32:(i + 1) * 32])
                for i in range(2)]  # 2 batches of 32 -> one flush of k=2
        pw.fit(data)
        # manual simulation with the model's own (single-device) step
        import jax.numpy as jnp
        sim_p, sim_s, sim_u = None, None, None
        for w in range(workers):
            worker = conv_bn_net()
            worker.params = [dict(p) for p in init_params]
            worker.states = [dict(s) for s in init_states]
            worker.updater_states = [dict(u) for u in init_upd]
            for step_i in range(k):
                xb = x[step_i * 32:(step_i + 1) * 32]
                yb = y[step_i * 32:(step_i + 1) * 32]
                xw = xb[w * (32 // workers):(w + 1) * (32 // workers)]
                yw = yb[w * (32 // workers):(w + 1) * (32 // workers)]
                worker.fit(xw, yw)
            tm = jax.tree_util.tree_map
            acc = lambda tree, new: (tm(np.asarray, new) if tree is None
                                     else tm(lambda a, b: a + np.asarray(b),
                                             tree, new))
            sim_p = acc(sim_p, worker.params)
            sim_s = acc(sim_s, worker.states)
            sim_u = acc(sim_u, worker.updater_states)
        tm = jax.tree_util.tree_map
        for tree, got in ((sim_p, dist.params), (sim_s, dist.states),
                          (sim_u, dist.updater_states)):
            tm(lambda t, g: np.testing.assert_allclose(
                t / workers, np.asarray(g), rtol=2e-4, atol=1e-5), tree, got)

    def test_averaging_bn_running_mean_tracks_single_device(self, rng):
        """Averaged BN running MEAN equals the single-device value (mean of
        shard means == global mean for equal shards); running VAR may
        deviate by the between-shard variance — assert the mean agrees and
        the whole net stays close."""
        x, y = make_image_data(rng)
        ref = conv_bn_net()
        ref.fit(x, y)
        dist = conv_bn_net()
        pw = ParallelWrapper(dist, make_mesh({"data": 8}), mode="averaging",
                             averaging_frequency=1)
        pw.fit(x, y)
        np.testing.assert_allclose(np.asarray(dist.states[1]["mean"]),
                                   np.asarray(ref.states[1]["mean"]),
                                   rtol=1e-4, atol=1e-6)


class TestDistributedEvaluate:
    def test_mesh_evaluate_equals_single_device(self, rng):
        """ParallelWrapper.evaluate shards batches over the mesh and must
        reproduce the single-device Evaluation exactly (SparkDl4jMultiLayer
        .evaluate pattern)."""
        from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
        x, y = make_data(rng, n=96)
        net = small_net()
        net.fit(x, y)
        ref = net.evaluate(ListDataSetIterator(DataSet(x, y), 16))
        pw = ParallelWrapper(net, make_mesh({"data": 8}),
                             mode="shared_gradients")
        dist = pw.evaluate(ListDataSetIterator(DataSet(x, y), 16), top_n=2)
        np.testing.assert_array_equal(dist.confusion, ref.confusion)
        assert dist.top_n_accuracy() >= dist.accuracy()
        # ragged batches (batch 20 over 8 workers) take the unsharded path
        dist2 = pw.evaluate(ListDataSetIterator(DataSet(x, y), 20))
        np.testing.assert_array_equal(dist2.confusion, ref.confusion)


def test_mesh_evaluate_masked_sequences(rng):
    """ParallelWrapper.evaluate threads feature masks through the sharded
    forward (round-3 review fix) — equality with the unsharded evaluate."""
    from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import LSTMLayer, RnnOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    N, T, F, C = 16, 6, 4, 3
    x = rng.normal(size=(N, T, F)).astype(np.float32)
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, (N, T))]
    lengths = rng.integers(2, T + 1, N)
    m = (np.arange(T)[None, :] < lengths[:, None]).astype(np.float32)
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
            .list()
            .layer(LSTMLayer(n_in=F, n_out=8))
            .layer(RnnOutputLayer(n_in=8, n_out=C))
            .build())
    net = MultiLayerNetwork(conf).init()
    ds = DataSet(x, y, m, m)
    ref = net.evaluate(ListDataSetIterator(ds, 8))
    pw = ParallelWrapper(net, make_mesh({"data": 8}),
                         mode="shared_gradients")
    dist = pw.evaluate(ListDataSetIterator(ds, 8))
    np.testing.assert_array_equal(dist.confusion, ref.confusion)
    assert dist.confusion.sum() == int(m.sum())
