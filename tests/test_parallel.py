"""Distributed-training tests on the 8-virtual-device CPU mesh.

Mirrors the reference's strategy of validating distributed semantics without
a cluster (`BaseSparkTest.java:89` local[N] mode) and its equivalence test
`TestCompareParameterAveragingSparkVsSingleMachine.java`: the distributed
result must match single-machine SGD.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.parallel import (
    EncodingHandler,
    ParallelInference,
    ParallelWrapper,
    make_mesh,
    threshold_decode,
    threshold_encode,
)


def small_net(seed=7, lr=0.1, updater="sgd"):
    from deeplearning4j_tpu.nn.updaters import Sgd, Adam
    u = Sgd(lr) if updater == "sgd" else Adam(lr)
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(u)
            .list()
            .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def make_data(rng, n=64):
    x = rng.normal(size=(n, 12)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=n)]
    return x, y


class TestMesh:
    def test_make_mesh_infer(self):
        m = make_mesh({"data": -1})
        assert m.shape["data"] == len(jax.devices())

    def test_make_mesh_2d(self):
        m = make_mesh({"data": 4, "model": 2})
        assert m.shape["data"] == 4 and m.shape["model"] == 2


class TestSharedGradients:
    def test_matches_single_machine(self, rng):
        """Sharded-batch step == unsharded step (same global batch)."""
        x, y = make_data(rng)
        ref = small_net()
        dist = small_net()
        ref.fit(x, y)
        pw = ParallelWrapper(dist, make_mesh({"data": 8}), mode="shared_gradients")
        pw.fit(x, y)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-5, atol=1e-6)

    def test_multiple_steps_adam(self, rng):
        x, y = make_data(rng)
        ref = small_net(updater="adam")
        dist = small_net(updater="adam")
        data = [DataSet(x[i * 16:(i + 1) * 16], y[i * 16:(i + 1) * 16])
                for i in range(4)]
        ref.fit(data, epochs=2)
        ParallelWrapper(dist, make_mesh({"data": 4}),
                        mode="shared_gradients").fit(data, epochs=2)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-4, atol=1e-5)


class TestAveraging:
    def test_freq1_sgd_equals_single_machine(self, rng):
        """averaging_frequency=1 + SGD: pmean of per-worker updates ==
        full-batch update (the TestCompareParameterAveragingSparkVsSingleMachine
        invariant)."""
        x, y = make_data(rng, n=64)
        ref = small_net()
        dist = small_net()
        ref.fit(x, y)
        pw = ParallelWrapper(dist, make_mesh({"data": 8}), mode="averaging",
                             averaging_frequency=1)
        pw.fit(x, y)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-5, atol=1e-6)

    def test_freq4_runs_and_learns(self, rng):
        x, y = make_data(rng, n=256)
        net = small_net()
        data = [DataSet(x[i * 32:(i + 1) * 32], y[i * 32:(i + 1) * 32])
                for i in range(8)]
        s0 = None
        pw = ParallelWrapper(net, make_mesh({"data": 4}), mode="averaging",
                             averaging_frequency=4)
        for _ in range(6):
            pw.fit(data)
            if s0 is None:
                s0 = net.score_
        assert net.iteration == 48
        assert net.score_ < s0


class TestRaggedBatches:
    def test_tail_batch_not_divisible(self, rng):
        """Dataset size not divisible by workers: tail batch must still train
        (unsharded fallback), matching single-machine results."""
        x, y = make_data(rng, n=100)  # batches of 16 → tail of 4 on 8 workers
        ref = small_net()
        dist = small_net()
        data = [DataSet(x[s:s + 16], y[s:s + 16]) for s in range(0, 100, 16)]
        ref.fit(data)
        ParallelWrapper(dist, make_mesh({"data": 8}),
                        mode="shared_gradients").fit(data)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-4, atol=1e-5)

    def test_averaging_rejects_tp(self):
        net = small_net()
        with pytest.raises(ValueError, match="tensor parallelism"):
            ParallelWrapper(net, make_mesh({"data": 4, "model": 2}),
                            mode="averaging", tp_axis="model")


class TestTensorParallel:
    def test_tp_sharded_step(self, rng):
        """Dense weights sharded over a 'model' axis still produce the same
        training result as replicated execution."""
        x, y = make_data(rng)
        ref = small_net()
        dist = small_net()
        ref.fit(x, y)
        mesh = make_mesh({"data": 2, "model": 4})
        pw = ParallelWrapper(dist, mesh, mode="shared_gradients", tp_axis="model")
        pw.fit(x, y)
        for pr, pd in zip(ref.params, dist.params):
            for n in pr:
                np.testing.assert_allclose(np.asarray(pr[n]), np.asarray(pd[n]),
                                           rtol=1e-4, atol=1e-5)


class TestCompression:
    def test_encode_decode_roundtrip(self):
        r = jnp.asarray([0.0, 0.5, -0.2, 0.01, -0.9, 0.0, 0.3, -0.001])
        msg, new_r = threshold_encode(r, 0.25, capacity=8)
        assert int(msg.count) == 3  # 0.5, -0.9, 0.3 exceed the 0.25 threshold
        dense = threshold_decode(msg, 8)
        expect = np.array([0, 0.25, 0, 0, -0.25, 0, 0.25, 0], np.float32)
        np.testing.assert_allclose(np.asarray(dense), expect)
        # residual = original - sent
        np.testing.assert_allclose(np.asarray(new_r), np.asarray(r) - expect,
                                   atol=1e-7)

    def test_capacity_drop(self):
        r = jnp.ones(100) * 5.0
        msg, _ = threshold_encode(r, 1.0, capacity=10)
        assert int(msg.count) == 10
        dense = threshold_decode(msg, 100)
        assert float(jnp.sum(jnp.abs(dense))) == pytest.approx(10.0)

    def test_handler_residual_accumulates(self):
        h = EncodingHandler(threshold=1.0, capacity=4)
        g = jnp.full((8,), 0.6)
        msg1 = h.encode(g)          # residual 0.6 < 1.0 → nothing sent
        assert int(msg1.count) == 0
        msg2 = h.encode(g)          # residual 1.2 ≥ 1.0 → sent (capped at 4)
        assert int(msg2.count) == 4


class TestParallelInference:
    def test_batched_output_matches_direct(self, rng):
        net = small_net()
        x, _ = make_data(rng, n=8)
        pi = ParallelInference(net, mode="batched", max_batch_size=16)
        try:
            got = pi.output(x)
            want = np.asarray(net.output(x))
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        finally:
            pi.shutdown()

    def test_concurrent_requests(self, rng):
        import threading
        net = small_net()
        pi = ParallelInference(net, mode="batched", max_batch_size=64,
                               mesh=make_mesh({"data": 4}))
        xs = [rng.normal(size=(4, 12)).astype(np.float32) for _ in range(8)]
        results = [None] * 8

        def call(i):
            results[i] = pi.output(xs[i])

        threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            for i in range(8):
                want = np.asarray(net.output(xs[i]))
                np.testing.assert_allclose(results[i], want, rtol=1e-4, atol=1e-5)
        finally:
            pi.shutdown()


class TestParallelInferenceModes:
    def test_inplace_mode_concurrent(self, rng):
        import threading
        net = small_net()
        pi = ParallelInference(net, mode="inplace")
        xs = [rng.normal(size=(4, 12)).astype(np.float32) for _ in range(6)]
        results = [None] * 6

        def call(i):
            results[i] = pi.output(xs[i])

        threads = [threading.Thread(target=call, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, x in enumerate(xs):
            np.testing.assert_allclose(results[i], np.asarray(net.output(x)),
                                       rtol=1e-5, atol=1e-6)

    def test_update_model_swaps_serving(self, rng):
        net_a = small_net(seed=1)
        net_b = small_net(seed=2)
        x, _ = make_data(rng, n=4)
        pi = ParallelInference(net_a, mode="batched", max_batch_size=8)
        try:
            got_a = pi.output(x)
            np.testing.assert_allclose(got_a, np.asarray(net_a.output(x)),
                                       rtol=1e-5, atol=1e-6)
            pi.update_model(net_b)
            got_b = pi.output(x)
            np.testing.assert_allclose(got_b, np.asarray(net_b.output(x)),
                                       rtol=1e-5, atol=1e-6)
            assert not np.allclose(got_a, got_b)
        finally:
            pi.shutdown()
