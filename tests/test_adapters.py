"""sklearn adapter + preemption handler tests."""

import os
import signal

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.sklearn_adapter import (
    SklearnDl4jClassifier,
    SklearnDl4jRegressor,
)
from deeplearning4j_tpu.util.preemption import PreemptionHandler


def _clf_factory(n_in, n_out):
    return (NeuralNetConfiguration.builder().seed(0).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=n_out))
            .set_input_type(InputType.feed_forward(n_in)).build())


def _reg_factory(n_in, n_out):
    return (NeuralNetConfiguration.builder().seed(0).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="identity", loss="mse"))
            .set_input_type(InputType.feed_forward(n_in)).build())


class TestSklearnAdapter:
    def test_classifier_protocol(self, rng):
        y = rng.integers(0, 3, 256)
        x = rng.normal(size=(256, 6)).astype(np.float32)
        x[np.arange(256), y] += 2.5
        clf = SklearnDl4jClassifier(_clf_factory, epochs=10, batch_size=64)
        clf.fit(x, y)
        assert clf.score(x, y) > 0.9
        proba = clf.predict_proba(x[:5])
        assert proba.shape == (5, 3)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-3)
        # string labels work (classes_ mapping)
        ys = np.array(["a", "b", "c"])[y]
        clf2 = SklearnDl4jClassifier(_clf_factory, epochs=5, batch_size=64)
        clf2.fit(x, ys)
        assert set(clf2.predict(x[:10])) <= {"a", "b", "c"}

    def test_get_set_params(self):
        clf = SklearnDl4jClassifier(_clf_factory, epochs=3)
        assert clf.get_params()["epochs"] == 3
        clf.set_params(epochs=7)
        assert clf.epochs == 7
        with pytest.raises(ValueError):
            clf.set_params(nonsense=1)

    def test_regressor_r2(self, rng):
        x = rng.normal(size=(256, 4)).astype(np.float32)
        y = (x @ np.array([1.0, -2.0, 0.5, 3.0])).astype(np.float32)
        reg = SklearnDl4jRegressor(_reg_factory, epochs=40, batch_size=64)
        reg.fit(x, y)
        assert reg.predict(x).shape == (256,)
        r2 = reg.score(x, y)
        assert r2 > 0.9
        # column-vector y must give the same score, not an (n,n) broadcast
        assert abs(reg.score(x, y[:, None]) - r2) < 1e-6

    def test_works_in_sklearn_pipeline(self, rng):
        sklearn = pytest.importorskip("sklearn")
        from sklearn.pipeline import Pipeline
        from sklearn.preprocessing import StandardScaler

        y = rng.integers(0, 2, 128)
        x = (rng.normal(size=(128, 4)) * 10 + 5).astype(np.float32)
        x[np.arange(128), y] += 30
        pipe = Pipeline([
            ("scale", StandardScaler()),
            ("net", SklearnDl4jClassifier(_clf_factory, epochs=10,
                                          batch_size=32)),
        ])
        pipe.fit(x, y)
        assert pipe.score(x, y) > 0.85


class TestPreemption:
    def _net(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
        net = MultiLayerNetwork(_clf_factory(4, 2)).init()
        y = rng.integers(0, 2, 64)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        net.fit(ListDataSetIterator(
            DataSet(x, np.eye(2, dtype=np.float32)[y]), 32), epochs=2)
        return net

    def test_sigterm_checkpoints_and_resumes(self, tmp_path, rng):
        net = self._net(rng)
        ckpt = str(tmp_path / "pre.zip")
        handler = PreemptionHandler(net, ckpt).arm()
        try:
            os.kill(os.getpid(), signal.SIGTERM)
        finally:
            handler.disarm()
        assert handler.preempted.is_set()
        assert os.path.exists(ckpt)
        resumed, state = PreemptionHandler.resume(ckpt)
        assert state["iteration"] == net.iteration
        assert resumed.iteration == net.iteration
        for pl, pr in zip(net.params, resumed.params):
            for k in pl:
                np.testing.assert_allclose(np.asarray(pl[k]),
                                           np.asarray(pr[k]), rtol=1e-6)

    def test_context_manager_and_restore_handler(self, tmp_path, rng):
        net = self._net(rng)
        prev = signal.getsignal(signal.SIGTERM)
        with PreemptionHandler(net, str(tmp_path / "c.zip")):
            assert signal.getsignal(signal.SIGTERM) != prev
        assert signal.getsignal(signal.SIGTERM) == prev

    def test_atomic_save_no_partial_zip(self, tmp_path, rng):
        net = self._net(rng)
        h = PreemptionHandler(net, str(tmp_path / "a.zip"))
        h.save()
        assert not os.path.exists(str(tmp_path / "a.zip") + ".tmp")
        # no sidecar: state travels inside the single atomic zip
        assert not os.path.exists(str(tmp_path / "a.zip") + ".state.json")
        m, state = PreemptionHandler.resume(str(tmp_path / "a.zip"))
        assert m is not None and state["iteration"] == net.iteration

    def test_deferred_save_at_step_boundary(self, tmp_path, rng):
        """A save deferred from inside a donating step completes via
        maybe_save_pending (the armed listener hook calls it)."""
        net = self._net(rng)
        ckpt = str(tmp_path / "d.zip")
        h = PreemptionHandler(net, ckpt)
        h.preempted.set()  # as if the handler deferred
        assert h.maybe_save_pending() is True
        assert h.saved.is_set() and os.path.exists(ckpt)
        assert h.maybe_save_pending() is False  # idempotent

    def test_arm_registers_listener_hook(self, tmp_path, rng):
        net = self._net(rng)
        n_before = len(net.listeners)
        h = PreemptionHandler(net, str(tmp_path / "h.zip")).arm()
        assert len(net.listeners) == n_before + 1
        h.disarm()
        assert len(net.listeners) == n_before
