"""Cross-slice DCN bridge tests: two independently-training "slices"
exchanging threshold-compressed updates over the streaming transport
(the reference's inter-node Aeron path, SURVEY.md §5 "distributed
communication backend")."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.parallel.dcn import CrossSliceGradientBridge
from deeplearning4j_tpu.streaming import EmbeddedBroker, SocketConsumer, SocketPublisher


def _net(seed):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _data(n, seed):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 3, n)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    x[np.arange(n), y] += 2.5
    return DataSet(x, np.eye(3, dtype=np.float32)[y])


class _BrokerEndpoint:
    """publish/poll adapter over one EmbeddedBroker topic."""

    def __init__(self, broker, topic, group):
        self.broker = broker
        self.topic = topic
        self.group = group
        broker.subscribe(topic, group)

    def publish(self, payload):
        self.broker.publish(self.topic, payload)

    def poll(self, timeout=0.0):
        return self.broker.poll(self.topic, self.group, timeout=timeout or 0.01)


class TestCrossSliceBridge:
    def test_two_slices_converge_together(self):
        """Each slice trains on ITS OWN disjoint shard; with the bridge, both
        end up learning the full distribution (the cross-node capability the
        reference's Aeron path provides)."""
        broker = EmbeddedBroker()
        # both slices publish to one topic; each consumes under its own group
        end_a = _BrokerEndpoint(broker, "grads", "a")
        end_b = _BrokerEndpoint(broker, "grads", "b")
        bridge_a = CrossSliceGradientBridge(end_a, end_a, threshold=5e-4,
                                            slice_id="A")
        bridge_b = CrossSliceGradientBridge(end_b, end_b, threshold=5e-4,
                                            slice_id="B")

        net_a, net_b = _net(1), _net(1)  # same init, as after a broadcast
        # disjoint shards: A never sees B's classes distribution balance
        full = _data(512, seed=0)
        xa, ya = full.features[:256], full.labels[:256]
        xb, yb = full.features[256:], full.labels[256:]

        for _ in range(30):
            net_a.fit(DataSet(xa, ya))
            net_b.fit(DataSet(xb, yb))
            bridge_a.publish_update(net_a.params)
            bridge_b.publish_update(net_b.params)
            net_a.params, _ = bridge_a.poll_and_apply(net_a.params)
            net_b.params, _ = bridge_b.poll_and_apply(net_b.params)

        ev_a = net_a.evaluate(ListDataSetIterator(DataSet(xb, yb), 256))
        ev_b = net_b.evaluate(ListDataSetIterator(DataSet(xa, ya), 256))
        # each slice generalizes to the OTHER slice's shard
        assert ev_a.accuracy() > 0.85
        assert ev_b.accuracy() > 0.85
        # and the two replicas stay numerically close (bounded divergence)
        for la, lb in zip(net_a.params, net_b.params):
            for k in la:
                diff = float(np.max(np.abs(np.asarray(la[k]) - np.asarray(lb[k]))))
                assert diff < 0.5, f"replicas diverged on {k}: {diff}"

    def test_socket_transport_between_bridges(self):
        """Same exchange over real TCP sockets (the cross-host wire)."""
        cons_a, cons_b = SocketConsumer(), SocketConsumer()
        pub_to_b = SocketPublisher("127.0.0.1", cons_b.port)
        pub_to_a = SocketPublisher("127.0.0.1", cons_a.port)
        try:
            bridge_a = CrossSliceGradientBridge(pub_to_b, cons_a,
                                                threshold=1e-3, slice_id="A")
            bridge_b = CrossSliceGradientBridge(pub_to_a, cons_b,
                                                threshold=1e-3, slice_id="B")
            net_a, net_b = _net(1), _net(1)
            ds = _data(128, seed=1)
            for _ in range(5):
                net_a.fit(ds)
                bridge_a.publish_update(net_a.params)
            import time
            time.sleep(0.2)  # let frames land
            before = [np.asarray(v).copy() for v in net_b.params[0].values()]
            net_b.params, applied = bridge_b.poll_and_apply(net_b.params,
                                                            timeout=1.0)
            assert applied >= 1
            after = list(net_b.params[0].values())
            assert any(not np.allclose(b, np.asarray(a))
                       for b, a in zip(before, after))
        finally:
            pub_to_a.close()
            pub_to_b.close()
            cons_a.close()
            cons_b.close()

    def test_dense_fallback_when_sparse_overflows(self):
        """Updates too dense for the sparse capacity must still sync (the
        reference's bitmap worst case), not silently stall."""
        broker = EmbeddedBroker()
        a = _BrokerEndpoint(broker, "d", "ga")
        b = _BrokerEndpoint(broker, "d", "gb")
        # tiny capacity + low threshold → every tensor overflows the format
        bridge_a = CrossSliceGradientBridge(a, a, threshold=1e-8,
                                            capacity_fraction=0.01,
                                            slice_id="A")
        bridge_b = CrossSliceGradientBridge(b, b, threshold=1e-8,
                                            slice_id="B")
        net_a, net_b = _net(1), _net(1)
        bridge_a.publish_update(net_a.params)  # baseline (empty → no frame)
        bridge_b.poll_and_apply(net_b.params)
        net_a.fit(_data(64, seed=4))
        sent = bridge_a.publish_update(net_a.params)
        assert sent > 0
        new_params, applied = bridge_b.poll_and_apply(net_b.params, timeout=0.5)
        assert applied == 1
        # B's params moved toward A's (dense payload applied)
        moved = any(
            not np.allclose(np.asarray(o[k]), np.asarray(n[k]))
            for o, n in zip(net_b.params, new_params) for k in o)
        assert moved
        # the overflowing tensor (layer-0 W: 72 elems >> capacity 16) went
        # through the dense path and its residual is fully flushed; small
        # tensors that fit the sparse format keep sub-threshold remainder
        assert float(np.abs(bridge_a._residual[0]["W"]).sum()) < 1e-6

    def test_malformed_frame_skipped(self):
        """Truncated/corrupt frames log-and-skip instead of killing training."""
        broker = EmbeddedBroker()
        a = _BrokerEndpoint(broker, "m", "ga")
        b = _BrokerEndpoint(broker, "m", "gb")
        bridge_a = CrossSliceGradientBridge(a, a, threshold=1e-8,
                                            capacity_fraction=0.01,
                                            slice_id="A")
        bridge_b = CrossSliceGradientBridge(b, b, threshold=1e-8,
                                            slice_id="B")
        net_a, net_b = _net(1), _net(1)
        bridge_a.publish_update(net_a.params)
        net_a.fit(_data(64, seed=6))
        bridge_a.publish_update(net_a.params)
        # corrupt the frame in flight: truncate by a few bytes
        frame = b.broker.poll("m", "gb", timeout=0.5)
        assert frame is not None
        b.broker.publish("m", frame[:-5])
        # also inject pure garbage
        b.broker.publish("m", b"\x00\x00\x00\x02{}")
        params, applied = bridge_b.poll_and_apply(net_b.params, timeout=0.2)
        assert applied == 0  # nothing valid applied, nothing crashed

    def test_no_frame_when_nothing_passes(self):
        broker = EmbeddedBroker()
        end = _BrokerEndpoint(broker, "e", "g")
        bridge = CrossSliceGradientBridge(end, end, threshold=1e6, slice_id="Z")
        net = _net(5)
        assert bridge.publish_update(net.params) == 0  # baseline, nothing moved
        assert end.poll(timeout=0.05) is None  # no frame hit the wire

    def test_residual_carries_subthreshold_mass(self):
        broker = EmbeddedBroker()
        end = _BrokerEndpoint(broker, "t", "g")
        bridge = CrossSliceGradientBridge(end, end, threshold=1e6,
                                          slice_id="X")
        net = _net(2)
        bridge.publish_update(net.params)  # baseline snapshot
        ds = _data(64, seed=3)
        net.fit(ds)
        bridge.publish_update(net.params)
        total = sum(float(np.abs(r).sum())
                    for layer in bridge._residual.values() for r in layer.values())
        assert total > 0  # everything stayed in the residual
        net.fit(ds)
        bridge.publish_update(net.params)
        total2 = sum(float(np.abs(r).sum())
                     for layer in bridge._residual.values() for r in layer.values())
        assert total2 > total  # residual accumulates across rounds
