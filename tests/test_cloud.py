"""Cloud provisioning & storage (deeplearning4j-aws parity, TPU-native).

Every execution path is driven against an injected fake runner or the
``file://`` storage scheme — the same strategy the reference cannot use (its
AWS module ships untested); here the orchestration logic is fully covered.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.cloud import (
    BucketDataSetIterator,
    ClusterProvisioner,
    HostProvisioner,
    ObjectStorage,
    TpuJobRunner,
    TpuProvisioner,
)
from deeplearning4j_tpu.datasets.dataset import DataSet


class FakeRunner:
    """Records commands; scripted replies by subcommand."""

    def __init__(self, states=None):
        self.calls = []
        self.states = list(states or [])  # successive describe replies

    def __call__(self, cmd):
        self.calls.append(cmd)
        if "describe" in cmd:
            return self.states.pop(0) if self.states else "READY"
        return "ok"


class TestCommandBuilders:
    def test_create_delete_ssh(self):
        p = TpuProvisioner("proj", "us-central2-b")
        c = p.create_command("node1", accelerator_type="v5p-16")
        assert c[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "create",
                         "node1"]
        assert "--accelerator-type=v5p-16" in c
        assert "--project=proj" in c and "--zone=us-central2-b" in c
        assert "--quiet" in p.delete_command("node1")
        s = p.ssh_command("node1", "hostname", worker="0")
        assert "--worker=0" in s and "--command=hostname" in s

    def test_scp_and_upload_and_run(self, tmp_path):
        runner = FakeRunner()
        p = TpuProvisioner("proj", "z", runner=runner)
        host = HostProvisioner(p, "node1")
        script = tmp_path / "setup.sh"
        script.write_text("#!/bin/sh\necho hi\n")
        host.upload_and_run(str(script), root_dir="/tmp")
        scp, ssh = runner.calls
        assert scp[4] == "scp" and scp[5] == str(script)
        assert scp[6] == "node1:/tmp/setup.sh"
        assert any("chmod +x /tmp/setup.sh && /tmp/setup.sh" in a for a in ssh)


class TestClusterProvisioner:
    def test_create_wait_provision_teardown(self, tmp_path):
        # two workers; first poll: worker 0 CREATING, worker 1 READY;
        # second poll: worker 0 READY
        runner = FakeRunner(states=["CREATING", "READY", "READY"])
        p = TpuProvisioner("proj", "z", runner=runner)
        cluster = ClusterProvisioner(p, num_workers=2, name_prefix="t")
        assert cluster.names == ["t-0", "t-1"]
        cluster.create()
        creates = [c for c in runner.calls if "create" in c]
        assert len(creates) == 2
        cluster.block_till_all_running(poll_seconds=0.0)
        script = tmp_path / "w.sh"
        script.write_text("echo worker\n")
        outs = cluster.provision_workers(str(script))
        assert len(outs) == 2
        cluster.teardown()
        deletes = [c for c in runner.calls if "delete" in c]
        assert len(deletes) == 2

    def test_wait_times_out(self):
        runner = FakeRunner(states=["CREATING"] * 50)
        p = TpuProvisioner("proj", "z", runner=runner)
        cluster = ClusterProvisioner(p, num_workers=1)
        with pytest.raises(TimeoutError):
            cluster.block_till_all_running(poll_seconds=0.0, timeout=0.0)

    def test_job_runner_tears_down_on_failure(self, tmp_path):
        class Boom(FakeRunner):
            def __call__(self, cmd):
                super().__call__(cmd)
                if "scp" in cmd:
                    raise RuntimeError("network down")
                return "READY" if "describe" in cmd else "ok"

        runner = Boom()
        p = TpuProvisioner("proj", "z", runner=runner)
        cluster = ClusterProvisioner(p, num_workers=1)
        job = TpuJobRunner(cluster)
        script = tmp_path / "j.sh"
        script.write_text("echo job\n")
        with pytest.raises(RuntimeError):
            job.run(str(script))
        # the slice was deleted despite the failure (ephemeral semantics)
        assert any("delete" in c for c in runner.calls)

    def test_job_runner_keep_alive(self, tmp_path):
        runner = FakeRunner()
        p = TpuProvisioner("proj", "z", runner=runner)
        cluster = ClusterProvisioner(p, num_workers=1)
        job = TpuJobRunner(cluster, keep_alive=True)
        script = tmp_path / "j.sh"
        script.write_text("echo job\n")
        outs = job.run(str(script), setup_script=str(script))
        assert outs == ["ok"]
        assert not any("delete" in c for c in runner.calls)


class TestBucketDataSetIterator:
    def test_stage_and_iterate_file_scheme(self, tmp_path):
        rng = np.random.default_rng(0)
        dss = [DataSet(rng.normal(size=(4, 3)).astype(np.float32),
                       np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)])
               for _ in range(3)]
        uri = f"file://{tmp_path}/bucket"
        keys = BucketDataSetIterator.stage(dss, uri)
        assert keys == [f"part-{i:05d}.npz" for i in range(3)]
        it = BucketDataSetIterator(uri)
        got = list(it)
        assert len(got) == 3
        for a, b in zip(dss, got):
            np.testing.assert_allclose(a.features, b.features)
            np.testing.assert_allclose(a.labels, b.labels)
        # reset() replays (DataSetIterator contract)
        it.reset()
        assert it.has_next()
        assert len(list(it)) == 3

    def test_masks_round_trip(self, tmp_path):
        rng = np.random.default_rng(1)
        ds = DataSet(rng.normal(size=(2, 5, 3)).astype(np.float32),
                     rng.normal(size=(2, 5, 2)).astype(np.float32),
                     features_mask=np.ones((2, 5), np.float32),
                     labels_mask=np.ones((2, 5), np.float32))
        uri = f"file://{tmp_path}/b2"
        BucketDataSetIterator.stage([ds], uri)
        got = next(iter(BucketDataSetIterator(uri)))
        np.testing.assert_allclose(got.features_mask, ds.features_mask)

    def test_trains_from_bucket(self, tmp_path):
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        rng = np.random.default_rng(2)
        yc = rng.integers(0, 2, 32)
        x = rng.normal(size=(32, 4)).astype(np.float32)
        x[np.arange(32), yc] += 2.0
        dss = [DataSet(x[i:i + 8], np.eye(2, dtype=np.float32)[yc[i:i + 8]])
               for i in range(0, 32, 8)]
        uri = f"file://{tmp_path}/train"
        BucketDataSetIterator.stage(dss, uri)
        conf = (NeuralNetConfiguration.builder().seed(0).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(BucketDataSetIterator(uri), epochs=5)
        assert np.isfinite(float(net.score_))


class TestObjectStorageFileScheme:
    def test_upload_download(self, tmp_path):
        src = tmp_path / "a.txt"
        src.write_text("payload")
        uri = f"file://{tmp_path}/store/a.txt"
        st = ObjectStorage()
        st.upload(str(src), uri)
        dst = tmp_path / "back.txt"
        st.download(uri, str(dst))
        assert dst.read_text() == "payload"


class TestReviewDrivenFixes:
    def test_nested_keys_and_subdirs(self, tmp_path):
        rng = np.random.default_rng(0)
        ds = DataSet(rng.normal(size=(2, 3)).astype(np.float32),
                     np.eye(2, dtype=np.float32)[[0, 1]])
        uri = f"file://{tmp_path}/root"
        BucketDataSetIterator.stage([ds], f"{uri}/sub")
        it = BucketDataSetIterator(uri)
        assert it._keys == [os.path.join("sub", "part-00000.npz")]
        got = next(iter(it))
        np.testing.assert_allclose(got.features, ds.features)

    def test_zero_workers_noop(self, tmp_path):
        runner = FakeRunner()
        cluster = ClusterProvisioner(TpuProvisioner("p", "z", runner=runner),
                                     num_workers=0)
        assert cluster.create() == []
        s = tmp_path / "x.sh"
        s.write_text("echo\n")
        assert cluster.provision_workers(str(s)) == []
        cluster.teardown()
        assert runner.calls == []

    def test_partial_create_failure_still_tears_down(self, tmp_path):
        class FailSecondCreate(FakeRunner):
            def __call__(self, cmd):
                super().__call__(cmd)
                if "create" in cmd and cmd[5].endswith("-1"):
                    raise RuntimeError("quota")
                return "READY" if "describe" in cmd else "ok"

        runner = FailSecondCreate()
        cluster = ClusterProvisioner(TpuProvisioner("p", "z", runner=runner),
                                     num_workers=2)
        s = tmp_path / "j.sh"
        s.write_text("echo\n")
        with pytest.raises(RuntimeError):
            TpuJobRunner(cluster).run(str(s))
        assert any("delete" in c for c in runner.calls)  # no leaked VMs

    def test_script_paths_are_shell_quoted(self, tmp_path):
        runner = FakeRunner()
        p = TpuProvisioner("proj", "z", runner=runner)
        script = tmp_path / "my setup.sh"
        script.write_text("echo hi\n")
        HostProvisioner(p, "n").upload_and_run(str(script), root_dir="/tmp")
        ssh = runner.calls[-1]
        cmd_arg = next(a for a in ssh if a.startswith("--command="))
        assert "'/tmp/my setup.sh'" in cmd_arg

    def test_home_rooted_script_uses_dollar_home(self, tmp_path):
        runner = FakeRunner()
        p = TpuProvisioner("proj", "z", runner=runner)
        script = tmp_path / "s.sh"
        script.write_text("echo\n")
        HostProvisioner(p, "n").upload_and_run(str(script), root_dir="~")
        cmd_arg = next(a for a in runner.calls[-1] if a.startswith("--command="))
        assert '"$HOME"/s.sh' in cmd_arg and "'~" not in cmd_arg

    def test_teardown_survives_missing_vms(self):
        class DeleteBoom(FakeRunner):
            def __call__(self, cmd):
                super().__call__(cmd)
                if "delete" in cmd and cmd[5].endswith("-1"):
                    raise RuntimeError("not found")
                return "ok"
        import warnings
        runner = DeleteBoom()
        cluster = ClusterProvisioner(TpuProvisioner("p", "z", runner=runner),
                                     num_workers=2)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            cluster.teardown()  # must not raise
        deletes = [c for c in runner.calls if "delete" in c]
        assert len(deletes) == 2
        assert any("could not delete" in str(x.message) for x in w)

    def test_home_rooted_metacharacters_stay_quoted(self, tmp_path):
        runner = FakeRunner()
        p = TpuProvisioner("proj", "z", runner=runner)
        script = tmp_path / "se`tup`.sh"
        script.write_text("echo\n")
        HostProvisioner(p, "n").upload_and_run(str(script), root_dir="~")
        cmd_arg = next(a for a in runner.calls[-1] if a.startswith("--command="))
        assert '"$HOME"/' in cmd_arg
        # the backtick basename is single-quoted -> no remote substitution
        assert "'se`tup`.sh'" in cmd_arg


class TestClusterSetupCli:
    def test_dry_run_and_injected_runner(self, tmp_path, capsys):
        from deeplearning4j_tpu.cli import cluster_setup_main
        script = tmp_path / "w.sh"
        script.write_text("echo\n")
        runner = FakeRunner()
        cluster = cluster_setup_main(
            ["-w", "2", "--project", "p", "--zone", "z",
             "--accelerator-type", "v5e-4", "--wscript", str(script)],
            runner=runner)
        assert cluster.names == ["dl4j-tpu-0", "dl4j-tpu-1"]
        kinds = [c[4] for c in runner.calls]
        assert kinds.count("create") == 2
        assert kinds.count("scp") == 2 and kinds.count("ssh") == 2
        # dry run prints commands, touches nothing real
        cluster_setup_main(["-w", "1", "--project", "p", "--zone", "z",
                            "--dry-run"])
        out = capsys.readouterr().out
        assert "gcloud compute tpus tpu-vm create" in out
