"""Evaluation metrics tests (eval/EvalTest.java role): confusion-matrix
classification metrics, regression metrics, ROC family, binary multi-label
evaluation, and calibration — validated against hand-computed values."""

import numpy as np
import pytest

from deeplearning4j_tpu.eval.binary import EvaluationBinary
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass


def onehot(idx, n):
    return np.eye(n, dtype=np.float64)[idx]


class TestEvaluation:
    def _eval_fixed(self):
        # 3 classes; true: [0,0,1,1,2,2]; pred: [0,1,1,1,2,0]
        e = Evaluation(3)
        truth = np.array([0, 0, 1, 1, 2, 2])
        pred_cls = np.array([0, 1, 1, 1, 2, 0])
        e.eval(onehot(truth, 3), onehot(pred_cls, 3))
        return e

    def test_confusion_and_metrics(self):
        e = self._eval_fixed()
        cm = e.confusion_matrix()
        assert cm[0, 0] == 1 and cm[0, 1] == 1
        assert cm[1, 1] == 2
        assert cm[2, 2] == 1 and cm[2, 0] == 1
        assert e.accuracy() == pytest.approx(4 / 6)
        # class 1: tp=2, fp=1, fn=0
        assert e.precision(1) == pytest.approx(2 / 3)
        assert e.recall(1) == pytest.approx(1.0)
        assert e.f1(1) == pytest.approx(2 * (2 / 3) / (2 / 3 + 1.0))

    def test_merge_and_json(self):
        a = self._eval_fixed()
        b = self._eval_fixed()
        a.merge(b)
        assert a.confusion_matrix().sum() == 12
        rt = Evaluation.from_json(a.to_json())
        assert rt.accuracy() == pytest.approx(a.accuracy())
        assert "Accuracy" in a.stats() or "accuracy" in a.stats().lower()

    def test_time_series_with_mask(self):
        e = Evaluation(2)
        labels = onehot(np.array([[0, 1, 0], [1, 0, 1]]).ravel(), 2).reshape(2, 3, 2)
        preds = labels.copy()  # perfect predictions
        mask = np.array([[1, 1, 0], [1, 0, 0]], np.float64)
        e.eval_time_series(labels, preds, labels_mask=mask)
        assert e.confusion_matrix().sum() == 3  # only unmasked steps counted
        assert e.accuracy() == 1.0


class TestRegressionEvaluation:
    def test_known_values(self):
        r = RegressionEvaluation()
        labels = np.array([[1.0], [2.0], [3.0], [4.0]])
        preds = np.array([[1.5], [2.0], [2.5], [4.5]])
        r.eval(labels, preds)
        err = labels - preds
        assert r.mean_squared_error() == pytest.approx(float(np.mean(err ** 2)))
        assert r.mean_absolute_error() == pytest.approx(float(np.mean(np.abs(err))))
        assert r.root_mean_squared_error() == pytest.approx(
            float(np.sqrt(np.mean(err ** 2))))
        # matches numpy's definition exactly
        assert r.pearson_correlation() == pytest.approx(
            float(np.corrcoef(labels[:, 0], preds[:, 0])[0, 1]), abs=1e-9)
        assert r.r_squared() == pytest.approx(
            1 - np.sum(err ** 2) / np.sum((labels - labels.mean()) ** 2),
            abs=1e-6)

    def test_multi_column(self):
        r = RegressionEvaluation()
        labels = np.array([[1.0, 10.0], [2.0, 20.0]])
        preds = np.array([[1.0, 12.0], [2.0, 18.0]])
        r.eval(labels, preds)
        assert r.mean_squared_error(0) == pytest.approx(0.0)
        assert r.mean_squared_error(1) == pytest.approx(4.0)
        assert r.average_mean_squared_error() == pytest.approx(2.0)
        assert "MSE" in r.stats() or "mse" in r.stats().lower()


class TestROC:
    def test_perfect_separation_auc_one(self):
        roc = ROC()
        labels = np.array([0, 0, 1, 1])
        scores = np.array([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]])
        roc.eval(onehot(labels, 2), scores)
        assert roc.calculate_auc() == pytest.approx(1.0)
        assert roc.calculate_auc_pr() == pytest.approx(1.0)

    def test_random_scores_auc_half(self, rng):
        roc = ROC()
        n = 4000
        labels = rng.integers(0, 2, n)
        scores = rng.random(n)
        roc.eval(labels, np.stack([1 - scores, scores], 1))
        assert abs(roc.calculate_auc() - 0.5) < 0.05

    def test_inverted_scores_auc_zero(self):
        roc = ROC()
        labels = np.array([0, 0, 1, 1])
        scores = np.array([[0.1, 0.9], [0.2, 0.8], [0.8, 0.2], [0.9, 0.1]])
        roc.eval(onehot(labels, 2), scores)
        assert roc.calculate_auc() == pytest.approx(0.0)

    def test_roc_binary_per_column(self):
        rb = ROCBinary()
        labels = np.array([[1, 0], [1, 0], [0, 1], [0, 1]], np.float64)
        # col 0 scored perfectly, col 1 inverted
        scores = np.array([[0.9, 0.9], [0.8, 0.8], [0.1, 0.2], [0.2, 0.1]])
        rb.eval(labels, scores)
        assert rb.calculate_auc(0) == pytest.approx(1.0)
        assert rb.calculate_auc(1) == pytest.approx(0.0)

    def test_roc_multiclass_one_vs_all(self):
        rm = ROCMultiClass()
        truth = np.array([0, 1, 2, 0, 1, 2])
        scores = onehot(truth, 3) * 0.8 + 0.1  # correct class highest
        rm.eval(onehot(truth, 3), scores)
        for c in range(3):
            assert rm.calculate_auc(c) == pytest.approx(1.0)


class TestEvaluationBinary:
    def test_per_label_metrics(self):
        eb = EvaluationBinary(decision_threshold=0.5)
        labels = np.array([[1, 0], [1, 1], [0, 1], [0, 0]], np.float64)
        preds = np.array([[0.9, 0.2], [0.8, 0.4], [0.3, 0.9], [0.1, 0.6]])
        eb.eval(labels, preds)
        # col 0: predictions [1,1,0,0] vs [1,1,0,0] → perfect
        assert eb.accuracy(0) == pytest.approx(1.0)
        assert eb.f1(0) == pytest.approx(1.0)
        # col 1: predictions [0,0,1,1] vs [0,1,1,0] → 2/4 correct
        assert eb.accuracy(1) == pytest.approx(0.5)


class TestEvaluationCalibration:
    def test_perfectly_calibrated(self, rng):
        cal = EvaluationCalibration(reliability_bins=10)
        n = 20000
        p = rng.random(n)
        labels = (rng.random(n) < p).astype(np.float64)
        cal.eval(np.stack([1 - labels, labels], 1), np.stack([1 - p, p], 1))
        assert cal.expected_calibration_error() < 0.03

    def test_overconfident_model_has_high_ece(self, rng):
        cal = EvaluationCalibration(reliability_bins=10)
        n = 5000
        labels = rng.integers(0, 2, n).astype(np.float64)  # coin flips
        conf = np.full(n, 0.99)  # but the model claims 99% confidence
        preds = np.stack([1 - conf, conf], 1)
        cal.eval(np.stack([1 - labels, labels], 1), preds)
        assert cal.expected_calibration_error() > 0.3


class TestCalibrationPerClass:
    """Per-class depth (EvaluationCalibration.java getReliabilityDiagram /
    getResidualPlot / getProbabilityHistogram parity)."""

    def _three_class(self, rng, n=6000):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal = EvaluationCalibration(reliability_bins=10, histogram_bins=20)
        cls = rng.integers(0, 3, n)
        labels = np.eye(3)[cls]
        logits = rng.normal(0, 1, (n, 3)) + 2.0 * labels
        preds = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
        cal.eval(labels, preds)
        return cal, labels, preds

    def test_per_class_reliability(self, rng):
        cal, labels, preds = self._three_class(rng)
        for c in range(3):
            d = cal.get_reliability_diagram(c)
            assert len(d.mean_predicted_value) == len(d.frac_positives) > 0
            # curve must be increasing-ish: low-prob bins less often positive
            assert d.frac_positives[0] < d.frac_positives[-1]

    def test_probability_histogram_selects_labelled_class(self, rng):
        cal, labels, preds = self._three_class(rng)
        h1 = cal.get_probability_histogram(1)
        # counts = histogram of P(class 1) over examples LABELLED class 1
        want, _ = np.histogram(preds[labels[:, 1] > 0.5, 1],
                               bins=20, range=(0.0, 1.0))
        np.testing.assert_array_equal(h1.counts, want)
        # overall = every (example, class) probability
        hall = cal.get_probability_histogram_all_classes()
        wall, _ = np.histogram(preds.ravel(), bins=20, range=(0.0, 1.0))
        np.testing.assert_array_equal(hall.counts, wall)

    def test_residual_plots(self, rng):
        cal, labels, preds = self._three_class(rng)
        r0 = cal.get_residual_plot(0)
        resid = np.abs(labels - preds)
        want, _ = np.histogram(resid[labels[:, 0] > 0.5, 0],
                               bins=20, range=(0.0, 1.0))
        np.testing.assert_array_equal(r0.counts, want)
        rall = cal.get_residual_plot_all_classes()
        wall, _ = np.histogram(resid.ravel(), bins=20, range=(0.0, 1.0))
        np.testing.assert_array_equal(rall.counts, wall)

    def test_label_and_prediction_counts(self, rng):
        cal, labels, preds = self._three_class(rng)
        np.testing.assert_array_equal(cal.label_counts,
                                      labels.sum(0).astype(np.int64))
        np.testing.assert_array_equal(cal.prediction_counts,
                                      np.bincount(preds.argmax(1), minlength=3))

    def test_merge_and_reset(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal_a, labels, preds = self._three_class(rng, n=512)
        cal_b = EvaluationCalibration(reliability_bins=10, histogram_bins=20)
        cal_b.eval(labels, preds)
        both = EvaluationCalibration(reliability_bins=10, histogram_bins=20)
        both.eval(labels, preds)
        both.eval(labels, preds)
        cal_a.merge(cal_b)
        np.testing.assert_array_equal(cal_a.prob_by_class, both.prob_by_class)
        np.testing.assert_array_equal(cal_a.rdiag_total, both.rdiag_total)
        cal_a.reset()
        assert cal_a.num_classes == -1

    def test_per_example_mask(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal = EvaluationCalibration(histogram_bins=20)
        labels = np.eye(2)[rng.integers(0, 2, 100)]
        preds = rng.random((100, 2))
        preds = preds / preds.sum(1, keepdims=True)
        keep = (rng.random(100) > 0.5).astype(np.float64)
        cal.eval(labels, preds, mask=keep)
        ref = EvaluationCalibration(histogram_bins=20)
        ref.eval(labels[keep > 0], preds[keep > 0])
        np.testing.assert_array_equal(cal.prob_overall, ref.prob_overall)
        np.testing.assert_array_equal(cal.rdiag_total, ref.rdiag_total)

    def test_ui_calibration_module(self, rng):
        from deeplearning4j_tpu.ui.modules import CalibrationModule
        cal, _, _ = self._three_class(rng, n=512)
        mod = CalibrationModule(cal)
        code, summary = mod.handle("/calibration")
        assert code == 200 and summary["num_classes"] == 3
        assert 0.0 <= summary["expected_calibration_error"] <= 1.0
        code, rel = mod.handle("/calibration/reliability/1")
        assert code == 200 and len(rel["mean_predicted_value"]) > 0
        code, hist = mod.handle("/calibration/probabilities/2")
        assert code == 200 and len(hist["counts"]) == 20
        code, resid = mod.handle("/calibration/residual")
        assert code == 200 and sum(resid["counts"]) == 512 * 3
        code, panel = mod.handle("/calibration/panel")
        assert code == 200 and "svg" in panel["html"].lower()
        # unattached module 404s cleanly
        code, err = CalibrationModule().handle("/calibration")
        assert code == 404

    def test_reset_clears_and_fresh_instance_is_safe(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        fresh = EvaluationCalibration()
        assert fresh.expected_calibration_error() == 0.0
        assert fresh.get_residual_plot_all_classes().counts.sum() == 0
        cal, _, _ = self._three_class(rng, n=256)
        assert cal.expected_calibration_error() > 0
        cal.reset()
        assert cal.expected_calibration_error() == 0.0
        assert cal.get_probability_histogram_all_classes().counts.sum() == 0
        with pytest.raises(ValueError):
            cal.get_reliability_diagram(0)

    def test_class_index_validation(self, rng):
        from deeplearning4j_tpu.ui.modules import CalibrationModule
        cal, _, _ = self._three_class(rng, n=128)
        with pytest.raises(IndexError):
            cal.get_residual_plot(-1)
        with pytest.raises(IndexError):
            cal.get_probability_histogram(3)
        mod = CalibrationModule(cal)
        assert mod.handle("/calibration/reliability/-1")[0] == 404
        assert mod.handle("/calibration/probabilities/99")[0] == 404

    def test_3d_per_output_mask(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        labels = np.eye(2)[rng.integers(0, 2, (4, 5))]      # [N,T,C]
        preds = rng.random((4, 5, 2))
        preds = preds / preds.sum(-1, keepdims=True)
        m3 = (rng.random((4, 5, 2)) > 0.4).astype(np.float64)
        cal = EvaluationCalibration(histogram_bins=20)
        cal.eval(labels, preds, mask=m3)                     # must not crash
        assert cal.prob_overall.sum() == int(m3.sum())

    def test_out_of_range_probs_counted_in_edge_bins(self):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal = EvaluationCalibration(histogram_bins=10)
        labels = np.array([[1.0, 0.0]])
        preds = np.array([[-0.05, 1.05]])  # drifted out of [0,1]
        cal.eval(labels, preds)
        assert cal.prob_overall.sum() == 2  # nothing silently dropped
        assert cal.prob_overall[0] == 1 and cal.prob_overall[-1] == 1

    def test_merge_rejects_class_mismatch(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        a = EvaluationCalibration()
        a.eval(np.eye(3)[[0, 1]], np.full((2, 3), 1 / 3))
        b = EvaluationCalibration()
        b.eval(np.ones((2, 1)), np.full((2, 1), 0.5))
        with pytest.raises(ValueError, match="class counts"):
            a.merge(b)

    def test_prediction_counts_respect_per_output_mask(self):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal = EvaluationCalibration()
        labels = np.array([[0.0, 1.0, 0.0]])
        preds = np.array([[0.1, 0.2, 0.7]])   # argmax=2 but class 2 masked
        m = np.array([[1.0, 1.0, 0.0]])
        cal.eval(labels, preds, mask=m)
        np.testing.assert_array_equal(cal.prediction_counts, [0, 1, 0])


class TestTopNAccuracy:
    """Evaluation.java:144 constructor + :437 counting: top-N correct when
    fewer than N probabilities are strictly greater than the true class's."""

    def test_imagenet_shape_logits(self):
        rng = np.random.default_rng(0)
        n, c = 512, 1000                      # ImageNet-shape output
        true = rng.integers(0, c, n)
        preds = rng.dirichlet(np.ones(c), size=n).astype(np.float64)
        # plant: first 200 exactly right, next 150 true class at rank 2-5,
        # rest leave random (true prob tiny)
        for i in range(200):
            preds[i, true[i]] = 1.0           # rank 1
        for i in range(200, 350):
            order = np.argsort(-preds[i])
            k = int(rng.integers(1, 5))       # rank 2..5
            preds[i, true[i]] = (preds[i, order[k - 1]]
                                 + preds[i, order[k]]) / 2
        labels = np.eye(c)[true]
        e1 = Evaluation(top_n=1)
        e1.eval(labels, preds)
        e5 = Evaluation(top_n=5)
        e5.eval(labels, preds)
        assert e5.top_n_accuracy() >= e5.accuracy()
        assert e5.top_n_accuracy() == pytest.approx(350 / 512, abs=0.02)
        assert e1.top_n_accuracy() == e1.accuracy()
        assert "Top 5 Accuracy" in e5.stats()

    def test_exact_counting_small(self):
        labels = np.eye(4)[[0, 1, 2, 3]]
        preds = np.array([
            [0.4, 0.3, 0.2, 0.1],   # true 0 at rank 1
            [0.4, 0.3, 0.2, 0.1],   # true 1 at rank 2
            [0.4, 0.3, 0.2, 0.1],   # true 2 at rank 3
            [0.4, 0.3, 0.2, 0.1],   # true 3 at rank 4
        ])
        e2 = Evaluation(top_n=2)
        e2.eval(labels, preds)
        assert e2.top_n_correct_count == 2 and e2.top_n_total_count == 4
        assert e2.top_n_accuracy() == pytest.approx(0.5)
        e3 = Evaluation(top_n=3)
        e3.eval(labels, preds)
        assert e3.top_n_accuracy() == pytest.approx(0.75)

    def test_merge_and_serde_carry_topn(self):
        labels = np.eye(3)[[0, 1]]
        preds = np.array([[0.5, 0.3, 0.2], [0.5, 0.3, 0.2]])
        a = Evaluation(top_n=2)
        a.eval(labels, preds)
        b = Evaluation(top_n=2)
        b.eval(labels, preds)
        a.merge(b)
        assert a.top_n_total_count == 4 and a.top_n_correct_count == 4
        back = Evaluation.from_json(a.to_json())
        assert back.top_n == 2
        assert back.top_n_accuracy() == pytest.approx(1.0)


class TestPredictionRecording:
    """Evaluation.java:1481/:1506/:1583 — metadata-backed error drilldown,
    wired through records.py RecordMetaData."""

    def _eval_with_meta(self):
        from deeplearning4j_tpu.datasets.records import RecordMetaData
        labels = np.eye(3)[[0, 0, 1, 2, 2]]
        preds = np.array([
            [0.8, 0.1, 0.1],   # 0 → 0 correct
            [0.1, 0.8, 0.1],   # 0 → 1 ERROR
            [0.1, 0.8, 0.1],   # 1 → 1 correct
            [0.7, 0.2, 0.1],   # 2 → 0 ERROR
            [0.1, 0.2, 0.7],   # 2 → 2 correct
        ])
        metas = [RecordMetaData(i, uri="data.csv") for i in range(5)]
        e = Evaluation()
        e.eval(labels, preds, record_meta_data=metas)
        return e, metas

    def test_errors_sorted_and_diagonal_skipped(self):
        e, metas = self._eval_with_meta()
        errs = e.get_prediction_errors()
        assert [(p.actual, p.predicted) for p in errs] == [(0, 1), (2, 0)]
        assert errs[0].record_meta_data is metas[1]
        assert errs[1].record_meta_data is metas[3]
        assert "data.csv:3" == errs[1].record_meta_data.get_location()

    def test_by_actual_and_predicted_class(self):
        e, metas = self._eval_with_meta()
        by_actual = e.get_predictions_by_actual_class(2)
        assert sorted((p.actual, p.predicted) for p in by_actual) == \
            [(2, 0), (2, 2)]
        by_pred = e.get_prediction_by_predicted_class(1)
        assert sorted((p.actual, p.predicted) for p in by_pred) == \
            [(0, 1), (1, 1)]
        cell = e.get_predictions(0, 1)
        assert len(cell) == 1 and cell[0].record_meta_data is metas[1]

    def test_none_without_metadata(self):
        e = Evaluation()
        e.eval(np.eye(2)[[0, 1]], np.array([[0.9, 0.1], [0.2, 0.8]]))
        assert e.get_prediction_errors() is None
        assert e.get_predictions_by_actual_class(0) is None

    def test_merge_combines_metadata(self):
        a, _ = self._eval_with_meta()
        b, _ = self._eval_with_meta()
        a.merge(b)
        assert len(a.get_prediction_errors()) == 4

    def test_end_to_end_through_records_and_network(self):
        """CSV → RecordReaderDataSetIterator(collect_meta_data=True) →
        net.evaluate → get_prediction_errors → load_from_meta_data returns
        the original source records (the full reference drilldown loop)."""
        import tempfile, os
        from deeplearning4j_tpu.datasets.records import (
            CSVRecordReader, RecordReaderDataSetIterator)
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updaters import Adam

        rng = np.random.default_rng(3)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "data.csv")
            rows = []
            for i in range(60):
                cls = i % 3
                f = rng.normal(0, 0.2, 4)
                f[cls] += 2.0
                rows.append(",".join(f"{v:.6f}" for v in f) + f",{cls}")
            with open(path, "w") as fh:
                fh.write("\n".join(rows))
            conf = (NeuralNetConfiguration.builder().seed(1)
                    .updater(Adam(0.05)).list()
                    .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
                    .layer(OutputLayer(n_in=16, n_out=3))
                    .build())
            net = MultiLayerNetwork(conf).init()
            train_it = RecordReaderDataSetIterator(
                CSVRecordReader(path), 16, label_index=4,
                num_possible_labels=3)
            for _ in range(15):
                net.fit(train_it)
            eval_it = RecordReaderDataSetIterator(
                CSVRecordReader(path), 16, label_index=4,
                num_possible_labels=3, collect_meta_data=True)
            e = net.evaluate(eval_it)
            assert e.accuracy() > 0.9
            errs = e.get_prediction_errors()
            assert errs is not None  # metadata was collected
            # every recorded prediction maps back to its source record
            recorded = e.get_predictions_by_actual_class(1)
            assert len(recorded) == 20
            reloaded = eval_it.load_from_meta_data(
                [p.record_meta_data for p in recorded])
            assert reloaded.num_examples() == 20
            lab = np.asarray(reloaded.labels)
            assert (np.argmax(lab, 1) == 1).all()


class TestBinnedROC:
    """ROC.java:61-85 thresholded mode: O(steps) mergeable state for
    batched/distributed evaluation."""

    def _scored(self, n, seed):
        rng = np.random.default_rng(seed)
        labels = (rng.random(n) < 0.4).astype(np.float64)
        # informative but noisy scores
        scores = np.clip(0.5 * labels + rng.normal(0.35, 0.25, n), 0, 1)
        return labels, scores

    def test_binned_close_to_exact(self):
        from deeplearning4j_tpu.eval.roc import ROC
        labels, scores = self._scored(4000, 0)
        exact = ROC()
        exact.eval(labels, scores)
        binned = ROC(threshold_steps=200)
        binned.eval(labels, scores)
        assert binned.calculate_auc() == pytest.approx(
            exact.calculate_auc(), abs=0.01)
        assert binned.calculate_auc_pr() == pytest.approx(
            exact.calculate_auc_pr(), abs=0.02)

    def test_sharded_merge_equals_single_pass(self):
        from deeplearning4j_tpu.eval.roc import ROC
        labels, scores = self._scored(6000, 1)
        whole = ROC(threshold_steps=100)
        whole.eval(labels, scores)
        shards = []
        for k in range(6):  # six "workers"
            r = ROC(threshold_steps=100)
            r.eval(labels[k * 1000:(k + 1) * 1000],
                   scores[k * 1000:(k + 1) * 1000])
            shards.append(r)
        merged = shards[0]
        for r in shards[1:]:
            merged.merge(r)
        np.testing.assert_array_equal(merged.tp_counts, whole.tp_counts)
        np.testing.assert_array_equal(merged.fp_counts, whole.fp_counts)
        assert merged.calculate_auc() == whole.calculate_auc()
        # and the merged-binned AUC tracks the exact AUC
        exact = ROC()
        exact.eval(labels, scores)
        assert merged.calculate_auc() == pytest.approx(
            exact.calculate_auc(), abs=0.01)

    def test_curve_endpoints_and_monotonicity(self):
        from deeplearning4j_tpu.eval.roc import ROC
        labels, scores = self._scored(1000, 2)
        r = ROC(threshold_steps=50)
        r.eval(labels, scores)
        thr, fpr, tpr = r.get_roc_curve()
        assert thr[0] == 0.0 and thr[-1] == 1.0
        assert fpr[0] == 1.0 and tpr[0] == 1.0     # t=0: everything positive
        assert fpr[-1] == 0.0 and tpr[-1] == 0.0   # t=1: nothing positive
        assert (np.diff(fpr) <= 0).all() and (np.diff(tpr) <= 0).all()

    def test_threshold_boundary_is_geq(self):
        from deeplearning4j_tpu.eval.roc import ROC
        r = ROC(threshold_steps=10)
        # score exactly at threshold 0.3 must count as predicted-positive
        r.eval(np.array([1.0, 0.0]), np.array([0.3, 0.3]))
        i = 3  # threshold 0.3
        assert r.tp_counts[i] == 1 and r.fp_counts[i] == 1
        assert r.tp_counts[i + 1] == 0

    def test_serde_round_trip(self):
        from deeplearning4j_tpu.eval.roc import ROC
        labels, scores = self._scored(500, 3)
        r = ROC(threshold_steps=40)
        r.eval(labels, scores)
        back = ROC.from_json(r.to_json())
        assert back.calculate_auc() == r.calculate_auc()
        exact = ROC()
        exact.eval(labels, scores)
        with pytest.raises(ValueError, match="exact-mode"):
            exact.to_json()
        with pytest.raises(ValueError, match="threshold_steps"):
            r.merge(ROC(threshold_steps=20))

    def test_masked_and_two_column_inputs(self):
        from deeplearning4j_tpu.eval.roc import ROC
        labels2 = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float64)
        preds2 = np.array([[0.8, 0.2], [0.3, 0.7], [0.4, 0.6], [0.9, 0.1]])
        r = ROC(threshold_steps=10)
        r.eval(labels2, preds2, mask=np.array([1, 1, 1, 0]))
        assert r.count_actual_positive == 2
        assert r.count_actual_negative == 1


class TestBinnedROCFamilies:
    def test_rocbinary_binned_merge_tracks_exact(self):
        from deeplearning4j_tpu.eval.roc import ROCBinary
        rng = np.random.default_rng(4)
        labels = (rng.random((2000, 3)) < 0.3).astype(np.float64)
        scores = np.clip(0.5 * labels + rng.normal(0.3, 0.25, (2000, 3)),
                         0, 1)
        exact = ROCBinary()
        exact.eval(labels, scores)
        a = ROCBinary(threshold_steps=150)
        b = ROCBinary(threshold_steps=150)
        a.eval(labels[:1000], scores[:1000])
        b.eval(labels[1000:], scores[1000:])
        a.merge(b)
        for col in range(3):
            assert a.calculate_auc(col) == pytest.approx(
                exact.calculate_auc(col), abs=0.015)

    def test_rocmulticlass_binned_merge_tracks_exact(self):
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        rng = np.random.default_rng(5)
        true = rng.integers(0, 4, 2000)
        labels = np.eye(4)[true]
        scores = rng.dirichlet(np.ones(4), 2000)
        scores[np.arange(2000), true] += 0.3
        scores = scores / scores.sum(1, keepdims=True)
        exact = ROCMultiClass()
        exact.eval(labels, scores)
        a = ROCMultiClass(threshold_steps=150)
        b = ROCMultiClass(threshold_steps=150)
        a.eval(labels[:1000], scores[:1000])
        b.eval(labels[1000:], scores[1000:])
        a.merge(b)
        for cls in range(4):
            assert a.calculate_auc(cls) == pytest.approx(
                exact.calculate_auc(cls), abs=0.02)


class TestROCFamilyMasks:
    def test_rocbinary_per_output_mask(self):
        from deeplearning4j_tpu.eval.roc import ROCBinary
        labels = np.array([[1, 0], [0, 1], [1, 1], [0, 0]], np.float64)
        scores = np.array([[0.9, 0.2], [0.1, 0.8], [0.8, 0.7], [0.2, 0.1]])
        m2 = np.array([[1, 1], [1, 0], [1, 1], [0, 1]], np.float64)
        for steps in (0, 50):
            r = ROCBinary(threshold_steps=steps)
            r.eval(labels, scores, mask=m2)
            # col 0 keeps rows 0,1,2; col 1 keeps rows 0,2,3
            ref0 = ROCBinary(threshold_steps=steps)
            ref0.eval(labels[[0, 1, 2]], scores[[0, 1, 2]])
            assert r.calculate_auc(0) == pytest.approx(ref0.calculate_auc(0))
            ref1 = ROCBinary(threshold_steps=steps)
            ref1.eval(labels[[0, 2, 3]], scores[[0, 2, 3]])
            assert r.calculate_auc(1) == pytest.approx(ref1.calculate_auc(1))

    def test_rocbinary_exact_mode_1d_mask(self):
        from deeplearning4j_tpu.eval.roc import ROCBinary
        labels = np.array([[1, 0], [0, 1], [1, 0]], np.float64)
        scores = np.array([[0.9, 0.2], [0.1, 0.8], [0.3, 0.4]])
        r = ROCBinary()
        r.eval(labels, scores, mask=np.array([1, 1, 0]))
        ref = ROCBinary()
        ref.eval(labels[:2], scores[:2])
        assert r.calculate_auc(0) == pytest.approx(ref.calculate_auc(0))

    def test_rocmulticlass_mask_shapes(self):
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        labels = np.eye(3)[[0, 1, 2, 0]]
        scores = np.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2],
                           [0.1, 0.2, 0.7], [0.5, 0.3, 0.2]])
        r = ROCMultiClass()
        r.eval(labels, scores, mask=np.array([[1], [1], [1], [0]]))
        ref = ROCMultiClass()
        ref.eval(labels[:3], scores[:3])
        assert r.calculate_auc(0) == pytest.approx(ref.calculate_auc(0))
        with pytest.raises(ValueError, match="per-example"):
            ROCMultiClass().eval(labels, scores,
                                 mask=np.ones((4, 3)))


class TestRemainingMerges:
    """Every evaluation class merges (BaseEvaluation.merge parity) — the
    distributed-eval requirement."""

    def test_evaluation_binary_merge(self):
        from deeplearning4j_tpu.eval.binary import EvaluationBinary
        rng = np.random.default_rng(0)
        labels = (rng.random((200, 3)) < 0.4).astype(float)
        preds = np.clip(labels * 0.6 + rng.random((200, 3)) * 0.5, 0, 1)
        whole = EvaluationBinary()
        whole.eval(labels, preds)
        a, b = EvaluationBinary(), EvaluationBinary()
        a.eval(labels[:120], preds[:120])
        b.eval(labels[120:], preds[120:])
        a.merge(b)
        for col in range(3):
            assert a.f1(col) == pytest.approx(whole.f1(col))
            assert a.accuracy(col) == pytest.approx(whole.accuracy(col))
        empty = EvaluationBinary()
        empty.merge(whole)
        assert empty.accuracy(0) == pytest.approx(whole.accuracy(0))

    def test_regression_evaluation_merge(self):
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        rng = np.random.default_rng(1)
        labels = rng.normal(size=(300, 2))
        preds = labels + rng.normal(0, 0.3, size=(300, 2))
        whole = RegressionEvaluation()
        whole.eval(labels, preds)
        a, b = RegressionEvaluation(), RegressionEvaluation()
        a.eval(labels[:100], preds[:100])
        b.eval(labels[100:], preds[100:])
        a.merge(b)
        for col in range(2):
            assert a.mean_squared_error(col) == pytest.approx(
                whole.mean_squared_error(col))
            assert a.pearson_correlation(col) == pytest.approx(
                whole.pearson_correlation(col))
            assert a.r_squared(col) == pytest.approx(whole.r_squared(col))


class TestAveragingAndCurves:
    def _eval(self):
        e = Evaluation()
        labels = np.eye(3)[[0, 0, 1, 1, 2, 2]]
        preds = np.array([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1],
                          [0.1, 0.8, 0.1], [0.1, 0.8, 0.1],
                          [0.1, 0.1, 0.8], [0.8, 0.1, 0.1]])
        e.eval(labels, preds)
        return e

    def test_micro_vs_macro_precision_recall(self):
        e = self._eval()
        # micro precision == micro recall == accuracy for single-label
        assert e.precision_averaged("micro") == pytest.approx(e.accuracy())
        assert e.recall_averaged("micro") == pytest.approx(e.accuracy())
        assert e.precision_averaged("macro") == pytest.approx(
            np.mean([e.precision(i) for i in range(3)]))

    def test_gmeasure_and_mcc(self):
        e = self._eval()
        assert e.g_measure(0) == pytest.approx(
            np.sqrt(e.precision(0) * e.recall(0)))
        macro = np.mean([np.sqrt(e.precision(i) * e.recall(i))
                         for i in range(3)])
        assert e.g_measure(averaging="macro") == pytest.approx(macro)
        assert -1.0 <= e.matthews_correlation_averaged("micro") <= 1.0
        assert e.matthews_correlation_averaged("macro") == pytest.approx(
            np.mean([e.matthews_correlation(i) for i in range(3)]))

    def test_score_for_metric(self):
        e = self._eval()
        assert e.score_for_metric("ACCURACY") == e.accuracy()
        assert e.score_for_metric("f1") == e.f1()
        assert e.score_for_metric("GMEASURE") == pytest.approx(
            e.g_measure(averaging="macro"))
        with pytest.raises(ValueError, match="Unknown metric"):
            e.score_for_metric("BLEU")

    def test_roc_family_curves(self):
        from deeplearning4j_tpu.eval.roc import ROCBinary, ROCMultiClass
        rng = np.random.default_rng(7)
        labels = (rng.random((500, 2)) < 0.4).astype(np.float64)
        scores = np.clip(0.5 * labels + rng.normal(0.3, 0.2, (500, 2)), 0, 1)
        for steps in (0, 60):
            rb = ROCBinary(threshold_steps=steps)
            rb.eval(labels, scores)
            thr, fpr, tpr = rb.get_roc_curve(1)
            assert len(thr) == len(fpr) == len(tpr) > 2
            t2, prec, rec = rb.get_precision_recall_curve(1)
            assert len(prec) == len(rec)
        true = rng.integers(0, 3, 500)
        ml = np.eye(3)[true]
        ms = rng.dirichlet(np.ones(3), 500)
        for steps in (0, 60):
            rm = ROCMultiClass(threshold_steps=steps)
            rm.eval(ml, ms)
            thr, fpr, tpr = rm.get_roc_curve(2)
            assert len(thr) == len(fpr) == len(tpr) > 2


class TestFBetaAndLabeledStats:
    def test_fbeta_reduces_to_f1(self):
        e = Evaluation()
        e.eval(np.eye(3)[[0, 1, 2, 0]], np.array(
            [[0.8, 0.1, 0.1], [0.1, 0.8, 0.1],
             [0.1, 0.1, 0.8], [0.1, 0.8, 0.1]]))
        for c in range(3):
            assert e.f_beta(1.0, c) == pytest.approx(e.f1(c))
        # beta=2 weighs recall more: for class 1 (recall 1, precision 0.5)
        assert e.f_beta(2.0, 1) > e.f1(1)
        assert 0.0 <= e.f_beta(0.5, averaging="micro") <= 1.0

    def test_stats_uses_label_names(self):
        e = Evaluation(labels_list=["cat", "dog"])
        e.eval(np.eye(2)[[0, 1]], np.array([[0.9, 0.1], [0.2, 0.8]]))
        s = e.stats()
        assert "cat" in s and "dog" in s


def test_binary_and_roc_stats_strings():
    from deeplearning4j_tpu.eval.binary import EvaluationBinary
    from deeplearning4j_tpu.eval.roc import ROC
    e = EvaluationBinary()
    e.eval(np.array([[1, 0], [0, 1]]), np.array([[0.9, 0.2], [0.3, 0.8]]))
    s = e.stats(labels=["toxic", "spam"])
    assert "toxic" in s and "spam" in s and "f1" in s
    r = ROC()
    r.eval(np.array([1.0, 0.0, 1.0]), np.array([0.8, 0.3, 0.6]))
    assert r.stats().startswith("AUC: [")


def test_network_evaluate_roc_methods():
    """MultiLayerNetwork.evaluateROC / evaluateROCMultiClass parity."""
    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    rng = np.random.default_rng(2)
    cls = rng.integers(0, 2, 128)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    x[np.arange(128), cls] += 2.0
    y = np.eye(2, dtype=np.float32)[cls]
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.05))
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(x, y, epochs=20)
    it = ListDataSetIterator(DataSet(x, y), 32)
    roc = net.evaluate_roc(it)
    assert roc.calculate_auc() > 0.9
    binned = net.evaluate_roc(it, threshold_steps=100)
    assert binned.calculate_auc() == pytest.approx(roc.calculate_auc(),
                                                   abs=0.02)
    multi = net.evaluate_roc_multi_class(it, threshold_steps=50)
    assert multi.calculate_auc(0) > 0.9


def test_evaluation_serde_keeps_labels_list():
    e = Evaluation(labels_list=["cat", "dog"])
    e.eval(np.eye(2)[[0, 1]], np.array([[0.9, 0.1], [0.2, 0.8]]))
    back = Evaluation.from_json(e.to_json())
    assert back.labels_list == ["cat", "dog"]
    assert "cat" in back.stats()
