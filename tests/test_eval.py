"""Evaluation metrics tests (eval/EvalTest.java role): confusion-matrix
classification metrics, regression metrics, ROC family, binary multi-label
evaluation, and calibration — validated against hand-computed values."""

import numpy as np
import pytest

from deeplearning4j_tpu.eval.binary import EvaluationBinary
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass


def onehot(idx, n):
    return np.eye(n, dtype=np.float64)[idx]


class TestEvaluation:
    def _eval_fixed(self):
        # 3 classes; true: [0,0,1,1,2,2]; pred: [0,1,1,1,2,0]
        e = Evaluation(3)
        truth = np.array([0, 0, 1, 1, 2, 2])
        pred_cls = np.array([0, 1, 1, 1, 2, 0])
        e.eval(onehot(truth, 3), onehot(pred_cls, 3))
        return e

    def test_confusion_and_metrics(self):
        e = self._eval_fixed()
        cm = e.confusion_matrix()
        assert cm[0, 0] == 1 and cm[0, 1] == 1
        assert cm[1, 1] == 2
        assert cm[2, 2] == 1 and cm[2, 0] == 1
        assert e.accuracy() == pytest.approx(4 / 6)
        # class 1: tp=2, fp=1, fn=0
        assert e.precision(1) == pytest.approx(2 / 3)
        assert e.recall(1) == pytest.approx(1.0)
        assert e.f1(1) == pytest.approx(2 * (2 / 3) / (2 / 3 + 1.0))

    def test_merge_and_json(self):
        a = self._eval_fixed()
        b = self._eval_fixed()
        a.merge(b)
        assert a.confusion_matrix().sum() == 12
        rt = Evaluation.from_json(a.to_json())
        assert rt.accuracy() == pytest.approx(a.accuracy())
        assert "Accuracy" in a.stats() or "accuracy" in a.stats().lower()

    def test_time_series_with_mask(self):
        e = Evaluation(2)
        labels = onehot(np.array([[0, 1, 0], [1, 0, 1]]).ravel(), 2).reshape(2, 3, 2)
        preds = labels.copy()  # perfect predictions
        mask = np.array([[1, 1, 0], [1, 0, 0]], np.float64)
        e.eval_time_series(labels, preds, labels_mask=mask)
        assert e.confusion_matrix().sum() == 3  # only unmasked steps counted
        assert e.accuracy() == 1.0


class TestRegressionEvaluation:
    def test_known_values(self):
        r = RegressionEvaluation()
        labels = np.array([[1.0], [2.0], [3.0], [4.0]])
        preds = np.array([[1.5], [2.0], [2.5], [4.5]])
        r.eval(labels, preds)
        err = labels - preds
        assert r.mean_squared_error() == pytest.approx(float(np.mean(err ** 2)))
        assert r.mean_absolute_error() == pytest.approx(float(np.mean(np.abs(err))))
        assert r.root_mean_squared_error() == pytest.approx(
            float(np.sqrt(np.mean(err ** 2))))
        # matches numpy's definition exactly
        assert r.pearson_correlation() == pytest.approx(
            float(np.corrcoef(labels[:, 0], preds[:, 0])[0, 1]), abs=1e-9)
        assert r.r_squared() == pytest.approx(
            1 - np.sum(err ** 2) / np.sum((labels - labels.mean()) ** 2),
            abs=1e-6)

    def test_multi_column(self):
        r = RegressionEvaluation()
        labels = np.array([[1.0, 10.0], [2.0, 20.0]])
        preds = np.array([[1.0, 12.0], [2.0, 18.0]])
        r.eval(labels, preds)
        assert r.mean_squared_error(0) == pytest.approx(0.0)
        assert r.mean_squared_error(1) == pytest.approx(4.0)
        assert r.average_mean_squared_error() == pytest.approx(2.0)
        assert "MSE" in r.stats() or "mse" in r.stats().lower()


class TestROC:
    def test_perfect_separation_auc_one(self):
        roc = ROC()
        labels = np.array([0, 0, 1, 1])
        scores = np.array([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]])
        roc.eval(onehot(labels, 2), scores)
        assert roc.calculate_auc() == pytest.approx(1.0)
        assert roc.calculate_auc_pr() == pytest.approx(1.0)

    def test_random_scores_auc_half(self, rng):
        roc = ROC()
        n = 4000
        labels = rng.integers(0, 2, n)
        scores = rng.random(n)
        roc.eval(labels, np.stack([1 - scores, scores], 1))
        assert abs(roc.calculate_auc() - 0.5) < 0.05

    def test_inverted_scores_auc_zero(self):
        roc = ROC()
        labels = np.array([0, 0, 1, 1])
        scores = np.array([[0.1, 0.9], [0.2, 0.8], [0.8, 0.2], [0.9, 0.1]])
        roc.eval(onehot(labels, 2), scores)
        assert roc.calculate_auc() == pytest.approx(0.0)

    def test_roc_binary_per_column(self):
        rb = ROCBinary()
        labels = np.array([[1, 0], [1, 0], [0, 1], [0, 1]], np.float64)
        # col 0 scored perfectly, col 1 inverted
        scores = np.array([[0.9, 0.9], [0.8, 0.8], [0.1, 0.2], [0.2, 0.1]])
        rb.eval(labels, scores)
        assert rb.calculate_auc(0) == pytest.approx(1.0)
        assert rb.calculate_auc(1) == pytest.approx(0.0)

    def test_roc_multiclass_one_vs_all(self):
        rm = ROCMultiClass()
        truth = np.array([0, 1, 2, 0, 1, 2])
        scores = onehot(truth, 3) * 0.8 + 0.1  # correct class highest
        rm.eval(onehot(truth, 3), scores)
        for c in range(3):
            assert rm.calculate_auc(c) == pytest.approx(1.0)


class TestEvaluationBinary:
    def test_per_label_metrics(self):
        eb = EvaluationBinary(decision_threshold=0.5)
        labels = np.array([[1, 0], [1, 1], [0, 1], [0, 0]], np.float64)
        preds = np.array([[0.9, 0.2], [0.8, 0.4], [0.3, 0.9], [0.1, 0.6]])
        eb.eval(labels, preds)
        # col 0: predictions [1,1,0,0] vs [1,1,0,0] → perfect
        assert eb.accuracy(0) == pytest.approx(1.0)
        assert eb.f1(0) == pytest.approx(1.0)
        # col 1: predictions [0,0,1,1] vs [0,1,1,0] → 2/4 correct
        assert eb.accuracy(1) == pytest.approx(0.5)


class TestEvaluationCalibration:
    def test_perfectly_calibrated(self, rng):
        cal = EvaluationCalibration(reliability_bins=10)
        n = 20000
        p = rng.random(n)
        labels = (rng.random(n) < p).astype(np.float64)
        cal.eval(np.stack([1 - labels, labels], 1), np.stack([1 - p, p], 1))
        assert cal.expected_calibration_error() < 0.03

    def test_overconfident_model_has_high_ece(self, rng):
        cal = EvaluationCalibration(reliability_bins=10)
        n = 5000
        labels = rng.integers(0, 2, n).astype(np.float64)  # coin flips
        conf = np.full(n, 0.99)  # but the model claims 99% confidence
        preds = np.stack([1 - conf, conf], 1)
        cal.eval(np.stack([1 - labels, labels], 1), preds)
        assert cal.expected_calibration_error() > 0.3


class TestCalibrationPerClass:
    """Per-class depth (EvaluationCalibration.java getReliabilityDiagram /
    getResidualPlot / getProbabilityHistogram parity)."""

    def _three_class(self, rng, n=6000):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal = EvaluationCalibration(reliability_bins=10, histogram_bins=20)
        cls = rng.integers(0, 3, n)
        labels = np.eye(3)[cls]
        logits = rng.normal(0, 1, (n, 3)) + 2.0 * labels
        preds = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
        cal.eval(labels, preds)
        return cal, labels, preds

    def test_per_class_reliability(self, rng):
        cal, labels, preds = self._three_class(rng)
        for c in range(3):
            d = cal.get_reliability_diagram(c)
            assert len(d.mean_predicted_value) == len(d.frac_positives) > 0
            # curve must be increasing-ish: low-prob bins less often positive
            assert d.frac_positives[0] < d.frac_positives[-1]

    def test_probability_histogram_selects_labelled_class(self, rng):
        cal, labels, preds = self._three_class(rng)
        h1 = cal.get_probability_histogram(1)
        # counts = histogram of P(class 1) over examples LABELLED class 1
        want, _ = np.histogram(preds[labels[:, 1] > 0.5, 1],
                               bins=20, range=(0.0, 1.0))
        np.testing.assert_array_equal(h1.counts, want)
        # overall = every (example, class) probability
        hall = cal.get_probability_histogram_all_classes()
        wall, _ = np.histogram(preds.ravel(), bins=20, range=(0.0, 1.0))
        np.testing.assert_array_equal(hall.counts, wall)

    def test_residual_plots(self, rng):
        cal, labels, preds = self._three_class(rng)
        r0 = cal.get_residual_plot(0)
        resid = np.abs(labels - preds)
        want, _ = np.histogram(resid[labels[:, 0] > 0.5, 0],
                               bins=20, range=(0.0, 1.0))
        np.testing.assert_array_equal(r0.counts, want)
        rall = cal.get_residual_plot_all_classes()
        wall, _ = np.histogram(resid.ravel(), bins=20, range=(0.0, 1.0))
        np.testing.assert_array_equal(rall.counts, wall)

    def test_label_and_prediction_counts(self, rng):
        cal, labels, preds = self._three_class(rng)
        np.testing.assert_array_equal(cal.label_counts,
                                      labels.sum(0).astype(np.int64))
        np.testing.assert_array_equal(cal.prediction_counts,
                                      np.bincount(preds.argmax(1), minlength=3))

    def test_merge_and_reset(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal_a, labels, preds = self._three_class(rng, n=512)
        cal_b = EvaluationCalibration(reliability_bins=10, histogram_bins=20)
        cal_b.eval(labels, preds)
        both = EvaluationCalibration(reliability_bins=10, histogram_bins=20)
        both.eval(labels, preds)
        both.eval(labels, preds)
        cal_a.merge(cal_b)
        np.testing.assert_array_equal(cal_a.prob_by_class, both.prob_by_class)
        np.testing.assert_array_equal(cal_a.rdiag_total, both.rdiag_total)
        cal_a.reset()
        assert cal_a.num_classes == -1

    def test_per_example_mask(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal = EvaluationCalibration(histogram_bins=20)
        labels = np.eye(2)[rng.integers(0, 2, 100)]
        preds = rng.random((100, 2))
        preds = preds / preds.sum(1, keepdims=True)
        keep = (rng.random(100) > 0.5).astype(np.float64)
        cal.eval(labels, preds, mask=keep)
        ref = EvaluationCalibration(histogram_bins=20)
        ref.eval(labels[keep > 0], preds[keep > 0])
        np.testing.assert_array_equal(cal.prob_overall, ref.prob_overall)
        np.testing.assert_array_equal(cal.rdiag_total, ref.rdiag_total)

    def test_ui_calibration_module(self, rng):
        from deeplearning4j_tpu.ui.modules import CalibrationModule
        cal, _, _ = self._three_class(rng, n=512)
        mod = CalibrationModule(cal)
        code, summary = mod.handle("/calibration")
        assert code == 200 and summary["num_classes"] == 3
        assert 0.0 <= summary["expected_calibration_error"] <= 1.0
        code, rel = mod.handle("/calibration/reliability/1")
        assert code == 200 and len(rel["mean_predicted_value"]) > 0
        code, hist = mod.handle("/calibration/probabilities/2")
        assert code == 200 and len(hist["counts"]) == 20
        code, resid = mod.handle("/calibration/residual")
        assert code == 200 and sum(resid["counts"]) == 512 * 3
        code, panel = mod.handle("/calibration/panel")
        assert code == 200 and "svg" in panel["html"].lower()
        # unattached module 404s cleanly
        code, err = CalibrationModule().handle("/calibration")
        assert code == 404

    def test_reset_clears_and_fresh_instance_is_safe(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        fresh = EvaluationCalibration()
        assert fresh.expected_calibration_error() == 0.0
        assert fresh.get_residual_plot_all_classes().counts.sum() == 0
        cal, _, _ = self._three_class(rng, n=256)
        assert cal.expected_calibration_error() > 0
        cal.reset()
        assert cal.expected_calibration_error() == 0.0
        assert cal.get_probability_histogram_all_classes().counts.sum() == 0
        with pytest.raises(ValueError):
            cal.get_reliability_diagram(0)

    def test_class_index_validation(self, rng):
        from deeplearning4j_tpu.ui.modules import CalibrationModule
        cal, _, _ = self._three_class(rng, n=128)
        with pytest.raises(IndexError):
            cal.get_residual_plot(-1)
        with pytest.raises(IndexError):
            cal.get_probability_histogram(3)
        mod = CalibrationModule(cal)
        assert mod.handle("/calibration/reliability/-1")[0] == 404
        assert mod.handle("/calibration/probabilities/99")[0] == 404

    def test_3d_per_output_mask(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        labels = np.eye(2)[rng.integers(0, 2, (4, 5))]      # [N,T,C]
        preds = rng.random((4, 5, 2))
        preds = preds / preds.sum(-1, keepdims=True)
        m3 = (rng.random((4, 5, 2)) > 0.4).astype(np.float64)
        cal = EvaluationCalibration(histogram_bins=20)
        cal.eval(labels, preds, mask=m3)                     # must not crash
        assert cal.prob_overall.sum() == int(m3.sum())

    def test_out_of_range_probs_counted_in_edge_bins(self):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal = EvaluationCalibration(histogram_bins=10)
        labels = np.array([[1.0, 0.0]])
        preds = np.array([[-0.05, 1.05]])  # drifted out of [0,1]
        cal.eval(labels, preds)
        assert cal.prob_overall.sum() == 2  # nothing silently dropped
        assert cal.prob_overall[0] == 1 and cal.prob_overall[-1] == 1

    def test_merge_rejects_class_mismatch(self, rng):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        a = EvaluationCalibration()
        a.eval(np.eye(3)[[0, 1]], np.full((2, 3), 1 / 3))
        b = EvaluationCalibration()
        b.eval(np.ones((2, 1)), np.full((2, 1), 0.5))
        with pytest.raises(ValueError, match="class counts"):
            a.merge(b)

    def test_prediction_counts_respect_per_output_mask(self):
        from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
        cal = EvaluationCalibration()
        labels = np.array([[0.0, 1.0, 0.0]])
        preds = np.array([[0.1, 0.2, 0.7]])   # argmax=2 but class 2 masked
        m = np.array([[1.0, 1.0, 0.0]])
        cal.eval(labels, preds, mask=m)
        np.testing.assert_array_equal(cal.prediction_counts, [0, 1, 0])
