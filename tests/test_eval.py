"""Evaluation metrics tests (eval/EvalTest.java role): confusion-matrix
classification metrics, regression metrics, ROC family, binary multi-label
evaluation, and calibration — validated against hand-computed values."""

import numpy as np
import pytest

from deeplearning4j_tpu.eval.binary import EvaluationBinary
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass


def onehot(idx, n):
    return np.eye(n, dtype=np.float64)[idx]


class TestEvaluation:
    def _eval_fixed(self):
        # 3 classes; true: [0,0,1,1,2,2]; pred: [0,1,1,1,2,0]
        e = Evaluation(3)
        truth = np.array([0, 0, 1, 1, 2, 2])
        pred_cls = np.array([0, 1, 1, 1, 2, 0])
        e.eval(onehot(truth, 3), onehot(pred_cls, 3))
        return e

    def test_confusion_and_metrics(self):
        e = self._eval_fixed()
        cm = e.confusion_matrix()
        assert cm[0, 0] == 1 and cm[0, 1] == 1
        assert cm[1, 1] == 2
        assert cm[2, 2] == 1 and cm[2, 0] == 1
        assert e.accuracy() == pytest.approx(4 / 6)
        # class 1: tp=2, fp=1, fn=0
        assert e.precision(1) == pytest.approx(2 / 3)
        assert e.recall(1) == pytest.approx(1.0)
        assert e.f1(1) == pytest.approx(2 * (2 / 3) / (2 / 3 + 1.0))

    def test_merge_and_json(self):
        a = self._eval_fixed()
        b = self._eval_fixed()
        a.merge(b)
        assert a.confusion_matrix().sum() == 12
        rt = Evaluation.from_json(a.to_json())
        assert rt.accuracy() == pytest.approx(a.accuracy())
        assert "Accuracy" in a.stats() or "accuracy" in a.stats().lower()

    def test_time_series_with_mask(self):
        e = Evaluation(2)
        labels = onehot(np.array([[0, 1, 0], [1, 0, 1]]).ravel(), 2).reshape(2, 3, 2)
        preds = labels.copy()  # perfect predictions
        mask = np.array([[1, 1, 0], [1, 0, 0]], np.float64)
        e.eval_time_series(labels, preds, labels_mask=mask)
        assert e.confusion_matrix().sum() == 3  # only unmasked steps counted
        assert e.accuracy() == 1.0


class TestRegressionEvaluation:
    def test_known_values(self):
        r = RegressionEvaluation()
        labels = np.array([[1.0], [2.0], [3.0], [4.0]])
        preds = np.array([[1.5], [2.0], [2.5], [4.5]])
        r.eval(labels, preds)
        err = labels - preds
        assert r.mean_squared_error() == pytest.approx(float(np.mean(err ** 2)))
        assert r.mean_absolute_error() == pytest.approx(float(np.mean(np.abs(err))))
        assert r.root_mean_squared_error() == pytest.approx(
            float(np.sqrt(np.mean(err ** 2))))
        # matches numpy's definition exactly
        assert r.pearson_correlation() == pytest.approx(
            float(np.corrcoef(labels[:, 0], preds[:, 0])[0, 1]), abs=1e-9)
        assert r.r_squared() == pytest.approx(
            1 - np.sum(err ** 2) / np.sum((labels - labels.mean()) ** 2),
            abs=1e-6)

    def test_multi_column(self):
        r = RegressionEvaluation()
        labels = np.array([[1.0, 10.0], [2.0, 20.0]])
        preds = np.array([[1.0, 12.0], [2.0, 18.0]])
        r.eval(labels, preds)
        assert r.mean_squared_error(0) == pytest.approx(0.0)
        assert r.mean_squared_error(1) == pytest.approx(4.0)
        assert r.average_mean_squared_error() == pytest.approx(2.0)
        assert "MSE" in r.stats() or "mse" in r.stats().lower()


class TestROC:
    def test_perfect_separation_auc_one(self):
        roc = ROC()
        labels = np.array([0, 0, 1, 1])
        scores = np.array([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]])
        roc.eval(onehot(labels, 2), scores)
        assert roc.calculate_auc() == pytest.approx(1.0)
        assert roc.calculate_auc_pr() == pytest.approx(1.0)

    def test_random_scores_auc_half(self, rng):
        roc = ROC()
        n = 4000
        labels = rng.integers(0, 2, n)
        scores = rng.random(n)
        roc.eval(labels, np.stack([1 - scores, scores], 1))
        assert abs(roc.calculate_auc() - 0.5) < 0.05

    def test_inverted_scores_auc_zero(self):
        roc = ROC()
        labels = np.array([0, 0, 1, 1])
        scores = np.array([[0.1, 0.9], [0.2, 0.8], [0.8, 0.2], [0.9, 0.1]])
        roc.eval(onehot(labels, 2), scores)
        assert roc.calculate_auc() == pytest.approx(0.0)

    def test_roc_binary_per_column(self):
        rb = ROCBinary()
        labels = np.array([[1, 0], [1, 0], [0, 1], [0, 1]], np.float64)
        # col 0 scored perfectly, col 1 inverted
        scores = np.array([[0.9, 0.9], [0.8, 0.8], [0.1, 0.2], [0.2, 0.1]])
        rb.eval(labels, scores)
        assert rb.calculate_auc(0) == pytest.approx(1.0)
        assert rb.calculate_auc(1) == pytest.approx(0.0)

    def test_roc_multiclass_one_vs_all(self):
        rm = ROCMultiClass()
        truth = np.array([0, 1, 2, 0, 1, 2])
        scores = onehot(truth, 3) * 0.8 + 0.1  # correct class highest
        rm.eval(onehot(truth, 3), scores)
        for c in range(3):
            assert rm.calculate_auc(c) == pytest.approx(1.0)


class TestEvaluationBinary:
    def test_per_label_metrics(self):
        eb = EvaluationBinary(decision_threshold=0.5)
        labels = np.array([[1, 0], [1, 1], [0, 1], [0, 0]], np.float64)
        preds = np.array([[0.9, 0.2], [0.8, 0.4], [0.3, 0.9], [0.1, 0.6]])
        eb.eval(labels, preds)
        # col 0: predictions [1,1,0,0] vs [1,1,0,0] → perfect
        assert eb.accuracy(0) == pytest.approx(1.0)
        assert eb.f1(0) == pytest.approx(1.0)
        # col 1: predictions [0,0,1,1] vs [0,1,1,0] → 2/4 correct
        assert eb.accuracy(1) == pytest.approx(0.5)


class TestEvaluationCalibration:
    def test_perfectly_calibrated(self, rng):
        cal = EvaluationCalibration(reliability_bins=10)
        n = 20000
        p = rng.random(n)
        labels = (rng.random(n) < p).astype(np.float64)
        cal.eval(np.stack([1 - labels, labels], 1), np.stack([1 - p, p], 1))
        assert cal.expected_calibration_error() < 0.03

    def test_overconfident_model_has_high_ece(self, rng):
        cal = EvaluationCalibration(reliability_bins=10)
        n = 5000
        labels = rng.integers(0, 2, n).astype(np.float64)  # coin flips
        conf = np.full(n, 0.99)  # but the model claims 99% confidence
        preds = np.stack([1 - conf, conf], 1)
        cal.eval(np.stack([1 - labels, labels], 1), preds)
        assert cal.expected_calibration_error() > 0.3
