"""Ring attention / Ulysses sequence parallelism vs single-device reference.

Pattern follows the reference's native-helper validation
(`ValidateCudnnLSTM.java`, SURVEY.md §4.6): the parallel path must produce
the same numbers as the plain path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
from deeplearning4j_tpu.parallel.mesh import SEQUENCE_AXIS, make_mesh
from deeplearning4j_tpu.parallel.ring import ring_self_attention, ulysses_attention


def _qkv(rng, n=2, h=4, t=32, dh=8):
    q = rng.normal(size=(n, h, t, dh)).astype(np.float32)
    k = rng.normal(size=(n, h, t, dh)).astype(np.float32)
    v = rng.normal(size=(n, h, t, dh)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.fixture
def mesh():
    return make_mesh({SEQUENCE_AXIS: 8})


def test_ring_matches_full(rng, mesh):
    q, k, v = _qkv(rng)
    ref = dot_product_attention(q, k, v)
    out = ring_self_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_causal(rng, mesh):
    q, k, v = _qkv(rng)
    t = q.shape[2]
    tri = jnp.tril(jnp.ones((t, t), jnp.float32))[None, None]
    ref = dot_product_attention(q, k, v, mask=tri)
    out = ring_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_padding_mask(rng, mesh):
    q, k, v = _qkv(rng)
    n, _, t, _ = q.shape
    lengths = np.array([t, t - 11])
    mask = (np.arange(t)[None, :] < lengths[:, None]).astype(np.float32)
    ref = dot_product_attention(q, k, v, mask=jnp.asarray(mask))
    out = ring_self_attention(q, k, v, mesh, mask=jnp.asarray(mask))
    # key mask only: every query row attends over the same valid keys in
    # both paths, so the full arrays must agree
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_fully_masked_rows_are_zero(rng, mesh):
    """A batch element with zero valid keys must emit zeros (documented
    contract), not nan or mean(v)."""
    q, k, v = _qkv(rng)
    mask = np.ones((q.shape[0], q.shape[2]), np.float32)
    mask[1, :] = 0.0
    out = np.asarray(ring_self_attention(q, k, v, mesh, mask=jnp.asarray(mask)))
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))


def test_ring_jit_grad(rng, mesh):
    """Ring attention must be differentiable and jittable end to end."""
    q, k, v = _qkv(rng, t=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


def test_ulysses_matches_full(rng, mesh):
    q, k, v = _qkv(rng, h=8)
    ref = dot_product_attention(q, k, v)
    out = ulysses_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_causal(rng, mesh):
    q, k, v = _qkv(rng, h=8)
    t = q.shape[2]
    tri = jnp.tril(jnp.ones((t, t), jnp.float32))[None, None]
    ref = dot_product_attention(q, k, v, mask=tri)
    out = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_head_divisibility(rng, mesh):
    q, k, v = _qkv(rng, h=4)  # 4 heads, 8 shards -> error
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh)


class TestSequenceParallelHelper:
    def test_encoder_forward_matches_single_device(self, mesh):
        """Registering the SP helper must leave the transformer encoder's
        outputs unchanged (ring attention == full attention) while running
        the attention sequence-sharded."""
        import numpy as np
        from deeplearning4j_tpu.nn import helpers
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.parallel.ring import SequenceParallelAttentionHelper
        from deeplearning4j_tpu.zoo.models import TransformerEncoder

        m = TransformerEncoder(num_labels=2, n_layers=2, d_model=16,
                               n_heads=8, d_ff=32, vocab_size=50,
                               max_length=16, seed=3)
        net = ComputationGraph(m.conf()).init()
        x = np.random.default_rng(0).integers(0, 50, size=(2, 16)).astype(np.float32)
        ref = np.asarray(net.output(x))
        for strategy in ("ring", "ulysses"):
            helpers.set_helper("attention", SequenceParallelAttentionHelper(
                mesh, strategy=strategy))
            try:
                out = np.asarray(net.output(x))
            finally:
                helpers.clear_helper("attention")
            np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_sp_helper_training_step(self, mesh):
        import numpy as np
        from deeplearning4j_tpu.nn import helpers
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.parallel.ring import SequenceParallelAttentionHelper
        from deeplearning4j_tpu.zoo.models import TransformerEncoder

        m = TransformerEncoder(num_labels=2, n_layers=1, d_model=16,
                               n_heads=2, d_ff=32, vocab_size=50,
                               max_length=16, seed=3)
        net = ComputationGraph(m.conf()).init()
        rng = np.random.default_rng(0)
        x = rng.integers(0, 50, size=(8, 16)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        helpers.set_helper("attention",
                           SequenceParallelAttentionHelper(mesh))
        try:
            net.fit(x, y)  # gradient flows through the shard_map'd ring
        finally:
            helpers.clear_helper("attention")
        assert np.isfinite(float(net.score_))


def test_ulysses_helper_no_reentry(rng):
    """Regression: the ulysses shard body must not consult the helper seam
    again — with per-shard head count divisible by the shard count the
    nested supports() used to pass and nest a second shard_map (crash)."""
    from deeplearning4j_tpu.nn import helpers
    from deeplearning4j_tpu.parallel.ring import SequenceParallelAttentionHelper

    mesh2 = make_mesh({SEQUENCE_AXIS: 2})
    q, k, v = _qkv(rng, n=2, h=4, t=16, dh=8)  # 4 heads % 2 shards == 0
    ref = np.asarray(dot_product_attention(q, k, v))
    helpers.set_helper("attention", SequenceParallelAttentionHelper(
        mesh2, strategy="ulysses"))
    try:
        out = np.asarray(dot_product_attention(q, k, v))
    finally:
        helpers.clear_helper("attention")
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_causal_sp_helper_on_transformer_lm(mesh):
    """One-line long-context for DECODERS: a causal=True sequence-parallel
    helper serves every CausalSelfAttentionLayer (causality is part of the
    helper request), outputs unchanged vs the unregistered model."""
    import numpy as np
    from deeplearning4j_tpu.nn import helpers
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.parallel.ring import SequenceParallelAttentionHelper
    from deeplearning4j_tpu.zoo.models import TransformerLM

    m = TransformerLM(vocab_size=50, max_length=16, n_layers=2, d_model=16,
                      n_heads=8, d_ff=32, seed=3)
    net = ComputationGraph(m.conf()).init()
    x = np.random.default_rng(0).integers(0, 50, size=(2, 16)).astype(np.float32)
    ref = np.asarray(net.output(x))
    for strategy in ("ring", "ulysses"):
        helpers.set_helper("attention", SequenceParallelAttentionHelper(
            mesh, strategy=strategy, causal=True))
        try:
            out = np.asarray(net.output(x))
        finally:
            helpers.clear_helper("attention")
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5,
                                   err_msg=strategy)
