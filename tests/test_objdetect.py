"""YOLO detection extraction: DetectedObject / get_predicted_objects / NMS.

Reference semantics: ``YoloUtils.getPredictedObjects:144`` (decode raw
output to absolute grid-unit boxes, threshold on sigmoid confidence),
``YoloUtils.nms:105`` (same-class, higher-confidence, IOU-above-threshold
suppression), ``DetectedObject.java:17`` (grid-cell units, top-left /
bottom-right accessors). Fixtures are hand-computed: raw logits are chosen
so the sigmoid/exp/softmax decode has closed-form expected values.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers import (
    DetectedObject,
    Yolo2OutputLayer,
    get_predicted_objects,
    nms,
)
from deeplearning4j_tpu.nn.layers.objdetect import iou


def sigmoid(v):
    return 1.0 / (1.0 + np.exp(-v))


def make_output(h=4, w=4, boxes=((1.0, 1.0), (2.0, 0.5)), n_classes=3,
                cells=()):
    """Raw [1,H,W,B*(5+C)] grid. ``cells`` is a list of
    (y, x, b, tx, ty, tw, th, tconf, class_idx): everything else gets a
    very negative confidence logit (sigmoid ≈ 0 → below any threshold)."""
    b, c = len(boxes), n_classes
    out = np.zeros((1, h, w, b * (5 + c)), np.float32)
    out[..., :] = 0.0
    # default: confidence logit -20 everywhere
    for bi in range(b):
        out[0, :, :, bi * (5 + c) + 4] = -20.0
    for (y, x, bi, tx, ty, tw, th, tconf, cls) in cells:
        base = bi * (5 + c)
        out[0, y, x, base + 0] = tx
        out[0, y, x, base + 1] = ty
        out[0, y, x, base + 2] = tw
        out[0, y, x, base + 3] = th
        out[0, y, x, base + 4] = tconf
        out[0, y, x, base + 5 + cls] = 10.0  # softmax ≈ one-hot
    return out


class TestDecode:
    def test_hand_computed_box(self):
        boxes = ((1.0, 1.0), (2.0, 0.5))
        # cell (y=2, x=1), anchor 1: tx=0 → sigmoid 0.5 → cx = 1.5
        # tw=ln(2) → w = 2*2 = 4 ; th=0 → h = 0.5 ; tconf=2 → conf=sigmoid(2)
        out = make_output(boxes=boxes, cells=[
            (2, 1, 1, 0.0, 0.0, np.log(2.0), 0.0, 2.0, 2)])
        dets = get_predicted_objects(boxes, out, conf_threshold=0.5)
        assert len(dets) == 1
        d = dets[0]
        assert d.example == 0
        assert d.center_x == pytest.approx(1.5, abs=1e-5)
        assert d.center_y == pytest.approx(2.5, abs=1e-5)
        assert d.width == pytest.approx(4.0, rel=1e-5)
        assert d.height == pytest.approx(0.5, rel=1e-5)
        assert d.confidence == pytest.approx(sigmoid(2.0), rel=1e-5)
        assert d.predicted_class == 2
        assert d.class_predictions.shape == (3,)
        assert d.class_predictions[2] > 0.99
        tl, br = d.top_left_xy(), d.bottom_right_xy()
        assert tl == (pytest.approx(-0.5, abs=1e-5), pytest.approx(2.25, abs=1e-5))
        assert br == (pytest.approx(3.5, abs=1e-5), pytest.approx(2.75, abs=1e-5))

    def test_threshold_filters(self):
        boxes = ((1.0, 1.0),)
        out = make_output(boxes=boxes, n_classes=2, cells=[
            (0, 0, 0, 0, 0, 0, 0, 2.0, 0),    # conf ≈ 0.88
            (1, 1, 0, 0, 0, 0, 0, -1.0, 1),   # conf ≈ 0.27
        ])
        assert len(get_predicted_objects(boxes, out, 0.5, n_classes=2)) == 1
        assert len(get_predicted_objects(boxes, out, 0.2, n_classes=2)) == 2
        assert len(get_predicted_objects(boxes, out, 0.9, n_classes=2)) == 0

    def test_minibatch_example_indices(self):
        boxes = ((1.0, 1.0),)
        a = make_output(boxes=boxes, n_classes=2,
                        cells=[(0, 0, 0, 0, 0, 0, 0, 3.0, 0)])
        bth = make_output(boxes=boxes, n_classes=2,
                          cells=[(2, 3, 0, 0, 0, 0, 0, 3.0, 1)])
        out = np.concatenate([a, bth], axis=0)
        dets = get_predicted_objects(boxes, out, 0.5, n_classes=2)
        assert sorted(d.example for d in dets) == [0, 1]
        d1 = next(d for d in dets if d.example == 1)
        assert d1.center_x == pytest.approx(3.5, abs=1e-5)
        assert d1.center_y == pytest.approx(2.5, abs=1e-5)

    def test_rank_and_threshold_validation(self):
        with pytest.raises(ValueError, match="rank 4"):
            get_predicted_objects(((1.0, 1.0),), np.zeros((4, 4, 7)), 0.5)
        with pytest.raises(ValueError, match="confidence threshold"):
            get_predicted_objects(((1.0, 1.0),),
                                  np.zeros((1, 4, 4, 7), np.float32), 1.5)


class TestNms:
    def _obj(self, cx, cy, w, h, conf, cls, n_classes=3, example=0):
        probs = np.full(n_classes, 0.001)
        probs[cls] = 1.0 - 0.001 * (n_classes - 1)
        return DetectedObject(example, cx, cy, w, h, probs, conf)

    def test_iou_hand_computed(self):
        a = self._obj(1.0, 1.0, 2.0, 2.0, 0.9, 0)   # box [0,2]x[0,2]
        b = self._obj(2.0, 1.0, 2.0, 2.0, 0.8, 0)   # box [1,3]x[0,2]
        # intersection 1x2=2, union 4+4-2=6
        assert iou(a, b) == pytest.approx(2.0 / 6.0)
        c = self._obj(10.0, 10.0, 2.0, 2.0, 0.8, 0)
        assert iou(a, c) == 0.0

    def test_lower_confidence_overlap_suppressed(self):
        a = self._obj(1.0, 1.0, 2.0, 2.0, 0.9, 0)
        b = self._obj(1.2, 1.0, 2.0, 2.0, 0.7, 0)   # heavy overlap, same class
        kept = nms([a, b], 0.4)
        assert kept == [a]

    def test_different_class_not_suppressed(self):
        a = self._obj(1.0, 1.0, 2.0, 2.0, 0.9, 0)
        b = self._obj(1.2, 1.0, 2.0, 2.0, 0.7, 1)
        assert len(nms([a, b], 0.4)) == 2

    def test_below_iou_threshold_not_suppressed(self):
        a = self._obj(1.0, 1.0, 2.0, 2.0, 0.9, 0)
        b = self._obj(3.0, 3.0, 2.0, 2.0, 0.7, 0)   # barely touching
        assert len(nms([a, b], 0.4)) == 2

    def test_suppressed_box_does_not_suppress_others(self):
        # Reference semantics (nms nulls in place, scans in list order):
        # b(0.8) is suppressed by a(0.9); c(0.7) overlaps only b, and by
        # the time c is checked b is already nulled, so c SURVIVES.
        a = self._obj(0.0, 0.0, 2.0, 2.0, 0.9, 0)
        b = self._obj(1.0, 0.0, 2.0, 2.0, 0.8, 0)   # iou(a,b)=2/6 > 0.3
        c = self._obj(2.6, 0.0, 2.0, 2.0, 0.7, 0)   # iou(b,c)=0.8/7.2≈0.39? no:
        # b=[0,2], c=[1.6,3.6]: inter 0.4*2=0.8, union 8-0.8=7.2 → 0.111 <0.3
        # make c overlap b ABOVE threshold but not a:
        c = self._obj(2.0, 0.0, 2.0, 2.0, 0.7, 0)   # b∩c width 1 → iou 2/6
        kept = nms([a, b, c], 0.3)
        assert a in kept and b not in kept and c in kept

    def test_through_threshold_pipeline(self):
        boxes = ((1.0, 1.0),)
        out = make_output(boxes=boxes, n_classes=2, cells=[
            (1, 1, 0, 0.0, 0.0, np.log(3.0), np.log(3.0), 3.0, 0),
            (1, 2, 0, 0.0, 0.0, np.log(3.0), np.log(3.0), 2.0, 0),
        ])
        no_nms = get_predicted_objects(boxes, out, 0.5, n_classes=2)
        assert len(no_nms) == 2
        with_nms = get_predicted_objects(boxes, out, 0.5,
                                         nms_threshold=0.4, n_classes=2)
        assert len(with_nms) == 1
        assert with_nms[0].confidence == pytest.approx(sigmoid(3.0), rel=1e-5)


class TestLayerApi:
    def test_layer_method_and_matrices(self):
        boxes = ((1.0, 1.0), (2.0, 0.5))
        layer = Yolo2OutputLayer(boxes=boxes, n_classes=3)
        out = make_output(boxes=boxes, cells=[
            (2, 1, 1, 0.0, 0.0, 0.0, 0.0, 2.0, 1)])
        dets = layer.get_predicted_objects(out, 0.5)
        assert len(dets) == 1 and dets[0].predicted_class == 1
        conf = np.asarray(layer.get_confidence_matrix(out, 0, 1))
        assert conf.shape == (4, 4)
        assert conf[2, 1] == pytest.approx(sigmoid(2.0), rel=1e-5)
        assert conf[0, 0] < 1e-6
        probs = np.asarray(layer.get_probability_matrix(out, 0, 1))
        assert probs.shape == (4, 4, 2)
        assert probs[2, 1, 1] > 0.99

    def test_end_to_end_trained_net_emits_detections(self):
        """A conv net with a Yolo2OutputLayer head must produce detections
        through the real network output path (the round-2 verdict's 'user
        literally cannot get detections out' gap)."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import ConvolutionLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        boxes = ((1.0, 1.0),)
        n_classes = 2
        conf = (NeuralNetConfiguration.builder().seed(7).list()
                .layer(ConvolutionLayer(n_in=3, n_out=8, kernel_size=(3, 3),
                                        convolution_mode="same", activation="relu"))
                .layer(ConvolutionLayer(n_in=8,
                                        n_out=len(boxes) * (5 + n_classes),
                                        kernel_size=(1, 1),
                                        activation="identity"))
                .layer(Yolo2OutputLayer(boxes=boxes, n_classes=n_classes))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
        # labels [N,H,W,5+C]: one object at cell (3,4) of example 0
        y = np.zeros((2, 8, 8, 5 + n_classes), np.float32)
        y[0, 3, 4] = [4.5, 3.5, 1.0, 1.0, 1.0, 1.0, 0.0]
        net.fit(x, y, epochs=2)  # just exercise the loss path
        raw = np.asarray(net.output(x))
        assert raw.shape == (2, 8, 8, len(boxes) * (5 + n_classes))
        dets = net.layers[-1].get_predicted_objects(raw, 0.0)
        assert len(dets) > 0
        assert all(isinstance(d, DetectedObject) for d in dets)
