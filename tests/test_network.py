"""MultiLayerNetwork behavior + config serde round-trips (reference: config
serde tests + MultiLayerTest patterns in deeplearning4j-core)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    GravesLSTMLayer,
    LSTMLayer,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs, StepSchedule

RNG = np.random.default_rng(7)


def _class_data(n=256, d=8, c=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, c))
    y = np.eye(c, dtype=np.float32)[np.argmax(x @ w, 1)]
    return x, y


class TestConfigSerde:
    def _roundtrip(self, conf):
        j = conf.to_json()
        conf2 = MultiLayerConfiguration.from_json(j)
        assert conf2.to_json() == j
        return conf2

    def test_dense_conf_roundtrip(self):
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(0.01))
                .l2(1e-4).list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(DropoutLayer(dropout=0.8))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(8)).build())
        conf2 = self._roundtrip(conf)
        assert conf2.layers[0].n_in == 8
        assert conf2.layers[0].n_out == 16
        assert isinstance(conf2.global_conf.updater, Adam)

    def test_cnn_conf_roundtrip(self):
        conf = (NeuralNetConfiguration.builder().updater(Nesterovs(0.1, 0.9)).list()
                .layer(ConvolutionLayer(n_out=6, kernel_size=(5, 5), stride=(1, 1),
                                        padding=(2, 2), activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(BatchNormalizationLayer())
                .layer(OutputLayer(n_out=10))
                .set_input_type(InputType.convolutional(28, 28, 1)).build())
        conf2 = self._roundtrip(conf)
        assert conf2.layers[0].kernel_size == (5, 5)
        assert conf2.layers[0].n_in == 1

    def test_rnn_conf_roundtrip_with_schedule(self):
        conf = (NeuralNetConfiguration.builder()
                .updater(Adam(StepSchedule("iteration", 0.01, 0.5, 100.0))).list()
                .layer(GravesLSTMLayer(n_out=32))
                .layer(RnnOutputLayer(n_out=5))
                .set_input_type(InputType.recurrent(5, 20))
                .t_bptt_length(10).build())
        conf2 = self._roundtrip(conf)
        assert conf2.backprop_type == "truncated_bptt"
        assert conf2.tbptt_fwd_length == 10
        assert isinstance(conf2.global_conf.updater.learning_rate, StepSchedule)

    def test_trained_params_survive_conf_rebuild(self):
        x, y = _class_data()
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01)).list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(8)).build())
        m = MultiLayerNetwork(conf).init()
        m.fit(ListDataSetIterator(DataSet(x, y), 64), epochs=3)
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        m2 = MultiLayerNetwork(conf2).init()
        m2.params = m.params
        m2.states = m.states
        np.testing.assert_allclose(np.asarray(m.output(x[:8])),
                                   np.asarray(m2.output(x[:8])), rtol=1e-6)


class TestTraining:
    def test_fit_reduces_loss_and_accuracy(self):
        x, y = _class_data()
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01)).list()
                .layer(DenseLayer(n_out=32, activation="relu"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(8)).build())
        m = MultiLayerNetwork(conf).init()
        it = ListDataSetIterator(DataSet(x, y), 64, shuffle=True)
        m.fit(it, epochs=1)
        early = m.score_
        m.fit(it, epochs=15)
        assert m.score_ < early
        ev = m.evaluate(ListDataSetIterator(DataSet(x, y), 128))
        assert ev.accuracy() > 0.9

    def test_deterministic_init(self):
        conf_json = (NeuralNetConfiguration.builder().seed(99).list()
                     .layer(DenseLayer(n_out=4))
                     .layer(OutputLayer(n_out=2))
                     .set_input_type(InputType.feed_forward(3)).build().to_json())
        m1 = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json)).init()
        m2 = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json)).init()
        for p1, p2 in zip(m1.params, m2.params):
            for k in p1:
                np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(p2[k]))

    def test_params_flat_roundtrip(self):
        conf = (NeuralNetConfiguration.builder().seed(3).list()
                .layer(DenseLayer(n_out=4, activation="tanh"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(3)).build())
        m = MultiLayerNetwork(conf).init()
        flat = m.params_flat()
        assert flat.shape == (m.num_params(),)
        flat2 = flat * 2
        m.set_params_flat(flat2)
        np.testing.assert_allclose(m.params_flat(), flat2, rtol=1e-6)

    def test_batchnorm_running_stats_update(self):
        x, y = _class_data(64, 6, 2, seed=3)
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01)).list()
                .layer(DenseLayer(n_out=8, activation="identity"))
                .layer(BatchNormalizationLayer())
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(6)).build())
        m = MultiLayerNetwork(conf).init()
        before = np.asarray(m.states[1]["mean"]).copy()
        m.fit(DataSet(x, y))
        after = np.asarray(m.states[1]["mean"])
        assert not np.allclose(before, after)

    def test_dropout_only_in_train(self):
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_out=16, activation="relu", dropout=0.5))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        m = MultiLayerNetwork(conf).init()
        x = RNG.normal(size=(8, 4)).astype(np.float32)
        o1 = np.asarray(m.output(x))
        o2 = np.asarray(m.output(x))
        np.testing.assert_array_equal(o1, o2)  # inference is deterministic

    def test_rnn_time_step_matches_full_forward(self):
        T = 6
        conf = (NeuralNetConfiguration.builder().seed(2).list()
                .layer(LSTMLayer(n_out=8))
                .layer(RnnOutputLayer(n_out=3))
                .set_input_type(InputType.recurrent(4, T)).build())
        m = MultiLayerNetwork(conf).init()
        x = RNG.normal(size=(2, T, 4)).astype(np.float32)
        full = np.asarray(m.output(x))
        m.rnn_clear_previous_state()
        outs = []
        for t in range(T):
            outs.append(np.asarray(m.rnn_time_step(x[:, t, :])))
        stepped = np.stack(outs, axis=1)
        np.testing.assert_allclose(full, stepped, rtol=1e-4, atol=1e-5)

    def test_tbptt_runs(self):
        T = 16
        x = RNG.normal(size=(4, T, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[RNG.integers(0, 2, (4, T))]
        conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(0.01)).list()
                .layer(LSTMLayer(n_out=8))
                .layer(RnnOutputLayer(n_out=2))
                .set_input_type(InputType.recurrent(3, T))
                .t_bptt_length(4).build())
        m = MultiLayerNetwork(conf).init()
        m.fit(DataSet(x, y))
        assert np.isfinite(m.score_)
        # 16 steps / 4 per chunk = 4 iterations
        assert m.iteration == 4

    def test_memory_report(self):
        conf = (NeuralNetConfiguration.builder().list()
                .layer(DenseLayer(n_out=100))
                .layer(OutputLayer(n_out=10))
                .set_input_type(InputType.feed_forward(50)).build())
        rep = conf.memory_report(batch=32)
        assert rep["total_param_bytes"] == (50 * 100 + 100 + 100 * 10 + 10) * 4
        assert len(rep["layers"]) == 2


class TestConvLSTMStateful:
    def test_tbptt_and_rnn_time_step(self):
        from deeplearning4j_tpu.nn.layers import ConvLSTM2DLayer, RnnOutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3)).list()
                .layer(ConvLSTM2DLayer(n_out=3, kernel_size=(3, 3),
                                       convolution_mode="same"))
                .layer(RnnOutputLayer(n_out=2))
                .t_bptt_length(4)
                .set_input_type(InputType.recurrent_convolutional(5, 5, 1, 8))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 8, 5, 5, 1)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, 8))]
        net.fit(x, y)  # TBPTT: chunked scan with carried conv state
        assert np.isfinite(float(net.score_))
        # stateful single-step inference over the conv carry
        net.rnn_clear_previous_state()
        step_outs = [np.asarray(net.rnn_time_step(x[:, t:t + 1]))
                     for t in range(8)]
        full = np.asarray(net.output(x))
        np.testing.assert_allclose(np.concatenate(step_outs, axis=1), full,
                                   rtol=1e-4, atol=1e-5)


class TestMaskLayer:
    """MaskLayer (nn/conf/layers/util/MaskLayer.java:24): applies the mask to
    activations (and, via autodiff, gradients), otherwise pass-through."""

    def test_2d_per_example_mask(self):
        from deeplearning4j_tpu.nn.layers import MaskLayer
        x = np.arange(12, dtype=np.float32).reshape(3, 4) + 1
        m = np.array([1.0, 0.0, 1.0], np.float32)
        y, _ = MaskLayer().forward({}, x, mask=m)
        np.testing.assert_allclose(np.asarray(y), x * m[:, None])
        y2, _ = MaskLayer().forward({}, x, mask=m[:, None])  # column vector
        np.testing.assert_allclose(np.asarray(y2), x * m[:, None])

    def test_3d_step_mask_and_4d_cnn(self):
        from deeplearning4j_tpu.nn.layers import MaskLayer
        rng = np.random.default_rng(0)
        x3 = rng.normal(size=(2, 5, 3)).astype(np.float32)
        m3 = (rng.random((2, 5)) > 0.4).astype(np.float32)
        y3, _ = MaskLayer().forward({}, x3, mask=m3)
        np.testing.assert_allclose(np.asarray(y3), x3 * m3[:, :, None])
        x4 = rng.normal(size=(3, 4, 4, 2)).astype(np.float32)
        m4 = np.array([0.0, 1.0, 1.0], np.float32)
        y4, _ = MaskLayer().forward({}, x4, mask=m4)
        np.testing.assert_allclose(np.asarray(y4),
                                   x4 * m4[:, None, None, None])

    def test_full_elementwise_mask(self):
        from deeplearning4j_tpu.nn.layers import MaskLayer
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 5, 3)).astype(np.float32)
        m = (rng.random((2, 5, 3)) > 0.5).astype(np.float32)
        y, _ = MaskLayer().forward({}, x, mask=m)
        np.testing.assert_allclose(np.asarray(y), x * m)

    def test_per_example_mask_reaches_mid_network_layer(self):
        # a DL4J-style [N,1] feature mask must survive past 2d activations
        # and zero the masked example's outputs at the MaskLayer
        from deeplearning4j_tpu.nn.layers import MaskLayer
        conf = (NeuralNetConfiguration.builder().seed(4).list()
                .layer(DenseLayer(n_out=6, activation="tanh"))
                .layer(MaskLayer())
                .layer(ActivationLayer(activation="identity"))
                .set_input_type(InputType.feed_forward(3)).build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
        m = np.array([[1.0], [0.0], [1.0], [0.0]], np.float32)
        out = np.asarray(net.output(x, mask=m))
        np.testing.assert_allclose(out[1], 0.0)
        np.testing.assert_allclose(out[3], 0.0)
        assert np.abs(out[0]).sum() > 0

    def test_no_mask_is_identity_and_bad_mask_rejected(self):
        from deeplearning4j_tpu.nn.layers import MaskLayer
        x = np.ones((2, 3), np.float32)
        y, _ = MaskLayer().forward({}, x)
        np.testing.assert_allclose(np.asarray(y), x)
        with np.testing.assert_raises_regex(ValueError, "MaskLayer"):
            MaskLayer().forward({}, x, mask=np.ones((3,), np.float32))

    def test_gradients_masked_through_network(self):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers import MaskLayer

        def f(x, m):
            y, _ = MaskLayer().forward({}, x, mask=m)
            return jnp.sum(y ** 2)

        x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        m = np.array([1.0, 0.0], np.float32)
        g = np.asarray(jax.grad(f)(jnp.asarray(x), jnp.asarray(m)))
        np.testing.assert_allclose(g[0], 2 * x[0])
        np.testing.assert_allclose(g[1], 0.0)  # masked row: zero gradient

    def test_per_example_mask_masks_the_loss(self):
        # fitting with a [N,1] feature mask must equal fitting on the
        # unmasked subset: masked examples contribute neither loss nor
        # gradients (DL4J per-example score masking)
        from deeplearning4j_tpu.nn.updaters import Sgd

        def _make():
            conf = (NeuralNetConfiguration.builder().seed(6).updater(Sgd(0.1))
                    .list()
                    .layer(DenseLayer(n_out=6, activation="tanh"))
                    .layer(OutputLayer(n_out=3))
                    .set_input_type(InputType.feed_forward(4)).build())
            return MultiLayerNetwork(conf).init()

        rng = np.random.default_rng(3)
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        keep = np.array([1, 1, 0, 1, 0, 1, 1, 0], np.float32)

        masked = _make()
        masked.fit(DataSet(x, y, features_mask=keep[:, None]))
        subset = _make()
        subset.fit(DataSet(x[keep == 1], y[keep == 1]))

        assert np.isclose(float(masked.score_), float(subset.score_),
                          rtol=1e-5)
        for pm, ps in zip(masked.params, subset.params):
            for k in pm:
                np.testing.assert_allclose(np.asarray(pm[k]),
                                           np.asarray(ps[k]),
                                           rtol=1e-5, atol=1e-6)

    def test_in_network_serde_and_fit(self):
        from deeplearning4j_tpu.nn.layers import MaskLayer
        from deeplearning4j_tpu.nn.updaters import Adam
        conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(MaskLayer())
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(5)).build())
        from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert type(conf2.layers[1]).__name__ == "MaskLayer"
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(x, y)
        assert np.isfinite(float(net.score_))
        out = np.asarray(net.output(x))
        assert out.shape == (16, 3)


class TestFitBatchesOnDeviceMLN:
    def test_matches_sequential_fit(self):
        from deeplearning4j_tpu.nn.updaters import Sgd

        def make():
            conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1))
                    .list()
                    .layer(DenseLayer(n_out=10, activation="tanh"))
                    .layer(OutputLayer(n_out=3))
                    .set_input_type(InputType.feed_forward(6)).build())
            return MultiLayerNetwork(conf).init()

        rng = np.random.default_rng(0)
        batches = []
        for _ in range(4):
            yc = rng.integers(0, 3, 16)
            x = rng.normal(size=(16, 6)).astype(np.float32)
            x[np.arange(16), yc] += 2.0
            batches.append(DataSet(x, np.eye(3, dtype=np.float32)[yc]))
        seq = make()
        for ds in batches:
            seq.fit(ds)
        dev = make()
        dev.fit_batches_on_device(batches)
        assert dev.iteration == seq.iteration == 4
        for pl, pd in zip(seq.params, dev.params):
            for k in pl:
                np.testing.assert_allclose(np.asarray(pd[k]),
                                           np.asarray(pl[k]),
                                           rtol=2e-5, atol=2e-6)

    def test_listener_sees_every_iteration(self):
        from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener
        conf = (NeuralNetConfiguration.builder().seed(5).list()
                .layer(DenseLayer(n_out=6, activation="tanh"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        lst = CollectScoresIterationListener(frequency=1)
        net.listeners.append(lst)
        rng = np.random.default_rng(1)
        batches = [DataSet(rng.normal(size=(8, 4)).astype(np.float32),
                           np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)])
                   for _ in range(3)]
        net.fit_batches_on_device(batches)
        assert len(lst.scores) == 3


class TestYamlSerde:
    """MultiLayerConfiguration.toYaml/fromYaml parity (the reference's
    Jackson YAML face) — same dict as the JSON round trip."""

    def test_mln_yaml_round_trip(self):
        from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(0.01))
                .l2(1e-4).list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(8))
                .build())
        y = conf.to_yaml()
        assert "layers" in y and "dense" in y.lower()
        back = MultiLayerConfiguration.from_yaml(y)
        assert back.to_json() == conf.to_json()
        net = MultiLayerNetwork(back).init()
        assert net.output(np.zeros((2, 8), np.float32)).shape == (2, 3)

    def test_graph_yaml_round_trip(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        g = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
             .graph_builder().add_inputs("in"))
        g.add_layer("h", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
        g.add_layer("out", OutputLayer(n_in=8, n_out=2), "h")
        conf = g.set_outputs("out").build()
        back = ComputationGraphConfiguration.from_yaml(conf.to_yaml())
        assert back.to_json() == conf.to_json()


def test_summary_tables():
    """MultiLayerNetwork.summary() / ComputationGraph.summary() parity."""
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu",
                              name="hidden"))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    net = MultiLayerNetwork(conf).init()
    s = net.summary()
    assert "DenseLayer (hidden)" in s and "OutputLayer" in s
    assert f"Total parameters: {net.num_params():,}" in s

    from deeplearning4j_tpu.nn.graph import ComputationGraph
    g = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
         .graph_builder().add_inputs("in"))
    g.add_layer("h", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
    g.add_layer("out", OutputLayer(n_in=8, n_out=2), "h")
    cg = ComputationGraph(g.set_outputs("out").build()).init()
    s2 = cg.summary()
    assert "h" in s2 and "OutputLayer" in s2 and "Total parameters" in s2


def test_output_accepts_iterator_and_dataset():
    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(20, 4)).astype(np.float32)
    y = np.zeros((20, 3), np.float32)
    direct = np.asarray(net.output(x))
    via_it = np.asarray(net.output(ListDataSetIterator(DataSet(x, y), 8)))
    np.testing.assert_allclose(via_it, direct, rtol=1e-6)
    via_ds = np.asarray(net.output(DataSet(x, y)))
    np.testing.assert_allclose(via_ds, direct, rtol=1e-6)


def test_layerwise_pretraining():
    """MultiLayerNetwork.pretrain / pretrainLayer: unsupervised layer-wise
    training drives the autoencoder layer's reconstruction loss down."""
    from deeplearning4j_tpu.nn.layers import AutoEncoderLayer

    conf = (NeuralNetConfiguration.builder().seed(4).updater(Adam(0.01))
            .list()
            .layer(AutoEncoderLayer(n_in=8, n_out=4, activation="sigmoid"))
            .layer(OutputLayer(n_in=4, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = (rng.random((64, 8)) < 0.3).astype(np.float32)
    import jax.numpy as jnp
    import jax
    l0 = float(jax.jit(net.layers[0].pretrain_loss)(
        net.params[0], jnp.asarray(x), jax.random.PRNGKey(0)))
    net.pretrain(x, epochs=30)
    l1 = float(jax.jit(net.layers[0].pretrain_loss)(
        net.params[0], jnp.asarray(x), jax.random.PRNGKey(0)))
    assert l1 < l0 * 0.9
    # non-pretrainable layer rejected loudly
    with pytest.raises(ValueError, match="no\\s+pretrain_loss|no pretrain_loss"):
        net.pretrain_layer(1, x)


def test_score_examples_per_example_losses():
    """MultiLayerNetwork.scoreExamples: per-example data-term losses; the
    mean matches score(), and regularization adds uniformly on request."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(0.01))
            .l2(1e-3).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    cls = rng.integers(0, 2, 16)
    y = np.eye(2, dtype=np.float32)[cls]
    ds = DataSet(x, y)
    scores = net.score_examples(ds)
    assert scores.shape == (16,)
    # score(ds) includes the reg term once; per-example data terms average
    # to the data component
    reg = float(net._regularization(net.params))
    assert np.mean(scores) == pytest.approx(net.score(ds) - reg, rel=1e-4)
    with_reg = net.score_examples(ds, add_regularization=True)
    np.testing.assert_allclose(with_reg, scores + reg, rtol=1e-5)
    # an obviously-wrong-labeled example scores higher than a correct one
    y_bad = y.copy()
    y_bad[0] = 1 - y_bad[0]
    s_bad = net.score_examples(DataSet(x, y_bad))
    assert s_bad[0] != pytest.approx(scores[0])


def test_set_learning_rate_layer_names_and_to_graph():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu",
                              name="enc"))
            .layer(OutputLayer(n_in=8, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert net.get_layer_names() == ["enc", "OutputLayer"]
    assert net.layer_size(0) == 8 and net.layer_size(1) == 2

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    net.fit(x, y)
    net.set_learning_rate(0.0)  # frozen from here
    w = np.asarray(net.params[0]["W"]).copy()
    net.fit(x, y)
    np.testing.assert_allclose(np.asarray(net.params[0]["W"]), w)

    cg = net.to_computation_graph()
    np.testing.assert_allclose(np.asarray(cg.output(x)),
                               np.asarray(net.output(x)), rtol=1e-6)
    cg.fit(x, y)  # the converted graph trains
    assert np.isfinite(cg.score_)
