"""MultiLayerNetwork API-surface parity: layer/param access, stored rnn
state, classifier conveniences, save/load facades.

Reference: MultiLayerNetwork.java (getLayer/paramTable/getParam/setParam,
feedForwardToLayer:949, rnnGetPreviousState/rnnSetPreviousState,
f1Score/labelProbabilities/numLabels, save/load).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.core import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import LSTMLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def dense_net():
    conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd").list()
            .layer(DenseLayer(n_in=3, n_out=4, activation="tanh", name="d0"))
            .layer(OutputLayer(n_in=4, n_out=2))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


class TestLayerParamAccess:
    def test_layer_getters(self):
        net = dense_net()
        assert net.n_layers == 2
        assert net.get_layer(0) is net.layers[0]
        assert net.get_layer("d0") is net.layers[0]
        assert net.get_output_layer() is net.layers[1]
        assert net.get_layers() == net.layers
        with pytest.raises(KeyError):
            net.get_layer("missing")

    def test_param_table_keys(self):
        net = dense_net()
        table = net.param_table()
        assert set(table) == {"0_W", "0_b", "1_W", "1_b"}
        assert table["0_W"].shape == (3, 4)

    def test_get_set_param_roundtrip(self):
        net = dense_net()
        w = np.asarray(net.get_param("0_W"))
        net.set_param("0_W", w * 0.0)
        assert float(np.abs(np.asarray(net.get_param("0_W"))).sum()) == 0.0
        with pytest.raises(ValueError):
            net.set_param("0_W", np.zeros((2, 2)))

    def test_set_param_changes_output(self):
        net = dense_net()
        x = np.ones((2, 3), np.float32)
        before = np.asarray(net.output(x))
        net.set_param("1_b", np.asarray([5.0, -5.0]))
        after = np.asarray(net.output(x))
        assert not np.allclose(before, after)

    def test_num_labels(self):
        assert dense_net().num_labels() == 2


class TestFeedForwardToLayer:
    def test_prefix_of_feed_forward(self):
        net = dense_net()
        x = np.ones((2, 3), np.float32)
        acts = net.feed_forward_to_layer(0, x)
        full = net.feed_forward(x)
        assert len(acts) == 2  # input + layer0
        np.testing.assert_allclose(np.asarray(acts[1]), np.asarray(full[1]))
        with pytest.raises(ValueError):
            net.feed_forward_to_layer(5, x)


class TestClassifierConvenience:
    def test_f1_and_probabilities(self):
        net = dense_net()
        x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.random.RandomState(1).randint(0, 2, 8)]
        f1 = net.f1_score(x, y)
        assert 0.0 <= f1 <= 1.0
        probs = net.label_probabilities(x)
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)


class TestRnnStoredState:
    def _rnn_net(self):
        conf = (NeuralNetConfiguration.builder().seed(2).updater("sgd").list()
                .layer(LSTMLayer(n_in=3, n_out=5))
                .layer(RnnOutputLayer(n_in=5, n_out=2))
                .build())
        net = MultiLayerNetwork(conf)
        net.init()
        return net

    def test_get_set_previous_state(self):
        net = self._rnn_net()
        assert net.rnn_get_previous_state(0) is None
        x = np.random.RandomState(0).randn(2, 4, 3).astype(np.float32)
        net.rnn_time_step(x)
        state = net.rnn_get_previous_state(0)
        assert state is not None
        # continuing from a saved state == re-setting it and continuing
        x2 = np.random.RandomState(1).randn(2, 2, 3).astype(np.float32)
        out_a = np.asarray(net.rnn_time_step(x2))
        net.rnn_clear_previous_state()
        net.rnn_time_step(x)  # rebuild the same state
        net.rnn_set_previous_state(0, state)
        out_b = np.asarray(net.rnn_time_step(x2))
        np.testing.assert_allclose(out_a, out_b, rtol=1e-5)

    def test_set_before_step_raises(self):
        net = self._rnn_net()
        with pytest.raises(ValueError):
            net.rnn_set_previous_state(0, None)


class TestSaveLoadFacade:
    def test_instance_save_static_load(self, tmp_path):
        net = dense_net()
        p = str(tmp_path / "m.zip")
        net.save(p)
        again = MultiLayerNetwork.load(p)
        x = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(np.asarray(again.output(x)),
                                   np.asarray(net.output(x)), rtol=1e-6)


class TestGraphApiSurface:
    """ComputationGraph mirrors: getLayer/paramTable/getParam/setParam/save/load."""

    def _graph(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
                .graph_builder()
                .add_inputs("in")
                .add_layer("dense_0", DenseLayer(n_in=3, n_out=4, activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_in=4, n_out=2), "dense_0")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf)
        g.init()
        return g

    def test_layer_and_param_access(self):
        g = self._graph()
        assert g.get_layer("dense_0").n_out == 4
        assert len(g.get_layers()) == 2
        table = g.param_table()
        assert "dense_0_W" in table and "out_b" in table
        # vertex names containing underscores resolve correctly
        w = np.asarray(g.get_param("dense_0_W"))
        assert w.shape == (3, 4)
        g.set_param("dense_0_W", w * 0)
        assert float(np.abs(np.asarray(g.get_param("dense_0_W"))).sum()) == 0
        with pytest.raises(KeyError):
            g.get_param("nope_W")
        with pytest.raises(ValueError):
            g.set_param("out_b", np.zeros(7))

    def test_save_load_facade(self, tmp_path):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        g = self._graph()
        p = str(tmp_path / "g.zip")
        g.save(p)
        again = ComputationGraph.load(p)
        x = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(np.asarray(again.output_single(x)),
                                   np.asarray(g.output_single(x)), rtol=1e-6)

    def test_graph_introspection(self):
        g = self._graph()
        assert g.get_num_layers() == 2
        assert g.get_num_input_arrays() == 1
        assert g.get_num_output_arrays() == 1
        assert g.get_output_layer(0).n_out == 2
        assert "dense_0" in g.get_vertices()
        order = g.topological_sort_order()
        assert order.index("dense_0") < order.index("out")

    def test_graph_rnn_state_roundtrip(self):
        import numpy as np
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
        from deeplearning4j_tpu.nn.layers.recurrent import LSTMLayer
        conf = (NeuralNetConfiguration.builder().seed(3).updater("sgd")
                .graph_builder()
                .add_inputs("in")
                .add_layer("lstm", LSTMLayer(n_in=3, n_out=5), "in")
                .add_layer("out", RnnOutputLayer(n_in=5, n_out=2), "lstm")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf)
        g.init()
        assert g.rnn_get_previous_state("lstm") is None
        x = np.random.RandomState(0).randn(2, 4, 3).astype(np.float32)
        g.rnn_time_step(x)
        states = g.rnn_get_previous_states()
        assert "lstm" in states and states["lstm"] is not None
        x2 = np.random.RandomState(1).randn(2, 2, 3).astype(np.float32)
        out_a = np.asarray(g.rnn_time_step(x2))
        g.rnn_clear_previous_state()
        g.rnn_time_step(x)
        g.rnn_set_previous_states(states)
        out_b = np.asarray(g.rnn_time_step(x2))
        np.testing.assert_allclose(out_a, out_b, rtol=1e-5)
