"""Worker process for the worker-failure recovery test.

Launched twice (process_id 0 and 1) by tests/test_multiprocess.py's
failure-recovery test. Trains the same deterministic job as
``distributed_worker.py`` but one EPOCH per fit() call, with process 0
writing an orbax rotation checkpoint after every epoch (the preemption
pattern: ``util/preemption.py`` + ``util/orbax_checkpoint.py``).

Modes (argv[4]):
- ``full``:   train all EPOCHS epochs uninterrupted, dump params.
- ``victim``: train normally; the TEST kills this job mid-epoch-4 (after
  the epoch-3 checkpoint marker appears). Nothing special in-process —
  death arrives as SIGKILL, like a real preemption without grace.
- ``resume``: restore the latest checkpoint (epoch 3), train the
  remaining epochs, dump params.

The recovery contract (beyond the reference, whose worker membership is
fixed at job start — ``SharedTrainingWrapper.java:131-156``): resumed
params must EQUAL the uninterrupted run's.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly ONE local CPU device

import jax

jax.config.update("jax_platforms", "cpu")

EPOCHS = 6
CKPT_EPOCH = 3  # the epoch whose checkpoint the resume restarts from


def build_data():
    import numpy as np
    rng = np.random.default_rng(0)
    yc = rng.integers(0, 3, 256)
    x = rng.normal(size=(256, 6)).astype(np.float32)
    x[np.arange(256), yc] += 2.5
    y = np.eye(3, dtype=np.float32)[yc]
    return x, y


def build_net():
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def dump(net, out_path):
    import numpy as np
    flat = {}
    for i, layer in enumerate(net.params):
        for k, v in layer.items():
            flat[f"{i}:{k}"] = np.asarray(v)
    np.savez(out_path, **flat)


def main():
    coordinator, pid = sys.argv[1], int(sys.argv[2])
    out_path, mode, workdir = sys.argv[3], sys.argv[4], sys.argv[5]
    from deeplearning4j_tpu.parallel import (
        DistributedMultiLayerNetwork,
        SharedTrainingMaster,
        init_distributed,
    )
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.util.orbax_checkpoint import OrbaxCheckpointManager

    init_distributed(coordinator_address=coordinator, num_processes=2,
                     process_id=pid)
    assert jax.device_count() == 2

    x, y = build_data()
    ckpt_dir = os.path.join(workdir, "ckpt")

    if mode == "resume":
        # every process restores the same checkpoint — identical state,
        # like the deterministic broadcast at first start. Each process
        # reads independently (active_processes={pid}) so no cross-process
        # barrier is needed for the read-only restore.
        with OrbaxCheckpointManager(
                ckpt_dir, active_processes={pid},
                barrier_sync_key_prefix=f"resume{pid}") as mgr:
            start_epoch = mgr.latest_step()
            net = mgr.restore()
        assert start_epoch == CKPT_EPOCH, start_epoch
    else:
        start_epoch = 0
        net = build_net()

    mesh = make_mesh({"data": 2})
    master = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                  mesh=mesh)
    def master_state_path(p, epoch):
        return os.path.join(workdir, f"master_state.{p}.epoch{epoch}.npz")

    if mode == "resume":
        # exact resume needs the compression state too (adaptive threshold
        # + this process's residual shard) — rank-local, so each process
        # loads its own file
        master.load_state(master_state_path(pid, CKPT_EPOCH))
    front = DistributedMultiLayerNetwork(net, master)

    # only the coordinator writes the orbax model checkpoint (replicated
    # state; active_processes keeps orbax's barriers inside that process);
    # the compression state is rank-local, so EVERY process saves its own
    mgr = OrbaxCheckpointManager(ckpt_dir, max_to_keep=2,
                                 active_processes={0}) \
        if (mode == "victim" and pid == 0) else None
    for epoch in range(start_epoch, EPOCHS):
        front.fit(ListDataSetIterator(DataSet(x, y), 32), epochs=1)
        print(f"[{pid}] epoch {epoch + 1} done", flush=True)
        if mode == "victim":
            master.save_state(master_state_path(pid, epoch + 1))
        if mgr is not None:
            mgr.save(epoch + 1, net)
            mgr.wait_until_finished()
            if epoch + 1 == CKPT_EPOCH:
                # marker the test watches for before killing this job —
                # written only once the PEER's rank-local state for this
                # epoch exists too, so the kill can't race its save
                import time
                deadline = time.time() + 120
                while not os.path.exists(master_state_path(1, epoch + 1)):
                    if time.time() > deadline:
                        raise RuntimeError("peer master state never appeared")
                    time.sleep(0.2)
                with open(os.path.join(workdir, "epoch3_saved"), "w") as fh:
                    fh.write("ok")
                # hold here until the SIGKILL arrives: letting training race
                # ahead could land a LATER checkpoint before the kill and
                # make the resume start from the wrong epoch (flaky on fast
                # machines). The peer blocks at its next collective.
                import time
                while True:
                    time.sleep(1)
    if mgr is not None:
        mgr.close()

    if pid == 0 and mode in ("full", "resume"):
        dump(net, out_path)
    print(f"WORKER{pid}_DONE", flush=True)


if __name__ == "__main__":
    main()
