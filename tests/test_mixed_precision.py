"""Mixed-precision (f32 master weights, bf16 compute) tests."""

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _data(n=512, seed=0):
    rng = np.random.default_rng(seed)
    y_idx = rng.integers(0, 3, n)
    x = rng.normal(size=(n, 10)).astype(np.float32)
    x[np.arange(n), y_idx] += 2.5
    return DataSet(x, np.eye(3, dtype=np.float32)[y_idx])


def _conf(compute_dtype):
    return (NeuralNetConfiguration.builder().seed(1)
            .compute_dtype(compute_dtype).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(10)).build())


class TestMixedPrecision:
    def test_params_stay_f32_and_training_works(self):
        net = MultiLayerNetwork(_conf("bfloat16")).init()
        ds = _data()
        net.fit(ListDataSetIterator(ds, 128, shuffle=True), epochs=8)
        # master weights keep the storage dtype
        assert net.params[0]["W"].dtype == jnp.float32
        ev = net.evaluate(ListDataSetIterator(ds, 256))
        assert ev.accuracy() > 0.9

    def test_forward_activation_is_compute_dtype(self):
        net = MultiLayerNetwork(_conf("bfloat16")).init()
        x = jnp.zeros((4, 10), jnp.float32)
        h, _, _ = net._forward_all(net.params, net.states, x, train=False,
                                   rng=None, mask=None)
        assert h.dtype == jnp.bfloat16

    def test_matches_f32_training_approximately(self):
        ds = _data(256, seed=3)

        def train(cd):
            net = MultiLayerNetwork(_conf(cd)).init()
            net.fit(ListDataSetIterator(ds, 128, shuffle=True, seed=5), epochs=5)
            return net

        f32 = train(None)
        mixed = train("bfloat16")
        # same data/seed: losses land in the same regime
        assert abs(float(f32.score_) - float(mixed.score_)) < 0.15

    def test_graph_mixed_precision(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        g = (NeuralNetConfiguration.builder().seed(1)
             .compute_dtype("bfloat16").graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.feed_forward(10)))
        g.add_layer("d", DenseLayer(n_out=16, activation="relu"), "in")
        g.add_layer("out", OutputLayer(n_out=3), "d")
        conf = g.set_outputs("out").build()
        net = ComputationGraph(conf)
        net.init()
        ds = _data(256)
        net.fit(ListDataSetIterator(ds, 128), epochs=5)
        first = next(iter(net.params.values()))
        assert first["W"].dtype == jnp.float32
        assert float(net.score_) < 1.2


class TestGradientCheckpointing:
    def test_same_results_with_remat(self):
        """Remat changes memory, not math: training trajectories match."""
        ds = _data(256, seed=2)

        def train(remat):
            conf = (NeuralNetConfiguration.builder().seed(1)
                    .gradient_checkpointing(remat).list()
                    .layer(DenseLayer(n_out=32, activation="tanh"))
                    .layer(DenseLayer(n_out=32, activation="tanh"))
                    .layer(OutputLayer(n_out=3))
                    .set_input_type(InputType.feed_forward(10)).build())
            net = MultiLayerNetwork(conf).init()
            net.fit(ListDataSetIterator(ds, 128, shuffle=True, seed=3),
                    epochs=4)
            return net

        plain, remat = train(False), train(True)
        assert abs(float(plain.score_) - float(remat.score_)) < 1e-5
        for pl, pr in zip(plain.params, remat.params):
            for k in pl:
                np.testing.assert_allclose(np.asarray(pl[k]), np.asarray(pr[k]),
                                           rtol=1e-5, atol=1e-6)

    def test_remat_compiles_and_reports_memory(self):
        """Remat composes with the XLA memory analysis. (The buffer-assignment
        savings materialize on the TPU backend; the CPU scheduler may order
        the recompute clusters differently, so no inequality is asserted
        here — see the TPU verification in BASELINE.md.)"""
        from deeplearning4j_tpu.nn.conf import compiled_memory_analysis

        def analyze(remat):
            b = (NeuralNetConfiguration.builder().seed(1)
                 .gradient_checkpointing(remat).list())
            for _ in range(12):
                b.layer(DenseLayer(n_out=512, activation="tanh"))
            conf = (b.layer(OutputLayer(n_out=8))
                    .set_input_type(InputType.feed_forward(64)).build())
            net = MultiLayerNetwork(conf).init()
            return compiled_memory_analysis(net, batch=256)

        plain = analyze(False)
        remat = analyze(True)
        if not (plain and remat):
            import pytest
            pytest.skip("backend does not expose XLA memory analysis")
        assert plain["total"] > 0 and remat["total"] > 0

    def test_graph_remat(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        g = (NeuralNetConfiguration.builder().seed(1)
             .gradient_checkpointing(True).graph_builder()
             .add_inputs("in").set_input_types(InputType.feed_forward(10)))
        g.add_layer("d1", DenseLayer(n_out=16, activation="relu"), "in")
        g.add_layer("out", OutputLayer(n_out=3), "d1")
        net = ComputationGraph(g.set_outputs("out").build())
        net.init()
        ds = _data(128)
        net.fit(ListDataSetIterator(ds, 64), epochs=3)
        assert float(net.score_) < 1.2


class TestBatchNormMixedPrecisionInference:
    """Regression: f32 BN running stats must not promote the bf16 stream
    back to f32 mid-network — inference after bf16 training used to crash
    with a conv dtype mismatch."""

    def _bn_conf(self, compute_dtype):
        from deeplearning4j_tpu.nn.layers import BatchNormalizationLayer
        return (NeuralNetConfiguration.builder().seed(2)
                .compute_dtype(compute_dtype).list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(BatchNormalizationLayer(activation="relu"))
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.convolutional(8, 8, 1)).build())

    def test_mln_train_then_infer(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 8, 8, 1)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        net = MultiLayerNetwork(self._bn_conf("bfloat16")).init()
        net.fit(x, y, epochs=2)
        out = np.asarray(net.output(x))
        assert out.shape == (8, 2)
        assert np.isfinite(out).all()
        # running stats stay f32 even though compute is bf16
        assert net.states[1]["mean"].dtype == jnp.float32

    def test_graph_train_then_infer(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.layers import BatchNormalizationLayer, LossLayer
        g = (NeuralNetConfiguration.builder().seed(3)
             .compute_dtype("bfloat16").graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.convolutional(8, 8, 1)))
        g.add_layer("c1", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                           convolution_mode="same"), "in")
        g.add_layer("bn", BatchNormalizationLayer(activation="relu"), "c1")
        g.add_layer("c2", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                           convolution_mode="same"), "bn")
        g.add_layer("gap", __import__("deeplearning4j_tpu.nn.layers",
                                      fromlist=["GlobalPoolingLayer"]
                                      ).GlobalPoolingLayer(), "c2")
        g.add_layer("out", OutputLayer(n_out=2), "gap")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(4, 8, 8, 1)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        net.fit(x, y)
        out = np.asarray(net.output(x))
        assert out.shape == (4, 2) and np.isfinite(out).all()


def test_batchnorm_f32_large_mean_stable():
    """Full-precision BN must keep the two-pass variance: E[x^2]-E[x]^2 at
    f32 cancels catastrophically for large-mean features (the fused
    formulation is bf16/f16-only, where the f32 accumulator is wide)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nn.layers import BatchNormalizationLayer

    l = BatchNormalizationLayer(n_in=4)
    p = l.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = (rng.normal(size=(64, 4)) + 1e4).astype(np.float32)  # mean 1e4, std 1
    y, st = l.forward(p, jnp.asarray(x), state=l.init_state(), train=True)
    y = np.asarray(y)
    assert np.isfinite(y).all()
    # normalized output: per-feature std ~1 (variance was not clamped to 0)
    assert 0.5 < y.std() < 2.0, y.std()
    var = np.asarray(st["var"]) * 10  # decay 0.9: blended 0.1 * batch var
    assert (var > 0.3).all(), var


def test_layernorm_bf16_accumulates_in_f32():
    """bf16 LayerNorm moments must accumulate in f32: the normalized output
    should track the f32 reference much closer than bf16 resolution."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nn.layers import LayerNormalizationLayer

    l = LayerNormalizationLayer(n_in=768)
    p = l.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x32 = (rng.normal(size=(4, 768)) + 5.0).astype(np.float32)  # nonzero mean
    ref, _ = l.forward(p, jnp.asarray(x32))
    out16, _ = l.forward(jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16), p), jnp.asarray(x32, jnp.bfloat16))
    err = np.abs(np.asarray(out16, np.float32) - np.asarray(ref)).max()
    assert err < 0.05, err  # bf16-rounded inputs, f32-accumulated moments


def test_lowp_moments_f16_no_overflow():
    """f16 streams square in f32 inside the moment reduction — |x| > 256
    must not overflow to inf variance (bf16 shares f32's exponent range and
    squares in-stream)."""
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nn.layers.norm import _lowp_moments

    x = jnp.asarray(np.full((4, 8), 1000.0), jnp.float16)
    mean, var = _lowp_moments(x, -1, keepdims=True)
    assert np.isfinite(np.asarray(mean)).all()
    assert np.isfinite(np.asarray(var)).all()
    xb = jnp.asarray(np.full((4, 8), 1e10), jnp.bfloat16)
    mean, var = _lowp_moments(xb, -1, keepdims=True)
    assert np.isfinite(np.asarray(mean)).all()


def test_lowp_moments_large_mean_accuracy():
    """bf16 rows with mean >> std: the f32 square keeps the variance
    estimate meaningful (a bf16 square's rounding error ~2^-9*mean^2 would
    swamp it)."""
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nn.layers.norm import _lowp_moments

    rng = np.random.default_rng(0)
    x32 = (rng.normal(size=(8, 768)) + 100.0).astype(np.float32)
    mean, var = _lowp_moments(jnp.asarray(x32, jnp.bfloat16), -1,
                              keepdims=True)
    true_var = x32.var(axis=-1, keepdims=True)
    # the bf16 INPUT quantization itself adds ~(100*2^-9)^2/12 ≈ 0.003
    # variance noise; the estimate must stay within ~25% of truth, not
    # collapse toward the zero clamp
    rel = np.abs(np.asarray(var) - true_var) / true_var
    assert rel.max() < 0.25, (rel.max(), np.asarray(var).min())
