"""Listener family additions: ComposableIterationListener,
ParamAndGradientIterationListener, EvaluativeListener callbacks.

Reference: optimize/listeners/ComposableIterationListener.java,
ParamAndGradientIterationListener.java, callbacks/EvaluationCallback.java.
"""

class TestComposableListener:
    def test_fans_out_to_children(self):
        from deeplearning4j_tpu.optimize.listeners import (
            ComposableIterationListener, TrainingListener)

        calls = []

        class Probe(TrainingListener):
            def __init__(self, tag):
                self.tag = tag

            def iteration_done(self, model, iteration, epoch):
                calls.append(("it", self.tag, iteration))

            def on_epoch_end(self, model):
                calls.append(("ep", self.tag))

        comp = ComposableIterationListener(Probe("a"), Probe("b"))
        comp.iteration_done(None, 3, 0)
        comp.on_epoch_end(None)
        assert calls == [("it", "a", 3), ("it", "b", 3), ("ep", "a"), ("ep", "b")]


class TestParamAndGradientListener:
    def test_stats_lines(self):
        import numpy as np
        from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers.core import DenseLayer
        from deeplearning4j_tpu.nn.layers.output import OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.optimize.listeners import (
            ParamAndGradientIterationListener)

        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd").list()
                .layer(DenseLayer(n_in=3, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2)).build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.random.RandomState(1).randint(0, 2, 8)]
        lines = []
        from deeplearning4j_tpu.datasets.dataset import DataSet
        probe = DataSet(x, y)
        listener = ParamAndGradientIterationListener(
            iterations=2, print_min_max=True, gradient_batch=probe,
            printer=lines.append)
        net.add_listeners(listener)
        for _ in range(4):
            net.fit(x, y)
        assert lines[0].startswith("iteration\tscore")
        assert "0_W_mean_mag" in lines[0] and "1_b_max" in lines[0]
        assert "0_W_grad_mean_mag" in lines[0]  # gradient half present
        assert len(lines) >= 3  # header + iterations 0 and 2
        # gradient values are finite numbers
        first = lines[1].split("\t")
        assert all(np.isfinite(float(v)) for v in first[1:])


class TestEvaluativeCallback:
    def test_callback_fires_after_eval(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
        from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers.core import DenseLayer
        from deeplearning4j_tpu.nn.layers.output import OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.optimize.listeners import EvaluativeListener

        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd").list()
                .layer(DenseLayer(n_in=3, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2)).build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.random.RandomState(1).randint(0, 2, 8)]
        it = ListDataSetIterator(DataSet(x, y), 8)
        seen = []
        listener = EvaluativeListener(it, frequency=1, unit="epoch",
                                      printer=lambda s: None)
        listener.set_callback(lambda l, evals, m: seen.append(evals))
        net.add_listeners(listener)
        net.fit(it, epochs=2)
        assert len(seen) == 2
        # callback always receives a LIST (IEvaluation[] parity), even in
        # default single-Evaluation mode
        assert isinstance(seen[0], list) and hasattr(seen[0][0], "accuracy")


class TestEarlyStoppingListener:
    def test_hooks_fire(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
        from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers.core import DenseLayer
        from deeplearning4j_tpu.nn.layers.output import OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.optimize.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingListener, EarlyStoppingTrainer, InMemoryModelSaver,
            MaxEpochsTerminationCondition)

        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd").list()
                .layer(DenseLayer(n_in=3, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2)).build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).randn(16, 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.random.RandomState(1).randint(0, 2, 16)]
        it = ListDataSetIterator(DataSet(x, y), 8)

        events = []

        class Probe(EarlyStoppingListener):
            def on_start(self, config, model):
                events.append("start")

            def on_epoch(self, epoch, score, config, model):
                events.append(("epoch", epoch))

            def on_completion(self, result):
                events.append(("done", result.total_epochs))

        es = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(it),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            model_saver=InMemoryModelSaver())
        trainer = EarlyStoppingTrainer(es, net, it)
        trainer.set_listener(Probe())
        trainer.fit()
        assert events[0] == "start"
        assert ("epoch", 0) in events and ("epoch", 2) in events
        assert events[-1][0] == "done"

    def test_on_epoch_only_fires_with_fresh_score(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
        from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers.core import DenseLayer
        from deeplearning4j_tpu.nn.layers.output import OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.optimize.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingListener, EarlyStoppingTrainer, InMemoryModelSaver,
            MaxEpochsTerminationCondition)

        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd").list()
                .layer(DenseLayer(n_in=3, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2)).build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).randn(16, 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.random.RandomState(1).randint(0, 2, 16)]
        it = ListDataSetIterator(DataSet(x, y), 8)
        scores = []

        class Probe(EarlyStoppingListener):
            def on_epoch(self, epoch, score, config, model):
                scores.append((epoch, score))

        es = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(it),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(4)],
            model_saver=InMemoryModelSaver(), evaluate_every_n_epochs=2)
        trainer = EarlyStoppingTrainer(es, net, it)
        trainer.set_listener(Probe())
        trainer.fit()
        # fires only on evaluated epochs (1 and 3), never with NaN
        assert [e for e, _ in scores] == [1, 3]
        assert all(np.isfinite(s) for _, s in scores)


class TestSimpleClassificationResults:
    def test_rank_result(self):
        import numpy as np
        from deeplearning4j_tpu.nn.simple import RankClassificationResult
        probs = np.asarray([[0.1, 0.7, 0.2], [0.5, 0.2, 0.3]])
        r = RankClassificationResult(probs, labels=["a", "b", "c"])
        assert r.max_outcomes() == ["b", "a"]
        assert r.ranked_labels_for_row(0) == ["b", "c", "a"]
        assert r.probability_for_row(0, 1) == np.float32(0.7)
        # default integer labels, vector input
        r2 = RankClassificationResult(np.asarray([0.2, 0.8]))
        assert r2.max_outcomes() == ["1"]

    def test_binary_result(self):
        import numpy as np
        from deeplearning4j_tpu.nn.simple import BinaryClassificationResult
        b = BinaryClassificationResult(decision_threshold=0.6)
        out = b.decide(np.asarray([[0.5, 0.5], [0.2, 0.8]]))
        np.testing.assert_array_equal(out, [0, 1])
        weighted = BinaryClassificationResult(
            decision_threshold=0.5, class_weights=[1.0, 3.0])
        # weighting pushes borderline probabilities over the threshold
        assert weighted.decide(np.asarray([0.3]))[0] == 1
