"""Dictionary-backed Viterbi tokenizer (kuromoji/ansj mechanism parity).

The reference bundles kuromoji's lattice decoder + ipadic; here the SAME
decoding objective (word costs + connection costs, minimum-cost path) runs
behind the TokenizerFactory SPI over a LOADED MeCab-format dictionary. The
mini dictionary in tests/fixtures/mini_ja_dict exercises the machinery,
including the classic disambiguation greedy longest-match fails.
"""

import os

import pytest

from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
    DictEntry,
    DictionaryTokenizerFactory,
    MorphologicalDictionary,
    viterbi_segment,
)

DICT_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "mini_ja_dict")


@pytest.fixture(scope="module")
def mini_dict():
    return MorphologicalDictionary.load(DICT_DIR)


class TestLoading:
    def test_entries_and_matrix(self, mini_dict):
        hits = {e.surface for e in mini_dict.lookup("すもも", 0)}
        assert hits == {"すもも"}
        assert mini_dict.connection(1, 2) == -100  # noun → particle cheap
        assert mini_dict.connection(1, 1) == 500   # noun → noun pricey
        assert mini_dict.max_len >= 3

    def test_single_csv_file_load(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("abc,1,1,100,pos\nab,1,1,50,pos\n", encoding="utf-8")
        d = MorphologicalDictionary.load(str(p))
        assert {e.surface for e in d.lookup("abc", 0)} == {"abc", "ab"}

    def test_base_form_feature(self):
        e = DictEntry("食べ", 3, 3, 900,
                      ("動詞", "自立", "*", "*", "一段", "連用形", "食べる"))
        assert e.base_form == "食べる"
        assert DictEntry("x", 0, 0, 0, ("a", "b")).base_form == "x"


class TestViterbi:
    def test_costs_beat_greedy_longest_match(self, mini_dict):
        # すもももももももものうち: greedy longest-match takes もも after
        # すもも and derails into ...もの|うち; the cost lattice recovers
        # すもも|も|もも|も|もも|の|うち (kuromoji's answer)
        text = "すもももももももものうち"
        segs = [e.surface for e in viterbi_segment(text, mini_dict)]
        assert segs == ["すもも", "も", "もも", "も", "もも", "の", "うち"]

    def test_word_cost_disambiguation(self, mini_dict):
        # 食べた: the single noun entry (cost 5000) must LOSE to
        # 食べ(900)+た(350)+conn(-300)
        segs = [e.surface for e in viterbi_segment("食べた", mini_dict)]
        assert segs == ["食べ", "た"]

    def test_unknown_chars_fall_back(self, mini_dict):
        segs = [e.surface for e in viterbi_segment("もXもY", mini_dict)]
        assert segs == ["も", "X", "も", "Y"]

    def test_empty(self, mini_dict):
        assert viterbi_segment("", mini_dict) == []


class TestFactorySPI:
    def test_tokenizer_factory_protocol(self, mini_dict):
        fac = DictionaryTokenizerFactory(mini_dict)
        tok = fac.create("すもももももももものうち")
        assert tok.get_tokens() == ["すもも", "も", "もも", "も", "もも",
                                    "の", "うち"]

    def test_base_form_mode(self, mini_dict):
        fac = DictionaryTokenizerFactory(mini_dict, use_base_form=True)
        assert fac.create("食べた").get_tokens() == ["食べる", "た"]

    def test_from_path_and_word2vec_pipeline(self, tmp_path):
        # the factory slots into the NLP training pipeline like any other
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        fac = DictionaryTokenizerFactory.from_path(DICT_DIR)
        corpus = ["すもももももももものうち"] * 30
        w2v = (Word2Vec.Builder().min_word_frequency(1).layer_size(8)
               .seed(1).epochs(2).tokenizer_factory(fac)
               .iterate(corpus).build())
        w2v.fit()
        assert w2v.has_word("すもも")
        assert w2v.has_word("もも")
        assert w2v.get_word_vector("すもも").shape == (8,)


class TestMeasuredAccuracy:
    """The round-2 verdict's 'measured accuracy' bar: a 296-entry
    MeCab-format dictionary (tests/fixtures/ja_eval_dict, ipadic-shaped
    context classes + full connection matrix) and a 55-sentence tagged
    corpus. Boundary F1 is measured for the lattice tokenizer and for the
    greedy longest-match baseline over the SAME word list."""

    EVAL_DICT = os.path.join(os.path.dirname(__file__), "fixtures",
                             "ja_eval_dict")
    CORPUS = os.path.join(os.path.dirname(__file__), "fixtures",
                          "ja_tagged_corpus.tsv")

    @staticmethod
    def _spans(toks):
        out, p = set(), 0
        for t in toks:
            out.add((p, p + len(t)))
            p += len(t)
        return out

    @classmethod
    def _f1(cls, pred, gold):
        a, b = cls._spans(pred), cls._spans(gold)
        return 2 * len(a & b) / (len(a) + len(b)) if a and b else 0.0

    def _corpus(self):
        # the 296-entry eval dict covers this base corpus; the round-4
        # greedy-trap sentences live in their own fixture
        # (ja_tagged_corpus_traps.tsv, evaluated by
        # TestBootstrappedLexiconAccuracy with a corpus-derived lexicon)
        with open(self.CORPUS, encoding="utf-8") as f:
            for line in f:
                sent, gold = line.rstrip("\n").split("\t")
                yield sent, gold.split("|")

    def test_lattice_f1_and_greedy_gap(self):
        from deeplearning4j_tpu.nlp.language_packs import (
            JapaneseTokenizerFactory)
        d = MorphologicalDictionary.load(self.EVAL_DICT)
        greedy = JapaneseTokenizerFactory(dictionary=set(d._by_surface))
        lat_f1 = gre_f1 = n = 0.0
        for sent, gold in self._corpus():
            lat = [e.surface for e in viterbi_segment(sent, d)]
            gre = greedy.create(sent).get_tokens()
            lat_f1 += self._f1(lat, gold)
            gre_f1 += self._f1(gre, gold)
            n += 1
        lat_f1, gre_f1 = lat_f1 / n, gre_f1 / n
        # measured 2026-07: lattice 1.000, greedy 0.677 (n=55)
        assert lat_f1 >= 0.98, f"lattice F1 regressed: {lat_f1:.4f}"
        assert lat_f1 - gre_f1 >= 0.15, (
            f"lattice ({lat_f1:.4f}) should clearly beat greedy "
            f"longest-match ({gre_f1:.4f})")

    def test_adversarial_sentences_exact(self):
        d = MorphologicalDictionary.load(self.EVAL_DICT)
        segs = [e.surface for e in
                viterbi_segment("すもももももももものうち。", d)]
        assert segs == ["すもも", "も", "もも", "も", "もも", "の",
                        "うち", "。"]
        # 食べた-noun trap: compositional verb+aux must win
        segs = [e.surface for e in viterbi_segment("魚を食べた犬。", d)]
        assert segs == ["魚", "を", "食べ", "た", "犬", "。"]
        # 今日は-noun trap
        segs = [e.surface for e in viterbi_segment("今日は休みです。", d)]
        assert segs == ["今日", "は", "休み", "です", "。"]

    def test_word2vec_trains_over_eval_dict(self):
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        fac = DictionaryTokenizerFactory.from_path(self.EVAL_DICT)
        corpus = [sent for sent, _ in self._corpus()] * 3
        w2v = (Word2Vec.Builder().min_word_frequency(2).layer_size(8)
               .seed(3).epochs(1).tokenizer_factory(fac)
               .iterate(corpus).build())
        w2v.fit()
        assert w2v.has_word("私") and w2v.has_word("は")


class TestChineseSegmentationAccuracy:
    """Round-5 (VERDICT r4 Missing #2): the Japanese measurement
    methodology applied to Chinese — a 50-sentence hand-tagged corpus
    (tests/fixtures/zh_tagged_corpus.tsv) with the classic greedy-trap
    ambiguities (研究生命, 北京大学生物系, 人才能, 和尚未, 马上下来),
    bootstrapped bigram lexicon, span-F1 regression floors."""

    CORPUS = os.path.join(os.path.dirname(__file__), "fixtures",
                          "zh_tagged_corpus.tsv")

    def test_bigram_lattice_beats_greedy(self):
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            derive_dictionary_from_tagged_corpus, evaluate_segmentation)
        d = derive_dictionary_from_tagged_corpus(self.CORPUS)
        r = evaluate_segmentation(self.CORPUS, d)
        assert r["sentences"] == 50
        # regression floors just under the measured 1.000 / 0.967
        assert r["viterbi_f1"] > 0.99
        assert r["greedy_f1"] < 0.98
        assert r["viterbi_f1"] > r["greedy_f1"] + 0.01

    def test_classic_greedy_traps_resolved(self):
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            derive_dictionary_from_tagged_corpus, greedy_segment,
            viterbi_segment)
        d = derive_dictionary_from_tagged_corpus(self.CORPUS)
        # 研究生 is in the lexicon, but 研究|生命 must win by bigram cost
        v = [e.surface for e in viterbi_segment("他研究生命的起源。", d)]
        assert v == ["他", "研究", "生命", "的", "起源", "。"]
        g = greedy_segment("他研究生命的起源。", d)
        assert g[:2] == ["他", "研究生"]  # greedy falls into the trap
        # 和尚 vs 和|尚未
        v2 = [e.surface for e in
              viterbi_segment("结婚的和尚未结婚的都来了。", d)]
        assert v2 == ["结婚", "的", "和", "尚未", "结婚", "的", "都",
                      "来", "了", "。"]
        # 大学生 vs 北京大学|生物
        v3 = [e.surface for e in viterbi_segment("北京大学生物系很有名。", d)]
        assert v3 == ["北京大学", "生物", "系", "很", "有名", "。"]

    def test_held_out_split_lattice_still_beats_greedy(self, tmp_path):
        """Beyond the train-on-test number: lexicon from 40 sentences,
        eval on the 10 held out (deterministic 1-in-5 interleave). OOV
        words cost both decoders, but typed unknown-word nodes keep the
        lattice clearly ahead (measured 0.900 vs greedy 0.787)."""
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            derive_dictionary_from_tagged_corpus, evaluate_segmentation)
        with open(self.CORPUS, encoding="utf-8") as f:
            lines = [ln for ln in f if ln.strip()]
        train = [ln for i, ln in enumerate(lines) if i % 5 != 4]
        test = [ln for i, ln in enumerate(lines) if i % 5 == 4]
        tr = tmp_path / "tr.tsv"
        te = tmp_path / "te.tsv"
        tr.write_text("".join(train), encoding="utf-8")
        te.write_text("".join(test), encoding="utf-8")
        d = derive_dictionary_from_tagged_corpus(str(tr))
        r = evaluate_segmentation(str(te), d)
        assert r["sentences"] == 10
        assert r["viterbi_f1"] > 0.85
        assert r["viterbi_f1"] > r["greedy_f1"] + 0.05

    def test_chinese_factory_lattice_mode(self):
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            derive_dictionary_from_tagged_corpus)
        from deeplearning4j_tpu.nlp.language_packs import (
            ChineseTokenizerFactory)
        d = derive_dictionary_from_tagged_corpus(self.CORPUS)
        fac = ChineseTokenizerFactory(dictionary=d)
        assert fac.create("他研究生命的起源").get_tokens() == \
            ["他", "研究", "生命", "的", "起源"]
        # word-list mode still behaves as before (greedy max-match)
        fac2 = ChineseTokenizerFactory(dictionary=set(d._by_surface))
        assert fac2.create("他研究生命的起源").get_tokens()[:2] == \
            ["他", "研究生"]


class TestUnknownWordHandling:
    """kuromoji char.def/unk.def parity (VERDICT r4 Missing #3's algorithm
    half): out-of-lexicon spans become TYPED unknown tokens grouped by
    character category instead of per-character soup. Measured by deleting
    lexicon entries from the bootstrapped Japanese dictionary."""

    CORPUS = [os.path.join(os.path.dirname(__file__), "fixtures",
                           "ja_tagged_corpus.tsv"),
              os.path.join(os.path.dirname(__file__), "fixtures",
                           "ja_tagged_corpus_traps.tsv")]

    def _dict_without(self, *words):
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            derive_dictionary_from_tagged_corpus)
        d = derive_dictionary_from_tagged_corpus(self.CORPUS)
        for w in words:
            d._by_surface.pop(w, None)
        return d

    def test_katakana_run_stays_one_token(self):
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            UNK_FEATURE, viterbi_segment)
        d = self._dict_without()
        # テレビゲーム appears in NO corpus — grouped katakana unknown
        segs = viterbi_segment("私はテレビゲームです。", d)
        surfaces = [e.surface for e in segs]
        assert "テレビゲーム" in surfaces
        unk = next(e for e in segs if e.surface == "テレビゲーム")
        assert unk.features == (UNK_FEATURE, "KATAKANA")

    def test_alpha_and_numeric_group(self):
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            viterbi_segment)
        d = self._dict_without()
        surfaces = [e.surface for e in viterbi_segment("私はABC123です。", d)]
        assert "ABC" in surfaces and "123" in surfaces

    def test_deleted_kanji_word_degrades_to_pieces_not_soup(self):
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            UNK_FEATURE, viterbi_segment)
        d = self._dict_without("牛乳")
        segs = viterbi_segment("子供は牛乳を飲みました。", d)
        surfaces = [e.surface for e in segs]
        # KANJI length=2: the two-char word comes back as ONE unknown
        # node (kanji pieces up to length 2), not two orphan chars
        assert "牛乳" in surfaces
        unk = next(e for e in segs if e.surface == "牛乳")
        assert unk.features == (UNK_FEATURE, "KANJI")
        # the rest of the sentence still segments exactly
        assert surfaces == ["子供", "は", "牛乳", "を", "飲み", "ました",
                            "。"]

    def test_entirely_oov_text_never_dead_ends(self):
        """A sentence with ZERO lexicon coverage must still segment (the
        lattice always has unknown candidates at every position), with
        category-grouped runs."""
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            MorphologicalDictionary, viterbi_segment)
        empty = MorphologicalDictionary([])
        segs = viterbi_segment("カメラ2024ABCで写真を撮る!", empty)
        assert "".join(e.surface for e in segs) == "カメラ2024ABCで写真を撮る!"
        surfaces = [e.surface for e in segs]
        assert "カメラ" in surfaces      # grouped katakana
        assert "2024" in surfaces        # grouped numerals
        assert "ABC" in surfaces         # grouped latin
        assert all(e.features[:1] == ("UNK",) for e in segs)

    def test_unknown_handling_improves_f1_on_depleted_lexicon(self):
        """The measurable claim: delete lexicon entries, F1 with
        category-grouped unknowns beats F1 with the old single-char
        fallback."""
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            CharCategoryDef, evaluate_segmentation)
        deleted = ("牛乳", "学生", "先生", "映画", "健康")
        with_unk = self._dict_without(*deleted)
        without_unk = self._dict_without(*deleted)
        # cripple the category config back to per-char fallback
        without_unk.categories = {
            "DEFAULT": CharCategoryDef(invoke=False, group=False, length=1,
                                       cost=20000)}
        r_with = evaluate_segmentation(self.CORPUS, with_unk)
        r_without = evaluate_segmentation(self.CORPUS, without_unk)
        assert r_with["viterbi_f1"] > r_without["viterbi_f1"]


class TestBootstrappedLexiconAccuracy:
    """Round-4 companion to TestMeasuredAccuracy: instead of the
    hand-built eval dict, the lexicon is BOOTSTRAPPED from the tagged
    corpus itself (derive_dictionary_from_tagged_corpus — MeCab's
    word+connection cost decomposition, bigram-estimated), evaluated over
    the base corpus PLUS the greedy-trap fixture (67 sentences)."""

    CORPUS = [os.path.join(os.path.dirname(__file__), "fixtures",
                           "ja_tagged_corpus.tsv"),
              os.path.join(os.path.dirname(__file__), "fixtures",
                           "ja_tagged_corpus_traps.tsv")]

    def test_bigram_lattice_beats_greedy(self):
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            derive_dictionary_from_tagged_corpus, evaluate_segmentation)
        d = derive_dictionary_from_tagged_corpus(self.CORPUS)
        r = evaluate_segmentation(self.CORPUS, d)
        assert r["sentences"] == 67
        # regression floors just under the measured 0.990 / 0.973
        assert r["viterbi_f1"] > 0.985
        assert r["greedy_f1"] < 0.98
        assert r["viterbi_f1"] > r["greedy_f1"] + 0.01

    def test_unigram_only_undersegments(self):
        """Documented negative result: without connection costs, cheap
        frequent particles undercut longer words and the greedy baseline
        actually WINS — the bigram matrix is load-bearing."""
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            derive_dictionary_from_tagged_corpus, evaluate_segmentation)
        d = derive_dictionary_from_tagged_corpus(self.CORPUS, bigram=False)
        r = evaluate_segmentation(self.CORPUS, d)
        assert r["viterbi_f1"] < r["greedy_f1"]

    def test_classic_greedy_traps_resolved(self):
        """The textbook ambiguities: greedy longest-match takes くるま/もも
        eagerly; the lattice recovers the particle readings."""
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            derive_dictionary_from_tagged_corpus, greedy_segment,
            viterbi_segment)
        d = derive_dictionary_from_tagged_corpus(self.CORPUS)
        v = [e.surface for e in viterbi_segment("くるまでまつ。", d)]
        assert v == ["くる", "まで", "まつ", "。"]
        assert greedy_segment("くるまでまつ。", d) == ["くるま", "で", "まつ", "。"]
        v2 = [e.surface for e in viterbi_segment("すもももももももものうち。", d)]
        assert v2 == ["すもも", "も", "もも", "も", "もも", "の", "うち", "。"]
