"""Stats/UI subsystem tests: listener → storage → server → remote round trip
(BaseStatsListener / StatsStorage / PlayUIServer / RemoteReceiverModule
parity, without a browser)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ui import (
    FileStatsStorage,
    InMemoryStatsStorage,
    Persistable,
    RemoteUIStatsStorageRouter,
    StatsListener,
    StatsStorageEvent,
    StatsStorageListener,
    StatsUpdateConfiguration,
    UIServer,
)
from deeplearning4j_tpu.ui.stats import TYPE_ID


def _train_with_listener(storage, cfg=None, iters=6):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.datasets.dataset import DataSet

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 5)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    listener = StatsListener(storage, cfg, session_id="sess1")
    net.listeners.append(listener)
    for _ in range(iters):
        net.fit(DataSet(x, y))
    return net, listener


class TestStorage:
    def test_in_memory_round_trip(self):
        ss = InMemoryStatsStorage()
        p = Persistable("s1", "T", "w0", 1.0, {"score": 0.5})
        ss.put_update(p)
        assert ss.list_session_ids() == ["s1"]
        assert ss.list_type_ids_for_session("s1") == ["T"]
        assert ss.list_worker_ids_for_session("s1") == ["w0"]
        assert ss.get_latest_update("s1", "T", "w0").data["score"] == 0.5
        assert ss.get_num_update_records_for("s1") == 1

    def test_updates_after_and_times(self):
        ss = InMemoryStatsStorage()
        for t in (1.0, 2.0, 3.0):
            ss.put_update(Persistable("s", "T", "w", t, {"t": t}))
        after = ss.get_all_updates_after("s", "T", 1.5)
        assert [p.timestamp for p in after] == [2.0, 3.0]
        assert ss.get_all_update_times("s", "T", "w") == [1.0, 2.0, 3.0]

    def test_listener_events(self):
        events = []

        class L(StatsStorageListener):
            def notify(self, e):
                events.append(e.kind)

        ss = InMemoryStatsStorage()
        ss.register_stats_storage_listener(L())
        ss.put_update(Persistable("s", "T", "w", 1.0, {}))
        assert StatsStorageEvent.NEW_SESSION in events
        assert StatsStorageEvent.POST_UPDATE in events

    def test_file_storage_reload(self, tmp_path):
        path = str(tmp_path / "stats.jsonl")
        ss = FileStatsStorage(path)
        ss.put_static_info(Persistable("s", "T", "w", 1.0, {"info": 1}))
        ss.put_update(Persistable("s", "T", "w", 2.0, {"score": 0.1}))
        ss.close()
        re = FileStatsStorage(path)
        assert re.get_static_info("s", "T", "w").data == {"info": 1}
        assert re.get_latest_update("s", "T", "w").data["score"] == 0.1
        re.close()


class TestStatsListener:
    def test_collects_score_params_lr(self):
        ss = InMemoryStatsStorage()
        _train_with_listener(ss)
        latest = ss.get_latest_update_all_workers("sess1", TYPE_ID)
        assert latest
        data = latest[0].data
        assert data["score"] > 0
        assert "0_W" in data["param_stats"]
        stats = data["param_stats"]["0_W"]
        assert {"mean", "stdev", "mean_magnitude", "norm2"} <= set(stats)
        assert data["learning_rates"]
        # static info posted once
        infos = ss.get_all_static_infos("sess1", TYPE_ID)
        assert len(infos) == 1 and infos[0].data["n_layers"] == 2

    def test_histograms(self):
        ss = InMemoryStatsStorage()
        cfg = StatsUpdateConfiguration(collect_histograms=True,
                                       histogram_bin_count=10)
        _train_with_listener(ss, cfg, iters=2)
        data = ss.get_latest_update_all_workers("sess1", TYPE_ID)[0].data
        hist = data["param_stats"]["0_W"]["histogram"]
        assert len(hist["counts"]) == 10
        assert len(hist["edges"]) == 11

    def test_report_frequency(self):
        ss = InMemoryStatsStorage()
        cfg = StatsUpdateConfiguration(report_iterations=3)
        _train_with_listener(ss, cfg, iters=6)
        assert ss.get_num_update_records_for("sess1") == 2


class TestServer:
    def test_endpoints_and_remote(self):
        ss = InMemoryStatsStorage()
        _train_with_listener(ss, iters=3)
        server = UIServer(port=0)
        server.attach(ss)
        server.enable_remote_listener()
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            html = urllib.request.urlopen(f"{base}/").read().decode()
            assert "training UI" in html
            sessions = json.loads(urllib.request.urlopen(
                f"{base}/train/sessions").read())
            assert "sess1" in sessions
            ov = json.loads(urllib.request.urlopen(
                f"{base}/train/overview/sess1").read())
            assert len(ov["iterations"]) == 3
            assert len(ov["scores"]) == 3
            assert ov["param_mean_magnitudes"]
            # remote router posts into the same storage
            router = RemoteUIStatsStorageRouter(base)
            router.put_update(Persistable("remote-sess", TYPE_ID, "w9", 5.0,
                                          {"iteration": 1, "score": 0.7}))
            sessions = json.loads(urllib.request.urlopen(
                f"{base}/train/sessions").read())
            assert "remote-sess" in sessions
        finally:
            server.stop()

    def test_remote_disabled_403(self):
        server = UIServer(port=0)
        port = server.start()
        try:
            router = RemoteUIStatsStorageRouter(f"http://127.0.0.1:{port}",
                                                retries=1, raise_on_error=True)
            with pytest.raises(Exception):
                router.put_update(Persistable("s", "T", "w", 1.0, {}))
            # default mode drops silently instead of killing the caller
            quiet = RemoteUIStatsStorageRouter(f"http://127.0.0.1:{port}",
                                               retries=1)
            quiet.put_update(Persistable("s", "T", "w", 1.0, {}))
        finally:
            server.stop()


class TestUiModules:
    def test_tsne_module_routes(self, rng):
        import urllib.request
        from deeplearning4j_tpu.ui.modules import TsneModule, register_module
        server = UIServer(port=0)
        mod = TsneModule()
        register_module(server, mod)
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            coords = rng.normal(size=(20, 2)).tolist()
            req = urllib.request.Request(
                f"{base}/tsne", method="POST",
                data=json.dumps({"name": "s1", "coords": coords,
                                 "labels": ["a"] * 10 + ["b"] * 10}).encode(),
                headers={"Content-Type": "application/json"})
            assert urllib.request.urlopen(req).status == 200
            sets = json.loads(urllib.request.urlopen(f"{base}/tsne").read())
            assert sets == ["s1"]
            data = json.loads(urllib.request.urlopen(f"{base}/tsne/s1").read())
            assert len(data["coords"]) == 20
            svg = mod.render_svg("s1")
            assert "<svg" in svg and "circle" in svg
        finally:
            server.stop()

    def test_activations_module(self, rng):
        from deeplearning4j_tpu.ui.modules import ConvolutionalListenerModule
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, InputType
        from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                                  OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.datasets.dataset import DataSet

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        activation="relu", name="conv"))
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.convolutional(8, 8, 1)).build())
        net = MultiLayerNetwork(conf).init()
        sample = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
        mod = ConvolutionalListenerModule(sample_input=sample, frequency=1)
        net.listeners.append(mod)
        x = rng.normal(size=(16, 8, 8, 1)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        net.fit(DataSet(x, y))
        assert mod.latest["layers"]["conv"]["channel_means"]
        code, payload = mod.handle("/activations")
        assert code == 200 and "layers" in payload

    def test_timeline_html(self):
        from deeplearning4j_tpu.parallel.master import TrainingStats
        from deeplearning4j_tpu.ui.modules import timeline_html
        st = TrainingStats()
        st.add("fit", 0.5)
        st.add("fit", 0.7)
        st.add("split", 0.1)
        page = timeline_html(st)
        assert "<table" in page and "fit" in page and "<svg" in page

    def test_one_time_logger(self):
        from deeplearning4j_tpu.optimize.listeners import OneTimeLogger
        import logging as _logging
        records = []
        h = _logging.Handler()
        h.emit = lambda r: records.append(r.getMessage())
        logger = _logging.getLogger("deeplearning4j_tpu.optimize.listeners")
        logger.addHandler(h)
        logger.setLevel(_logging.INFO)
        try:
            OneTimeLogger.reset()
            OneTimeLogger.warn("only once %s", "x")
            OneTimeLogger.warn("only once %s", "x")
            OneTimeLogger.info("another")
            assert records.count("only once x") == 1
            assert records.count("another") == 1
        finally:
            logger.removeHandler(h)


class TestModelDrilldownAndI18n:
    def test_model_and_layer_endpoints(self):
        storage = InMemoryStatsStorage()
        _train_with_listener(storage, iters=5)
        server = UIServer(port=0)
        server.attach(storage)
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            model = json.loads(urllib.request.urlopen(
                f"{base}/train/model/sess1").read())
            assert model["layer_names"], model
            layer = model["layer_names"][0]
            assert "params" in model["layers"][layer]
            assert "W" in model["layers"][layer]["params"]
            det = json.loads(urllib.request.urlopen(
                f"{base}/train/model/sess1/{layer}").read())
            assert det["iterations"]
            assert "W" in det["param_mean_magnitudes"]
            assert len(det["param_mean_magnitudes"]["W"]) == len(det["iterations"])
        finally:
            server.stop()

    def test_i18n_endpoints_and_dashboard_hooks(self):
        server = UIServer(port=0)
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            langs = json.loads(urllib.request.urlopen(f"{base}/i18n").read())
            assert {"en", "de", "ja"} <= set(langs)
            de = json.loads(urllib.request.urlopen(f"{base}/i18n/de").read())
            assert de["train.model.layer"] == "Schicht"
            # unknown language falls back to english
            xx = json.loads(urllib.request.urlopen(f"{base}/i18n/xx").read())
            assert xx["train.model.layer"] == "Layer"
            html = urllib.request.urlopen(f"{base}/train").read().decode()
            assert "data-i18n" in html and "/train/model/" in html
        finally:
            server.stop()


class TestEvaluationModule:
    """Metadata-backed error drilldown served through the UI module SPI
    (Evaluation.getPredictionErrors -> web surface)."""

    def _eval(self):
        from deeplearning4j_tpu.datasets.records import RecordMetaData
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        e = Evaluation(top_n=2)
        labels = np.eye(3)[[0, 0, 1, 2]]
        preds = np.array([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1],
                          [0.1, 0.8, 0.1], [0.6, 0.3, 0.1]])
        metas = [RecordMetaData(i, uri="eval.csv") for i in range(4)]
        e.eval(labels, preds, record_meta_data=metas)
        return e

    def test_routes(self):
        from deeplearning4j_tpu.ui.modules import EvaluationModule
        m = EvaluationModule(self._eval())
        code, body = m.handle("/evaluation")
        assert code == 200 and body["num_classes"] == 3
        assert body["has_metadata"] is True
        assert body["top_n"] == 2
        code, body = m.handle("/evaluation/errors")
        assert code == 200
        assert [(p["actual"], p["predicted"]) for p in body["errors"]] == \
            [(0, 1), (2, 0)]
        assert body["errors"][0]["record"] == "eval.csv:1"
        code, body = m.handle("/evaluation/by-predicted/1")
        assert code == 200 and len(body["predictions"]) == 2
        code, body = m.handle("/evaluation/cell/2/0")
        assert code == 200 and len(body["predictions"]) == 1
        code, body = m.handle("/evaluation/panel")
        assert code == 200 and "misclassified" in body["html"]

    def test_no_metadata_404(self):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.ui.modules import EvaluationModule
        e = Evaluation()
        e.eval(np.eye(2)[[0, 1]], np.array([[0.9, 0.1], [0.2, 0.8]]))
        m = EvaluationModule(e)
        code, body = m.handle("/evaluation/errors")
        assert code == 404
        code, body = m.handle("/evaluation")
        assert code == 200 and body["has_metadata"] is False

    def test_registered_on_server(self):
        from deeplearning4j_tpu.ui.modules import (EvaluationModule,
                                                   register_module)
        from deeplearning4j_tpu.ui.server import UIServer
        server = UIServer(port=0)
        mod = EvaluationModule(self._eval())
        register_module(server, mod)
        port = server.start()
        try:
            import json as _json
            import urllib.request
            url = f"http://127.0.0.1:{port}/evaluation/errors"
            with urllib.request.urlopen(url, timeout=10) as r:
                body = _json.loads(r.read())
            assert len(body["errors"]) == 2
        finally:
            server.stop()


class TestUiConnectionInfo:
    """UiConnectionInfo address building (deeplearning4j-core/ui)."""

    def test_address_parts(self):
        from deeplearning4j_tpu.ui import UiConnectionInfo
        u = UiConnectionInfo("host1", 9000, path="train", use_https=True,
                             session_id="s1")
        assert u.get_first_part() == "https://host1:9000"
        assert u.get_full_address() == "https://host1:9000/train/"
        assert u.get_full_address("remote") == \
            "https://host1:9000/train/remote/?sid=s1"

    def test_defaults(self):
        from deeplearning4j_tpu.ui import UiConnectionInfo
        u = UiConnectionInfo()
        assert u.get_first_part() == "http://localhost:8080"
        assert u.session_id  # generated


class TestKerasSequentialConfigImport:
    def test_rejects_functional(self, tmp_path):
        import json
        import pytest as _pytest
        from deeplearning4j_tpu.modelimport.keras.importer import KerasModelImport
        functional = {"class_name": "Model", "config": {
            "name": "m", "layers": [], "input_layers": [], "output_layers": []}}
        p = tmp_path / "f.json"
        p.write_text(json.dumps(functional))
        with _pytest.raises(ValueError):
            KerasModelImport.import_keras_sequential_configuration(str(p))
