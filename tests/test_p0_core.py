"""P0 tests: activations, losses, weight inits, updaters, schedules, config serde."""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import activations, losses, weights
from deeplearning4j_tpu.nn.updaters import (
    Adam, AdaDelta, AdaGrad, AdaMax, AMSGrad, ExponentialSchedule, FixedSchedule,
    InverseSchedule, MapSchedule, Nadam, Nesterovs, NoOp, PolySchedule, RmsProp,
    Schedule, Sgd, SigmoidSchedule, StepSchedule, Updater, normalize_gradients,
    resolve_updater,
)


class TestActivations:
    def test_all_registered_run(self):
        x = jnp.linspace(-3, 3, 32).reshape(4, 8)
        for name in activations.names():
            y = activations.resolve(name)(x)
            assert y.shape == x.shape, name
            assert bool(jnp.all(jnp.isfinite(y))), name

    def test_values(self):
        x = jnp.asarray([-2.0, -0.5, 0.0, 0.5, 2.0])
        np.testing.assert_allclose(activations.relu(x), [0, 0, 0, 0.5, 2])
        np.testing.assert_allclose(activations.hardtanh(x), [-1, -0.5, 0, 0.5, 1])
        np.testing.assert_allclose(activations.identity(x), x)
        np.testing.assert_allclose(
            activations.leakyrelu(x, 0.1), [-0.2, -0.05, 0, 0.5, 2], atol=1e-7)

    def test_softmax_rows_sum_to_one(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (5, 7))
        s = activations.softmax(x)
        np.testing.assert_allclose(np.asarray(jnp.sum(s, -1)), np.ones(5), rtol=1e-6)

    def test_parametric_tuple(self):
        fn = activations.resolve(("leakyrelu", {"alpha": 0.2}))
        np.testing.assert_allclose(fn(jnp.asarray([-1.0])), [-0.2], atol=1e-7)

    def test_selu_fixed_point(self):
        # selu(0)=0 and approximately preserves N(0,1) moments
        assert float(activations.selu(jnp.asarray(0.0))) == 0.0


class TestLosses:
    def test_mse(self):
        y = jnp.asarray([[1.0, 2.0]])
        p = jnp.asarray([[2.0, 4.0]])
        # ((1)^2 + (2)^2)/2 outputs = 2.5
        np.testing.assert_allclose(float(losses.mse(y, p)), 2.5)

    def test_mcxent_logits_matches_probs(self):
        key = jax.random.PRNGKey(1)
        logits = jax.random.normal(key, (6, 4))
        labels = jax.nn.one_hot(jnp.asarray([0, 1, 2, 3, 0, 1]), 4)
        a = losses.mcxent_logits(labels, logits)
        b = losses.mcxent_probs(labels, jax.nn.softmax(logits, -1))
        np.testing.assert_allclose(float(a), float(b), rtol=1e-5)

    def test_sparse_matches_dense(self):
        logits = jax.random.normal(jax.random.PRNGKey(2), (5, 3))
        idx = jnp.asarray([0, 2, 1, 0, 2])
        dense = losses.mcxent_logits(jax.nn.one_hot(idx, 3), logits)
        sparse = losses.sparse_mcxent_logits(idx, logits)
        np.testing.assert_allclose(float(dense), float(sparse), rtol=1e-6)

    def test_xent_logits_matches_probs(self):
        logits = jax.random.normal(jax.random.PRNGKey(3), (4, 2))
        labels = jnp.asarray([[1., 0.], [0., 1.], [1., 1.], [0., 0.]])
        a = losses.xent_logits(labels, logits)
        b = losses.xent_probs(labels, jax.nn.sigmoid(logits))
        np.testing.assert_allclose(float(a), float(b), rtol=1e-5)

    def test_mask_excludes_examples(self):
        y = jnp.asarray([[1.0], [5.0]])
        p = jnp.asarray([[2.0], [100.0]])
        m = jnp.asarray([1.0, 0.0])
        np.testing.assert_allclose(float(losses.mse(y, p, mask=m)), 1.0)

    def test_resolve_fused(self):
        fn, wants_logits = losses.resolve("mcxent", "softmax")
        assert wants_logits
        fn, wants_logits = losses.resolve("mcxent", "sigmoid")
        assert not wants_logits
        fn, wants_logits = losses.resolve("mse", "identity")
        assert not wants_logits

    def test_hinge(self):
        y = jnp.asarray([[1.0], [-1.0]])
        p = jnp.asarray([[0.5], [2.0]])
        # max(0,1-0.5)=0.5 ; max(0,1+2)=3 → mean 1.75
        np.testing.assert_allclose(float(losses.hinge(y, p)), 1.75)


class TestWeightInit:
    def test_all_schemes_shapes_and_variance(self):
        key = jax.random.PRNGKey(0)
        fan_in, fan_out = 256, 128
        shape = (fan_in, fan_out)
        for scheme in weights.ALL_SCHEMES:
            if scheme == "identity":
                w = weights.init_weight(key, (64, 64), scheme, 64, 64)
                np.testing.assert_allclose(np.asarray(w), np.eye(64))
                continue
            dist = weights.Distribution("normal", std=0.3) if scheme == "distribution" else None
            w = weights.init_weight(key, shape, scheme, fan_in, fan_out,
                                    distribution=dist)
            assert w.shape == shape, scheme
            assert bool(jnp.all(jnp.isfinite(w))), scheme

    def test_xavier_std(self):
        key = jax.random.PRNGKey(42)
        w = weights.init_weight(key, (1000, 1000), "xavier", 1000, 1000)
        expected = math.sqrt(2.0 / 2000)
        assert abs(float(jnp.std(w)) - expected) < expected * 0.05

    def test_relu_std(self):
        key = jax.random.PRNGKey(43)
        w = weights.init_weight(key, (1000, 500), "relu", 1000, 500)
        expected = math.sqrt(2.0 / 1000)
        assert abs(float(jnp.std(w)) - expected) < expected * 0.05

    def test_zero_ones(self):
        key = jax.random.PRNGKey(0)
        assert float(jnp.sum(weights.init_weight(key, (3, 3), "zero", 3, 3))) == 0
        assert float(jnp.sum(weights.init_weight(key, (3, 3), "ones", 3, 3))) == 9

    def test_uniform_bound(self):
        key = jax.random.PRNGKey(1)
        w = weights.init_weight(key, (400, 10), "uniform", 400, 10)
        bound = 1.0 / math.sqrt(400)
        assert float(jnp.max(jnp.abs(w))) <= bound

    def test_distribution_serde(self):
        d = weights.Distribution("uniform", lower=-0.2, upper=0.2)
        d2 = weights.Distribution.from_dict(d.to_dict())
        assert d == d2


class TestUpdaters:
    def _converges(self, updater, iters=300, tol=1e-2):
        """Minimize f(w) = ||w - 3||^2 with the updater."""
        w = jnp.asarray([0.0, 0.0])
        state = updater.init_state(w)
        for t in range(1, iters + 1):
            g = 2 * (w - 3.0)
            lr = updater.lr_at(t, 0)
            upd, state = updater.update(g, state, lr, float(t))
            w = w - upd
        return float(jnp.max(jnp.abs(w - 3.0))) < tol

    @pytest.mark.parametrize("updater", [
        Sgd(0.1), Adam(0.1), AdaMax(0.1), Nadam(0.1), AMSGrad(0.1),
        AdaGrad(0.5), AdaDelta(rho=0.9), RmsProp(0.05), Nesterovs(0.05, 0.9),
    ], ids=lambda u: type(u).__name__)
    def test_convergence(self, updater):
        assert self._converges(updater, iters=1500 if isinstance(updater, AdaDelta) else 300,
                               tol=0.15 if isinstance(updater, AdaDelta) else 1e-2)

    def test_sgd_exact(self):
        u = Sgd(0.5)
        upd, _ = u.update(jnp.asarray([2.0]), {}, 0.5, 1.0)
        np.testing.assert_allclose(np.asarray(upd), [1.0])

    def test_adam_first_step(self):
        # after one step Adam's update is lr * sign-ish of gradient
        u = Adam(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8)
        g = jnp.asarray([0.5])
        state = u.init_state(g)
        upd, _ = u.update(g, state, 0.001, 1.0)
        # m_hat = g, v_hat = g^2 → update ≈ lr * g/|g| = lr
        np.testing.assert_allclose(np.asarray(upd), [0.001], rtol=1e-3)

    def test_noop(self):
        u = NoOp()
        upd, _ = u.update(jnp.asarray([5.0]), {}, 0.0, 1.0)
        assert float(upd[0]) == 0.0

    def test_serde_roundtrip(self):
        for u in [Sgd(0.1), Adam(0.01, 0.8, 0.95, 1e-7),
                  Nesterovs(0.05, 0.95), RmsProp(0.002, 0.9, 1e-7)]:
            u2 = Updater.from_dict(u.to_dict())
            assert u == u2

    def test_schedule_serde(self):
        for s in [FixedSchedule(value_=0.1), ExponentialSchedule("epoch", 0.1, 0.9),
                  InverseSchedule("iteration", 0.1, 0.9, 2.0),
                  PolySchedule("iteration", 0.1, 2.0, 100),
                  SigmoidSchedule("iteration", 0.1, 0.5, 10),
                  StepSchedule("iteration", 0.1, 0.5, 50.0),
                  MapSchedule("iteration", ((0, 0.1), (100, 0.01)))]:
            s2 = Schedule.from_dict(s.to_dict())
            assert s == s2

    def test_schedule_values(self):
        s = StepSchedule("iteration", initial_value=1.0, decay_rate=0.5, step=10.0)
        assert float(s.value(0, 0)) == 1.0
        assert float(s.value(10, 0)) == 0.5
        assert float(s.value(25, 0)) == 0.25
        m = MapSchedule("iteration", ((0, 0.1), (5, 0.01)))
        assert float(m.value(4, 0)) == pytest.approx(0.1)
        assert float(m.value(5, 0)) == pytest.approx(0.01)

    def test_updater_with_schedule(self):
        u = Sgd(ExponentialSchedule("iteration", 1.0, 0.5))
        assert float(u.lr_at(0, 0)) == 1.0
        assert float(u.lr_at(2, 0)) == 0.25
        u2 = Updater.from_dict(u.to_dict())
        assert u2 == u

    def test_resolve_updater(self):
        assert isinstance(resolve_updater("adam"), Adam)
        assert isinstance(resolve_updater("nesterovs"), Nesterovs)
        assert isinstance(resolve_updater(None), Sgd)


class TestGradientNormalization:
    def test_clip_elementwise(self):
        g = {"W": jnp.asarray([3.0, -2.0, 0.5])}
        out = normalize_gradients(g, "clip_elementwise_absolute_value", 1.0)
        np.testing.assert_allclose(np.asarray(out["W"]), [1.0, -1.0, 0.5])

    def test_clip_l2_per_layer(self):
        g = {"W": jnp.asarray([3.0, 4.0])}  # norm 5
        out = normalize_gradients(g, "clip_l2_per_layer", 1.0)
        np.testing.assert_allclose(float(jnp.linalg.norm(out["W"])), 1.0, rtol=1e-6)

    def test_renormalize_per_layer(self):
        g = {"W": jnp.asarray([3.0, 0.0]), "b": jnp.asarray([4.0])}
        out = normalize_gradients(g, "renormalize_l2_per_layer")
        total = math.sqrt(float(jnp.sum(out["W"]**2) + jnp.sum(out["b"]**2)))
        np.testing.assert_allclose(total, 1.0, rtol=1e-6)

    def test_noop_mode(self):
        g = {"W": jnp.asarray([3.0])}
        assert normalize_gradients(g, None) is g
